"""benchmarks/longctx.py drives (tiny scale, CPU) — keeps the battery's
long-context lane from bit-rotting between TPU windows."""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_longctx(tmp_path, *extra):
    spec = importlib.util.spec_from_file_location(
        "longctx", os.path.join(REPO, "benchmarks", "longctx.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "longctx.json")
    argv = ["longctx.py", "--model", "tiny-llama", "--ctx", "96",
            "--chunk", "32", "--decode-tokens", "6", "--out", out, *extra]
    old = sys.argv
    sys.argv = argv
    try:
        rec = mod.main()
    finally:
        sys.argv = old
    with open(out) as f:
        assert json.load(f) == rec
    return rec


def test_longctx_smoke(tmp_path):
    """Chunked prefill (3 chunks of 32) + decode through the production
    scheduler; the emitted record carries real, positive measurements."""
    rec = _run_longctx(tmp_path)
    assert rec["ctx"] == 96 and rec["decode_tokens"] == 6
    assert rec["prefill_tok_s"] > 0 and rec["ttft_s"] > 0
    assert rec["tpot_ms"] > 0 and rec["decode_tok_s"] > 0
    assert rec["platform"] == "cpu" and rec["backend"] == "dense"


def test_longctx_kv_int8(tmp_path):
    """The KV-int8 A/B lane the battery runs, at test scale."""
    rec = _run_longctx(tmp_path, "--quant", "int8", "--kv-quant", "int8")
    assert rec["quant"] == "int8" and rec["kv_quant"] == "int8"
    assert rec["tpot_ms"] > 0
