"""Step ledger + roofline attribution + flight recorder (README
"Performance attribution").

Unit level: ring semantics and overflow, pinned bottleneck verdicts on
synthetic records through the analytic cost model, the MFU EWMA replay,
fleet merging, the flight recorder's capture/retention/rate-limit
behavior, the blackbox index, and the telemetry kill switch.

Process level: ONE consolidated dp=2 subprocess-fleet test drives real
traffic over HTTP, reads per-replica verdicts from GET /debug/steps
(cross-checking the ledger-replayed MFU against ``tpu_inf_mfu_estimate``
within 20%), then kill -9s a worker and finds its surviving blackbox
capture at GET /debug/blackbox.
"""

import json
import math
import os
import time

import pytest

from tpu_inference import telemetry
from tpu_inference.telemetry import (NULL_LEDGER, STEP_FIELDS, EngineTelemetry,
                                     FlightRecorder, Histogram, StepCostModel,
                                     StepLedger, attach_flight_recorder,
                                     blackbox_index, merge_steps_reports,
                                     percentile_from_cumulative,
                                     roofline_report)

# ------------------------------------------------------------- ring


def test_ledger_ring_semantics_and_overflow():
    led = StepLedger(depth=2)
    assert led.depth == 8, "depth must floor at 8"
    led = StepLedger(depth=8)
    for i in range(5):
        led.push("decode", rung=4, slots=2, tokens=i, chunk_tokens=0,
                 steps=1, device_s=0.01, staging_s=0.0, bubble_s=0.0,
                 kv_read_tokens=10, kv_swap_bytes=0.0, spec_accepted=0,
                 compile_event=False)
    assert led.count == 5 and not led.overflowed
    recs = led.records()
    assert [r[4] for r in recs] == [0, 1, 2, 3, 4], "oldest first"
    # Overflow: ring keeps exactly depth records, still oldest-first.
    for i in range(5, 20):
        led.push("decode", 4, 2, i, 0, 1, 0.01, 0.0, 0.0, 10, 0.0, 0,
                 False)
    assert led.count == 20 and led.overflowed
    recs = led.records()
    assert len(recs) == 8
    assert [r[4] for r in recs] == list(range(12, 20))
    # snapshot: one dict per record, keyed exactly by STEP_FIELDS.
    snap = led.snapshot()
    assert len(snap) == 8 and set(snap[0]) == set(STEP_FIELDS)
    assert snap[-1]["tokens"] == 19 and snap[-1]["kind"] == "decode"


def test_null_ledger_is_inert():
    NULL_LEDGER.push("decode", 4, 2, 1, 0, 1, 0.01, 0.0, 0.0, 0, 0.0, 0,
                     False)
    assert NULL_LEDGER.records() == []
    assert NULL_LEDGER.snapshot() == []
    assert NULL_LEDGER.count == 0 and not NULL_LEDGER.overflowed


# ------------------------------------------------------- roofline


def _model(**kw):
    base = dict(n_params=1000, n_layers=1, n_heads=1, head_dim=1,
                weight_bytes=1000, kv_token_bytes=0, peak_flops=1e6,
                peak_hbm_bw=1e6)
    base.update(kw)
    return StepCostModel(**base)


def test_roofline_pinned_verdicts():
    """Three synthetic records, one per bottleneck regime, graded by a
    hand-sized cost model — the verdict semantics the README documents,
    pinned."""
    model = _model()
    led = StepLedger(depth=16)
    # compute-bound: 500 tokens in 1 s = 2*1000*500 = 1e6 FLOPs/s
    # (compute_frac 1.0) vs 1000 weight bytes/s (hbm_frac 1e-3).
    led.push("decode", rung=4, slots=4, tokens=500, chunk_tokens=0,
             steps=1, device_s=1.0, staging_s=0.0, bubble_s=0.0,
             kv_read_tokens=0, kv_swap_bytes=0.0, spec_accepted=0,
             compile_event=False)
    # hbm-bound: 1000 device iterations stream the weights 1000 times
    # (1e6 bytes/s, hbm_frac 1.0) for only 2 positions of matmul work.
    led.push("prefill_chunk", rung=0, slots=1, tokens=1, chunk_tokens=1,
             steps=1000, device_s=1.0, staging_s=0.0, bubble_s=0.0,
             kv_read_tokens=0, kv_swap_bytes=0.0, spec_accepted=0,
             compile_event=True)
    # host-bound: staging + bubble (0.5 s) dominates device wall (0.1 s)
    # -> host_frac ~0.83 regardless of the roofline fractions.
    led.push("hybrid", rung=2, slots=2, tokens=10, chunk_tokens=16,
             steps=2, device_s=0.1, staging_s=0.3, bubble_s=0.2,
             kv_read_tokens=50, kv_swap_bytes=0.0, spec_accepted=0,
             compile_event=False)

    rep = roofline_report(led, model)
    assert rep["enabled"] and rep["records_window"] == 3
    assert not rep["truncated"]
    kinds = rep["kinds"]
    assert kinds["decode"]["verdict"] == "compute-bound"
    assert kinds["prefill_chunk"]["verdict"] == "hbm-bound"
    assert kinds["hybrid"]["verdict"] == "host-bound"
    # Achieved rates come straight from the analytic model.
    assert kinds["decode"]["achieved_flops_per_s"] == pytest.approx(1e6)
    assert kinds["prefill_chunk"]["achieved_bytes_per_s"] == (
        pytest.approx(1e6, rel=1e-3))
    assert kinds["hybrid"]["host_frac"] == pytest.approx(0.5 / 0.6,
                                                         rel=1e-3)
    # Occupancy: prefill_chunk is excluded (no decode lanes).
    assert set(rep["rung_occupancy"]) == {"4", "2"}
    assert rep["rung_occupancy"]["4"] == {"dispatches": 1,
                                          "mean_slots": 4.0}
    # Top sinks are the largest time components, descending.
    assert rep["top_sinks"][0]["sink"] == "decode.device"
    secs = [s["seconds"] for s in rep["top_sinks"]]
    assert secs == sorted(secs, reverse=True) and len(secs) == 3
    assert rep["compile_events"] == 1
    # Window filtering: a "now" past the window empties the report.
    empty = roofline_report(led, model, now=time.time() + 3600)
    assert empty["records_window"] == 0 and empty["kinds"] == {}


def test_kv_read_attention_flops_counted():
    """Attention FLOPs scale with (query, context) pairs attended —
    the term that makes long-context decode drift toward hbm-bound."""
    model = _model(n_layers=2, n_heads=4, head_dim=8)
    rec = (time.time(), "decode", 4, 4, 10, 0, 1, 0.5, 0.0, 0.0,
           1000, 0.0, 0, 0)
    assert model.flops(rec) == pytest.approx(
        2.0 * 1000 * 10 + 4.0 * 2 * 4 * 8 * 1000)
    assert model.hbm_bytes(rec) == pytest.approx(1000 * 1 + 0 + 0.0)


def _mfu_rec(ts, tokens):
    return (ts, "decode", 4, 1, tokens, 0, 1, 0.01, 0.0, 0.0, 0, 0.0,
            0, 0)


def test_ledger_mfu_ewma_replay_converges():
    """The ledger replay reproduces the gauge's dt-weighted EWMA: a
    steady 10 tokens/s for many time constants converges to MFU =
    10 * 2 * n_params / peak."""
    t0 = 1_000_000.0
    recs = [_mfu_rec(t0 + i, 10.0) for i in range(1, 201)]
    mfu = telemetry._ledger_mfu_ewma(recs, n_params=10**6,
                                     peak_flops=1e9, bind_unix=t0,
                                     now=t0 + 200)
    assert mfu == pytest.approx(10 * 2 * 10**6 / 1e9, rel=0.05)
    # Trailing idle decays the rate exactly like the gauge would.
    idle = telemetry._ledger_mfu_ewma(recs, n_params=10**6,
                                      peak_flops=1e9, bind_unix=t0,
                                      now=t0 + 200 + 30)
    assert idle == pytest.approx(mfu * math.exp(-1.0), rel=0.05)
    assert telemetry._ledger_mfu_ewma([], 1, 1.0, None, 0.0) is None


def test_merge_steps_reports_pools_and_refinalizes():
    model = _model()
    led = StepLedger(depth=16)
    led.push("decode", 4, 4, 500, 0, 1, 1.0, 0.0, 0.0, 0, 0.0, 0, False)
    rep = roofline_report(led, model)
    merged = merge_steps_reports([rep, rep, None, {"enabled": False}])
    assert merged["enabled"] and merged["replicas_merged"] == 2
    assert merged["records_window"] == 2
    k = merged["kinds"]["decode"]
    assert k["records"] == 2 and k["tokens"] == 1000
    # Pooled rate: 2e6 FLOPs over 2 s of device wall — same verdict.
    assert k["achieved_flops_per_s"] == pytest.approx(1e6)
    assert k["verdict"] == "compute-bound"
    assert merged["rung_occupancy"]["4"] == {"dispatches": 2,
                                             "mean_slots": 4.0}
    assert merge_steps_reports([]) == {"enabled": False}
    assert merge_steps_reports([None, {"enabled": False}]) == {
        "enabled": False}


def test_quantile_implementations_unified():
    """Histogram.percentile and percentile_from_cumulative are ONE
    implementation (the server-side interpolation the traffic
    generator's client-side percentiles mirror) — pinned on a known
    distribution."""
    h = Histogram("t", "t", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    for p in (0.5, 0.95, 0.99):
        assert h.percentile(p) == percentile_from_cumulative(
            h.bounds, h.cumulative(), p)
    # 4 samples, target p50 = 2.0 cum -> bucket (1, 2], 1 prior, 2 in
    # bucket -> 1 + (2 - 1) * (2 - 1) / 2 = 1.5.
    assert h.percentile(0.5) == pytest.approx(1.5)
    assert percentile_from_cumulative((1.0, 2.0, 4.0), (0, 0, 0), 0.5) \
        is None


# -------------------------------------------------- kill switch


def test_telemetry_disabled_kills_ledger_and_recorder(tmp_path):
    tel = EngineTelemetry(enabled=False)
    assert tel.step_ledger is NULL_LEDGER
    tel.step_ledger.push("decode", 4, 1, 1, 0, 1, 0.01, 0.0, 0.0, 0,
                         0.0, 0, False)
    assert tel.steps_report() == {"enabled": False}
    assert attach_flight_recorder(tel, str(tmp_path), 0) is None
    assert tel.flight is None
    assert list(tmp_path.iterdir()) == [], "no blackbox I/O when off"
    # Empty root dir: no-op even with telemetry on.
    assert attach_flight_recorder(EngineTelemetry(enabled=True),
                                  "", 0) is None


# ---------------------------------------------- flight recorder


def test_flight_recorder_capture_retention_rate_limit(tmp_path):
    root = str(tmp_path / "bb")
    steps = [{"kind": "decode", "tokens": 3}]
    fr = FlightRecorder(root, replica=1, retain=2,
                        config={"dp": 2},
                        steps_fn=lambda: steps,
                        spans_fn=lambda: [{"name": "request"}],
                        stats_fn=lambda: {"ok": True})
    path = fr.capture("step_error", min_interval_s=0.0)
    assert path and os.path.exists(path)
    payload = json.loads(open(path).read())
    assert payload["trigger"] == "step_error"
    assert payload["replica"] == 1 and payload["pid"] == os.getpid()
    assert payload["steps"] == steps
    assert payload["spans"] == [{"name": "request"}]
    assert payload["config"] == {"dp": 2}
    assert payload["stats"] == {"ok": True}
    # Per-trigger rate limit: an immediate repeat is dropped.
    assert fr.capture("step_error", min_interval_s=60.0) is None
    # Retention: only the newest `retain` captures survive pruning.
    for i in range(4):
        assert fr.capture(f"t{i}", min_interval_s=0.0)
    caps = sorted(f for f in os.listdir(fr.dir)
                  if f.startswith("capture-"))
    assert len(caps) == 2 and caps == ["capture-000003-t2.json",
                                       "capture-000004-t3.json"]
    # Periodic heartbeat: single refreshed file, interval-gated.
    fr.maybe_periodic()
    assert os.path.exists(os.path.join(fr.dir, "periodic.json"))
    # A restart adopts the dead incarnation's heartbeat as a numbered
    # postmortem (the kill -9 evidence) before it can be overwritten,
    # and sequence numbers resume past every existing capture.
    fr2 = FlightRecorder(root, replica=1, retain=2)
    pm = os.path.join(fr2.dir, "capture-000005-postmortem.json")
    assert os.path.exists(pm)
    assert json.loads(open(pm).read())["trigger"] == "postmortem"
    assert not os.path.exists(os.path.join(fr2.dir, "periodic.json"))
    p2 = fr2.capture("boot", min_interval_s=0.0)
    assert os.path.basename(p2) == "capture-000006-boot.json"
    # A failing section callback degrades to empty, never raises.
    fr3 = FlightRecorder(root, replica=1, retain=8,
                         steps_fn=lambda: 1 / 0)
    p3 = fr3.capture("bad_fn", min_interval_s=0.0)
    assert json.loads(open(p3).read())["steps"] == []


def test_blackbox_index_lists_newest_first(tmp_path):
    root = str(tmp_path)
    assert blackbox_index("") == {"dir": "", "captures": []}
    assert blackbox_index(str(tmp_path / "nope"))["captures"] == []
    for rep in (0, 1):
        fr = FlightRecorder(root, replica=rep, retain=8,
                            steps_fn=lambda: [{}, {}])
        fr.capture("watchdog", min_interval_s=0.0)
    # An unreadable capture is reported, not fatal.
    bad = tmp_path / "replica-0" / "capture-999999-junk.json"
    bad.write_text("{not json")
    idx = blackbox_index(root)
    assert idx["dir"] == root
    entries = idx["captures"]
    assert {e["replica"] for e in entries} == {0, 1}
    good = [e for e in entries if "error" not in e]
    assert all(e["trigger"] == "watchdog" and e["n_steps"] == 2
               and e["pid"] == os.getpid() for e in good)
    ts = [e["ts"] for e in good]
    assert ts == sorted(ts, reverse=True), "newest first"
    assert any(e.get("error") == "unreadable" for e in entries)


def test_attach_flight_recorder_binds_ledger_and_spans(tmp_path):
    tel = EngineTelemetry(enabled=True)
    tel.step_ledger = StepLedger(depth=8)
    tel.step_ledger.push("decode", 4, 1, 7, 0, 1, 0.01, 0.0, 0.0, 0,
                         0.0, 0, False)
    tel.recorder.add("request", "tid-1", 0.0, 1.0, parent="")
    tel.recorder.seal("tid-1")
    fr = attach_flight_recorder(tel, str(tmp_path), 3, retain=4,
                                config={"x": 1},
                                stats_fn=lambda: {"n": 1})
    assert fr is not None and tel.flight is fr
    path = fr.capture("watchdog", min_interval_s=0.0)
    payload = json.loads(open(path).read())
    assert payload["replica"] == 3 and payload["config"] == {"x": 1}
    assert payload["steps"][0]["tokens"] == 7
    assert any(s.get("name") == "request" for s in payload["spans"])
    assert payload["stats"] == {"n": 1}


# ------------------------------------------- committed artifact


def test_committed_smoke_artifact_carries_attribution():
    """The committed replay smoke artifact embeds the step_attribution
    block — verdicts per step kind, rung occupancy, top sinks, and the
    MFU cross-check — so a regression that silently drops attribution
    from the bench pipeline fails tier-1."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    art_path = os.path.join(root, "benchmarks", "results",
                            "replay_smoke.json")
    art = json.loads(open(art_path).read())
    att = art["summary"]["step_attribution"]
    assert att["enabled"] is True
    assert att["records"] > 0
    assert att["verdicts"], "no step kinds attributed"
    for kind, verdict in att["verdicts"].items():
        assert kind in telemetry.STEP_KINDS
        assert verdict in ("compute-bound", "hbm-bound", "host-bound")
    assert att["rung_occupancy"], "no rung occupancy histogram"
    assert 1 <= len(att["top_sinks"]) <= 3
    assert att["mfu"]["ledger"] is not None
    assert att["replica_verdicts"]


# ------------------------------------- live dp=2 subprocess fleet


def test_fleet_steps_and_blackbox_over_http(tmp_path):
    """ONE consolidated process-level acceptance run: real traffic over
    HTTP against a dp=2 subprocess fleet, per-replica bottleneck
    verdicts from GET /debug/steps with the ledger-replayed MFU agreeing
    with ``tpu_inf_mfu_estimate`` within 20%, then a kill -9'd worker
    whose surviving blackbox capture shows up at GET /debug/blackbox."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from tpu_inference.config import (EngineConfig, FrameworkConfig,
                                      ParallelConfig, ServerConfig,
                                      tiny_llama)
    from tpu_inference.server.http import InferenceServer

    bb = str(tmp_path / "blackbox")
    cfg = FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(page_size=8, num_pages=64,
                            max_pages_per_seq=8, max_batch_size=2,
                            prefill_buckets=(16,), host_cache_pages=32),
        parallel=ParallelConfig(dp=2),
        server=ServerConfig(model_name="tiny-llama", tokenizer="byte",
                            warmup=False, fleet="subprocess",
                            enable_debug=True, worker_restart_max=10,
                            worker_restart_backoff_s=0.1,
                            drain_timeout_s=8.0, blackbox_dir=bb,
                            blackbox_retain=4))
    srv = InferenceServer(cfg)

    async def go(client):
        # Concurrent streams: with max_batch_size=2 per replica, six
        # in-flight requests force the router to use both workers.
        async def one(i):
            resp = await client.post("/api/generate", json={
                "model": "tiny-llama", "prompt": f"roofline probe {i}",
                "temperature": 0.0, "max_tokens": 24, "stream": True})
            assert resp.status == 200
            await resp.read()

        await asyncio.gather(*(one(i) for i in range(6)))

        resp = await client.get("/debug/steps")
        assert resp.status == 200
        snap = await resp.json()
        assert set(snap["replicas"]) == {"0", "1"}
        for rep in snap["replicas"].values():
            assert rep["enabled"]
            assert rep["records_window"] > 0, "a replica saw no traffic"
            assert rep["kinds"], "no step kinds attributed"
            for kind, agg in rep["kinds"].items():
                assert kind in telemetry.STEP_KINDS
                assert agg["verdict"] in ("compute-bound", "hbm-bound",
                                          "host-bound")
            # Cross-check: ledger-replayed MFU vs the live gauge.
            mfu = rep["mfu"]
            assert mfu["gauge"] and mfu["ledger"] is not None
            assert 0.8 <= mfu["agreement"] <= 1.2, mfu
        fleet = snap["fleet"]
        assert fleet["enabled"] and fleet["replicas_merged"] == 2
        assert fleet["records_window"] > 0 and fleet["rung_occupancy"]
        assert 0.8 <= fleet["mfu"]["agreement"] <= 1.2, fleet["mfu"]

        # kill -9 one worker: its blackbox directory survives the kill
        # (periodic heartbeat at minimum) and the index lists it.
        victim = 0
        resp = await client.post("/debug/chaos",
                                 json={"replica": victim,
                                       "kill": "kill9"})
        assert resp.status == 200
        deadline = time.monotonic() + 30
        caps = []
        while time.monotonic() < deadline:
            idx = await (await client.get("/debug/blackbox")).json()
            assert idx["dir"] == bb
            caps = [e for e in idx["captures"]
                    if e["replica"] == victim and "error" not in e]
            if caps:
                break
            await asyncio.sleep(0.2)
        assert caps, "kill -9'd worker left no harvested capture"
        assert any(e.get("n_steps", 0) > 0 or e.get("has_config")
                   for e in caps), caps

        # The supervisor restarts the victim under the same label.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(h.state == "up" for h in srv.group.workers):
                break
            await asyncio.sleep(0.1)
        assert all(h.state == "up" for h in srv.group.workers)

    async def wrapper():
        app = srv.make_app()
        async with TestClient(TestServer(app)) as client:
            await go(client)

    asyncio.run(wrapper())
