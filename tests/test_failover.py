"""Replica supervision: health state machine, step watchdog, failover,
and admission control (README "Failure handling & degraded operation").

Engine-level fault injection (EngineConfig.chaos_step_*) makes the
documented TPU failure modes — per-step exceptions and wedged dispatches
— deterministic on CPU, so these tests drive the full path: injected
fault -> quarantine -> resubmission on a healthy replica -> tokens
identical to a no-fault run, plus the 429/503 + Retry-After shedding the
harness's traffic generator backs off on.
"""

import asyncio
import json
import re
import threading
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpu_inference.config import (EngineConfig, FrameworkConfig,
                                  ParallelConfig, ServerConfig, tiny_llama)
from tpu_inference.engine.engine import Sequence
from tpu_inference.server.http import InferenceServer, build_engine_group
from tpu_inference.server.replicas import (DEGRADED, HEALTHY, QUARANTINED,
                                           RECOVERED, ReplicaHealth)

ENGINE_KW = dict(page_size=8, num_pages=64, max_pages_per_seq=4,
                 max_batch_size=2, prefill_buckets=(16,))


def _cfg(dp=1, **server_kw) -> FrameworkConfig:
    return FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(**ENGINE_KW),
        parallel=(ParallelConfig(dp=2, tp=2) if dp == 2 else
                  ParallelConfig()),
        server=ServerConfig(model_name="t", tokenizer="byte", **server_kw))


def _run(server, coro_fn):
    async def wrapper():
        app = server.make_app()
        async with TestClient(TestServer(app)) as client:
            return await coro_fn(client)

    return asyncio.run(wrapper())


# ---------------------------------------------------------------- unit


def test_health_state_machine():
    """healthy -> degraded -> quarantined -> recovered -> healthy, with
    probation failure going straight back to quarantine."""
    cfg = ServerConfig(quarantine_after_failures=3,
                       quarantine_cooldown_s=0.05)
    h = ReplicaHealth(cfg)
    assert h.state == HEALTHY and h.routable

    h.on_error()
    assert h.state == DEGRADED and h.routable
    h.on_ok()                               # one clean step heals
    assert h.state == HEALTHY and h.consecutive_failures == 0

    for _ in range(3):
        h.on_error()
    assert h.state == QUARANTINED and not h.routable
    assert h.quarantines == 1

    h.on_ok()                               # a late success does not
    assert h.state == QUARANTINED           # beat the cooldown

    time.sleep(0.06)
    h.maybe_recover()
    assert h.state == RECOVERED and h.routable

    h.on_error()                            # probation failure
    assert h.state == QUARANTINED and h.quarantines == 2

    time.sleep(0.06)
    h.maybe_recover()
    h.on_ok()                               # probation pass
    assert h.state == HEALTHY

    # Watchdog path: wedge transitions exactly once.
    assert h.mark_wedged() is True
    assert h.state == QUARANTINED and h.wedges == 1
    assert h.mark_wedged() is False         # already quarantined


# ------------------------------------------------- group-level failover


def _submit_and_wait(group, rid, prompt, max_new, timeout=60.0):
    """Submit one request through the group; return (tokens, finish_seq)
    once its on_finish fires."""
    tokens, done, box = [], threading.Event(), {}

    def on_token(s, t):
        tokens.append(t)

    def on_finish(s):
        box["seq"] = s
        done.set()

    seq = Sequence(request_id=rid, prompt_tokens=list(prompt),
                   max_new_tokens=max_new)
    group.submit(seq, on_token, on_finish)
    assert done.wait(timeout), "request did not finish"
    return tokens, box["seq"]


def _occupy(group, sched, rid, max_new=64):
    """Pin load on one scheduler so the least-loaded router sends the
    next request elsewhere. Returns an event set on finish."""
    got_token, done = threading.Event(), threading.Event()
    seq = Sequence(request_id=rid, prompt_tokens=[5, 6, 7],
                   max_new_tokens=max_new)
    sched.submit(seq, lambda s, t: got_token.set(), lambda s: done.set())
    assert got_token.wait(30), "busy request produced no token"
    return done


def test_step_failure_quarantines_and_fails_over():
    """Acceptance path: dp=2, chaos_step_failure_rate pinned on replica 1
    -> the sick replica is quarantined, the in-flight request resubmitted
    to replica 0 and its tokens are identical to a no-fault run, with the
    quarantine and retry counters visible in health/stats snapshots."""
    cfg = _cfg(dp=2, quarantine_after_failures=1, failover_max_retries=1,
               quarantine_cooldown_s=3600.0)
    group = build_engine_group(cfg).start()
    try:
        probe = [1, 2, 3, 4]
        baseline, seq0 = _submit_and_wait(group, 100, probe, 8)
        assert seq0.finish_reason in ("stop", "length") and baseline

        # Replica 0 busy -> the probe routes to replica 1, which now
        # fails every dispatch.
        busy_done = _occupy(group, group.schedulers[0], 101)
        group.engines[1].chaos_step_failure_rate = 1.0

        r0_before = group.schedulers[0].stats.requests_finished
        tokens, fseq = _submit_and_wait(group, 102, probe, 8)
        assert fseq.finish_reason in ("stop", "length")
        assert tokens == baseline, (
            "failover must replay from the prompt and match a no-fault run")
        # Chaos-injected failover marks the resubmitted span: the
        # finishing sequence and its /debug/requests timeline both carry
        # attempt >= 1, so a replayed request is distinguishable from a
        # first try.
        assert fseq.attempt >= 1
        marked = [t for t in group.recent_snapshot(50)
                  if t["request_id"] == 102]
        assert marked and any(t["attempt"] >= 1 for t in marked)

        assert group.health[1].state == QUARANTINED
        assert group.schedulers[0].stats.requests_finished > r0_before
        assert group.schedulers[1].stats.step_failures >= 1

        snap = group.health_snapshot()
        assert snap["status"] == "degraded"
        assert snap["replicas"][1]["state"] == QUARANTINED
        assert snap["supervision"]["retries_attempted"] >= 1
        assert snap["supervision"]["retries_succeeded"] >= 1

        stats = group.stats_snapshot()
        assert stats["supervision"]["retries_succeeded"] >= 1
        assert stats["replicas"][1]["health"]["state"] == QUARANTINED

        busy_done.wait(30)
        # Page-leak invariant: after every request terminates (finish,
        # chaos failure, failover resubmission) both pools must return
        # to fully free. Stop first so no engine thread is mid-reap
        # while the allocator is inspected.
        group.stop(drain=True, timeout=10.0)
        from tests._leak import assert_pool_clean
        for sched in group.schedulers:
            sched.engine.drain_pipeline()
            assert_pool_clean(sched.engine)
    finally:
        group.stop(drain=False, timeout=5.0)


def test_wedged_step_watchdog_failover():
    """A dispatch that hangs (chaos_step_wedge_s) trips the in-process
    watchdog: the replica is quarantined mid-flight and its stranded
    request is resubmitted to the healthy replica."""
    cfg = _cfg(dp=2, step_watchdog_s=0.15, quarantine_after_failures=3,
               failover_max_retries=1, quarantine_cooldown_s=3600.0)
    group = build_engine_group(cfg)
    # Compile everything OUTSIDE the scheduler threads first: a cold
    # first dispatch includes XLA compile, which would trip the 150ms
    # watchdog on a healthy replica (the documented --no-warmup caveat).
    group.warmup()
    group.start()
    try:
        probe = [9, 2, 4, 8]
        baseline, _ = _submit_and_wait(group, 200, probe, 6)

        busy_done = _occupy(group, group.schedulers[0], 201)
        group.engines[1].chaos_step_wedge_s = 0.8

        tokens, fseq = _submit_and_wait(group, 202, probe, 6)
        assert fseq.finish_reason in ("stop", "length")
        assert tokens == baseline

        assert group.health[1].state == QUARANTINED
        assert group.health[1].snapshot()["wedges"] >= 1
        assert group.supervision_counters()["failovers"] >= 1

        busy_done.wait(30)
    finally:
        # Replica 1's engine thread may still be sleeping in the wedge
        # gate; disarm so drainless stop joins promptly.
        group.engines[1].chaos_step_wedge_s = 0.0
        group.stop(drain=False, timeout=5.0)


def test_streamed_request_fails_cleanly_not_regenerated():
    """A request that already delivered tokens must NOT be silently
    re-generated after its replica dies mid-stream: it finishes with an
    error instead."""
    cfg = _cfg(dp=2, quarantine_after_failures=1, failover_max_retries=1,
               quarantine_cooldown_s=3600.0)
    group = build_engine_group(cfg).start()
    try:
        busy_done = _occupy(group, group.schedulers[0], 301)

        got_token, done, box = threading.Event(), threading.Event(), {}

        def on_token(s, t):
            # Arm chaos only after the first token streamed: the NEXT
            # decode dispatch on replica 1 fails the request mid-stream.
            group.engines[1].chaos_step_failure_rate = 1.0
            got_token.set()

        seq = Sequence(request_id=302, prompt_tokens=[3, 1, 4],
                       max_new_tokens=32)
        group.submit(seq, on_token,
                     lambda s: (box.setdefault("seq", s), done.set()))
        assert done.wait(60)
        assert got_token.is_set()
        assert box["seq"].finish_reason == "error"
        assert group.supervision_counters()["retries_attempted"] == 0

        busy_done.wait(30)
    finally:
        group.stop(drain=False, timeout=5.0)


# ------------------------------------------------- prefix-affinity routing


def test_prefix_affinity_routes_conversations_to_warm_replica():
    """Returning turns land on the replica holding their KV pages, cold
    conversations spread by the rotating tie-break, and the routing
    span/counters surface the decisions."""
    cfg = _cfg(dp=2)
    group = build_engine_group(cfg).start()
    try:
        t1a = list(range(10, 24))            # 14 tokens, distinct prefixes
        t1b = list(range(100, 114))
        rep_a, sa = _submit_and_wait(group, 400, t1a, 6)
        rep_b, sb = _submit_and_wait(group, 401, t1b, 6)
        # Rotating tie-break: two cold submissions at equal load do NOT
        # herd onto replica 0.
        assert {sa.routed_replica, sb.routed_replica} == {0, 1}
        assert sa.route_hit_pages == 0 and sb.route_hit_pages == 0

        # Turn 2 resends each history: affinity returns each
        # conversation to ITS warm replica (loads are equal, so
        # least-loaded would have rotated instead).
        h2a = t1a + rep_a + [7, 7]
        rep2a, fa = _submit_and_wait(group, 402, h2a, 4)
        assert fa.routed_replica == sa.routed_replica
        assert fa.route_hit_pages >= 2        # 22-token history, 8/page
        h2b = t1b + rep_b + [7, 7]
        rep2b, fb = _submit_and_wait(group, 403, h2b, 4)
        assert fb.routed_replica == sb.routed_replica

        snap = group.health_snapshot()
        assert snap["routing"] == "prefix_affinity"
        assert sum(r["routing"]["hits"] for r in snap["replicas"]) >= 2
        assert sum(r["routing"]["cold"] for r in snap["replicas"]) >= 2
        assert group.route_prefix_hits >= 2
        # /debug/requests spans carry the routing decision.
        spans = group.recent_snapshot(10)
        assert any(t["route_hit_pages"] >= 2
                   and t["routed_replica"] in (0, 1) for t in spans)
    finally:
        group.stop(drain=False, timeout=5.0)


def test_prefix_affinity_failover_mid_conversation():
    """Acceptance path: the warm replica dies mid-conversation — the
    turn routed to it for warmth fails over to the cold sibling and
    completes with byte-identical greedy tokens, and the quarantined
    replica receives no further traffic."""
    cfg = _cfg(dp=2, quarantine_after_failures=1, failover_max_retries=1,
               quarantine_cooldown_s=3600.0)
    group = build_engine_group(cfg).start()
    try:
        t1 = list(range(30, 44))             # 14 tokens
        rep1, s1 = _submit_and_wait(group, 500, t1, 6)
        warm = s1.routed_replica
        h2 = t1 + rep1 + [7, 7]
        rep2, s2 = _submit_and_wait(group, 501, h2, 4)
        assert s2.routed_replica == warm     # conversation stuck warm

        # No-fault baseline for turn 3, then replay it with the warm
        # replica failing every dispatch: cache reuse and failover are
        # both output-invariant, so the tokens must match exactly.
        h3 = h2 + rep2 + [7, 7]              # 28 tokens
        expect3, s3a = _submit_and_wait(group, 502, h3, 2)
        assert s3a.routed_replica == warm
        group.engines[warm].chaos_step_failure_rate = 1.0
        rep3, s3 = _submit_and_wait(group, 503, h3, 2)
        assert s3.finish_reason in ("stop", "length")
        assert rep3 == expect3
        assert s3.attempt >= 1               # failover resubmission
        assert s3.routed_replica == 1 - warm
        assert group.health[warm].state == QUARANTINED
        assert group.supervision_counters()["retries_succeeded"] >= 1

        # Quarantined-warm replica gets no traffic, warm or cold.
        rep4, s4 = _submit_and_wait(group, 504, h3, 2)
        assert s4.routed_replica == 1 - warm
        assert rep4 == expect3
    finally:
        group.engines[0].chaos_step_failure_rate = 0.0
        group.engines[1].chaos_step_failure_rate = 0.0
        group.stop(drain=False, timeout=5.0)


@pytest.mark.parametrize("hit_weight,expect_warm", [(1.0, False),
                                                    (8.0, True)])
def test_pressured_warm_replica_vs_cold_idle(hit_weight, expect_warm):
    """Affinity composes with preemption pressure: at the default hit
    weight a warm replica under watermark pressure loses to a cold idle
    sibling (a preemption-likely placement re-prefills anyway); raising
    --route-hit-weight lets warmth buy the placement back."""
    cfg = _cfg(dp=2, route_hit_weight=hit_weight)
    group = build_engine_group(cfg).start()
    try:
        t1 = list(range(50, 64))             # 14 tokens
        rep1, s1 = _submit_and_wait(group, 600, t1, 6)
        warm = s1.routed_replica
        eng = group.engines[warm]
        # Choke the warm pool to exactly 3 reclaimable pages: below the
        # preempt watermark (4) yet still enough to admit turn 2, so
        # the weighted arm can actually run where it routed.
        target_free = max(0, 3 - eng.prefix_cache.evictable)
        eng.request_page_pressure(eng.allocator.num_free - target_free)
        deadline = time.monotonic() + 5
        while (not eng.under_pressure and time.monotonic() < deadline):
            time.sleep(0.01)
        assert eng.under_pressure

        h2 = t1 + rep1 + [7, 7]              # 22 tokens, 2 pages warm
        rep2, s2 = _submit_and_wait(group, 601, h2, 2)
        assert s2.finish_reason in ("stop", "length")
        assert (s2.routed_replica == warm) is expect_warm
    finally:
        group.stop(drain=False, timeout=5.0)


# ------------------------------------------------------- HTTP shedding


def test_admission_queue_cap_sheds_with_retry_after():
    """Saturation returns 429 + Retry-After immediately instead of
    queueing to request_timeout_s."""
    cfg = _cfg(admission_queue_depth=1, retry_after_s=2.5)
    srv = InferenceServer(cfg)

    async def scenario(client):
        resp = await client.post("/api/generate", json={
            "prompt": "occupy the only slot", "stream": True,
            "max_tokens": 64})
        assert resp.status == 200
        await resp.content.readline()       # admitted: first token out

        shed = await client.post("/api/generate", json={
            "prompt": "over cap", "stream": False, "max_tokens": 2})
        assert shed.status == 429
        assert shed.headers["Retry-After"] == "3"   # ceil(2.5)
        body = await shed.json()
        assert "admission queue cap" in body["error"]
        await resp.read()       # drain the occupying stream cleanly

        stats = await (await client.get("/metrics?format=json")).json()
        assert stats["supervision"]["requests_shed"] >= 1

    _run(srv, scenario)
    # Finished + shed mix left no page behind.
    from tests._leak import assert_pool_clean
    assert_pool_clean(srv.engine)


def test_wedged_fleet_returns_503_and_healthz_degrades():
    """dp=1 wedge: the watchdog quarantines the only replica, the
    stranded request gets a clean retryable 503 (no other replica to
    fail over to), and /healthz flips to 503/unavailable."""
    cfg = _cfg(step_watchdog_s=0.15, quarantine_cooldown_s=3600.0,
               failover_max_retries=1, retry_after_s=1.0)
    srv = InferenceServer(cfg)
    srv.engine.chaos_step_wedge_s = 0.8

    async def scenario(client):
        health = await client.get("/healthz")
        assert health.status == 200
        assert (await health.json())["status"] == "ok"

        resp = await client.post("/api/generate", json={
            "prompt": "wedge me", "stream": False, "max_tokens": 4})
        assert resp.status == 503
        assert "Retry-After" in resp.headers
        assert "replica failure" in (await resp.json())["error"]

        health = await client.get("/healthz")
        assert health.status == 503
        body = await health.json()
        assert body["status"] == "unavailable"
        assert body["replicas"][0]["state"] == QUARANTINED
        assert body["replicas"][0]["wedges"] >= 1

        # Fully quarantined fleet sheds new work at admission — embed
        # clients included, and both count as unavailable rejections.
        rej = await client.post("/api/generate", json={
            "prompt": "nope", "stream": False, "max_tokens": 2})
        assert rej.status == 503
        assert "Retry-After" in rej.headers
        emb = await client.post("/api/embed", json={"input": "x"})
        assert emb.status == 503
        assert "Retry-After" in emb.headers
        stats = await (await client.get("/metrics?format=json")).json()
        assert stats["supervision"]["requests_unavailable"] >= 2

    try:
        _run(srv, scenario)
    finally:
        srv.engine.chaos_step_wedge_s = 0.0


def test_debug_chaos_endpoint_arms_engine_faults():
    """POST /debug/chaos arms/disarms engine-level injection per replica
    at runtime (debug-only surface)."""
    cfg = _cfg(enable_debug=True)
    srv = InferenceServer(cfg)

    async def scenario(client):
        resp = await client.post("/debug/chaos", json={
            "replica": 0, "step_failure_rate": 0.5, "step_wedge_s": 0.1})
        assert resp.status == 200
        body = await resp.json()
        assert body["replicas"][0] == {"step_failure_rate": 0.5,
                                       "step_wedge_s": 0.1,
                                       "page_pressure": 0}
        assert srv.engine.chaos_step_failure_rate == 0.5

        # Page-pressure chaos: holds real pages out of the pool. The
        # mutation applies on the engine thread (the HTTP thread only
        # stores the target), so poll briefly for it to land.
        async def wait_free(expect):
            for _ in range(200):
                if srv.engine.allocator.num_free == expect:
                    return
                await asyncio.sleep(0.01)
            raise AssertionError(
                f"page pressure never applied: free="
                f"{srv.engine.allocator.num_free}, want {expect}")

        free_before = srv.engine.allocator.num_free
        resp = await client.post("/debug/chaos", json={
            "replica": 0, "page_pressure": 5})
        assert (await resp.json())["replicas"][0]["page_pressure"] == 5
        await wait_free(free_before - 5)
        resp = await client.post("/debug/chaos", json={
            "replica": 0, "page_pressure": 0})
        assert (await resp.json())["replicas"][0]["page_pressure"] == 0
        await wait_free(free_before)

        resp = await client.post("/debug/chaos", json={
            "replica": None, "step_failure_rate": 0.0, "step_wedge_s": 0.0})
        assert resp.status == 200
        assert srv.engine.chaos_step_failure_rate == 0.0

        bad = await client.post("/debug/chaos", json={"replica": 7})
        assert bad.status == 400

    _run(srv, scenario)


# ----------------------------------------------------------- satellites


def test_chaos_gate_covers_chat_and_embed():
    """HTTP fault injection applies to chat and embed clients too, not
    just /api/generate."""
    cfg = _cfg(chaos_failure_rate=1.0)
    srv = InferenceServer(cfg)

    async def scenario(client):
        chat = await client.post("/api/chat", json={
            "model": "t", "messages": [{"role": "user", "content": "x"}]})
        assert chat.status == 503
        for route in ("/api/embed", "/api/embeddings"):
            emb = await client.post(route, json={"input": "x"})
            assert emb.status == 503

    _run(srv, scenario)


def test_api_ps_ollama_semantics():
    """/api/ps reports ONE model copy (dp exposed separately) and
    Ollama-shaped parameter_size / quantization_level strings."""
    srv = InferenceServer(_cfg())

    async def scenario(client):
        body = await (await client.get("/api/ps")).json()
        entry = body["models"][0]
        assert entry["size"] == int(srv.engine.weight_bytes)
        assert entry["replicas"] == 1
        details = entry["details"]
        assert re.fullmatch(r"\d+(\.\d+)?[BMK]", details["parameter_size"])
        assert details["quantization_level"] in (
            "F32", "F16", "BF16", "Q8_0", "Q4_0")
        tags = await (await client.get("/api/tags")).json()
        assert (tags["models"][0]["details"]["parameter_size"]
                == details["parameter_size"])

    _run(srv, scenario)

    # dp=2 pins the Ollama semantics under replication: size/size_vram
    # stay ONE model copy (never dp-multiplied); fleet HBM is
    # size * replicas via the additive field.
    srv2 = InferenceServer(_cfg(dp=2, warmup=False))

    async def scenario_dp(client):
        body = await (await client.get("/api/ps")).json()
        entry = body["models"][0]
        assert entry["size"] == int(srv2.engine.weight_bytes)
        assert entry["size_vram"] == entry["size"]
        assert entry["replicas"] == 2

    _run(srv2, scenario_dp)


def test_traffic_generator_resilience_accounting():
    """429/503 backoff = Retry-After hint + FULL-jitter exponential
    backoff (uniform on [0, base*2^attempt], capped), and the collector
    tracks retry/shed counts."""
    from traffic_generator.generator import TrafficGenerator
    from traffic_generator.metrics import MetricCollector

    gen = object.__new__(TrafficGenerator)   # _shed_delay needs config only
    gen.config = {"retry_backoff_s": 0.25}

    class Resp:
        def __init__(self, headers):
            self.headers = headers

    for _ in range(16):
        d = gen._shed_delay(Resp({"Retry-After": "3"}), attempt=0)
        assert 3.0 <= d <= 3.25              # hint floor + full jitter
        d = gen._shed_delay(Resp({}), attempt=2)
        assert 0.0 <= d <= 1.0               # uniform on [0, 0.25*2^2]
        d = gen._shed_delay(Resp({"Retry-After": "nonsense"}), attempt=0)
        assert 0.0 <= d <= 0.25              # bad hint -> jitter only
        d = gen._shed_delay(Resp({}), attempt=30)
        assert d <= 10.0                     # backoff span is capped
    # Full jitter actually spreads: not every draw lands in the top
    # quarter of the span (the old multiplicative jitter put 100% of a
    # synchronized wave in [span, 1.25*span]).
    draws = [gen._shed_delay(Resp({}), attempt=2) for _ in range(64)]
    assert min(draws) < 0.75

    # Shared retry budget: one pool across all queries; a dry pool
    # means shed-now, and 0/None disables the pool entirely.
    gen2 = object.__new__(TrafficGenerator)
    gen2._retry_budget = 2
    assert gen2._consume_retry() and gen2._consume_retry()
    assert not gen2._consume_retry()         # pool dry -> shed
    gen2._retry_budget = None                # disabled -> always retry
    assert all(gen2._consume_retry() for _ in range(8))

    mc = MetricCollector()
    mc.init_query(0, n_input_tokens=3, scheduled_start=0.0)
    mc.record_retry(0)
    mc.record_retry(0)
    mc.record_shed(0)
    assert mc.metrics[0]["num_retries"] == 2
    assert mc.metrics[0]["shed"] is True
    assert mc.metrics[0]["success"] is False
    assert mc.retries_total == 2 and mc.shed_total == 1
