"""Engine correctness: paged incremental decode == full-context forward.

The canonical KV-cache invariant: greedy generation through the engine's
bucketed prefill + paged batched decode must produce exactly the tokens that
repeated full-sequence forwards (no cache) produce.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_inference import config as cfgs
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.sampling import SamplingParams, sample
from tpu_inference.models import build_model, common


@pytest.fixture(scope="module")
def setup():
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    engine_cfg = cfgs.EngineConfig(
        page_size=8, num_pages=64, max_pages_per_seq=16, max_batch_size=4,
        prefill_buckets=(16, 32, 64))
    params, mod = build_model(model_cfg, seed=0)
    return model_cfg, engine_cfg, params, mod


# One compiled oracle forward per (family, config, bucket) — the old
# eager per-step forward compiled a fresh XLA graph for EVERY decoded
# token at every new length, dominating the whole suite's wall time.
_ORACLE_FWD: dict = {}


def _oracle_forward(mod, cfg, pad):
    key = (mod.__name__, cfg, pad)
    if key not in _ORACLE_FWD:
        def fwd(params, toks, n):
            """Logits at position n-1 of a [1, pad] right-padded batch
            (causal attention: padding after n-1 cannot leak in). Honors
            cfg.sliding_window (part of the cache key via cfg), so SWA
            tests share this oracle too."""
            pos = jnp.broadcast_to(jnp.arange(pad), (1, pad))
            attn = common.make_dense_attn(cfg.sliding_window or 0)
            logits, _ = mod.forward(params, cfg, toks, pos, None, attn)
            return logits[0, n - 1]

        _ORACLE_FWD[key] = jax.jit(fwd)
    return _ORACLE_FWD[key]


def reference_greedy(params, mod, cfg, prompt, n_new):
    """Greedy decode via repeated full forwards (no cache), padded to a
    shared 64-token bucket so all steps/prompts reuse one compile."""
    total = len(prompt) + n_new
    pad = min(-(-total // 64) * 64, cfg.max_seq_len)
    assert pad >= total, "prompt + n_new exceeds max_seq_len"
    fwd = _oracle_forward(mod, cfg, pad)
    toks = list(prompt)
    buf = np.zeros((1, pad), np.int32)
    buf[0, :len(toks)] = toks
    for i in range(n_new):
        n = len(toks)
        logits = fwd(params, jnp.asarray(buf), jnp.asarray(n))
        tok = int(jnp.argmax(logits))
        buf[0, n] = tok
        toks.append(tok)
    return toks[len(prompt):]


def test_engine_matches_full_forward(setup):
    model_cfg, engine_cfg, params, mod = setup
    engine = InferenceEngine(model_cfg, engine_cfg, params=params)
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 11, 23, 9)]

    got = engine.generate(prompts, max_new_tokens=12)
    for prompt, gen in zip(prompts, got):
        want = reference_greedy(params, mod, model_cfg, prompt, 12)
        assert gen == want, f"prompt len {len(prompt)}: {gen} != {want}"


@pytest.mark.parametrize("dialect", ["qwen2", "gemma"])
def test_engine_dialects_match_full_forward(dialect):
    """Qwen2 (qkv bias) and Gemma (norm offset, GeGLU, embed scale,
    decoupled head_dim) serve correctly through the paged engine."""
    if dialect == "qwen2":
        model_cfg = cfgs.tiny_qwen2(vocab_size=256)
    else:
        model_cfg = cfgs.tiny_gemma(vocab_size=256)
    engine_cfg = cfgs.EngineConfig(
        page_size=8, num_pages=64, max_pages_per_seq=16, max_batch_size=4,
        prefill_buckets=(16, 32, 64))
    params, mod = build_model(model_cfg, seed=0)
    if dialect == "qwen2":
        from tests.conftest import randomize_qkv_biases
        randomize_qkv_biases(params)
    engine = InferenceEngine(model_cfg, engine_cfg, params=params)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 19)]
    got = engine.generate(prompts, max_new_tokens=10)
    for prompt, gen in zip(prompts, got):
        want = reference_greedy(params, mod, model_cfg, prompt, 10)
        assert gen == want, f"{dialect} prompt len {len(prompt)}"


def test_engine_continuous_join(setup):
    """A request admitted mid-flight must not perturb running sequences."""
    model_cfg, engine_cfg, params, mod = setup
    engine = InferenceEngine(model_cfg, engine_cfg, params=params)
    rng = np.random.default_rng(2)
    p1 = rng.integers(0, 256, size=7).tolist()
    p2 = rng.integers(0, 256, size=19).tolist()

    s1 = Sequence(request_id=1, prompt_tokens=p1, max_new_tokens=10)
    s2 = Sequence(request_id=2, prompt_tokens=p2, max_new_tokens=6)
    engine.prefill(s1)
    engine.decode_step()
    engine.decode_step()
    engine.prefill(s2)          # joins while s1 is mid-generation
    while engine.active_sequences():
        engine.decode_step()

    assert s1.generated == reference_greedy(params, mod, model_cfg, p1, 10)
    assert s2.generated == reference_greedy(params, mod, model_cfg, p2, 6)
    engine.release(s1)
    engine.release(s2)
    # All pages returned or reclaimable (full pages stay in the prefix
    # cache as evictable capacity).
    assert (engine.allocator.num_free + engine.prefix_cache.evictable
            == engine_cfg.num_pages - 1)


def test_page_allocator():
    a = kvc.PageAllocator(8)
    assert a.num_free == 7           # page 0 reserved
    pages = a.allocate(3)
    assert 0 not in pages
    shared = a.share(pages[0])
    a.free(pages)
    assert a.num_free == 6           # pages[0] still held by the share
    a.free([shared])
    assert a.num_free == 7
    with pytest.raises(MemoryError):
        a.allocate(8)


def test_pages_needed():
    assert kvc.pages_needed(1, 8) == 1
    assert kvc.pages_needed(8, 8) == 1
    assert kvc.pages_needed(9, 8) == 2
    assert kvc.pages_needed(1, 8, already=8) == 1
    assert kvc.pages_needed(1, 8, already=7) == 0
    assert kvc.pages_needed(0, 8) == 0


def _sp(b, **kw):
    base = SamplingParams.greedy(b)._asdict()
    base.update({k: jnp.asarray(v) for k, v in kw.items()})
    return SamplingParams(**base)


def test_sampling_modes():
    # Eager sample() pays ~1s of op-by-op dispatch per call on this box;
    # production always runs it inside jitted graphs, so jit here too
    # (SamplingParams is a NamedTuple — a pytree — so values, not
    # shapes, vary freely across calls under one compile).
    jsample = jax.jit(sample)
    key = jax.random.PRNGKey(0)
    logits = jnp.asarray(np.array([[0.0, 5.0, 1.0, -2.0],
                                   [10.0, 0.0, 0.0, 0.0]], np.float32))
    # Greedy rows pick argmax regardless of key.
    sp = SamplingParams.greedy(2)
    toks = jsample(logits, key, sp)
    assert toks.tolist() == [1, 0]
    # Temperature sampling with top_k=1 degenerates to greedy.
    sp = _sp(2, temperature=jnp.ones((2,)), top_k=jnp.ones((2,), jnp.int32))
    toks = jsample(logits, key, sp)
    assert toks.tolist() == [1, 0]
    # Per-row top_k: row 0 restricted to its argmax, row 1 unrestricted
    # at huge temperature still yields a valid token.
    sp = _sp(2, temperature=jnp.full((2,), 100.0),
             top_k=jnp.asarray([1, 0], jnp.int32))
    assert jsample(logits, key, sp).tolist()[0] == 1
    # top_p tiny keeps only the argmax.
    sp = _sp(2, temperature=jnp.ones((2,)), top_p=jnp.full((2,), 1e-6))
    toks = jsample(logits, key, sp)
    assert toks.tolist() == [1, 0]
    # High temperature covers the support (statistical sanity).
    sp = _sp(16, temperature=jnp.full((16,), 100.0))
    wide = jnp.zeros((16, 4))
    seen = set()
    for i in range(20):
        seen.update(jsample(wide, jax.random.PRNGKey(i), sp).tolist())
    assert seen == {0, 1, 2, 3}


def test_sampling_seeded_reproducible():
    """seed >= 0 rows depend only on (seed, ctx) — not the engine key or
    batch position; seed < 0 rows follow the engine key."""
    jsample = jax.jit(sample)          # see test_sampling_modes
    wide = jnp.zeros((2, 64))
    ctx = jnp.asarray([7, 7], jnp.int32)
    sp = _sp(2, temperature=jnp.ones((2,)),
             seed=jnp.asarray([42, -1], jnp.int32))
    a = jsample(wide, jax.random.PRNGKey(0), sp, ctx=ctx)
    b = jsample(wide, jax.random.PRNGKey(999), sp, ctx=ctx)
    assert a[0] == b[0]                     # seeded row: key-independent
    # Same seed in a different slot gives the same token at the same ctx.
    sp_swapped = _sp(2, temperature=jnp.ones((2,)),
                     seed=jnp.asarray([-1, 42], jnp.int32))
    c = jsample(wide, jax.random.PRNGKey(0), sp_swapped, ctx=ctx)
    assert c[1] == a[0]
    # Unseeded rows vary with the engine key (statistically).
    outs = {int(jsample(wide, jax.random.PRNGKey(i), sp, ctx=ctx)[1])
            for i in range(10)}
    assert len(outs) > 1


def test_chunked_prefill_long_prompt(setup):
    """Prompts longer than the largest prefill bucket are prefilled in
    chunks and still match the no-cache reference exactly."""
    model_cfg, _, params, mod = setup
    engine_cfg = cfgs.EngineConfig(
        page_size=8, num_pages=64, max_pages_per_seq=16, max_batch_size=2,
        prefill_buckets=(16, 32))          # max bucket 32 < prompt length
    engine = InferenceEngine(model_cfg, engine_cfg, params=params)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 256, size=50).tolist()   # 2 chunks: 32 + 18
    got = engine.generate([prompt], max_new_tokens=8)[0]
    want = reference_greedy(params, mod, model_cfg, prompt, 8)
    assert got == want


def test_generate_rejects_impossible_request(setup):
    model_cfg, _, params, _ = setup
    engine_cfg = cfgs.EngineConfig(
        page_size=8, num_pages=4, max_pages_per_seq=64, max_batch_size=2,
        prefill_buckets=(16,))
    engine = InferenceEngine(model_cfg, engine_cfg, params=params)
    with pytest.raises(ValueError, match="pages"):
        engine.generate([list(range(10))], max_new_tokens=512)


def test_sampling_oom_finish(setup):
    """Pool exhaustion mid-decode fails the sequence, not the engine."""
    model_cfg, _, params, _ = setup
    tiny_pool = cfgs.EngineConfig(
        page_size=8, num_pages=3, max_pages_per_seq=4, max_batch_size=2,
        prefill_buckets=(16,))
    engine = InferenceEngine(model_cfg, tiny_pool, params=params)
    s = Sequence(request_id=0, prompt_tokens=list(range(14)),
                 max_new_tokens=64)
    engine.prefill(s)           # 14 tokens = 2 pages; 0 free pages left
    while engine.active_sequences():
        engine.decode_step()
    assert s.finish_reason == "oom"
    assert len(s.generated) >= 2   # kept generating until the boundary


def test_decode_steps_matches_single_steps(setup):
    """K fused decode steps == K sequential decode_step calls (greedy)."""
    model_cfg, _, params, mod = setup
    base = dict(page_size=8, num_pages=64, max_pages_per_seq=16,
                max_batch_size=4, prefill_buckets=(16, 32, 64))
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 13, 26)]

    e1 = InferenceEngine(model_cfg, cfgs.EngineConfig(
        **base, decode_steps_per_call=1), params=params)
    e2 = InferenceEngine(model_cfg, cfgs.EngineConfig(
        **base, decode_steps_per_call=4), params=params)
    got1 = e1.generate(prompts, max_new_tokens=11)   # not a multiple of K
    got2 = e2.generate(prompts, max_new_tokens=11)
    assert got1 == got2


def test_decode_steps_eos_stops_lane(setup):
    """A lane hitting EOS mid-scan stops; others keep generating."""
    model_cfg, _, params, mod = setup
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=16,
                             max_batch_size=4, prefill_buckets=(16,),
                             decode_steps_per_call=8)
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    rng = np.random.default_rng(8)
    prompt = rng.integers(0, 256, size=9).tolist()
    # Find what greedy generates, then rerun with EOS = its 3rd token.
    ref = reference_greedy(params, mod, model_cfg, prompt, 8)
    eos = ref[2]
    s = Sequence(request_id=0, prompt_tokens=prompt, max_new_tokens=8,
                 eos_token_id=eos)
    other = Sequence(request_id=1,
                     prompt_tokens=rng.integers(0, 256, size=6).tolist(),
                     max_new_tokens=8)
    engine.prefill(s)
    engine.prefill(other)
    while engine.active_sequences():
        engine.decode_steps()
    if s.generated[0] == eos or (len(s.generated) > 1
                                 and s.generated[1] == eos):
        pytest.skip("EOS appeared before the scan — not the case under test")
    assert s.finish_reason == "stop"
    assert s.generated[-1] == eos
    assert len(s.generated) == 3
    assert len(other.generated) == 8
    engine.release(s)
    engine.release(other)
    assert (engine.allocator.num_free + engine.prefix_cache.evictable
            == ecfg.num_pages - 1)


def test_decode_steps_pool_pressure_partial_advance(setup):
    """Under pool pressure a lane advances only as far as its page slack
    instead of corrupting other sequences' pages."""
    model_cfg, _, params, _ = setup
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=4, max_pages_per_seq=4,
                             max_batch_size=2, prefill_buckets=(16,),
                             decode_steps_per_call=8)
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    s = Sequence(request_id=0, prompt_tokens=list(range(14)),
                 max_new_tokens=64)
    engine.prefill(s)           # 2 pages used; pool of 3 → 1 free
    while engine.active_sequences():
        engine.decode_steps()
    assert s.finish_reason == "oom"
    # Advanced to page slack (2 tokens) + one granted page (8 tokens).
    assert len(s.generated) == 1 + 2 + 8


def test_prefill_many_matches_serial():
    """Batched [P, S] prefill (mixed buckets, padded lanes) produces the
    same first tokens and KV state as serial prefill."""
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=128, max_pages_per_seq=8,
                             max_batch_size=8, prefill_buckets=(16, 32),
                             max_prefill_batch=4, enable_prefix_cache=False)
    params, _ = build_model(model_cfg, seed=0)
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, 256, size=n).tolist()
               for n in (5, 12, 27, 9, 31)]

    serial = InferenceEngine(model_cfg, ecfg, params=params)
    seqs_s = [Sequence(request_id=i, prompt_tokens=p, max_new_tokens=6)
              for i, p in enumerate(prompts)]
    for s in seqs_s:
        serial.prefill(s)

    batched = InferenceEngine(model_cfg, ecfg, params=params)
    seqs_b = [Sequence(request_id=i, prompt_tokens=p, max_new_tokens=6)
              for i, p in enumerate(prompts)]
    batched.prefill_many(seqs_b)

    assert [s.generated for s in seqs_b] == [s.generated for s in seqs_s]
    # Decode continues identically from the batched-prefill KV state.
    for _ in range(3):
        a = serial.decode_steps(max_steps=1)
        b = batched.decode_steps(max_steps=1)
        assert a == b


def test_check_numerics():
    """Sanitizer: clean params pass; a NaN-poisoned leaf is caught and
    named (SURVEY.md §5 sanitizer tier)."""
    model_cfg = cfgs.tiny_llama(vocab_size=128)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=16, max_pages_per_seq=4,
                             max_batch_size=2, prefill_buckets=(16,))
    engine = InferenceEngine(model_cfg, ecfg)
    engine.check_numerics()               # clean: no raise

    poisoned = jax.tree.map(lambda x: x, engine.params)
    poisoned["blocks"]["wq"] = poisoned["blocks"]["wq"].at[0, 0, 0].set(
        jnp.nan)
    engine.params = poisoned
    with pytest.raises(FloatingPointError, match="wq"):
        engine.check_numerics()


def test_decode_steps_chained_matches_sync():
    """Dispatch-ahead decode (device-chained carry tokens, one final
    sync) produces exactly the synchronous loop's tokens."""
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=128, max_pages_per_seq=16,
                             max_batch_size=4, prefill_buckets=(16,),
                             decode_steps_per_call=4, max_new_tokens=64,
                             enable_prefix_cache=False)
    params, _ = build_model(model_cfg, seed=0)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 9, 12)]

    sync = InferenceEngine(model_cfg, ecfg, params=params)
    seqs_a = [Sequence(request_id=i, prompt_tokens=p, max_new_tokens=33)
              for i, p in enumerate(prompts)]
    for s in seqs_a:
        sync.prefill(s)
    for _ in range(8):
        sync.decode_steps()

    chained = InferenceEngine(model_cfg, ecfg, params=params)
    seqs_b = [Sequence(request_id=i, prompt_tokens=p, max_new_tokens=33)
              for i, p in enumerate(prompts)]
    for s in seqs_b:
        chained.prefill(s)
    out = chained.decode_steps_chained(8)
    assert [s.generated for s in seqs_a] == [s.generated for s in seqs_b]
    assert sorted(out) == [0, 1, 2] and all(len(v) == 32
                                            for v in out.values())


def test_decode_steps_pipelined_matches_sync():
    """Depth-2 dispatch-ahead serving loop == synchronous loop: same
    tokens, same finish reasons, with EOS stops, different budgets, and a
    mid-flight join."""
    model_cfg = cfgs.tiny_llama(vocab_size=256)

    def run(depth):
        ecfg = cfgs.EngineConfig(
            page_size=8, num_pages=128, max_pages_per_seq=16,
            max_batch_size=4, prefill_buckets=(16,),
            decode_steps_per_call=4, decode_pipeline_depth=depth,
            enable_prefix_cache=False)
        params, _ = build_model(model_cfg, seed=0)
        engine = InferenceEngine(model_cfg, ecfg, params=params)
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 9)]
        seqs = [Sequence(request_id=0, prompt_tokens=prompts[0],
                         max_new_tokens=30, eos_token_id=7),
                Sequence(request_id=1, prompt_tokens=prompts[1],
                         max_new_tokens=11)]
        for s in seqs:
            engine.prefill(s)
        joined = False
        tokens_out = {0: list(seqs[0].generated), 1: list(seqs[1].generated)}
        for it in range(40):
            out = engine.decode_steps_pipelined()
            for rid, toks in out.items():
                tokens_out.setdefault(rid, []).extend(toks)
            if it == 2 and not joined:
                s3 = Sequence(request_id=2,
                              prompt_tokens=rng.integers(
                                  0, 256, size=6).tolist(),
                              max_new_tokens=9)
                # Same join prompt each run (rng consumed identically).
                engine.prefill(s3)
                seqs.append(s3)
                tokens_out[2] = list(s3.generated)
            if all(s.done for s in seqs) and not engine.pipeline_pending:
                break
        for rid, toks in engine.drain_pipeline().items():
            tokens_out[rid].extend(toks)
        return ([s.generated for s in seqs],
                [s.finish_reason for s in seqs], tokens_out)

    gen_sync, fin_sync, out_sync = run(depth=1)
    gen_pipe, fin_pipe, out_pipe = run(depth=2)
    assert gen_sync == gen_pipe
    assert fin_sync == fin_pipe
    # Delivered token streams match the recorded generations.
    for i, gen in enumerate(gen_pipe):
        assert out_pipe[i] == gen


# ---------------------------------------------------------------------------
# Repetition penalty (Ollama repeat_penalty / repeat_last_n)
# ---------------------------------------------------------------------------


def _gen_with_penalty(eng, rpen, rlast=64, n=20, use_pipeline=False):
    from tpu_inference.engine.engine import Sequence
    seq = Sequence(request_id=0, prompt_tokens=list(range(1, 12)),
                   max_new_tokens=n, repeat_penalty=rpen,
                   repeat_last_n=rlast)
    eng.prefill(seq)
    while not seq.done:
        if use_pipeline:
            eng.decode_steps_pipelined()
        else:
            eng.decode_steps()
    eng.drain_pipeline()
    eng.release(seq)
    return seq.generated


def test_repeat_penalty_reduces_repetition():
    cfg = cfgs.tiny_llama()
    ecfg = cfgs.EngineConfig(num_pages=64, max_batch_size=2,
                             prefill_buckets=(64,), max_new_tokens=32)
    eng = InferenceEngine(cfg, ecfg, seed=0)
    plain = _gen_with_penalty(eng, 1.0)
    pen = _gen_with_penalty(eng, 1.8)
    # Greedy tiny-model output loops; the penalty must strictly increase
    # diversity over the same horizon.
    assert len(set(pen)) > len(set(plain))
    # rpen=1.0 is the exact pre-penalty behavior (no logit perturbation).
    assert _gen_with_penalty(eng, 1.0) == plain


def test_repeat_penalty_window_limits_lookback():
    cfg = cfgs.tiny_llama()
    ecfg = cfgs.EngineConfig(num_pages=64, max_batch_size=2,
                             prefill_buckets=(64,), max_new_tokens=32)
    eng = InferenceEngine(cfg, ecfg, seed=0)
    # A 1-token lookback penalizes only immediate repeats; a full window
    # penalizes any recent token — outputs must differ.
    short = _gen_with_penalty(eng, 1.8, rlast=1)
    full = _gen_with_penalty(eng, 1.8, rlast=64)
    assert short != full
    # last_n=0 disables the penalty entirely.
    off = _gen_with_penalty(eng, 1.8, rlast=0)
    assert off == _gen_with_penalty(eng, 1.0)


def test_repeat_penalty_pipelined_matches_sync():
    """The dispatch-ahead path carries penalty windows device-to-device;
    tokens must match the synchronous path exactly."""
    cfg = cfgs.tiny_llama()
    base = dict(num_pages=64, max_batch_size=2, prefill_buckets=(64,),
                max_new_tokens=32)
    sync_eng = InferenceEngine(cfg, cfgs.EngineConfig(**base), seed=0)
    sync = _gen_with_penalty(sync_eng, 1.8)
    pipe_eng = InferenceEngine(
        cfg, cfgs.EngineConfig(**base, decode_pipeline_depth=2), seed=0)
    pipe = _gen_with_penalty(pipe_eng, 1.8, use_pipeline=True)
    assert sync == pipe


def _drive(engine, prompts, n_new, pipelined):
    """Minimal serving loop: admit when possible, decode via the
    pipelined path when requested (engine.generate only exercises the
    synchronous one), drain before releasing finished slots — the same
    ordering the production scheduler uses."""
    seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                     max_new_tokens=n_new) for i, p in enumerate(prompts)]
    results = {}
    pending = list(seqs)
    while (pending or engine.active_sequences()
           or engine.pipeline_pending):
        while pending and engine.free_slots() and engine.can_admit(pending[0]):
            engine.prefill(pending.pop(0))
        if pipelined:
            engine.decode_steps_pipelined()
        else:
            engine.decode_steps()
        done = [s for s in engine.slots if s is not None and s.done]
        if done and engine.pipeline_pending:
            engine.drain_pipeline()
        for s in [s for s in engine.slots if s is not None and s.done]:
            results[s.request_id] = s.generated
            engine.release(s)
    return [results[i] for i in range(len(seqs))]


@pytest.mark.slow   # config-space fuzz; the canonical invariant runs fast in test_engine_matches_full_forward
def test_engine_matches_oracle_across_random_configs():
    """Config-space fuzz of the canonical invariant: engine output ==
    cache-free full-forward greedy, across randomized paging geometry,
    GQA ratios, bucket sets, fused-step counts, chunking, and prompt
    lengths. Catches interactions a single fixed config can't (page
    boundary off-by-ones, bucket selection, chunk seams)."""
    rng = np.random.default_rng(2026)
    for trial in range(5):
        n_heads = int(rng.choice([2, 4, 8]))
        n_kv = int(rng.choice([h for h in (1, 2, 4) if n_heads % h == 0]))
        model_cfg = cfgs.ModelConfig(
            name=f"fuzz-{trial}", family="llama", vocab_size=256,
            d_model=64, n_layers=2, n_heads=n_heads, n_kv_heads=n_kv,
            d_ff=128, max_seq_len=512, rope_theta=10000.0,
            dtype=jnp.float32)
        page = int(rng.choice([4, 8, 16]))
        bucket_hi = int(rng.choice([32, 64]))
        ecfg = cfgs.EngineConfig(
            page_size=page, num_pages=96,
            max_pages_per_seq=max(8, 128 // page),
            max_batch_size=int(rng.choice([2, 3])),
            prefill_buckets=(16, bucket_hi),
            chunked_prefill_size=int(rng.choice([0, 16])),
            decode_steps_per_call=int(rng.choice([1, 3, 8])),
            decode_pipeline_depth=int(rng.choice([1, 2])),
        )
        params, mod = build_model(model_cfg, seed=trial)
        engine = InferenceEngine(model_cfg, ecfg, params=params)
        # Prompt lengths land on/around page and chunk boundaries, but
        # stay within max_context - n_new so the engine's context cap
        # (which the cache-free oracle doesn't have) never cuts a run.
        n_new = int(rng.integers(3, 12))
        max_len = min(3 * bucket_hi, ecfg.max_context - n_new - 2)
        lens = [int(rng.integers(1, max_len)) for _ in range(2)]
        lens.append(page)                     # exactly one page
        prompts = [rng.integers(0, 256, size=n).tolist() for n in lens]
        got = _drive(engine, prompts, n_new,
                     pipelined=ecfg.decode_pipeline_depth > 1)
        for prompt, gen in zip(prompts, got):
            want = reference_greedy(params, mod, model_cfg, prompt, n_new)
            assert gen == want, (
                f"trial {trial} cfg page={page} heads={n_heads}/{n_kv} "
                f"k={ecfg.decode_steps_per_call} "
                f"depth={ecfg.decode_pipeline_depth} "
                f"chunk={ecfg.chunked_prefill_size} "
                f"len={len(prompt)}: {gen} != {want}")
