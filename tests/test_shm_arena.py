"""Zero-copy KV data plane (README "KV data plane"): the shared-memory
page arena and the descriptor frames that replace through-router blob
relays.

Covers the subsystem at three levels:

- pure arena units: slab alloc/free/coalesce with refcount-style
  directory accounting, ArenaFull relay fallback, free-then-read
  failing closed, crc rejection typed apart from staleness, and the
  dead-incarnation reclaim (epoch bump) invalidating every outstanding
  descriptor without the owner's cooperation — the kill -9 story.
- serialized-page round-trips: one descriptor per kv_quant host-page
  layout (none/int8/int4) travels segment -> descriptor -> read ->
  deserialize bit-exactly, from the writer, the router, and a second
  attached reader.
- the real fleet: a 1-prefill+1-decode subprocess fleet on
  ``--kv-plane shm`` serves byte-identical outputs with ZERO handoff
  bytes over the RPC sockets, and keeps serving byte-identically after
  a supervisor region reclaim staled every pooled descriptor (the
  relay/recompute fallback equivalence).
"""

import sys
import threading
import time

import numpy as np
import pytest

from tests._leak import assert_arena_clean, assert_fabric_clean
from tpu_inference.config import (EngineConfig, FrameworkConfig,
                                  ParallelConfig, ServerConfig, tiny_llama)
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.server import shm_arena
from tpu_inference.server.shm_arena import (ArenaCorrupt, ArenaFull,
                                            ArenaSegment, ArenaStale,
                                            SlabDirectory, WorkerArena,
                                            effective_kv_plane)

pytestmark = pytest.mark.skipif(
    not sys.platform.startswith("linux"),
    reason="shm arena needs POSIX shared memory (Linux)")


@pytest.fixture()
def seg():
    s = ArenaSegment(64 * 1024, regions=4)
    yield s
    s.close()


def _worker(seg_, rg=0) -> WorkerArena:
    return WorkerArena(seg_.region_spec(rg))


# ------------------------------------------------------------ resolution


def test_effective_kv_plane_decision_table():
    """The knob is a request, not a promise: shm resolves only for the
    subprocess fleet on Linux; every other combination rides relay."""
    mk = lambda **kw: ServerConfig(model_name="t", tokenizer="byte", **kw)
    assert effective_kv_plane(mk()) == "relay"
    assert effective_kv_plane(mk(kv_plane="shm")) == "relay"
    assert effective_kv_plane(
        mk(kv_plane="shm", fleet="subprocess")) == "shm"
    assert effective_kv_plane(
        mk(kv_plane="relay", fleet="subprocess")) == "relay"


# ----------------------------------------------------------- slab units


def test_slab_alloc_read_free_roundtrip(seg):
    w = _worker(seg)
    payloads = [bytes([i]) * (17 + 13 * i) for i in range(5)]
    descs = [w.publish(p) for p in payloads]
    assert w.writer.slabs_used == 5
    for d, p in zip(descs, payloads):
        assert d["len"] == len(p) and d["gen"] > 0 and d["ep"] == 1
        assert w.read(d) == p          # owner read
        assert seg.read(d) == p        # router read
    assert w.puts == 5 and w.gets == 5
    assert w.put_bytes == sum(len(p) for p in payloads)
    # Free everything; the free list coalesces back to one extent.
    for d in descs:
        assert w.free(d["off"]) is True
        assert w.free(d["off"]) is False      # idempotent
    assert w.writer.slabs_used == 0 and w.writer.bytes_used == 0
    assert len(w.writer._free) == 1
    w.close()


def test_free_slab_read_fails_closed(seg):
    """A freed slab's gen word is zeroed — a stale descriptor can
    never return recycled bytes, even before reuse."""
    w = _worker(seg)
    d = w.publish(b"x" * 100)
    w.free(d["off"])
    with pytest.raises(ArenaStale):
        seg.read(d)
    # Reuse of the extent mints a NEW generation: the old descriptor
    # still fails closed while the new one reads clean.
    d2 = w.publish(b"y" * 100)
    assert d2["off"] == d["off"] and d2["gen"] != d["gen"]
    with pytest.raises(ArenaStale):
        seg.read(d)
    assert seg.read(d2) == b"y" * 100
    w.close()


def test_arena_full_signals_relay_fallback(seg):
    w = _worker(seg)
    big = b"z" * (seg.region_bytes // 2)
    w.publish(big)
    with pytest.raises(ArenaFull):
        w.publish(big)                 # header overhead makes it not fit
    assert w.writer.alloc_failures == 1
    # Single-writer discipline: region 1's writer is unaffected.
    w1 = _worker(seg, rg=1)
    assert w1.publish(big)["rg"] == 1
    w.close()
    w1.close()


def test_crc_rejection_typed_apart_from_stale(seg):
    """Corruption (payload bytes, length word) is ArenaCorrupt —
    counted like any corrupt KV blob; staleness (epoch, gen) is
    ArenaStale — a fallback, not an integrity event."""
    w = _worker(seg)
    d = w.publish(b"payload" * 40)
    # Flip one payload byte in shared memory behind the descriptor.
    seg.shm.buf[d["off"] + 3] ^= 0xFF
    with pytest.raises(ArenaCorrupt) as ei:
        seg.read(d)
    assert ei.value.reason == "crc"
    seg.shm.buf[d["off"] + 3] ^= 0xFF
    assert seg.read(d) == b"payload" * 40
    # Length mismatch between descriptor and slab header: corrupt.
    bad = dict(d, len=d["len"] - 1, crc=0)
    with pytest.raises(ArenaCorrupt):
        seg.read(bad)
    # Out-of-region geometry: corrupt (bounds), never an OOB read.
    with pytest.raises(ArenaCorrupt):
        seg.read(dict(d, off=seg.region_bytes * seg.regions + 64))
    # Wrong-epoch descriptor: stale.
    with pytest.raises(ArenaStale):
        seg.read(dict(d, ep=d["ep"] + 1))
    w.close()


def test_generation_reclaim_after_owner_death(seg):
    """The kill -9 story: the owner dies holding live slabs; the
    supervisor reclaims the region (ledger count + epoch bump) and
    every outstanding descriptor fails closed, while the respawned
    incarnation's fresh spec mints readable slabs again."""
    w = _worker(seg)
    adir = SlabDirectory()
    descs = [w.publish(bytes([i]) * 64) for i in range(3)]
    for d in descs:
        adir.register(d)
    adir.release(descs[2])             # one already pending-free
    assert adir.slabs_live == 2 and adir.slabs_tracked == 3
    w.close()                          # owner gone, frees never applied

    assert adir.reclaim(0) == 3        # live + pending, all settled
    assert adir.reclaims == 3 and adir.slabs_tracked == 0
    new_ep = seg.bump_epoch(0)
    assert new_ep == 2
    for d in descs:
        with pytest.raises(ArenaStale):
            seg.read(d)
    adir.release(descs[0])             # release-after-reclaim: no-op
    assert adir.slabs_tracked == 0

    w2 = WorkerArena(seg.region_spec(0))    # respawned incarnation
    d2 = w2.publish(b"fresh" * 10)
    assert d2["ep"] == new_ep and seg.read(d2) == b"fresh" * 10
    w2.close()


def test_slab_directory_free_batching(seg):
    """Release -> drain -> stats-RPC -> owner free, with the requeue
    path for a failed RPC: no free is ever lost or double-applied."""
    w = _worker(seg)
    adir = SlabDirectory()
    d = w.publish(b"a" * 32)
    adir.register(d)
    adir.release(d)
    offs = adir.drain_free(0)
    assert offs == [d["off"]] and adir.drain_free(0) == []
    adir.requeue_free(0, offs)         # the RPC failed; retry next tick
    offs = adir.drain_free(0)
    assert offs == [d["off"]]
    assert [w.free(o) for o in offs] == [True]
    assert w.writer.slabs_used == 0
    w.close()


def test_concurrent_reader_never_adopts_recycled_bytes(seg):
    """Torn-read guard under a real race: readers hammer a descriptor
    while the owner frees and recycles the extent with different
    bytes. Every read either returns the original payload or raises —
    recycled bytes must never surface under the old descriptor."""
    w = _worker(seg)
    payload = b"\xAA" * 4096
    d = w.publish(payload)
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            try:
                got = seg.read(d)
            except (ArenaStale, ArenaCorrupt):
                continue
            if got != payload:
                bad.append(got[:8])
                return

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    w.free(d["off"])
    for i in range(50):
        dn = w.publish(bytes([i % 251]) * 4096)
        w.free(dn["off"])
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    assert not bad, f"reader adopted recycled bytes: {bad[0]!r}"
    w.close()


# ------------------------------------------- serialized-page round-trip


def _page(quant: str, tag: int) -> kvc.HostKVPage:
    rng = np.random.default_rng(100 + tag)
    if quant == "none":
        mk = lambda: rng.standard_normal((2, 8, 2, 16)).astype(np.float32)
        return kvc.HostKVPage(mk(), mk())
    code_dt = np.uint8 if quant == "int4" else np.int8
    d = 8 if quant == "int4" else 16
    mk = lambda: rng.integers(0, 255, (2, 8, 2, d)).astype(code_dt)
    sc = lambda: rng.standard_normal((2, 8, 2)).astype(np.float32)
    return kvc.HostKVPage(mk(), mk(), sc(), sc())


def _pages_equal(a: kvc.HostKVPage, b: kvc.HostKVPage) -> bool:
    for f in ("k", "v", "k_scale", "v_scale"):
        x, y = getattr(a, f, None), getattr(b, f, None)
        if (x is None) != (y is None):
            return False
        if x is not None and not np.array_equal(x, y):
            return False
    return True


@pytest.mark.parametrize("quant", ["none", "int8", "int4"])
def test_descriptor_roundtrip_per_kv_quant(quant, seg):
    """serialize -> publish -> (descriptor crosses the wire) -> read ->
    deserialize is bit-exact for every host-page layout, from the
    owning worker, the router segment, and a second attached worker —
    the three consumers the data plane actually has."""
    src = _worker(seg, rg=0)
    dst = _worker(seg, rg=1)
    pages = [_page(quant, i) for i in range(3)]
    blob = kvc.serialize_host_pages(pages)
    desc = src.publish(blob)
    for reader in (lambda: src.read(desc), lambda: seg.read(desc),
                   lambda: dst.read(desc)):
        got = kvc.deserialize_host_pages(reader())
        assert len(got) == len(pages)
        assert all(_pages_equal(g, p) for g, p in zip(got, pages))
    assert dst.gets == 1 and dst.get_bytes == len(blob)
    src.close()
    dst.close()


# ------------------------------------------------------- fleet end-to-end

ENGINE_KW = dict(page_size=8, num_pages=64, max_pages_per_seq=8,
                 max_batch_size=2, prefill_buckets=(16,),
                 host_cache_pages=32)


def _cfg(**server_kw) -> FrameworkConfig:
    server_kw.setdefault("fleet", "subprocess")
    server_kw.setdefault("worker_restart_max", 10)
    server_kw.setdefault("worker_restart_backoff_s", 0.1)
    return FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(**ENGINE_KW),
        parallel=ParallelConfig(dp=2),
        server=ServerConfig(model_name="t", tokenizer="byte",
                            warmup=False, **server_kw))


def _submit(group, rid, prompt, max_new):
    from tpu_inference.engine.engine import Sequence
    toks, done, box = [], threading.Event(), {}
    seq = Sequence(request_id=rid, prompt_tokens=list(prompt),
                   max_new_tokens=max_new)
    group.submit(seq, lambda s, t: toks.append(t),
                 lambda s: (box.update(seq=s), done.set()))
    return toks, done, box


def _finish(done, box, timeout=180.0):
    assert done.wait(timeout), "request did not finish"
    return box["seq"]


@pytest.fixture(scope="module")
def shm_pd_fleet():
    """1 prefill + 1 decode worker on the shm plane with the fabric
    pool armed — every data-plane path (handoff, fabric publish) has a
    descriptor variant to exercise."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(
        worker_roles=("prefill", "decode"), kv_plane="shm",
        shm_arena_bytes=8 * 1024 * 1024, fabric_cache_pages=64,
        fabric_publish_min_pages=1))
    group.start()
    yield group
    group.stop(drain=False)


@pytest.fixture(scope="module")
def oracle():
    from tpu_inference.engine.engine import InferenceEngine
    return InferenceEngine(tiny_llama(vocab_size=512),
                           EngineConfig(**ENGINE_KW), seed=0)


def test_shm_plane_handoff_zero_blob_bytes(shm_pd_fleet, oracle):
    """Tentpole proof: on the shm plane the P/D handoff and the fabric
    publishes move ONLY descriptors over the RPC sockets — the per-verb
    relayed-blob-byte counters stay at zero — while outputs remain
    byte-identical to a single mixed engine."""
    group = shm_pd_fleet
    deadline = time.monotonic() + 60
    while not all(h.state == "up" for h in group.workers):
        assert time.monotonic() < deadline, "fleet never came up"
        time.sleep(0.05)
    assert group.arena is not None, "shm plane must be active on Linux"
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [4, 4, 4, 4]]
    pend = [_submit(group, 8100 + i, p, 16)
            for i, p in enumerate(prompts)]
    for (toks, done, box), p in zip(pend, prompts):
        fin = _finish(done, box)
        assert fin.finish_reason == "length"
        assert toks == oracle.generate([p], max_new_tokens=16)[0]
    assert group.pd_handoffs >= len(prompts)
    assert group.rpc_blob_bytes["handoff"] == 0, \
        "handoff payloads must not traverse the router socket"
    assert group.rpc_blob_bytes["fabric_put"] == 0, \
        "fabric publishes must not traverse the router socket"
    # The adopting side pulled real bytes out of shared memory.
    hs = group.health_snapshot()
    assert hs["replicas"][1]["pd_adoptions"] >= len(prompts)
    pt = group.prometheus_text()
    assert 'tpu_inf_rpc_blob_bytes_total{verb="handoff"} 0' in pt
    assert "tpu_inf_shm_slabs_total" in pt
    assert "tpu_inf_kv_plane_shm_puts_total" in pt


def test_shm_reclaim_staleness_falls_back_byte_identical(
        shm_pd_fleet, oracle):
    """Relay-fallback equivalence: reclaim the prefill worker's region
    (exactly what the supervisor does after a kill -9) so every pooled
    descriptor it minted is stale, then serve the same prompt again —
    stale reads fail closed, the recompute/relay machinery takes over,
    and the output stays byte-identical."""
    group = shm_pd_fleet
    prompt = [7, 7, 1, 2]
    toks1, done, box = _submit(group, 8200, prompt, 12)
    _finish(done, box)
    assert toks1 == oracle.generate([prompt], max_new_tokens=12)[0]

    reclaims0 = group.shm_reclaims
    group._reclaim_region(0)           # stale everything region 0 minted
    assert group.shm_reclaims >= reclaims0

    toks2, done, box = _submit(group, 8201, prompt, 12)
    fin = _finish(done, box)
    assert fin.finish_reason == "length"
    assert toks2 == toks1, "post-reclaim serve must stay byte-identical"


def test_shm_fleet_leak_invariants(shm_pd_fleet):
    """After the request mixes above settled, the arena books balance:
    the fabric pool's descriptors are the only live slabs, and clearing
    the pool releases every one (assert_arena_clean contract)."""
    group = shm_pd_fleet
    deadline = time.monotonic() + 30
    while group._tracked and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not group._tracked
    assert_fabric_clean(group.fabric)
    assert_arena_clean(group)
