"""Batch ladder (README "Batch ladder"): HBM-sized decode concurrency
through a ladder of compiled decode graphs.

The engine compiles the decode graphs at every configured rung, admits
up to the TOP rung's lanes, dispatches at the smallest rung covering the
occupied slots, and steps between rungs as occupancy changes. These
tests pin the load-bearing claims: greedy outputs are byte-identical at
every rung (graph width is never a behavior change), in-flight lanes
survive grow/shrink transitions, the page-leak invariant holds across
switches, preemption and the host KV tier compose under a full top-rung
batch, warmup covers every rung so NO XLA compile happens mid-serving,
the packed-int4 KV layout is rung-invariant like bf16, and the staging
reuse / admission-headroom satellites behave as documented.
"""

import logging
import threading

import numpy as np
import pytest

from tpu_inference import config as cfgs
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.scheduler import EngineScheduler
from tpu_inference.models import build_model
from tests._leak import assert_pool_clean

VOCAB = 256


@pytest.fixture(scope="module")
def model_setup():
    model_cfg = cfgs.tiny_llama(vocab_size=VOCAB)
    params, _ = build_model(model_cfg, seed=0)
    return model_cfg, params


def _ecfg(**kw):
    base = dict(page_size=8, num_pages=512, max_pages_per_seq=8,
                max_batch_size=16, decode_ladder=(4, 8, 16),
                prefill_buckets=(16, 32))
    base.update(kw)
    return cfgs.EngineConfig(**base)


def _submit_and_wait(sched, seqs, timeout=180.0, start=False):
    """Queue every request, then (with start=True) start the scheduler
    — pre-start submission makes burst tests deterministic: the first
    admission pass sees the whole burst instead of racing it."""
    events = {s.request_id: [] for s in seqs}
    done = {s.request_id: threading.Event() for s in seqs}
    for s in seqs:
        sched.submit(
            s, on_token=lambda sq, t: events[sq.request_id].append(t),
            on_finish=lambda sq: done[sq.request_id].set())
    if start:
        sched.start()
    for s in seqs:
        assert done[s.request_id].wait(timeout), f"request {s.request_id} hung"
    return events


def _prompts(n, rng=None, length=6):
    rng = rng or np.random.default_rng(7)
    return [rng.integers(0, VOCAB, size=length).tolist() for _ in range(n)]


def test_invalid_ladder_rejected(model_setup):
    model_cfg, params = model_setup
    for bad in ((16, 8), (4, 4, 16), (4, 8)):   # unordered, dup, wrong top
        with pytest.raises(ValueError, match="decode_ladder"):
            InferenceEngine(model_cfg, _ecfg(decode_ladder=bad),
                            params=params)


def test_byte_identity_across_rungs(model_setup):
    """The same request set served by the fixed base-rung graph and by
    the full ladder must emit byte-identical greedy tokens — graph
    width is a memory/latency decision, never a behavior change."""
    model_cfg, params = model_setup
    prompts = _prompts(12)

    def run(ecfg):
        engine = InferenceEngine(model_cfg, ecfg, params=params)
        sched = EngineScheduler(engine)
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=24) for i, p in enumerate(prompts)]
        events = _submit_and_wait(sched, seqs, start=True)
        sched.stop(drain=True, timeout=20)
        assert_pool_clean(engine)
        return events, engine

    base_events, base_eng = run(_ecfg(max_batch_size=4, decode_ladder=()))
    lad_events, lad_eng = run(_ecfg())
    assert base_events == lad_events
    assert all(len(v) == 24 for v in lad_events.values())
    # The ladder demonstrably climbed past the base rung and the single-
    # rung engine never left its one graph.
    assert lad_eng.rung_peak == 16
    assert lad_eng.rung_switches_total >= 1
    assert base_eng.ladder == (4,) and base_eng.rung_switches_total == 0


def test_inflight_lanes_survive_grow_and_shrink(model_setup):
    """Lanes admitted before a rung transition keep decoding through it
    (dispatch-ahead in flight included) and finish with their full
    budgets — growing compiles nothing away, shrinking steps down only
    once the high slots drain."""
    model_cfg, params = model_setup
    ecfg = _ecfg(decode_steps_per_call=4, decode_pipeline_depth=2,
                 latency_decode_threshold=0)
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    # Reference: the same long-budget requests at the single base rung.
    ref_ecfg = _ecfg(max_batch_size=4, decode_ladder=(),
                     decode_steps_per_call=4)
    ref_engine = InferenceEngine(model_cfg, ref_ecfg, params=params)
    rng = np.random.default_rng(11)
    long_prompts = _prompts(3, rng)
    want = ref_engine.generate(long_prompts, max_new_tokens=48)

    sched = EngineScheduler(engine).start()
    try:
        longs = [Sequence(request_id=i, prompt_tokens=list(p),
                          max_new_tokens=48)
                 for i, p in enumerate(long_prompts)]
        done = {s.request_id: threading.Event() for s in longs}
        events = {s.request_id: [] for s in longs}
        for s in longs:
            sched.submit(s,
                         lambda sq, t: events[sq.request_id].append(t),
                         lambda sq: done[sq.request_id].set())
        # Wait until the longs are decoding, then burst 12 shorts so the
        # rung climbs 4 -> 16 with the longs' dispatch-ahead calls in
        # flight; the shorts finish first, shrinking back down.
        import time
        deadline = time.time() + 60
        while (not all(events.values())) and time.time() < deadline:
            time.sleep(0.005)
        shorts = [Sequence(request_id=100 + i,
                           prompt_tokens=_prompts(1, rng)[0],
                           max_new_tokens=16) for i in range(12)]
        short_events = _submit_and_wait(sched, shorts)
        for s in longs:
            assert done[s.request_id].wait(120)
    finally:
        sched.stop(drain=True, timeout=20)
    for i, s in enumerate(longs):
        assert events[s.request_id] == want[i]      # survived transitions
        assert len(s.generated) == 48
    assert all(len(v) == 16 for v in short_events.values())
    assert engine.rung_peak == 16
    assert engine.rung_switches_total >= 2          # grew AND shrank
    assert_pool_clean(engine)


def test_rung_steps_down_after_drain(model_setup):
    """Once high slots drain, compaction relocates survivors and the
    next dispatch runs a smaller compiled graph."""
    model_cfg, params = model_setup
    engine = InferenceEngine(model_cfg, _ecfg(), params=params)
    prompts = _prompts(10)
    for i, p in enumerate(prompts):
        engine.prefill(Sequence(request_id=i, prompt_tokens=list(p),
                                max_new_tokens=32))
    engine.decode_steps()
    assert engine.decode_rung == 16
    # Finish the 8 highest slots; survivors compact into low slots.
    for s in list(engine.slots)[2:]:
        if s is not None:
            s.done = True
            engine.release(s)
    engine.decode_steps()
    assert engine.decode_rung == 4
    assert all(s.slot < 4 for s in engine.active_sequences())
    for s in engine.active_sequences():
        s.done = True
        engine.release(s)
    assert_pool_clean(engine)


def test_preemption_and_host_tier_compose_at_full_top_rung(model_setup):
    """A full top-rung batch under optimistic admission with the host
    KV tier attached: watermark preemption fires, recompute-resume
    completes every request, greedy outputs match the uncontended run,
    and the pool invariant holds — more lanes never corrupt the
    admission/preemption/tiering machinery."""
    model_cfg, params = model_setup
    rng = np.random.default_rng(3)
    prompts = _prompts(12, rng, length=8)

    ref = InferenceEngine(model_cfg, _ecfg(max_batch_size=4,
                                           decode_ladder=()),
                          params=params)
    want = {i: toks
            for i, toks in enumerate(ref.generate(prompts,
                                                  max_new_tokens=16))}

    ecfg = _ecfg(max_batch_size=8, decode_ladder=(2, 4, 8),
                 num_pages=16, admission="optimistic",
                 optimistic_headroom_pages=1, preempt_watermark_pages=4,
                 host_cache_pages=64)
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    assert engine.host_pool is not None
    sched = EngineScheduler(engine)
    try:
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=16)
                for i, p in enumerate(prompts)]
        events = _submit_and_wait(sched, seqs, start=True)
    finally:
        sched.stop(drain=True, timeout=30)
    for i, s in enumerate(seqs):
        assert s.finish_reason == "length", (i, s.finish_reason)
        assert events[i] == want[i]
    # The tight pool genuinely exercised preemption under the ladder.
    assert engine.preemptions_total >= 1
    assert engine.rung_peak >= 4
    assert_pool_clean(engine)


def test_warmup_covers_every_rung_no_midserve_compile(model_setup):
    """The warmup-completeness satellite: after the first served
    request, NO XLA compile may occur — a burst that climbs the whole
    ladder (and steps back down, single-step latency graph included)
    must find every executable warm. Mid-serving compiles block the GIL
    and starve the HTTP loop (ADVICE r3)."""
    import jax

    model_cfg, params = model_setup
    engine = InferenceEngine(
        model_cfg, _ecfg(decode_steps_per_call=4), params=params)
    engine.warmup()

    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    loggers = [logging.getLogger(n)
               for n in ("jax._src.interpreters.pxla", "jax._src.dispatch")]
    jax.config.update("jax_log_compiles", True)
    for lg in loggers:
        lg.addHandler(handler)
        lg.setLevel(logging.DEBUG)
    try:
        sched = EngineScheduler(engine).start()
        try:
            # First served request: any one-time non-graph stragglers
            # (transfer layouts etc.) land here, per the satellite's
            # contract.
            _submit_and_wait(sched, [Sequence(
                request_id=0, prompt_tokens=_prompts(1)[0],
                max_new_tokens=4)])
            records.clear()
            # Burst across every rung, then drain back to one lane.
            seqs = [Sequence(request_id=1 + i,
                             prompt_tokens=_prompts(1)[0],
                             max_new_tokens=16 + (i % 3))
                    for i in range(15)]
            _submit_and_wait(sched, seqs)
        finally:
            sched.stop(drain=True, timeout=20)
    finally:
        jax.config.update("jax_log_compiles", False)
        for lg in loggers:
            lg.removeHandler(handler)
    assert engine.rung_peak == 16       # the burst really climbed
    compiles = [m for m in records if m.startswith("Compiling ")]
    assert not compiles, (
        f"XLA compiled {len(compiles)} graph(s) after the first served "
        f"request: {compiles[:4]}")
    assert_pool_clean(engine)


@pytest.mark.parametrize("kv_quant", ["none", "int4"])
def test_kv_layout_rung_invariant(model_setup, kv_quant):
    """int4 lane hygiene: at EVERY ladder rung the packed-int4 KV
    layout emits exactly the tokens the base rung emits, just like the
    bf16 pool — rung width never touches the nibble-packed codes. (The
    cross-backend dense==pallas equality for int4 is pinned in
    test_kv_quant; this pins rung-invariance so the TPU int4 lane can
    be recorded at any ladder rung without new failure modes.)"""
    model_cfg, params = model_setup
    prompts = _prompts(8, np.random.default_rng(5), length=10)

    def outs(batch, ladder, n):
        eng = InferenceEngine(
            model_cfg, _ecfg(max_batch_size=batch, decode_ladder=ladder,
                             kv_quant=kv_quant),
            params=params)
        out = eng.generate(prompts[:n], max_new_tokens=8)
        assert_pool_clean(eng)
        return out

    base = outs(2, (), 8)                 # serial waves of 2
    for rung_count in (4, 8):             # exercises rungs 4 and 4->8
        assert outs(8, (4, 8), rung_count) == base[:rung_count]


def test_stage_reuse_is_output_invariant(model_setup):
    """stage_host_reuse=False (rebuild-per-dispatch, the bubble
    comparison arm) and the default reuse path must emit identical
    tokens under rung churn."""
    model_cfg, params = model_setup
    prompts = _prompts(10, np.random.default_rng(9))

    def run(reuse):
        eng = InferenceEngine(
            model_cfg, _ecfg(stage_host_reuse=reuse), params=params)
        out = eng.generate(prompts, max_new_tokens=12)
        assert_pool_clean(eng)
        return out

    assert run(True) == run(False)


def test_ladder_admit_headroom_guards_growth(model_setup):
    """ladder_admit_headroom_pages: growth past the base rung must
    leave the configured reclaimable slack, so a tight pool keeps the
    batch at the base rung instead of thrashing; with the guard off the
    same pool climbs."""
    model_cfg, params = model_setup
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, VOCAB, size=8).tolist() for _ in range(4)]

    def run(headroom):
        ecfg = _ecfg(max_batch_size=4, decode_ladder=(2, 4),
                     num_pages=12, max_pages_per_seq=2,
                     ladder_admit_headroom_pages=headroom)
        eng = InferenceEngine(model_cfg, ecfg, params=params)
        sched = EngineScheduler(eng)
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=8)
                for i, p in enumerate(prompts)]
        _submit_and_wait(sched, seqs, start=True)
        sched.stop(drain=True, timeout=20)
        assert all(s.finish_reason == "length" for s in seqs)
        assert_pool_clean(eng)
        return eng.rung_peak

    assert run(headroom=0) == 4         # unguarded pool climbs
    assert run(headroom=6) == 2         # guarded growth holds the base


def test_chunk_only_calls_never_block_the_pipeline(model_setup):
    """A chunk-only prefill call in flight (rung 0: no decode half, no
    carry to fold) must not read as a rung cap — that would drain the
    pipeline every chunk and re-serialize the hybrid chaining PR 4
    built. Only decode-half calls constrain the staging width."""
    model_cfg, params = model_setup
    engine = InferenceEngine(model_cfg, _ecfg(decode_pipeline_depth=2),
                             params=params)
    engine.prefill(Sequence(request_id=0, prompt_tokens=[1, 2, 3],
                            max_new_tokens=8))
    chunk_only = {"outs": None, "final": None, "final_window": None,
                  "allowed": {}, "seqs": {}, "rung": 0, "prefill": None}
    engine._inflight.append(chunk_only)
    assert not engine._pipeline_rung_blocked()
    engine._inflight.clear()
    for s in engine.active_sequences():
        s.done = True
        engine.release(s)
    assert_pool_clean(engine)


def test_parse_decode_ladder_validates_before_boot():
    """--decode-ladder specs fail as usage errors, not as an engine
    ValueError after the checkpoint loads."""
    from tpu_inference.engine import autosize

    assert autosize.parse_decode_ladder("auto", 32) == (8, 16, 32)
    assert autosize.parse_decode_ladder("off", 32) == (32,)
    assert autosize.parse_decode_ladder("4,8,16", 16) == (4, 8, 16)
    for bad, top in (("8,x", 32), ("8,16", 32), ("16,8,32", 32),
                     ("0,32", 32), ("8,8,32", 32)):
        with pytest.raises(ValueError, match="decode.ladder"):
            autosize.parse_decode_ladder(bad, top)


def test_metrics_expose_rung_occupancy_mfu(model_setup):
    """/metrics surfaces the ladder telemetry the acceptance names:
    active rung, top rung, graph-switch counter, lane occupancy, and
    the derived MFU estimate."""
    from tpu_inference import telemetry as tm

    model_cfg, params = model_setup
    engine = InferenceEngine(model_cfg, _ecfg(), params=params)
    EngineScheduler(engine)             # binds the MFU gauge
    text = tm.render_prometheus([({}, engine.telemetry.registry)])
    for name in ("tpu_inf_decode_rung", "tpu_inf_decode_ladder_top",
                 "tpu_inf_rung_switches_total", "tpu_inf_decode_occupancy",
                 "tpu_inf_mfu_estimate"):
        assert f"\n{name}" in text or text.startswith(name), name
    assert "tpu_inf_decode_ladder_top 16" in text


def test_spec_decode_collapses_ladder(model_setup):
    """Speculative decoding forces a single rung (the spec round has
    one fused graph); the engine must say so rather than mis-dispatch."""
    import dataclasses

    model_cfg, params = model_setup
    draft = dataclasses.replace(model_cfg, n_layers=1, name="draft")
    ecfg = _ecfg(max_batch_size=4, decode_ladder=(2, 4),
                 num_speculative_tokens=2, enable_prefix_cache=False)
    eng = InferenceEngine(model_cfg, ecfg, params=params, draft_cfg=draft)
    assert eng.ladder == (4,)
