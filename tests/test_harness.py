"""Benchmark-harness tests: unit coverage for the client components the
reference only exercised manually via notebooks (SURVEY.md §4), plus the
hermetic end-to-end replay — the harness driving the in-process TPU-stack
server over real HTTP (BASELINE.json config 0 acceptance)."""

import asyncio
import json
import os

import numpy as np
import pandas as pd
import pytest

from traffic_generator import (BurstUser, DataLoader, MetricCollector, Query,
                               Scheduler, SteadyUser, TrafficGenerator)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

METRIC_FIELDS = {"number_of_input_tokens", "request_start_time",
                 "response_headers_received_time", "first_token_arrive_time",
                 "response_end_time", "scheduled_start_time", "success"}


def test_steady_user_timestamps():
    u = SteadyUser(req_freq=2.0, duration=3.0, delay_start=1.0)
    ts = u.get_timestamps()
    assert len(ts) == 6
    assert ts[0] == 1.0
    assert ts[1] == pytest.approx(1.5)


def test_burst_user_timestamps():
    assert BurstUser(n_req=4, time=2.5).get_timestamps() == [2.5] * 4


def test_schedule_from_users_sorted():
    df = Scheduler.get_schedule_from_users([
        SteadyUser(req_freq=1.0, duration=2.0, delay_start=0.5,
                   prompt_tokens=100, response_tokens=50),
        BurstUser(n_req=2, time=1.0),
    ])
    assert list(df.columns) == ["Timestamp", "Request tokens",
                                "Response tokens", "User"]
    assert df["Timestamp"].is_monotonic_increasing
    assert set(df["Request tokens"]) == {100, 500}


def test_schedule_from_trace_respects_max():
    df = Scheduler.get_schedule_from_trace(
        os.path.join(REPO, "data", "trace1.csv"), max_trace=4)
    assert len(df) == 4
    assert df["Request tokens"].dtype.kind == "i"


def test_query_nearest_length_match():
    inputs = [("short", 5, 10, ""), ("medium", 50, 10, ""),
              ("long", 500, 10, ""), ("medium-long-out", 50, 200, "")]
    sched = pd.DataFrame({
        "Timestamp": [0.0, 1.0, 2.0, 3.0],
        "Request tokens": [6, 45, 5000, 52],
        "Response tokens": [10, 150, 10, 10],
    })
    q = Query(inputs, sched)
    picks = [q.get_query() for _ in range(4)]
    assert picks[0][0] == "short"
    assert picks[1][0] == "medium-long-out"   # same prompt dist, closer output
    assert picks[2][0] == "long"
    assert picks[2][1] == 1024                # clamped to max_prompt_len
    assert picks[3][0] in ("medium", "medium-long-out")
    assert picks[3][2] == 10
    q.reset()
    assert q.get_query()[3] == 0              # query ids restart


def test_query_rejects_empty_corpus():
    with pytest.raises(ValueError):
        Query([], pd.DataFrame({"Timestamp": [], "Request tokens": [],
                                "Response tokens": []}))


def test_dataloader_roundtrip(tmp_path):
    corpus = {"0": {"prompt": "p", "len_prompt": 1, "len_output": 2,
                    "output": "oo"}}
    path = tmp_path / "c.json"
    path.write_text(json.dumps(corpus))
    data = DataLoader.get_data_from_path(str(path))
    assert data == [("p", 1, 2, "oo")]


@pytest.fixture(scope="module")
def corpus_and_trace(tmp_path_factory):
    """Small corpus + dense 6-request trace for the hermetic replay."""
    rng = np.random.default_rng(0)
    tmp = tmp_path_factory.mktemp("harness")
    corpus = {}
    for i, (p, g) in enumerate([(5, 4), (12, 6), (30, 8), (60, 5)]):
        corpus[str(i)] = {"prompt": "x" * p, "len_prompt": p,
                          "len_output": g, "output": ""}
    (tmp / "conversations.json").write_text(json.dumps(corpus))
    with open(tmp / "trace.csv", "w") as f:
        f.write("Timestamp,Request tokens,Response tokens\n")
        for i in range(6):
            f.write(f"{i * 0.1:.1f},{int(rng.integers(4, 64))},"
                    f"{int(rng.integers(3, 8))}\n")
    return tmp


def test_end_to_end_replay_against_tpu_stack(corpus_and_trace):
    """The full config-0 loop: harness -> HTTP -> scheduler -> engine ->
    NDJSON stream -> metrics JSON, all in one process."""
    from aiohttp import web

    from tpu_inference.config import (EngineConfig, FrameworkConfig,
                                      ServerConfig, tiny_llama)
    from tpu_inference.server.http import InferenceServer

    cfg = FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(page_size=8, num_pages=256, max_pages_per_seq=16,
                            max_batch_size=4, prefill_buckets=(32, 64)),
        server=ServerConfig(tokenizer="byte"))
    server = InferenceServer(cfg)
    tmp = corpus_and_trace

    async def go():
        runner = web.AppRunner(server.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        data = DataLoader.get_data_from_path(str(tmp / "conversations.json"))
        schedule = Scheduler.get_schedule_from_trace(str(tmp / "trace.csv"))
        collector = MetricCollector()
        gen = TrafficGenerator(
            data, schedule,
            {"url": f"http://127.0.0.1:{port}/api/generate",
             "model": "tiny-llama", "temperature": 0.0, "max_tokens": None,
             "stream": True}, collector)
        metrics = await gen.issue_queries()
        await runner.cleanup()
        return metrics

    metrics = asyncio.run(go())
    assert len(metrics) == 6
    for qid, m in metrics.items():
        assert METRIC_FIELDS <= set(m), f"query {qid} missing fields"
        assert m["success"] is True
        assert (m["scheduled_start_time"] <= m["request_start_time"]
                <= m["first_token_arrive_time"] <= m["response_end_time"])
        # TTFT contract: headers arrive with the first token, not before.
        assert (m["first_token_arrive_time"] - m["response_headers_received_time"]
                < 0.25)


def test_replay_marks_failures(corpus_and_trace):
    """Connection refused -> success=False, no crash (reference caught the
    same errors; its exception *tracer* crashed on a global, main.py:220)."""
    tmp = corpus_and_trace
    data = DataLoader.get_data_from_path(str(tmp / "conversations.json"))
    schedule = Scheduler.get_schedule_from_trace(str(tmp / "trace.csv"),
                                                 max_trace=2)
    collector = MetricCollector()
    gen = TrafficGenerator(
        data, schedule,
        {"url": "http://127.0.0.1:9/api/generate", "model": "x",
         "temperature": 0.0, "max_tokens": 5, "stream": True}, collector)
    metrics = gen.start_profile()
    assert all(m["success"] is False for m in metrics.values())
