"""Test environment: force CPU backend with a virtual 8-device mesh.

Must run before jax initializes its backend, hence env mutation at import
time in conftest (pytest imports conftest before any test module).
Multi-chip sharding tests (TP/EP/ring attention) run on these 8 virtual CPU
devices; real-TPU behavior is exercised by bench.py and the driver's
dryrun_multichip hook.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# XLA:CPU's oneDNN matmuls run in reduced precision by default (~1e-1 abs
# error on standard-normal f32 inputs), which swamps parity tolerances.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402  (after env mutation, which is the point)

jax.config.update("jax_default_matmul_precision", "highest")
