"""Test environment: force CPU backend with a virtual 8-device mesh.

Multi-chip sharding tests (TP/EP/ring attention) run on 8 virtual CPU
devices; real-TPU behavior is exercised by bench.py and the driver's
dryrun_multichip hook.

This image boots every interpreter with JAX_PLATFORMS=axon and a
sitecustomize that imports jax and registers the axon (TPU-tunnel) PJRT
plugin before conftest runs, so setting JAX_PLATFORMS/XLA_FLAGS env vars
here is too late — jax read them at its (sitecustomize-time) import.
Backends initialize lazily though, so overriding via jax.config before
any computation still works and avoids the slow/flaky tunnel dial.
"""

import os

os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# jax < 0.5 has no jax_num_cpu_devices; the compat shim falls back to
# XLA_FLAGS, which the lazy backend init still honors at this point.
from tpu_inference.compat import set_cpu_device_count  # noqa: E402

set_cpu_device_count(8)
# XLA:CPU's oneDNN matmuls run in reduced precision by default (~1e-1 abs
# error on standard-normal f32 inputs), which swamps parity tolerances.
jax.config.update("jax_default_matmul_precision", "highest")

# Persistent XLA compilation cache: a warm test_speculative.py run drops
# 41s -> 11s (rationale + knobs in tests/_xla_cache.py).
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _xla_cache  # noqa: E402

_xla_cache.enable(jax)


def randomize_qkv_biases(params, seed: int = 7, scale: float = 0.1) -> None:
    """init_params zero-inits Qwen2's q/k/v biases; tests randomize them
    in place so the bias term actually participates in parity checks.
    Shared across test modules (engine + TP suites)."""
    key = jax.random.PRNGKey(seed)
    for i, name in enumerate(("bq", "bk", "bv")):
        b = params["blocks"][name]
        params["blocks"][name] = scale * jax.random.normal(
            jax.random.fold_in(key, i), b.shape, b.dtype)
