"""Elastic fleet (README "Elastic fleet"): SLO-driven autoscaling,
priority classes, crash-loop quarantine, and zero-downtime rollouts.

Covers the control plane at two levels:

- pure units: class ranking + request-clone plumbing, and the
  autoscaler SENSOR (hysteresis windows, cooldown, min/max bounds, the
  no-action-while-transitioning guard that prevents a restart/scale-up
  double-spawn) against a process-less group with hand-fed SLO windows.
- REAL processes: crash-loop breaker quarantine (pinned gauge, degraded
  /healthz, survivor keeps serving byte-identically), per-class
  admission (batch defers at the cap, interactive preempts the batch
  lane and the victim resumes byte-identically), SLO-breach scale-up
  racing a ``kill -9`` (no double-spawn, monotone counters), lossless
  scale-down, and a rolling upgrade under live traffic with a SIGTERM
  thrown mid-rollout (zero failed requests).
"""

import dataclasses
import re
import threading
import time

import pytest

from tpu_inference.config import (EngineConfig, FrameworkConfig,
                                  ParallelConfig, ServerConfig, class_rank,
                                  tiny_llama)
from tpu_inference.engine.engine import InferenceEngine, Sequence

ENGINE_KW = dict(page_size=8, num_pages=64, max_pages_per_seq=8,
                 max_batch_size=2, prefill_buckets=(16,),
                 host_cache_pages=32)


def _cfg(dp=2, engine_kw=None, **server_kw) -> FrameworkConfig:
    server_kw.setdefault("fleet", "subprocess")
    server_kw.setdefault("worker_restart_max", 10)
    server_kw.setdefault("worker_restart_backoff_s", 0.1)
    server_kw.setdefault("drain_timeout_s", 8.0)
    return FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(**{**ENGINE_KW, **(engine_kw or {})}),
        parallel=ParallelConfig(dp=dp),
        server=ServerConfig(model_name="t", tokenizer="byte",
                            warmup=False, **server_kw))


def _submit(group, rid, prompt, max_new, cls="interactive"):
    toks, done, box = [], threading.Event(), {}
    seq = Sequence(request_id=rid, prompt_tokens=list(prompt),
                   max_new_tokens=max_new, priority_class=cls)
    group.submit(seq, lambda s, t: toks.append(t),
                 lambda s: (box.update(seq=s), done.set()))
    return toks, done, box


def _finish(done, box, timeout=180.0):
    assert done.wait(timeout), "request did not finish"
    return box["seq"]


def _wait(pred, timeout=60.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture(scope="module")
def oracle():
    return InferenceEngine(tiny_llama(vocab_size=512),
                           EngineConfig(**ENGINE_KW), seed=0)


# ------------------------------------------------------------- units


def test_class_rank_and_plumbing():
    """interactive < batch < background; unknown names can never starve
    (they rank interactive); the class rides request clones and the
    worker submit payload field."""
    from tpu_inference.server.replicas import _clone_request

    assert class_rank("interactive") == 0
    assert class_rank("batch") == 1
    assert class_rank("background") == 2
    assert class_rank("tyop") == 0          # fail-open, never starved

    seq = Sequence(request_id=7, prompt_tokens=[1, 2],
                   max_new_tokens=4, priority_class="background")
    assert _clone_request(seq).priority_class == "background"
    assert Sequence(request_id=8, prompt_tokens=[1],
                    max_new_tokens=1).priority_class == "interactive"


def test_autoscale_sensor_hysteresis_and_guards():
    """The autoscaler sensor against hand-fed windows: breach must be
    SUSTAINED before a scale-up, a lull must be sustained before a
    scale-down, bounds and backlog gate both, and NO decision fires
    while any worker is mid-transition (the restart/scale-up
    double-spawn guard)."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    g = ProcessEngineGroup(_cfg(
        dp=2, autoscale=True, autoscale_breach_window_s=1.0,
        autoscale_idle_window_s=1.0, autoscale_cooldown_s=5.0,
        autoscale_max_replicas=3, autoscale_low_watermark=0.25,
        engine_kw={"slo_ttft_ms": 100}))
    try:
        calls = []
        g._scale_up = lambda reason: (calls.append(("up", reason)),
                                      setattr(g, "_breach_since", 0.0))
        g._scale_down = lambda reason: (calls.append(("down", reason)),
                                        setattr(g, "_idle_since", 0.0))
        for h in g.workers:
            h.state = "up"
            h.last_health = {"ladder_occupancy": 0.8}
        # p95 TTFT 0.5s >> the 100ms target: breached. The sensor reads
        # the ROUTER-observed window (submit -> first token, lane park
        # time included), not the workers' engine-side rings.
        g._ttft_obs.extend((time.perf_counter(), 0.5) for _ in range(20))

        t = 100.0
        g._autoscale_tick(t)            # arms the breach window
        g._autoscale_tick(t + 0.5)      # not sustained yet
        assert calls == []
        g._autoscale_tick(t + 1.2)      # sustained -> actuate
        assert calls == [("up", "slo_breach")]

        # Cooldown: an immediate second breach does nothing.
        g._last_scale_t = t + 1.2
        g._autoscale_tick(t + 1.5)
        g._autoscale_tick(t + 3.0)
        assert len(calls) == 1

        # Transition guard: a restarting worker freezes ALL decisions
        # (and disarms the breach window) — a chaos-killed worker's
        # respawn can never race a scale-up into a double-spawn.
        g.workers[1].state = "restarting"
        g._autoscale_tick(t + 50.0)
        g._autoscale_tick(t + 60.0)
        assert len(calls) == 1 and g._breach_since == 0.0
        g.workers[1].state = "up"

        # Max bound: breach sustained but n == max -> no actuation.
        g.server_cfg = dataclasses.replace(g.server_cfg,
                                           autoscale_max_replicas=2)
        g._autoscale_tick(t + 70.0)
        g._autoscale_tick(t + 72.0)
        assert len(calls) == 1
        g.server_cfg = dataclasses.replace(g.server_cfg,
                                           autoscale_max_replicas=3)

        # Idle path: the burst's breached samples AGE OUT of the time
        # horizon (count-based rings latch forever; the router window
        # must not), occupancy under the low watermark -> sustained
        # lull drains the coldest replica.
        g._ttft_obs.clear()
        g._ttft_obs.extend((time.perf_counter() - 60.0, 0.5)
                           for _ in range(20))
        for h in g.workers:
            h.last_health = {"ladder_occupancy": 0.0}
        g._autoscale_tick(t + 100.0)    # arms the idle window
        assert not g._ttft_obs          # horizon pruned the stale burst
        g._autoscale_tick(t + 101.2)
        assert calls[-1] == ("down", "idle")

        # A parked batch backlog blocks scale-down outright.
        g._deferred["batch"].append(object())
        g._autoscale_tick(t + 200.0)
        g._autoscale_tick(t + 202.0)
        assert calls[-1] == ("down", "idle") and len(calls) == 2
        g._deferred["batch"].clear()

        # Min bound: one live worker never drains away.
        g.workers[1].state = "retired"
        g._autoscale_tick(t + 300.0)
        g._autoscale_tick(t + 302.0)
        assert len(calls) == 2
    finally:
        g.stop(drain=False)


def test_retire_candidate_prefers_cold_and_respects_pd():
    """Scale-down picks the least-loaded, lowest-occupancy replica and
    never removes the last worker of a P/D phase."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    g = ProcessEngineGroup(_cfg(dp=3))
    try:
        for i, h in enumerate(g.workers):
            h.state = "up"
            h.last_health = {"ladder_occupancy": [0.9, 0.1, 0.5][i]}
        assert g._retire_candidate().replica == 1

        # P/D: with roles [prefill, decode, decode], replica 1 or 2 may
        # retire but the lone prefill worker (0) never can.
        g.roles[:] = ["prefill", "decode", "decode"]
        g.pd_enabled = True
        assert g._retire_candidate().replica in (1, 2)
        g.workers[2].state = "retired"
        cand = g._retire_candidate()
        assert cand is None or cand.replica != 0
    finally:
        g.stop(drain=False)


# ------------------------------------------------- real process fleets


def test_crash_loop_quarantine(oracle):
    """Crash-loop breaker: with the restart budget exhausted the
    replica lands QUARANTINED — visible in /healthz (degraded, not
    absent), pinned by tpu_inf_worker_quarantined, excluded from
    tpu_inf_replicas — and the survivor keeps serving byte-identically."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(dp=2, worker_restart_max=0))
    group.start()
    try:
        _wait(lambda: all(h.state == "up" for h in group.workers),
              what="fleet up")
        group.apply_chaos({"replica": 1, "kill": "kill9"})
        _wait(lambda: group.workers[1].state == "quarantined",
              what="quarantine")

        hs = group.health_snapshot()
        assert hs["status"] == "degraded"
        assert hs["replicas"][1]["worker_state"] == "quarantined"
        assert "quarantined" in hs["supervision"]["states"]

        text = group.prometheus_text()
        assert re.search(
            r'tpu_inf_worker_quarantined\{replica="1"\} 1(\.0)?\b', text)
        assert re.search(
            r'tpu_inf_worker_quarantined\{replica="0"\} 0(\.0)?\b', text)
        m = re.search(r"^tpu_inf_replicas (\S+)$", text, re.M)
        assert m and float(m.group(1)) == 1.0

        toks, done, box = _submit(group, 1, [5, 6, 7], 8)
        fin = _finish(done, box)
        assert fin.finish_reason == "length" and fin.routed_replica == 0
        assert toks == oracle.generate([[5, 6, 7]], max_new_tokens=8)[0]
    finally:
        group.stop(drain=False)


def test_priority_classes_defer_and_preempt(oracle):
    """Per-class admission on a saturated single worker: batch work
    parks in its lane instead of bouncing a 429, an interactive arrival
    preempts the running batch request (which resumes byte-identically
    from the router's token record), and every class drains to
    completion once pressure lifts."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(dp=1, admission_queue_depth=1,
                                    class_queue_depth=4))
    group.start()
    try:
        _wait(lambda: all(h.state == "up" for h in group.workers),
              what="fleet up")
        p1, p2, p3 = [1, 2, 3, 4, 5], [9, 8, 7], [3, 3, 3, 3]
        t1, d1, b1 = _submit(group, 1, p1, 48, cls="batch")
        t2, d2, b2 = _submit(group, 2, p2, 12, cls="batch")   # defers
        # The interactive arrival preempts the RUNNING batch request.
        t3, d3, b3 = _submit(group, 3, p3, 12, cls="interactive")

        fin3 = _finish(d3, b3)
        assert fin3.finish_reason == "length"
        assert t3 == oracle.generate([p3], max_new_tokens=12)[0]
        # Preempted + deferred batch work completes byte-identically.
        fin1 = _finish(d1, b1)
        fin2 = _finish(d2, b2)
        assert fin1.finish_reason == fin2.finish_reason == "length"
        assert t1 == oracle.generate([p1], max_new_tokens=48)[0]
        assert t2 == oracle.generate([p2], max_new_tokens=12)[0]

        sup = group.supervision_counters()
        assert sup["class_preemptions"].get("batch", 0) >= 1
        assert sup["requests_shed"] == 0
        assert sup["class_deferred"] == {"batch": 0, "background": 0}
        text = group.prometheus_text()
        assert re.search(
            r'tpu_inf_class_preempted_total\{class="batch"\} [1-9]', text)
        assert 'tpu_inf_class_deferred{class="batch"} 0' in text
    finally:
        group.stop(drain=False)


def test_autoscale_up_with_kill9_no_double_spawn(oracle):
    """End-to-end scale-up on a sustained SLO breach, then a kill -9
    thrown at the fleet: the victim RESTARTS (supervision) rather than
    triggering a second scale-up, requests fail over byte-identically,
    and the fleet counters stay monotone."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(
        dp=1, autoscale=True, autoscale_breach_window_s=0.5,
        autoscale_cooldown_s=1.0, autoscale_max_replicas=2,
        autoscale_low_watermark=0.0,     # never scale down in this test
        engine_kw={"slo_ttft_ms": 1}))   # 1 ms: every request breaches
    group.start()
    try:
        _wait(lambda: all(h.state == "up" for h in group.workers),
              what="fleet up")
        for i in range(3):
            toks, done, box = _submit(group, 10 + i, [1, 2, i], 6)
            _finish(done, box)
        _wait(lambda: len(group.workers) == 2
              and group.workers[1].state == "up",
              timeout=90.0, what="scale-up")
        assert group.scale_ups == 1
        assert group.trace_snapshot("scale-up-1") is not None
        text = group.prometheus_text()
        assert re.search(r"tpu_inf_fleet_scale_ups_total 1\b", text)

        # kill -9 the original worker with a request in flight.
        restarts_before = sum(h.restarts for h in group.workers)
        toks, done, box = _submit(group, 50, [4, 4, 4], 24)
        group.apply_chaos({"replica": 0, "kill": "kill9"})
        fin = _finish(done, box)
        assert fin.finish_reason == "length"
        assert toks == oracle.generate([[4, 4, 4]], max_new_tokens=24)[0]
        _wait(lambda: group.workers[0].state == "up", what="heal")
        time.sleep(2.5)   # past cooldown: breach may persist, max caps it
        assert len(group.workers) == 2     # restart, NOT a third spawn
        sup = group.supervision_counters()
        assert sup["scale_ups"] == 1 and sup["scale_downs"] == 0
        assert sum(h.restarts for h in group.workers) > restarts_before
    finally:
        group.stop(drain=False)


def test_scale_down_retires_coldest(oracle):
    """Lossless scale-down: the idle replica drain-retires (state
    RETIRED, excluded from tpu_inf_replicas and from /healthz status
    math) while the busy replica's request streams to completion."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(dp=2))
    group.start()
    try:
        _wait(lambda: all(h.state == "up" for h in group.workers),
              what="fleet up")
        prompt = [2, 4, 6, 8]
        toks, done, box = _submit(group, 1, prompt, 48)
        time.sleep(0.3)                   # let it land on its worker
        group._scale_down("test")
        retired = [h for h in group.workers if h.retiring or
                   h.state == "retired"]
        assert len(retired) == 1
        _wait(lambda: retired[0].state == "retired", what="retire")

        fin = _finish(done, box)
        assert fin.finish_reason == "length"
        assert toks == oracle.generate([prompt], max_new_tokens=48)[0]

        assert group.scale_downs == 1
        assert len(group._live_workers()) == 1
        hs = group.health_snapshot()
        assert hs["status"] == "ok"       # retired is NOT degraded
        text = group.prometheus_text()
        assert re.search(r"tpu_inf_fleet_scale_downs_total 1\b", text)
        m = re.search(r"^tpu_inf_replicas (\S+)$", text, re.M)
        assert m and float(m.group(1)) == 1.0
        assert group.trace_snapshot("scale-down-1") is not None
    finally:
        group.stop(drain=False)


def test_rollout_under_traffic_with_sigterm_chaos(oracle):
    """Rolling upgrade under live traffic with a SIGTERM thrown at an
    original worker mid-rollout: the in-flight request completes
    byte-identically (migrated or failed over, never failed), the
    rollout finishes, successors serve, and a second rollout attempt
    while one is running is refused."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(dp=2))
    group.start()
    try:
        _wait(lambda: all(h.state == "up" for h in group.workers),
              what="fleet up")
        prompt = [1, 3, 5, 7, 9]
        toks, done, box = _submit(group, 1, prompt, 48)

        res_box = {}
        th = threading.Thread(
            target=lambda: res_box.update(res=group.rollout()))
        th.start()
        time.sleep(0.3)
        assert group._rollout_lock.locked()
        with pytest.raises(ValueError, match="already in progress"):
            group.rollout()
        try:
            group.apply_chaos({"replica": 0, "kill": "sigterm"})
        except ValueError:
            pass                          # already exited post-drain
        th.join(timeout=180.0)
        assert not th.is_alive(), "rollout wedged"
        res = res_box["res"]

        # Zero failed requests: the live stream completed identically.
        fin = _finish(done, box)
        assert fin.finish_reason == "length"
        assert toks == oracle.generate([prompt], max_new_tokens=48)[0]

        assert res["replaced"] and not res["failed"]
        assert group.rollouts == 1
        assert group.trace_snapshot("rollout-1") is not None

        # Successors serve new traffic byte-identically.
        _wait(lambda: any(h.state == "up" and h.replica >= 2
                          for h in group.workers), what="successor up")
        toks2, done2, box2 = _submit(group, 2, [7, 7, 7], 10)
        fin2 = _finish(done2, box2)
        assert fin2.finish_reason == "length"
        assert toks2 == oracle.generate([[7, 7, 7]], max_new_tokens=10)[0]
        text = group.prometheus_text()
        assert re.search(r"tpu_inf_fleet_rollouts_total 1\b", text)
    finally:
        group.stop(drain=False)
