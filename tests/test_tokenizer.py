"""Byte tokenizer + incremental UTF-8-safe stream decoding."""

import pytest

from tpu_inference.server.tokenizer import (ByteTokenizer, IncrementalDecoder,
                                            build_tokenizer)


def test_byte_roundtrip():
    tok = ByteTokenizer()
    text = "hello, world! héllo 🌍"
    ids = tok.encode(text, add_bos=False)
    assert tok.decode(ids) == text
    with_bos = tok.encode(text)
    assert with_bos[0] == tok.bos_token_id
    assert tok.decode(with_bos) == text  # specials stripped


def test_incremental_decoder_splits_utf8():
    tok = ByteTokenizer()
    text = "héllo🌍x"
    ids = tok.encode(text, add_bos=False)
    dec = IncrementalDecoder(tok)
    chunks = [dec.push(i) for i in ids]
    # No chunk may contain a replacement char (split multibyte held back).
    assert all("�" not in c for c in chunks)
    assert "".join(chunks) + dec.flush() == text


def test_incremental_decoder_one_byte_at_a_time_ascii():
    tok = ByteTokenizer()
    dec = IncrementalDecoder(tok)
    out = [dec.push(i) for i in tok.encode("abc", add_bos=False)]
    assert out == ["a", "b", "c"]


def test_build_tokenizer_byte():
    tok = build_tokenizer("byte", vocab_size=512)
    assert tok.vocab_size == 512
    assert tok.eos_token_id == 257


def test_incremental_decoder_metaspace_spacing(tmp_path):
    """SentencePiece/Metaspace pieces ("▁the" -> " the" in context) must
    keep their inter-word spacing under incremental decoding — decoding
    tokens independently drops every space (the Llama-family failure)."""
    import json

    pytest.importorskip("transformers")
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import decoders, models, pre_tokenizers, trainers

    tok = tokenizers.Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.Metaspace()
    tok.decoder = decoders.Metaspace()
    trainer = trainers.BpeTrainer(vocab_size=400,
                                  special_tokens=["<s>", "</s>"])
    tok.train_from_iterator(
        ["hello world how is the weather today",
         "the quick brown fox jumps over the lazy dog"] * 20, trainer)
    tok.save(str(tmp_path / "tokenizer.json"))
    with open(tmp_path / "tokenizer_config.json", "w") as f:
        json.dump({"tokenizer_class": "PreTrainedTokenizerFast",
                   "bos_token": "<s>", "eos_token": "</s>"}, f)

    from tpu_inference.server.tokenizer import HFTokenizer

    hf = HFTokenizer(str(tmp_path))
    text = "hello world how is the weather"
    ids = hf.encode(text)
    assert " " in hf.decode(ids)
    dec = IncrementalDecoder(hf)
    streamed = "".join(dec.push(i) for i in ids) + dec.flush()
    assert streamed == hf.decode(ids) == text
    # Seeded with a prompt tail, the first generated piece keeps its
    # leading space relative to the prompt.
    prompt = hf.encode("hello world", add_bos=False)
    dec = IncrementalDecoder(hf, prompt_tail=prompt)
    cont = hf.encode(" how is", add_bos=False)
    streamed = "".join(dec.push(i) for i in cont) + dec.flush()
    assert streamed == " how is"


def test_chat_template_applied_and_bos_stripped(tmp_path):
    """HFTokenizer renders /api/chat messages with the checkpoint's own
    chat template (leading BOS text stripped so encode() doesn't double
    it); tokenizers without a template return None (role-prefix
    fallback)."""
    import json

    pytest.importorskip("transformers")
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import decoders, models, pre_tokenizers, trainers

    tok = tokenizers.Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.Metaspace()
    tok.decoder = decoders.Metaspace()
    trainer = trainers.BpeTrainer(vocab_size=400,
                                  special_tokens=["<s>", "</s>"])
    tok.train_from_iterator(["user assistant hello there"] * 20, trainer)
    tok.save(str(tmp_path / "tokenizer.json"))
    with open(tmp_path / "tokenizer_config.json", "w") as f:
        json.dump({"tokenizer_class": "PreTrainedTokenizerFast",
                   "bos_token": "<s>", "eos_token": "</s>"}, f)

    from tpu_inference.server.tokenizer import HFTokenizer

    hf = HFTokenizer(str(tmp_path))
    msgs = [{"role": "user", "content": "hello"}]
    assert hf.apply_chat_template(msgs) is None    # no template configured

    hf._tok.chat_template = (
        "{{ bos_token }}{% for m in messages %}[{{ m.role }}] "
        "{{ m.content }}\n{% endfor %}assistant:")
    out = hf.apply_chat_template(msgs)
    assert out == "[user] hello\nassistant:"       # BOS text stripped
    ids = hf.encode(out)
    assert ids[0] == hf.bos_token_id               # exactly one BOS
    assert ids[1] != hf.bos_token_id
