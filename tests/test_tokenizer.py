"""Byte tokenizer + incremental UTF-8-safe stream decoding."""

from tpu_inference.server.tokenizer import (ByteTokenizer, IncrementalDecoder,
                                            build_tokenizer)


def test_byte_roundtrip():
    tok = ByteTokenizer()
    text = "hello, world! héllo 🌍"
    ids = tok.encode(text, add_bos=False)
    assert tok.decode(ids) == text
    with_bos = tok.encode(text)
    assert with_bos[0] == tok.bos_token_id
    assert tok.decode(with_bos) == text  # specials stripped


def test_incremental_decoder_splits_utf8():
    tok = ByteTokenizer()
    text = "héllo🌍x"
    ids = tok.encode(text, add_bos=False)
    dec = IncrementalDecoder(tok)
    chunks = [dec.push(i) for i in ids]
    # No chunk may contain a replacement char (split multibyte held back).
    assert all("�" not in c for c in chunks)
    assert "".join(chunks) + dec.flush() == text


def test_incremental_decoder_one_byte_at_a_time_ascii():
    tok = ByteTokenizer()
    dec = IncrementalDecoder(tok)
    out = [dec.push(i) for i in tok.encode("abc", add_bos=False)]
    assert out == ["a", "b", "c"]


def test_build_tokenizer_byte():
    tok = build_tokenizer("byte", vocab_size=512)
    assert tok.vocab_size == 512
    assert tok.eos_token_id == 257
