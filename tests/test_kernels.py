"""Pallas kernel correctness vs the dense jnp reference paths.

Kernels run in interpreter mode on CPU (tests/conftest.py forces the cpu
backend); the same code compiles via Mosaic on a real TPU. The dense
gather-based attention in models/common.py + engine/kv_cache.py is the
correctness oracle (SURVEY.md §7 layer 5: "kernel validated against it").
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_inference import config as cfgs
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import InferenceEngine
from tpu_inference.kernels.paged_attention import paged_attention
from tpu_inference.models import build_model, common


def _random_paged_setup(rng, *, b=3, hq=8, hkv=2, d=64, page_size=8,
                        num_pages=32, max_pages=4, dtype=jnp.float32):
    """Build a pool + block tables with random per-seq lengths."""
    k_pool = jnp.asarray(rng.standard_normal(
        (num_pages, page_size, hkv, d)), dtype)
    v_pool = jnp.asarray(rng.standard_normal(
        (num_pages, page_size, hkv, d)), dtype)
    q = jnp.asarray(rng.standard_normal((b, hq, d)), dtype)
    # Distinct physical pages per sequence (page 0 reserved as trash).
    perm = rng.permutation(np.arange(1, num_pages))[:b * max_pages]
    bt = perm.reshape(b, max_pages).astype(np.int32)
    kv_len = rng.integers(1, page_size * max_pages + 1, size=b).astype(np.int32)
    return q, k_pool, v_pool, jnp.asarray(bt), jnp.asarray(kv_len)


def _dense_reference(q, k_pool, v_pool, bt, kv_len):
    kv = kvc.KVPages(k=k_pool[None], v=v_pool[None])
    k_all, v_all = kvc.gather_kv(kv, 0, bt)
    out = common.dense_causal_attention(
        q[:, None], k_all, v_all, q_offset=kv_len - 1, kv_len=kv_len)
    return out[:, 0]


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_paged_attention_matches_dense(dtype):
    rng = np.random.default_rng(0)
    q, k_pool, v_pool, bt, kv_len = _random_paged_setup(rng, dtype=dtype)
    got = paged_attention(q, k_pool, v_pool, bt, kv_len)
    want = _dense_reference(q, k_pool, v_pool, bt, kv_len)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


def test_paged_attention_single_token_context():
    """kv_len=1: only the current token is attendable (softmax of one)."""
    rng = np.random.default_rng(1)
    q, k_pool, v_pool, bt, _ = _random_paged_setup(rng, b=2)
    kv_len = jnp.asarray([1, 1], jnp.int32)
    got = paged_attention(q, k_pool, v_pool, bt, kv_len)
    want = _dense_reference(q, k_pool, v_pool, bt, kv_len)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_paged_attention_mha():
    """n_rep == 1 (no GQA grouping)."""
    rng = np.random.default_rng(2)
    q, k_pool, v_pool, bt, kv_len = _random_paged_setup(rng, hq=4, hkv=4)
    got = paged_attention(q, k_pool, v_pool, bt, kv_len)
    want = _dense_reference(q, k_pool, v_pool, bt, kv_len)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_engine_pallas_backend_matches_dense():
    """Full engine generation with the Pallas decode kernel == dense path."""
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=16,
                             max_batch_size=4, prefill_buckets=(16, 32),
                             decode_steps_per_call=4)
    params, _ = build_model(model_cfg, seed=0)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 12, 27)]

    dense = InferenceEngine(model_cfg, ecfg, params=params,
                            attn_backend="dense")
    pallas = InferenceEngine(model_cfg, ecfg, params=params,
                             attn_backend="pallas")
    got_d = dense.generate(prompts, max_new_tokens=10)
    got_p = pallas.generate(prompts, max_new_tokens=10)
    assert got_d == got_p


def test_engine_pallas_backend_mixtral_sharded_matches_dense():
    """MoE (expert-parallel) engine under a tp mesh with the Pallas
    decode+prefill kernels == dense single-device."""
    from tpu_inference.parallel.mesh import build_mesh

    model_cfg = cfgs.tiny_mixtral(vocab_size=256)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=16,
                             max_batch_size=4, prefill_buckets=(16, 32),
                             decode_steps_per_call=4)
    params, _ = build_model(model_cfg, seed=0)
    rng = np.random.default_rng(12)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 18)]

    dense = InferenceEngine(model_cfg, ecfg, params=params,
                            attn_backend="dense")
    got_d = dense.generate(prompts, max_new_tokens=8)
    mesh = build_mesh(cfgs.ParallelConfig(tp=2))
    pallas = InferenceEngine(model_cfg, ecfg, params=params,
                             attn_backend="pallas", mesh=mesh)
    got_p = pallas.generate(prompts, max_new_tokens=8)
    assert got_d == got_p


def test_engine_pallas_backend_sharded_matches_dense():
    """Pallas decode under a dp×tp mesh (shard_map over tp) == dense.

    The kernel is head-local: q shards on query heads, the pool on kv
    heads; no collective inside attention (engine.make_paged_attn)."""
    from tpu_inference.parallel.mesh import build_mesh

    model_cfg = cfgs.tiny_llama(vocab_size=256)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=16,
                             max_batch_size=4, prefill_buckets=(16, 32),
                             decode_steps_per_call=4)
    params, _ = build_model(model_cfg, seed=0)
    rng = np.random.default_rng(6)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 12, 27)]

    dense = InferenceEngine(model_cfg, ecfg, params=params,
                            attn_backend="dense")
    got_d = dense.generate(prompts, max_new_tokens=10)
    mesh = build_mesh(cfgs.ParallelConfig(dp=2, tp=2))
    pallas = InferenceEngine(model_cfg, ecfg, params=params,
                             attn_backend="pallas", mesh=mesh)
    got_p = pallas.generate(prompts, max_new_tokens=10)
    assert got_d == got_p


@pytest.mark.parametrize("block_q,q_offsets", [(16, (5, 0)), (8, (0, 13))])
def test_paged_prefill_attention_matches_dense(block_q, q_offsets):
    """Flash prefill over pool pages == dense gather+causal attention,
    including cached-prefix offsets and partially-filled last pages."""
    from tpu_inference.kernels.prefill_attention import paged_prefill_attention

    rng = np.random.default_rng(7)
    b, s, hq, hkv, d, pg, npg, mp = 2, 32, 8, 2, 64, 8, 64, 8
    k_pool = jnp.asarray(rng.standard_normal((npg, pg, hkv, d)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((npg, pg, hkv, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.float32)
    perm = rng.permutation(np.arange(1, npg))[:b * mp]
    bt = jnp.asarray(perm.reshape(b, mp).astype(np.int32))
    q_off = jnp.asarray(q_offsets, jnp.int32)
    prompt = jnp.asarray([20, 32], jnp.int32)
    kv_len = q_off + prompt

    got = paged_prefill_attention(q, k_pool, v_pool, bt, kv_len, q_off,
                                  block_q=block_q)
    kv = kvc.KVPages(k=k_pool[None], v=v_pool[None])
    k_all, v_all = kvc.gather_kv(kv, 0, bt)
    want = common.dense_causal_attention(q, k_all, v_all, q_offset=q_off,
                                         kv_len=kv_len)
    for i in range(b):
        n = int(prompt[i])                    # padded query rows unused
        np.testing.assert_allclose(np.asarray(got)[i, :n],
                                   np.asarray(want)[i, :n],
                                   rtol=2e-5, atol=2e-5)


def test_paged_prefill_non_power_of_two_bucket():
    """Lengths with no 128 divisor pick a smaller valid query block."""
    from tpu_inference.kernels.prefill_attention import paged_prefill_attention

    rng = np.random.default_rng(8)
    b, s, h, d, pg, npg, mp = 1, 24, 4, 32, 8, 16, 4
    k_pool = jnp.asarray(rng.standard_normal((npg, pg, h, d)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((npg, pg, h, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    bt = jnp.asarray(np.arange(1, 1 + mp)[None].astype(np.int32))
    kv_len = jnp.asarray([s], jnp.int32)
    got = paged_prefill_attention(q, k_pool, v_pool, bt, kv_len,
                                  jnp.zeros((b,), jnp.int32), block_q=16)
    kv = kvc.KVPages(k=k_pool[None], v=v_pool[None])
    k_all, v_all = kvc.gather_kv(kv, 0, bt)
    want = common.dense_causal_attention(q, k_all, v_all, q_offset=0,
                                         kv_len=kv_len)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("sp,hq,hkv", [(4, 4, 4), (8, 8, 2)])
def test_ring_attention_matches_dense(sp, hq, hkv):
    """Sequence-parallel ring attention == dense causal attention."""
    from jax.sharding import Mesh
    from tpu_inference.kernels.ring_attention import ring_attention

    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    rng = np.random.default_rng(4)
    b, s, d = 2, 8 * sp, 16
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)

    got = ring_attention(q, k, v, mesh=mesh)
    want = common.dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_bf16():
    from jax.sharding import Mesh
    from tpu_inference.kernels.ring_attention import ring_attention

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    rng = np.random.default_rng(5)
    b, s, h, d = 1, 32, 4, 32
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    got = ring_attention(q, k, v, mesh=mesh)
    want = common.dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("sp,hq,hkv", [(4, 4, 4), (4, 8, 4), (2, 8, 2)])
def test_ulysses_attention_matches_dense(sp, hq, hkv):
    """All-to-all (Ulysses) sequence parallelism == dense causal
    attention, including GQA head ratios."""
    from jax.sharding import Mesh
    from tpu_inference.kernels.ulysses_attention import ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    rng = np.random.default_rng(6)
    b, s, d = 2, 8 * sp, 16
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)

    got = ulysses_attention(q, k, v, mesh=mesh)
    want = common.dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_matches_ring():
    """The two sequence-parallel schemes agree with each other (and the
    dense oracle) on the same sharded inputs."""
    from jax.sharding import Mesh
    from tpu_inference.kernels.ring_attention import ring_attention
    from tpu_inference.kernels.ulysses_attention import ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    rng = np.random.default_rng(7)
    b, s, h, d = 1, 32, 4, 32
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(ulysses_attention(q, k, v, mesh=mesh)),
        np.asarray(ring_attention(q, k, v, mesh=mesh)),
        rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [5, 8, 20])
def test_sp_attention_sliding_window_matches_dense(window):
    """Windowed ring AND Ulysses SP attention == the window-masked dense
    oracle (VERDICT r4 item 5: SWA composes with sequence parallelism).
    Windows chosen to exercise all mask regimes on 8-token shards:
    window < shard (behind-window chunk-skip fires), window == shard,
    and window spanning multiple shards."""
    from jax.sharding import Mesh
    from tpu_inference.kernels.ring_attention import ring_attention
    from tpu_inference.kernels.ulysses_attention import ulysses_attention

    rng = np.random.default_rng(9)
    b, s, hq, hkv, d = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)

    want = common.dense_causal_attention(q, k, v, sliding_window=window)
    # Ring at sp=4 (8-token shards: window 5 puts whole chunks behind the
    # window, firing the chunk-skip); Ulysses at sp=2 (GQA head counts
    # must divide the axis).
    for name, fn, sp in (("ring", ring_attention, 4),
                         ("ulysses", ulysses_attention, 2)):
        mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
        got = fn(q, k, v, mesh=mesh, sliding_window=window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5, err_msg=name)
    # And the window actually binds (differs from full attention).
    full = common.dense_causal_attention(q, k, v)
    assert not np.allclose(np.asarray(want), np.asarray(full))


def test_ulysses_attention_bf16():
    """bf16 activations stay bf16 across the all-to-alls (raw-dtype
    wire bytes) and still match the dense oracle within bf16 tolerance."""
    from jax.sharding import Mesh
    from tpu_inference.kernels.ulysses_attention import ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    rng = np.random.default_rng(8)
    b, s, h, d = 1, 32, 4, 32
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    got = ulysses_attention(q, k, v, mesh=mesh)
    assert got.dtype == jnp.bfloat16
    want = common.dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=5e-2, atol=5e-2)
