"""The reference's OWN client, byte-for-byte, drives this server unchanged.

North-star compatibility claim (SURVEY §0/§2c): a user of the reference
switches inference endpoints by editing only the module-level ``config``
dict (reference: traffic_generator/main.py:302-313) — every class, the
asyncio pipeline, the aiohttp TraceConfig hooks, and the log schema run
as-is. That exercises the exact request shape the rewritten in-repo
harness no longer sends: top-level ``max_tokens``/``temperature`` with
no ``options`` object (reference: traffic_generator/main.py:241-247).

``tests/fixtures/reference_client_verbatim.py`` is an exact byte copy of
the reference client, vendored (see fixtures/README.md) so this claim is
executable; ``test_fixture_is_verbatim`` pins it against the reference
tree when present.
"""

from __future__ import annotations

import asyncio
import importlib.util
import json
import os
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "reference_client_verbatim.py")
REFERENCE = "/root/reference/traffic_generator/main.py"


def test_fixture_is_verbatim():
    """The vendored client must stay byte-identical to the reference."""
    if not os.path.exists(REFERENCE):
        pytest.skip("reference tree not present")
    with open(FIXTURE, "rb") as f, open(REFERENCE, "rb") as g:
        assert f.read() == g.read(), (
            "fixtures/reference_client_verbatim.py has drifted from the "
            "reference client; re-vendor it byte-for-byte")


def _import_reference_client():
    """Import the verbatim client as a module (``__name__`` !=
    "__main__", so only the classes + module ``config`` are defined —
    the driver block at its line 315 stays ours to invoke)."""
    spec = importlib.util.spec_from_file_location(
        "reference_client_verbatim", FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _start_server():
    """Boot the real HTTP server on a background event loop (the
    verbatim client owns the main thread via ``asyncio.run``); mirrors
    benchmarks/replay.py:start_server at test scale."""
    from aiohttp import web

    from tpu_inference.server.http import build_server

    # Sized for trace1.csv's first rows: prompts clamp to the client's
    # MAX_PROMPT_LEN=1024 byte-tokens + config max_tokens=200 decode.
    # warmup=False keeps the test fast; the committed artifact
    # (benchmarks/results/config0_verbatim_reference_client.json) records
    # a warmup=True run of this same path, so its TTFTs measure serving,
    # not XLA compiles.
    srv = build_server(model="tiny-llama", tokenizer="byte", warmup=False,
                       page_size=16, num_pages=448, max_pages_per_seq=128,
                       max_batch_size=4, prefill_buckets=(256, 1024))
    loop = asyncio.new_event_loop()
    ready = threading.Event()
    boot_err: list = []
    state: dict = {}

    def run():
        asyncio.set_event_loop(loop)
        try:
            runner = web.AppRunner(srv.make_app())
            loop.run_until_complete(runner.setup())
            # Port 0 (race-free pick, same as tests/test_harness.py).
            site = web.TCPSite(runner, "127.0.0.1", 0)
            loop.run_until_complete(site.start())
            state["runner"] = runner
            state["port"] = site._server.sockets[0].getsockname()[1]
        except BaseException as e:
            boot_err.append(e)
            ready.set()
            return
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=run, name="verbatim-server", daemon=True)
    t.start()
    assert ready.wait(timeout=120), "server failed to start"
    if boot_err:
        raise boot_err[0]

    def stop():
        # Release the socket + engine before the rest of the session.
        asyncio.run_coroutine_threadsafe(
            state["runner"].cleanup(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=30)

    return state["port"], stop


# The per-request field set the reference writes to logs/log.json
# (reference: traffic_generator/main.py:274-289 — number_of_input_tokens
# at issue time, the TraceConfig hook at 206, the tail fields at 274-277).
REFERENCE_LOG_FIELDS = {
    "number_of_input_tokens",
    "request_start_time",
    "response_headers_received_time",
    "first_token_arrive_time",
    "response_end_time",
    "scheduled_start_time",
    "success",
}

N_TRACE = 6


def test_verbatim_reference_client_replays_unchanged(tmp_path):
    mod = _import_reference_client()
    port, stop = _start_server()
    log_path = tmp_path / "log.json"
    try:
        # The ONLY permitted change: retarget the module-level config
        # dict (url was a hardcoded LAN address, reference main.py:306).
        mod.config.update({
            "trace_path": os.path.join(REPO, "data", "trace1.csv"),
            "data_path": os.path.join(REPO, "data", "conversations.json"),
            "max_trace": N_TRACE,
            "url": f"http://127.0.0.1:{port}/api/generate",
            "model": "tiny-llama",
            "save_log": True,
            "log_path": str(log_path),
        })

        # Statement-for-statement, the client's own __main__ block
        # (reference main.py:315-343, commented-out lines elided).
        data = mod.DataLoader().get_data_from_path(
            data_path=mod.config["data_path"])
        schedule = mod.Scheduler().get_schedule_from_trace(
            trace_path=mod.config["trace_path"],
            max_trace=mod.config["max_trace"])
        logger = mod.MetricCollector()
        # Running as __main__ would bind ``logger`` as a module global
        # (its exception tracer at line 220 reads it that way).
        mod.logger = logger
        generator = mod.TrafficGenerator(data=data, schedule=schedule,
                                         config=mod.config, logger=logger)
        generator.start_profile()
        logger.save(path=mod.config["log_path"])
    finally:
        stop()

    # The artifact the reference ships (logs/log.json): int query ids
    # serialize as string keys, one record per trace row.
    saved = json.loads(log_path.read_text())
    assert set(saved) == {str(i) for i in range(N_TRACE)}
    for qid, rec in saved.items():
        assert set(rec) == REFERENCE_LOG_FIELDS, (
            f"query {qid}: log schema mismatch: {sorted(rec)}")
        assert rec["success"] is True, f"query {qid} failed"
        # Causal ordering, and the deferred-header TTFT contract: the
        # server releases headers with the first token, never before
        # the request was sent.
        assert (rec["scheduled_start_time"] <= rec["request_start_time"]
                <= rec["response_headers_received_time"]
                <= rec["first_token_arrive_time"]
                <= rec["response_end_time"])
        assert rec["number_of_input_tokens"] > 0
