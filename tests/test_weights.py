"""Streaming safetensors loader vs the in-memory converter oracle.

``convert_state_dict`` (exercised against HF in test_model_parity.py) is
the correctness reference; ``load_checkpoint`` must produce the identical
pytree while reading from a sharded on-disk checkpoint — unsharded, and
streamed directly into a TP layout via make_array_from_callback.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_inference import config as cfgs
from tpu_inference.models import weights

safetensors = pytest.importorskip("safetensors")
from safetensors.numpy import save_file  # noqa: E402


def _random_llama_sd(cfg, rng):
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    sd = {"model.embed_tokens.weight": rng.standard_normal((v, d)),
          "model.norm.weight": rng.standard_normal((d,)),
          "lm_head.weight": rng.standard_normal((v, d))}
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        sd.update({
            p + "input_layernorm.weight": rng.standard_normal((d,)),
            p + "self_attn.q_proj.weight": rng.standard_normal((hq * hd, d)),
            p + "self_attn.k_proj.weight": rng.standard_normal((hkv * hd, d)),
            p + "self_attn.v_proj.weight": rng.standard_normal((hkv * hd, d)),
            p + "self_attn.o_proj.weight": rng.standard_normal((d, hq * hd)),
            p + "post_attention_layernorm.weight": rng.standard_normal((d,)),
            p + "mlp.gate_proj.weight": rng.standard_normal((f, d)),
            p + "mlp.up_proj.weight": rng.standard_normal((f, d)),
            p + "mlp.down_proj.weight": rng.standard_normal((d, f)),
        })
        if cfg.qkv_bias:
            sd.update({
                p + "self_attn.q_proj.bias": rng.standard_normal((hq * hd,)),
                p + "self_attn.k_proj.bias": rng.standard_normal((hkv * hd,)),
                p + "self_attn.v_proj.bias": rng.standard_normal((hkv * hd,)),
            })
    return {k: a.astype(np.float32) for k, a in sd.items()}


def _write_sharded(sd, path, n_shards=3):
    """Split a state dict across n_shards files + an HF index.json."""
    keys = sorted(sd)
    weight_map = {}
    for s in range(n_shards):
        part = {k: sd[k] for k in keys[s::n_shards]}
        fname = f"model-{s:05d}-of-{n_shards:05d}.safetensors"
        save_file(part, os.path.join(path, fname))
        weight_map.update({k: fname for k in part})
    with open(os.path.join(path, "model.safetensors.index.json"), "w") as f:
        json.dump({"weight_map": weight_map}, f)


def _assert_tree_equal(got, want):
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a, np.float32), np.asarray(b, np.float32)), got, want)


def test_load_checkpoint_matches_converter(tmp_path):
    cfg = cfgs.tiny_llama(vocab_size=128)
    sd = _random_llama_sd(cfg, np.random.default_rng(0))
    _write_sharded(sd, str(tmp_path))

    want = weights.convert_state_dict(cfg, sd)
    got = weights.load_checkpoint(cfg, str(tmp_path))
    _assert_tree_equal(got, want)


def test_load_checkpoint_qwen2_biases(tmp_path):
    """Qwen2 plan: the q/k/v biases stream (and TP-shard) like weights."""
    from tpu_inference.parallel import shardings as shd
    from tpu_inference.parallel.mesh import build_mesh

    cfg = cfgs.tiny_qwen2(vocab_size=128)
    sd = _random_llama_sd(cfg, np.random.default_rng(4))
    _write_sharded(sd, str(tmp_path))

    want = weights.convert_state_dict(cfg, sd)
    got = weights.load_checkpoint(cfg, str(tmp_path))
    assert "bq" in got["blocks"]
    _assert_tree_equal(got, want)

    mesh = build_mesh(cfgs.ParallelConfig(tp=2))
    shardings = shd.param_shardings(cfg, mesh)
    got_tp = weights.load_checkpoint(cfg, str(tmp_path), shardings=shardings)
    _assert_tree_equal(got_tp, want)


def test_config_from_hf_qwen2_and_gemma(tmp_path):
    """model_type qwen2 -> qkv_bias (window gated on use_sliding_window);
    model_type gemma -> norm offset, gelu_tanh, embed scale, head_dim."""
    from tpu_inference.models.weights import config_from_hf

    qwen = {"model_type": "qwen2", "vocab_size": 1024, "hidden_size": 128,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "intermediate_size": 256,
            "rope_theta": 1000000.0, "rms_norm_eps": 1e-6,
            "sliding_window": 4096, "use_sliding_window": False,
            "tie_word_embeddings": True}
    (tmp_path / "config.json").write_text(json.dumps(qwen))
    cfg = config_from_hf(str(tmp_path))
    assert cfg.family == "llama" and cfg.qkv_bias
    assert cfg.sliding_window == 0 and cfg.tie_embeddings
    assert cfg.rope_theta == 1000000.0

    # HF windows only layers >= max_window_layers (absent key = HF's
    # default 28, NOT 0); the global-window engine maps the
    # all-or-nothing cases and rejects mixed stacks.
    qwen["use_sliding_window"] = True
    (tmp_path / "config.json").write_text(json.dumps(qwen))
    # absent max_window_layers -> 28 >= 2 layers: full attention.
    assert config_from_hf(str(tmp_path)).sliding_window == 0
    qwen["max_window_layers"] = 0        # every layer windowed
    (tmp_path / "config.json").write_text(json.dumps(qwen))
    assert config_from_hf(str(tmp_path)).sliding_window == 4096
    qwen["max_window_layers"] = 2        # == num_hidden_layers: full attn
    (tmp_path / "config.json").write_text(json.dumps(qwen))
    assert config_from_hf(str(tmp_path)).sliding_window == 0
    qwen["max_window_layers"] = 1        # mixed: unsupported
    (tmp_path / "config.json").write_text(json.dumps(qwen))
    with pytest.raises(ValueError, match="max_window_layers"):
        config_from_hf(str(tmp_path))
    qwen["sliding_window"] = None        # no window at all: mixed is moot
    (tmp_path / "config.json").write_text(json.dumps(qwen))
    assert config_from_hf(str(tmp_path)).sliding_window == 0
    del qwen["max_window_layers"]

    gemma = {"model_type": "gemma", "vocab_size": 2048, "hidden_size": 128,
             "num_hidden_layers": 2, "num_attention_heads": 4,
             "num_key_value_heads": 1, "intermediate_size": 512,
             "head_dim": 48, "rms_norm_eps": 1e-6,
             "hidden_act": "gelu_pytorch_tanh"}
    (tmp_path / "config.json").write_text(json.dumps(gemma))
    cfg = config_from_hf(str(tmp_path))
    assert cfg.family == "llama" and cfg.norm_offset == 1.0
    assert cfg.hidden_act == "gelu_tanh" and cfg.embed_scale
    assert cfg.head_dim == 48 and cfg.tie_embeddings  # gemma default ties


def _fuse_phi3(cfg, sd):
    """Rewrite a split llama state dict into Phi-3's fused layout."""
    fused = {k: v for k, v in sd.items()
             if "q_proj" not in k and "k_proj" not in k
             and "v_proj" not in k and "gate_proj" not in k
             and "up_proj" not in k}
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        fused[p + "self_attn.qkv_proj.weight"] = np.concatenate(
            [sd[p + f"self_attn.{w}_proj.weight"] for w in "qkv"], axis=0)
        fused[p + "mlp.gate_up_proj.weight"] = np.concatenate(
            [sd[p + "mlp.gate_proj.weight"],
             sd[p + "mlp.up_proj.weight"]], axis=0)
    return fused


def test_load_checkpoint_phi3_fused_split(tmp_path):
    """Phi-3 fused qkv_proj / gate_up_proj checkpoints produce the exact
    pytree a split checkpoint would — eager converter, streaming loader,
    and streaming straight into a TP layout (row-range reads compose with
    device-slab reads)."""
    from tpu_inference.parallel import shardings as shd
    from tpu_inference.parallel.mesh import build_mesh

    cfg = cfgs.tiny_phi3(vocab_size=128)
    assert cfg.n_heads != cfg.n_kv_heads  # GQA: unequal q/k/v row spans
    sd_split = _random_llama_sd(cfg, np.random.default_rng(7))
    sd = _fuse_phi3(cfg, sd_split)
    assert "model.layers.0.self_attn.qkv_proj.weight" in sd
    _write_sharded(sd, str(tmp_path))

    want = weights.convert_state_dict(cfg, sd_split)  # split-layout oracle
    _assert_tree_equal(weights.convert_state_dict(cfg, sd), want)
    _assert_tree_equal(weights.load_checkpoint(cfg, str(tmp_path)), want)

    mesh = build_mesh(cfgs.ParallelConfig(tp=2))
    shardings = shd.param_shardings(cfg, mesh)
    got_tp = weights.load_checkpoint(cfg, str(tmp_path), shardings=shardings)
    _assert_tree_equal(got_tp, want)


def test_config_from_hf_phi3(tmp_path):
    """model_type phi3 -> llama family + sliding window; LongRoPE
    (rope_scaling) checkpoints are rejected with a clear error."""
    from tpu_inference.models.weights import config_from_hf

    phi = {"model_type": "phi3", "vocab_size": 32064, "hidden_size": 3072,
           "num_hidden_layers": 32, "num_attention_heads": 32,
           "num_key_value_heads": 32, "intermediate_size": 8192,
           "rope_theta": 10000.0, "rms_norm_eps": 1e-5,
           "sliding_window": 2047, "max_position_embeddings": 4096,
           "rope_scaling": None, "tie_word_embeddings": False}
    (tmp_path / "config.json").write_text(json.dumps(phi))
    cfg = config_from_hf(str(tmp_path))
    assert cfg.family == "llama" and not cfg.qkv_bias
    assert cfg.sliding_window == 2047 and not cfg.tie_embeddings
    assert cfg.d_ff == 8192 and cfg.max_seq_len == 4096

    phi["rope_scaling"] = {"type": "longrope",
                           "short_factor": [1.0], "long_factor": [1.0]}
    (tmp_path / "config.json").write_text(json.dumps(phi))
    with pytest.raises(ValueError, match="LongRoPE"):
        config_from_hf(str(tmp_path))


def test_config_from_hf_rope_scaling(tmp_path):
    """rope_scaling "llama3" (Llama-3.1) parses into RopeScaling; yarn &
    co. fail loudly (silently ignoring a rescale serves a different
    model); null and "default" mean vanilla rope."""
    from tpu_inference.models.weights import config_from_hf

    base = {"model_type": "llama", "vocab_size": 1024, "hidden_size": 128,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "intermediate_size": 256,
            "rope_theta": 500000.0, "rope_scaling": None}
    (tmp_path / "config.json").write_text(json.dumps(base))
    assert config_from_hf(str(tmp_path)).rope_scaling is None

    base["rope_scaling"] = {"rope_type": "llama3", "factor": 8.0,
                            "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                            "original_max_position_embeddings": 8192}
    (tmp_path / "config.json").write_text(json.dumps(base))
    rs = config_from_hf(str(tmp_path)).rope_scaling
    assert rs == cfgs.RopeScaling(factor=8.0, low_freq_factor=1.0,
                                  high_freq_factor=4.0, original_max_len=8192)

    # Legacy key spelling ("type" instead of "rope_type") still parses.
    base["rope_scaling"] = {"type": "llama3", "factor": 4.0,
                            "low_freq_factor": 1.0, "high_freq_factor": 2.0,
                            "original_max_position_embeddings": 4096}
    (tmp_path / "config.json").write_text(json.dumps(base))
    assert config_from_hf(str(tmp_path)).rope_scaling.factor == 4.0

    base["rope_scaling"] = {"rope_type": "yarn", "factor": 4.0}
    (tmp_path / "config.json").write_text(json.dumps(base))
    with pytest.raises(ValueError, match="yarn"):
        config_from_hf(str(tmp_path))


def test_load_checkpoint_streams_into_tp_layout(tmp_path):
    """Sharded load: every leaf lands with its TP NamedSharding and the
    assembled global values equal the unsharded oracle."""
    from tpu_inference.parallel import shardings as shd
    from tpu_inference.parallel.mesh import build_mesh

    cfg = cfgs.tiny_llama(vocab_size=128)
    sd = _random_llama_sd(cfg, np.random.default_rng(1))
    _write_sharded(sd, str(tmp_path))

    mesh = build_mesh(cfgs.ParallelConfig(tp=2))
    shardings = shd.param_shardings(cfg, mesh)
    got = weights.load_checkpoint(cfg, str(tmp_path), shardings=shardings)

    want = weights.convert_state_dict(cfg, sd)
    _assert_tree_equal(got, want)
    jax.tree.map(lambda a, s: (a.sharding == s or
                               pytest.fail(f"{a.sharding} != {s}")),
                 got, shardings)


def test_load_checkpoint_no_index_single_file(tmp_path):
    """Directories without index.json (single-file checkpoints) scan files."""
    cfg = cfgs.tiny_llama(vocab_size=128)
    sd = _random_llama_sd(cfg, np.random.default_rng(2))
    save_file(sd, os.path.join(str(tmp_path), "model.safetensors"))

    got = weights.load_checkpoint(cfg, str(tmp_path))
    _assert_tree_equal(got, weights.convert_state_dict(cfg, sd))


def test_load_checkpoint_gpt2_and_mixtral(tmp_path):
    """Conv1D (no transpose) and nested expert stacking plans."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    gcfg = cfgs.tiny_gpt2(vocab_size=96)
    hf_cfg = transformers.GPT2Config(
        vocab_size=gcfg.vocab_size, n_positions=gcfg.max_seq_len,
        n_embd=gcfg.d_model, n_layer=gcfg.n_layers, n_head=gcfg.n_heads,
        n_inner=gcfg.d_ff)
    torch.manual_seed(0)
    sd = {k: v.numpy() for k, v in
          transformers.GPT2LMHeadModel(hf_cfg).state_dict().items()
          if not k.endswith(".attn.masked_bias")
          and not k.endswith(".attn.bias") and k != "lm_head.weight"}
    gdir = tmp_path / "gpt2"
    gdir.mkdir()
    _write_sharded(sd, str(gdir), n_shards=2)
    got = weights.load_checkpoint(gcfg, str(gdir))
    _assert_tree_equal(got, weights.convert_state_dict(gcfg, sd))

    mcfg = cfgs.tiny_mixtral(vocab_size=96)
    hf_m = transformers.MixtralConfig(
        vocab_size=mcfg.vocab_size, hidden_size=mcfg.d_model,
        intermediate_size=mcfg.d_ff, num_hidden_layers=mcfg.n_layers,
        num_attention_heads=mcfg.n_heads, num_key_value_heads=mcfg.n_kv_heads,
        num_local_experts=mcfg.n_experts,
        num_experts_per_tok=mcfg.n_experts_per_tok, tie_word_embeddings=False)
    torch.manual_seed(0)
    msd = {k: v.numpy() for k, v in
           transformers.MixtralForCausalLM(hf_m).state_dict().items()}
    mdir = tmp_path / "mixtral"
    mdir.mkdir()
    _write_sharded(msd, str(mdir), n_shards=2)
    got = weights.load_checkpoint(mcfg, str(mdir))
    _assert_tree_equal(got, weights.convert_state_dict(mcfg, msd))


def test_load_checkpoint_quantizes_at_load(tmp_path):
    """quant="int8": matmul weights come back as QuantizedArray leaves,
    numerically equal to quantizing the full-precision load afterwards
    (but without ever materializing the whole bf16 tree)."""
    from tpu_inference.models.quant import QuantizedArray, quantize_array

    cfg = cfgs.tiny_llama()
    sd = _random_llama_sd(cfg, np.random.default_rng(5))
    _write_sharded(sd, str(tmp_path))

    full = weights.load_checkpoint(cfg, str(tmp_path))
    got = weights.load_checkpoint(cfg, str(tmp_path), quant="int8")
    assert isinstance(got["blocks"]["wq"], QuantizedArray)
    assert not isinstance(got["embed"], QuantizedArray)
    want = quantize_array(full["blocks"]["wq"])
    np.testing.assert_array_equal(np.asarray(got["blocks"]["wq"].q),
                                  np.asarray(want.q))
    np.testing.assert_allclose(np.asarray(got["blocks"]["wq"].scale),
                               np.asarray(want.scale), rtol=1e-6)
    # Norm/embed leaves untouched.
    np.testing.assert_array_equal(np.asarray(got["embed"]),
                                  np.asarray(full["embed"]))


def test_orbax_roundtrip_quantized_params(tmp_path):
    """Orbax save/restore preserves QuantizedArray trees (int8 codes +
    scales survive as pytree leaves) — checkpoint/resume works for a
    quantized deployment without re-quantizing from the HF source."""
    from tpu_inference.models.quant import QuantizedArray, quantize_params
    from tpu_inference.models.registry import build_model
    from tpu_inference.models.weights import load_native, save_native

    cfg = cfgs.tiny_llama()
    params, _ = build_model(cfg, seed=3)
    qp = quantize_params(params)
    path = str(tmp_path / "native-q")
    save_native(qp, path)
    restored = load_native(path, qp)
    assert isinstance(restored["blocks"]["wq"], QuantizedArray)
    assert restored["blocks"]["wq"].q.dtype == jnp.int8
    _assert_tree_equal(restored, qp)
