"""End-to-end test of the multi-host rendezvous path (VERDICT r3 item 6).

``parallel/multihost.py``'s ``initialize()`` was previously verified only
as a single-process no-op. Here two REAL processes rendezvous through
``jax.distributed`` (coordinator on localhost), build the hybrid ICI/DCN
mesh over their combined device set, and run a cross-process psum — the
same control flow a 2-host TPU pod slice uses, on the CPU backend's Gloo
collectives.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_multihost_worker.py")


def test_two_process_rendezvous_mesh_and_psum():
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    coord = f"127.0.0.1:{port}"

    # Subprocesses must dodge the in-process conftest platform override:
    # pin PYTHONPATH to the repo alone (drops any axon site dir) and give
    # each process 2 virtual CPU devices.
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    procs = [subprocess.Popen(
        [sys.executable, WORKER, coord, "2", str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                raise AssertionError(
                    "rendezvous hung: worker never finished")
            assert p.returncode == 0, (
                f"worker failed rc={p.returncode}\n{err.decode()[-2000:]}")
            rec = json.loads(out.decode().splitlines()[-1])
            outs.append(rec)
    finally:
        # One worker failing fast must not orphan the other inside
        # JAX's multi-minute rendezvous retry loop.
        for p in procs:
            if p.poll() is None:
                p.kill()

    for rec in outs:
        assert rec["process_count"] == 2
        assert rec["global_devices"] == 4
        assert rec["mesh_shape"] == {"dp": 2, "tp": 2, "sp": 1}
        # All 16 ones reduced across both processes.
        assert rec["psum"] == 16.0
        assert rec["role"]["local_devices_in_mesh"] == 2
    # Exactly the coordinator process hosts mesh row 0 (the frontend).
    frontend = {rec["pid"]: rec["role"]["hosts_frontend"] for rec in outs}
    assert frontend == {0: True, 1: False}

    # Serving under the hybrid mesh: each process served its own dp
    # replica row (VERDICT r4 item 6) — distinct rows, identical tokens,
    # and both match the unsharded single-process oracle.
    assert sorted(rec["replica_row"] for rec in outs) == [0, 1]
    assert outs[0]["tokens"] == outs[1]["tokens"]
    from tests import _multihost_worker as mw
    from tpu_inference.config import EngineConfig, tiny_llama
    from tpu_inference.engine.engine import InferenceEngine

    oracle = InferenceEngine(tiny_llama(), EngineConfig(**mw.ENGINE_KW),
                             seed=0)
    want = oracle.generate(mw.PROMPTS, max_new_tokens=mw.MAX_NEW)
    assert outs[0]["tokens"] == want
