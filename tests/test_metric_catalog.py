"""Metric-catalog drift gate (README "Observability").

Every ``tpu_inf_*`` series name constructed anywhere in
``tpu_inference/`` must appear in the README's observability catalog,
and every name the README documents must still exist in code — so the
catalog can never silently rot in either direction when a PR adds or
removes metrics. Names are string literals by construction (the
telemetry layer takes the name as the first positional argument), so a
plain literal grep is exhaustive.
"""

import pathlib
import re

ROOT = pathlib.Path(__file__).resolve().parent.parent

# Metric names appear in code only as double-quoted string literals
# (registry.counter("tpu_inf_...", ...) and friends). Help texts and
# CLI help that MENTION a metric by name are fine: they must name a
# real metric, which is exactly what the reverse check enforces.
_CODE_RE = re.compile(r'"(tpu_inf_[a-z0-9_]+)"')
# README mentions names bare, in label-annotated forms
# (tpu_inf_foo{bar=...}), and occasionally with exposition suffixes.
_DOC_RE = re.compile(r"tpu_inf_[a-z0-9_]+")
_EXPOSITION_SUFFIXES = ("_bucket", "_sum", "_count")


def _code_names() -> set:
    names = set()
    for path in (ROOT / "tpu_inference").rglob("*.py"):
        names |= set(_CODE_RE.findall(path.read_text()))
    return names


def _doc_names() -> set:
    names = set()
    for raw in _DOC_RE.findall((ROOT / "README.md").read_text()):
        for suffix in _EXPOSITION_SUFFIXES:
            if raw.endswith(suffix) and raw[: -len(suffix)].count("_") > 2:
                raw = raw[: -len(suffix)]
                break
        names.add(raw)
    return names


def test_every_code_metric_is_documented():
    code, doc = _code_names(), _doc_names()
    assert code, "grep found no metrics — the pattern rotted"
    missing = sorted(code - doc)
    assert not missing, (
        "metrics constructed in tpu_inference/ but absent from the "
        f"README observability catalog: {missing}")


def test_every_documented_metric_exists_in_code():
    code, doc = _code_names(), _doc_names()
    stale = sorted(n for n in doc - code)
    assert not stale, (
        "metrics documented in README but no longer constructed "
        f"anywhere in tpu_inference/: {stale}")


def test_catalog_covers_this_prs_series():
    """The series this PR introduces are present on both sides (a
    tripwire for the greps themselves going blind)."""
    code, doc = _code_names(), _doc_names()
    for name in ("tpu_inf_slo_ttft_seconds", "tpu_inf_slo_tpot_seconds",
                 "tpu_inf_slo_breaches_total", "tpu_inf_build_info"):
        assert name in code and name in doc, name
