"""Metric-catalog drift gate (README "Observability").

Every ``tpu_inf_*`` series name constructed anywhere in
``tpu_inference/`` must appear in the README's observability catalog,
and every name the README documents must still exist in code — so the
catalog can never silently rot in either direction when a PR adds or
removes metrics. Names are string literals by construction (the
telemetry layer takes the name as the first positional argument), so a
plain literal grep is exhaustive.
"""

import pathlib
import re

ROOT = pathlib.Path(__file__).resolve().parent.parent

# Metric names appear in code only as double-quoted string literals
# (registry.counter("tpu_inf_...", ...) and friends). Help texts and
# CLI help that MENTION a metric by name are fine: they must name a
# real metric, which is exactly what the reverse check enforces.
_CODE_RE = re.compile(r'"(tpu_inf_[a-z0-9_]+)"')
# README mentions names bare, in label-annotated forms
# (tpu_inf_foo{bar=...}), and occasionally with exposition suffixes.
_DOC_RE = re.compile(r"tpu_inf_[a-z0-9_]+")
_EXPOSITION_SUFFIXES = ("_bucket", "_sum", "_count")


def _code_names() -> set:
    names = set()
    for path in (ROOT / "tpu_inference").rglob("*.py"):
        names |= set(_CODE_RE.findall(path.read_text()))
    return names


def _doc_names() -> set:
    names = set()
    for raw in _DOC_RE.findall((ROOT / "README.md").read_text()):
        for suffix in _EXPOSITION_SUFFIXES:
            if raw.endswith(suffix) and raw[: -len(suffix)].count("_") > 2:
                raw = raw[: -len(suffix)]
                break
        names.add(raw)
    return names


def test_every_code_metric_is_documented():
    code, doc = _code_names(), _doc_names()
    assert code, "grep found no metrics — the pattern rotted"
    missing = sorted(code - doc)
    assert not missing, (
        "metrics constructed in tpu_inference/ but absent from the "
        f"README observability catalog: {missing}")


def test_every_documented_metric_exists_in_code():
    code, doc = _code_names(), _doc_names()
    stale = sorted(n for n in doc - code)
    assert not stale, (
        "metrics documented in README but no longer constructed "
        f"anywhere in tpu_inference/: {stale}")


def test_catalog_covers_this_prs_series():
    """The series this PR introduces are present on both sides (a
    tripwire for the greps themselves going blind)."""
    code, doc = _code_names(), _doc_names()
    for name in ("tpu_inf_slo_ttft_seconds", "tpu_inf_slo_tpot_seconds",
                 "tpu_inf_slo_breaches_total", "tpu_inf_build_info",
                 "tpu_inf_metrics_render_seconds",
                 "tpu_inf_trace_ring_traces",
                 "tpu_inf_trace_spans_dropped_total"):
        assert name in code and name in doc, name


# ---------------------------------------------------------------------------
# Span-name drift gate: the literals passed to SpanRecorder.add()/
# add_maintenance() across the codebase must agree with the canonical
# telemetry.SPAN_NAMES vocabulary AND with the README span table — in
# both directions — so a new span cannot ship undocumented and a
# documented span cannot outlive its emitter. Several call sites wrap
# the name onto the line after ``add(`` — the regex tolerates that.
_SPAN_ADD_RE = re.compile(r'\.add(?:_maintenance)?\(\s*\n?\s*"([a-z_0-9]+)"')
# README documents spans as table rows: | `name` | emitted by | ...
_SPAN_DOC_RE = re.compile(r"^\|\s*`([a-z_0-9]+)`(?:\s*/\s*`([a-z_0-9]+)`)*",
                          re.MULTILINE)


def _code_span_names() -> set:
    names = set()
    for path in (ROOT / "tpu_inference").rglob("*.py"):
        names |= set(_SPAN_ADD_RE.findall(path.read_text()))
    return names


def _doc_span_names() -> set:
    """Backticked names in README table rows that are span names (a row
    may document two spans: | `drain_export` / `migrate` | ...)."""
    text = (ROOT / "README.md").read_text()
    names = set()
    for line in text.splitlines():
        m = re.match(r"\|\s*`([a-z_0-9]+)`(\s*/\s*`([a-z_0-9]+)`)?\s*\|",
                     line)
        if m:
            names.add(m.group(1))
            if m.group(3):
                names.add(m.group(3))
    return names


def test_span_vocabulary_matches_code():
    from tpu_inference import telemetry
    code = _code_span_names()
    assert code, "span grep found no add() literals — the pattern rotted"
    vocab = set(telemetry.SPAN_NAMES)
    assert code <= vocab, (
        f"spans emitted in code but missing from SPAN_NAMES: "
        f"{sorted(code - vocab)}")
    assert vocab <= code, (
        f"SPAN_NAMES entries no code path emits: {sorted(vocab - code)}")


def test_span_vocabulary_documented():
    from tpu_inference import telemetry
    doc = _doc_span_names()
    vocab = set(telemetry.SPAN_NAMES)
    missing = sorted(vocab - doc)
    assert not missing, (
        f"SPAN_NAMES entries absent from the README span table: {missing}")


# ---------------------------------------------------------------------------
# Debug-endpoint drift gate: every "/debug/<name>" route registered in
# code must be mentioned in the README, and every /debug/ path the
# README documents must still be served.
_ROUTE_RE = re.compile(r'"(/debug/[a-z_]+)"')
_ROUTE_DOC_RE = re.compile(r"/debug/[a-z_]+")


def _code_routes() -> set:
    routes = set()
    for path in (ROOT / "tpu_inference").rglob("*.py"):
        routes |= set(_ROUTE_RE.findall(path.read_text()))
    return routes


def test_every_debug_route_is_documented():
    code = _code_routes()
    doc = set(_ROUTE_DOC_RE.findall((ROOT / "README.md").read_text()))
    assert code, "route grep found no /debug/ literals — pattern rotted"
    missing = sorted(code - doc)
    assert not missing, (
        f"/debug/ routes served but absent from the README: {missing}")
    stale = sorted(doc - code)
    assert not stale, (
        f"/debug/ routes documented in README but not served: {stale}")
