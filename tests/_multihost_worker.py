"""Worker process for tests/test_multihost_2proc.py — NOT a pytest file.

Each of the two worker processes joins the jax.distributed runtime via
``multihost.initialize`` (the rendezvous path under test), builds the
hybrid ICI/DCN mesh over the 4 global CPU devices (2 local to each
process), and runs a real cross-process psum through it. Prints one JSON
line with what this process observed; the parent test asserts on it.
"""

import json
import sys

import jax

# Persistent XLA compilation cache, same knobs as the suite (this file
# is launched as a bare subprocess, so conftest never runs here; script
# dir is sys.path[0]). The cross-process psum + engine graphs dominate
# this worker's runtime.
import _xla_cache

_xla_cache.enable(jax)

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_inference.config import EngineConfig, ParallelConfig, tiny_llama
from tpu_inference.parallel import multihost

# Shared with the parent test's oracle — drift between worker and oracle
# geometry would fail the token comparison confusingly.
ENGINE_KW = dict(page_size=8, num_pages=32, max_pages_per_seq=4,
                 max_batch_size=2, prefill_buckets=(16,))
PROMPTS = [[1, 2, 3, 4, 5], [9, 8, 7]]
MAX_NEW = 6


def main() -> None:
    coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    multihost.initialize(coordinator_address=coord, num_processes=nproc,
                         process_id=pid)
    # Idempotency: a second call must be a no-op, not a crash.
    multihost.initialize(coordinator_address=coord, num_processes=nproc,
                         process_id=pid)
    assert jax.process_count() == nproc
    assert len(jax.devices()) == 2 * nproc

    # dp spans the two processes (the DCN-like boundary), tp stays within
    # a process — the serving layout build_hybrid_mesh exists for.
    pcfg = ParallelConfig(dp=2, tp=2, sp=1)
    mesh = multihost.build_hybrid_mesh(pcfg, num_slices=2)
    role = multihost.process_local_engine_role(mesh)

    # Cross-process collective through the mesh: every element is 1, so
    # the full psum must see all 16 — impossible without real
    # inter-process reduction over the dp axis.
    sh = NamedSharding(mesh, P("dp", "tp"))
    x = jax.make_array_from_callback(
        (4, 4), sh, lambda idx: np.ones((2, 2), np.float32))
    from tpu_inference.compat import shard_map
    f = jax.jit(shard_map(
        lambda a: jax.lax.psum(jnp.sum(a), ("dp", "tp")),
        mesh=mesh, in_specs=P("dp", "tp"), out_specs=P()))
    psum = float(f(x))

    # A dp-replica SERVING step under the hybrid mesh (VERDICT r4 item
    # 6): each process builds the engine for its own dp row (tp stays on
    # the slice's ICI; DCN carries no serving traffic — the point of dp
    # over DCN) and generates. The parent asserts the two processes'
    # tokens are identical and match an unsharded oracle.
    from tpu_inference.engine.engine import InferenceEngine

    replicas = multihost.replica_meshes(mesh)
    assert len(replicas) == 1, replicas
    ridx, rmesh = replicas[0]
    assert dict(rmesh.shape) == {"dp": 1, "tp": 2, "sp": 1}
    assert all(d in set(jax.local_devices()) for d in rmesh.devices.flat)
    eng = InferenceEngine(tiny_llama(), EngineConfig(**ENGINE_KW),
                          seed=0, mesh=rmesh)
    tokens = eng.generate(PROMPTS, max_new_tokens=MAX_NEW)

    print(json.dumps({"pid": pid, "process_count": jax.process_count(),
                      "global_devices": len(jax.devices()),
                      "mesh_shape": dict(mesh.shape), "psum": psum,
                      "replica_row": ridx, "tokens": tokens,
                      "role": role}), flush=True)


if __name__ == "__main__":
    main()
