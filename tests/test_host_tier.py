"""Tiered KV cache: host-RAM offload, async swap-in, and the invariants
that make it invisible to generation output (README "Tiered KV cache").

The acceptance contract pinned here:
- demote -> promote round-trips are BIT-identical at the pool level,
  for bf16, int8 and nibble-packed int4 layouts alike;
- a digest lives in the HBM table OR the host table, never both (the
  publish path supersedes stale host copies);
- host-pool page/byte accounting never leaks (tests/_leak.py grew the
  host invariant and every churn test here runs it);
- with zero host capacity, eviction degrades to the classic
  free-on-evict behavior byte-for-byte;
- a preempted-then-resumed sequence RESTORES its pages from the host
  tier instead of re-prefilling when capacity allows (swap-in-resume),
  with byte-identical greedy output;
- the queue-wait prefetch promotes host pages into cache-owned device
  pages before admission, so the prefill sees plain HBM hits;
- evict() pops victims from the evictable-ordered table (oldest
  released first) and never touches share-pinned entries — the
  O(table)-scan fix, pinned under churn;
- one _chain_hashes pass per routed request (route -> admit -> publish
  share the digests instead of re-hashing three times).
"""

import threading

import numpy as np
import pytest

from tests._leak import assert_pool_clean
from tpu_inference import config as cfgs
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.kv_cache import HostPagePool, PageAllocator
from tpu_inference.engine.prefix_cache import (PrefixCache, _chain_hashes,
                                               extend_chain_hashes)

MODEL = cfgs.tiny_llama(vocab_size=256)


def _ecfg(**kw):
    base = dict(page_size=8, num_pages=14, max_pages_per_seq=8,
                max_batch_size=2, prefill_buckets=(16, 32, 64),
                decode_steps_per_call=4, host_cache_pages=64)
    base.update(kw)
    return cfgs.EngineConfig(**base)


# ---------------------------------------------------------- pool round-trip


@pytest.mark.parametrize("kv_quant", ["none", "int8", "int4"])
def test_offload_restore_roundtrip_bit_identical(kv_quant):
    """Pool bytes written to pages, offloaded to host, and restored into
    DIFFERENT page ids must compare bit-equal in the stored layout —
    including int8 codes + scales and uint8 nibble-packed int4."""
    ecfg = cfgs.EngineConfig(page_size=4, num_pages=16, max_pages_per_seq=4,
                             max_batch_size=2, kv_quant=kv_quant)
    kv = kvc.alloc_kv_pages(MODEL, ecfg)
    rng = np.random.default_rng(0)
    # Write two pages of sequence 0 (pages 1, 2) with random K/V.
    bt = np.zeros((1, 4), np.int32)
    bt[0, :2] = [1, 2]
    s = 8                                    # 2 full pages of 4
    positions = np.arange(s, dtype=np.int32)[None]
    valid = np.ones((1, s), bool)
    slots = kvc.slot_mapping(np.asarray(bt), positions, valid, 4)
    k_new = rng.standard_normal((1, s, MODEL.n_kv_heads, MODEL.head_dim),
                                np.float32)
    v_new = rng.standard_normal((1, s, MODEL.n_kv_heads, MODEL.head_dim),
                                np.float32)
    for layer in range(MODEL.n_layers):
        kv = kvc.write_kv(kv, layer, k_new * (layer + 1), v_new, slots)

    host = kvc.offload_pages(kv, [1, 2])
    assert len(host) == 2
    # Restore into fresh page ids 5, 6 and compare the stored bytes.
    kv = kvc.restore_pages(kv, [5, 6], host)
    for src, dst in ((1, 5), (2, 6)):
        np.testing.assert_array_equal(np.asarray(kv.k[:, src]),
                                      np.asarray(kv.k[:, dst]))
        np.testing.assert_array_equal(np.asarray(kv.v[:, src]),
                                      np.asarray(kv.v[:, dst]))
        if kv.quantized:
            np.testing.assert_array_equal(np.asarray(kv.k_scale[:, src]),
                                          np.asarray(kv.k_scale[:, dst]))
            np.testing.assert_array_equal(np.asarray(kv.v_scale[:, src]),
                                          np.asarray(kv.v_scale[:, dst]))


# ---------------------------------------------------------- unit: demote


def _fake_offload(pages):
    """Standalone offload_fn: one tiny distinct array per page so byte
    accounting is exercised without a device pool."""
    return [kvc.HostKVPage(k=np.full((1, 2), p, np.int8),
                           v=np.full((1, 2), -p, np.int8))
            for p in pages]


def test_evict_demotes_and_lookup_restores_ownership():
    alloc = PageAllocator(16)
    pool = HostPagePool(8)
    cache = PrefixCache(alloc, page_size=4, host_pool=pool,
                        offload_fn=_fake_offload)
    tokens = list(range(12))                 # 3 full pages
    pages = alloc.allocate(3)
    cache.insert(tokens, pages)
    alloc.free(pages)                        # cache holds the only refs
    assert cache.evict(3) == 3               # all demote
    assert alloc.num_free == 15 and len(cache) == 0
    assert pool.used == 3 and len(cache._host) == 3

    got, host_entries, n = cache.lookup(tokens)
    assert n == 12 and got == [None, None, None]
    assert [i for i, _, _ in host_entries] == [0, 1, 2]
    # Host entries left the tier (ownership passed to the caller).
    assert pool.used == 0 and len(cache._host) == 0
    # A failed restore returns them.
    cache.readmit_host([(d, e) for _, d, e in host_entries])
    assert pool.used == 3 and len(cache._host) == 3
    cache.clear()
    assert pool.used == 0 and pool.bytes_resident == 0


def test_readmit_never_exceeds_host_capacity():
    """A failed restore readmits its taken entries — but an intervening
    demote may have refilled the freed slots (evict runs inside the very
    allocation that failed), so readmit drops what no longer fits
    instead of blowing past the RAM cap."""
    alloc = PageAllocator(32)
    pool = HostPagePool(2)
    cache = PrefixCache(alloc, page_size=4, host_pool=pool,
                        offload_fn=_fake_offload)
    a = list(range(8))
    pa = alloc.allocate(2)
    cache.insert(a, pa)
    alloc.free(pa)
    cache.evict(2)                           # host full: a0, a1
    _, taken_entries, _ = cache.lookup(a)    # pops both (used = 0)
    taken = [(d, e) for _, d, e in taken_entries]
    b = list(range(40, 48))                  # refill host via a demote
    pb = alloc.allocate(2)
    cache.insert(b, pb)
    alloc.free(pb)
    cache.evict(2)                           # host full again: b0, b1
    assert pool.used == 2
    cache.readmit_host(taken)                # nothing fits — dropped
    assert pool.used == 2 and len(cache._host) == 2
    assert pool.bytes_resident == sum(e.nbytes
                                      for e in cache._host.values())
    cache.clear()
    assert pool.used == 0


def test_zero_host_capacity_degrades_to_free_on_evict():
    alloc = PageAllocator(16)
    pool = HostPagePool(0)
    cache = PrefixCache(alloc, page_size=4, host_pool=pool,
                        offload_fn=_fake_offload)
    tokens = list(range(8))
    pages = alloc.allocate(2)
    cache.insert(tokens, pages)
    alloc.free(pages)
    assert cache.evict(2) == 2
    assert alloc.num_free == 15
    assert pool.used == 0 and pool.offloaded_total == 0
    got, host_entries, n = cache.lookup(tokens)
    assert n == 0 and got == [] and host_entries == []


def test_second_tier_eviction_when_host_runs_dry():
    alloc = PageAllocator(32)
    pool = HostPagePool(2)                   # room for two pages only
    cache = PrefixCache(alloc, page_size=4, host_pool=pool,
                        offload_fn=_fake_offload)
    a, b = list(range(8)), list(range(50, 58))
    pa, pb = alloc.allocate(2), alloc.allocate(2)
    cache.insert(a, pa)
    cache.insert(b, pb)
    alloc.free(pa + pb)
    assert cache.evict(2) == 2               # a's pages demote (fills host)
    assert pool.used == 2
    assert cache.evict(2) == 2               # b demotes; a drops (2nd tier)
    assert pool.used == 2 and pool.evicted_total == 2
    assert cache.peek(a) == 0 and cache.peek(b) == 2


def test_oversized_victim_batch_never_flushes_host_tier():
    """A demote batch larger than the whole host capacity keeps the
    newest capacity-many victims and must not drop unrelated resident
    entries beyond what it can actually use."""
    alloc = PageAllocator(32)
    pool = HostPagePool(2)
    cache = PrefixCache(alloc, page_size=4, host_pool=pool,
                        offload_fn=_fake_offload)
    resident = list(range(900, 908))         # 2 pages already resident
    pr = alloc.allocate(2)
    cache.insert(resident, pr)
    alloc.free(pr)
    cache.evict(2)
    assert pool.used == 2 and cache.peek(resident) == 2
    big = list(range(100, 124))              # 6 pages — 3x host capacity
    pb = alloc.allocate(6)
    cache.insert(big, pb)
    alloc.free(pb)
    assert cache.evict(6) == 6
    # Host holds exactly capacity pages: the NEWEST two of the batch.
    assert pool.used == 2
    hbm, host = cache.peek_digests_tiered(
        extend_chain_hashes(big, 4, []))
    assert (hbm, host) == (0, 0)             # prefix broken: pages 0-3 gone
    assert len(cache._host) == 2
    cache.clear()


def test_tier_invariant_publish_supersedes_host():
    """A fresh HBM publish of a digest the host tier still holds must
    drop the host copy — a digest never lives in both tiers."""
    alloc = PageAllocator(16)
    pool = HostPagePool(8)
    cache = PrefixCache(alloc, page_size=4, host_pool=pool,
                        offload_fn=_fake_offload)
    tokens = list(range(8))
    pages = alloc.allocate(2)
    cache.insert(tokens, pages)
    alloc.free(pages)
    cache.evict(2)                           # both pages now host-tier
    assert len(cache._host) == 2
    # A sequence that recomputed the same prefix publishes new pages.
    fresh = alloc.allocate(2)
    cache.insert(tokens, fresh)
    assert not (set(cache._host) & set(cache._table))
    assert pool.used == 0                    # superseded copies dropped
    assert pool.evicted_total == 2
    alloc.free(fresh)
    cache.clear()


def test_evictable_order_skips_pinned_entries():
    """The O(table)-scan fix: evict() consumes the evictable-ordered
    table (oldest released first) and never walks share-pinned entries.
    Pinned behavior: a pinned digest survives any evict; once released
    it becomes the NEWEST evictable entry."""
    alloc = PageAllocator(32)
    cache = PrefixCache(alloc, page_size=4)
    streams = [list(range(i * 10, i * 10 + 4)) for i in range(4)]
    pages = {}
    for i, s in enumerate(streams):
        pg = alloc.allocate(1)
        cache.insert(s, pg)
        pages[i] = pg[0]
    # Streams 0..3 inserted in order; keep 0 pinned (seq still running),
    # release 1..3 in the order 2, 3, 1.
    for i in (2, 3, 1):
        alloc.free([pages[i]])
    alloc.free([])                           # no-op
    assert cache.evictable == 3
    assert list(cache._evict_order) == [
        _chain_hashes(streams[i], 4)[0] for i in (2, 3, 1)]
    # Evict 2: takes 2 then 3 (release order), never pinned 0.
    assert cache.evict(2) == 2
    assert cache.peek(streams[0]) == 1       # pinned survivor
    assert cache.peek(streams[1]) == 1
    assert cache.peek(streams[2]) == 0 and cache.peek(streams[3]) == 0
    # Releasing the pin makes stream 0 the newest evictable entry.
    alloc.free([pages[0]])
    assert list(cache._evict_order) == [
        _chain_hashes(streams[i], 4)[0] for i in (1, 0)]
    assert cache.evict(10) == 2
    assert len(cache) == 0
    assert alloc.num_free == 31


def test_evictable_order_tracks_churn(setup_engine=None):
    """Interleaved admit/release/evict churn keeps the evictable-ordered
    table exactly consistent with the allocator's counter."""
    eng = InferenceEngine(MODEL, _ecfg(num_pages=20), seed=0)
    rng = np.random.default_rng(3)
    for i in range(12):
        prompt = rng.integers(0, 256, 17 + (i % 5)).tolist()
        eng.generate([prompt], max_new_tokens=4)
        assert len(eng.prefix_cache._evict_order) == \
            eng.allocator.evictable_count
        for d in eng.prefix_cache._evict_order:
            page = eng.prefix_cache._table[d]
            assert eng.allocator.refcount(page) == 1
        assert not (set(eng.prefix_cache._host)
                    & set(eng.prefix_cache._table))
    assert_pool_clean(eng)


# ------------------------------------------------------- engine integration


def test_generation_byte_identical_under_tier_churn():
    """Working set far beyond the HBM pool: outputs must match a cold
    engine exactly while pages demote and restore underneath."""
    eng = InferenceEngine(MODEL, _ecfg(), seed=0)
    cold = InferenceEngine(MODEL, _ecfg(num_pages=64, host_cache_pages=0,
                                        enable_prefix_cache=False), seed=0)
    prompts = [list(range(i * 7, i * 7 + 30)) for i in range(5)]
    want = [cold.generate([p], max_new_tokens=6)[0] for p in prompts]
    for _ in range(3):
        for i, p in enumerate(prompts):
            assert eng.generate([p], max_new_tokens=6)[0] == want[i]
    st = eng.prefix_cache.stats()
    assert st["offloaded_pages"] > 0, "pool never pressured into demotes"
    assert st["restored_pages"] > 0, "returning prompts never swapped in"
    assert_pool_clean(eng)


def test_preempt_then_swap_in_resume_byte_identical():
    """The acceptance pin: a preempted sequence whose published pages
    demoted to host RESTORES them at resume (swap-in-resume) instead of
    re-prefilling, with byte-identical greedy output."""
    prompt = list(range(1, 13))
    baseline = InferenceEngine(
        MODEL, _ecfg(num_pages=40, max_pages_per_seq=16, max_batch_size=4,
                     host_cache_pages=0), seed=0).generate(
        [prompt], max_new_tokens=16)[0]

    eng = InferenceEngine(
        MODEL, _ecfg(num_pages=40, max_pages_per_seq=16, max_batch_size=4,
                     admission="optimistic"), seed=0)
    seq = Sequence(request_id=0, prompt_tokens=list(prompt),
                   max_new_tokens=16)
    eng.prefill(seq)
    while len(seq.generated) < 6:
        eng.decode_steps(max_steps=1)
    eng.preempt(seq)
    assert eng.take_preempted() == [seq]
    # The pressure that preempted it now evicts the whole HBM cache —
    # with the host tier, the published pages survive as host copies.
    assert eng.prefix_cache.evict(100) > 0
    assert len(eng.prefix_cache) == 0
    assert eng.prefix_cache.stats()["host_entries"] > 0

    eng.prefill(seq)                         # resume
    assert seq.host_restored_pages > 0, \
        "resume re-prefilled instead of restoring from the host tier"
    assert seq.cached_tokens > 0
    assert eng.swap_in_resumes == 1
    while eng.active_sequences():
        eng.decode_steps()
    assert seq.generated == baseline
    eng.release(seq)
    assert_pool_clean(eng)


def test_queue_wait_prefetch_promotes_host_pages():
    """prefetch_host_hits restores a WAITING request's host pages into
    cache-owned device pages, so the later prefill sees HBM hits (no
    swap inside TTFT) — and the promoted pages stay ordinary evictable
    entries."""
    eng = InferenceEngine(MODEL, _ecfg(num_pages=24, max_pages_per_seq=8),
                          seed=0)
    prompt = list(range(40, 70))             # 3 full pages of 8
    want = eng.generate([prompt], max_new_tokens=6)[0]
    assert eng.prefix_cache.evict(100) > 0   # demote everything
    assert eng.prefix_cache.stats()["host_entries"] > 0

    seq = Sequence(request_id=1, prompt_tokens=list(prompt),
                   max_new_tokens=6)
    promoted = eng.prefetch_host_hits(seq)
    assert promoted >= 3
    assert seq.host_prefetched
    assert eng.prefetch_host_hits(seq) == 0  # idempotent
    assert eng.allocator.evictable_count >= promoted
    # The prefill now hits HBM — no further restore needed.
    eng.prefill(seq)
    assert seq.cached_tokens >= promoted * 8 - 8
    assert seq.host_restored_pages == 0
    while eng.active_sequences():
        eng.decode_steps()
    assert seq.generated == want
    eng.release(seq)
    assert_pool_clean(eng)


def test_prefetch_without_free_pages_retries_later():
    """Prefetch never evicts to make room: with zero free pages it
    leaves the request eligible and succeeds on a later pass."""
    eng = InferenceEngine(MODEL, _ecfg(num_pages=12, max_pages_per_seq=8),
                          seed=0)
    prompt = list(range(40, 70))
    eng.generate([prompt], max_new_tokens=6)
    eng.prefix_cache.evict(100)
    assert eng.prefix_cache.stats()["host_entries"] > 0
    # Exhaust the free list (the cache was fully demoted, so free pages
    # are plain allocations).
    hold = eng.allocator.allocate(eng.allocator.num_free)
    seq = Sequence(request_id=2, prompt_tokens=list(prompt),
                   max_new_tokens=4)
    assert eng.prefetch_host_hits(seq) == 0
    assert not seq.host_prefetched           # still eligible
    eng.allocator.free(hold)
    assert eng.prefetch_host_hits(seq) > 0
    eng.prefix_cache.clear()
    assert_pool_clean(eng)


# -------------------------------------------------- scheduler / routing


def test_one_hash_pass_per_routed_request(monkeypatch):
    """The triple-hash fix: a request routed by the dp group hashes its
    prompt exactly once — the router's digest list rides the Sequence
    through admission (lookup) and publish (insert extends the chain
    instead of re-hashing the prefix)."""
    from tpu_inference.server import replicas as repl_mod
    from tpu_inference.engine import prefix_cache as pc_mod
    from tpu_inference.server.replicas import EngineGroup

    calls = {"n": 0}
    real = pc_mod._chain_hashes

    def counting(tokens, page_size):
        calls["n"] += 1
        return real(tokens, page_size)

    monkeypatch.setattr(pc_mod, "_chain_hashes", counting)
    monkeypatch.setattr(repl_mod, "_chain_hashes", counting)

    ecfg = _ecfg(num_pages=64, max_pages_per_seq=8, max_batch_size=2)
    engines = [InferenceEngine(MODEL, ecfg, seed=0) for _ in range(2)]
    group = EngineGroup(engines, cfgs.ServerConfig()).start()
    try:
        for rid in range(3):
            prompt = list(range(rid, rid + 30))
            ev = threading.Event()
            before = calls["n"]
            seq = Sequence(request_id=rid, prompt_tokens=prompt,
                           max_new_tokens=4)
            group.submit(seq, lambda s, t: None,
                         lambda s, ev=ev: ev.set())
            assert ev.wait(60)
            # Exactly one hash pass end to end: route -> admit -> publish.
            assert calls["n"] == before + 1, \
                f"request {rid} hashed its prompt {calls['n'] - before}x"
    finally:
        group.stop(drain=True, timeout=10)


def test_router_scores_three_temperatures():
    """HBM-warm > host-warm > cold: with equal load, the router prefers
    the replica holding the prompt in HBM, then the one holding it in
    the host tier, then a cold one (the fourth, fabric-warm temperature
    has its own suite in test_kv_fabric.py; with an empty pool the
    fabric term is zero here)."""
    from tpu_inference.server.replicas import EngineGroup

    ecfg = _ecfg(num_pages=64, max_pages_per_seq=8, max_batch_size=2)
    engines = [InferenceEngine(MODEL, ecfg, seed=0) for _ in range(3)]
    group = EngineGroup(engines, cfgs.ServerConfig())
    prompt = list(range(100, 130))           # 3 full pages

    def run_on(eng):
        eng.generate([prompt], max_new_tokens=4)

    # Replica 0: HBM-warm. Replica 1: host-warm (demoted). Replica 2 cold.
    run_on(engines[0])
    run_on(engines[1])
    engines[1].prefix_cache.evict(100)
    assert engines[1].prefix_cache.stats()["host_entries"] > 0

    seq = Sequence(request_id=9, prompt_tokens=list(prompt),
                   max_new_tokens=4)
    sched, (hbm, host, fab) = group._pick(group.schedulers, seq)
    assert sched is group.schedulers[0] and hbm > 0 and host == 0
    assert fab == 0                          # empty fabric pool
    # Without replica 0, host-warm replica 1 beats cold replica 2.
    seq2 = Sequence(request_id=10, prompt_tokens=list(prompt),
                    max_new_tokens=4)
    sched, (hbm, host, _) = group._pick(group.schedulers[1:], seq2)
    assert sched is group.schedulers[1] and host > 0 and hbm == 0
    # The digests were cached on the sequences (one hash pass).
    assert seq.prefix_digests is not None
    # Zero host weight: host warmth is ignored -> ties break by rotation
    # across (cold) equals, i.e. host replica no longer dominates.
    group.server_cfg = cfgs.ServerConfig(route_host_hit_weight=0.0)
    seq3 = Sequence(request_id=11, prompt_tokens=list(prompt),
                    max_new_tokens=4)
    _, (hbm3, host3, _) = group._pick(group.schedulers[1:], seq3)
    assert hbm3 == 0                         # never misreported as HBM


def test_scheduler_prefetches_during_queue_wait():
    """End to end through the scheduler: a request that must WAIT (slots
    full) gets its host-tier pages promoted while queued, so its prefill
    reports zero swap-ins and warm cached tokens."""
    from tpu_inference.engine.scheduler import EngineScheduler

    ecfg = _ecfg(num_pages=40, max_pages_per_seq=8, max_batch_size=1,
                 host_cache_pages=64)
    eng = InferenceEngine(MODEL, ecfg, seed=0)
    warm_prompt = list(range(40, 70))
    want = eng.generate([warm_prompt], max_new_tokens=6)[0]
    eng.prefix_cache.evict(100)              # demote the conversation
    assert eng.prefix_cache.stats()["host_entries"] > 0

    sched = EngineScheduler(eng).start()
    outs, events = {}, {}
    try:
        # Request A occupies the single slot; B (the warm one) waits.
        for rid, prompt, toks in ((0, list(range(200, 230)), 24),
                                  (1, warm_prompt, 6)):
            ev = threading.Event()
            events[rid] = ev
            sched.submit(
                Sequence(request_id=rid, prompt_tokens=list(prompt),
                         max_new_tokens=toks),
                lambda s, t: outs.setdefault(s.request_id, []).append(t),
                lambda s, ev=ev: ev.set())
        for ev in events.values():
            assert ev.wait(90)
    finally:
        sched.stop(drain=True, timeout=10)
    assert outs[1] == want
    # The wait was long enough for the prefetch to land: the restore
    # happened via prefetch (cache-owned), not inside B's prefill.
    assert eng.prefix_cache.host_pool.restored_total > 0
    assert_pool_clean(eng)
