"""Optimistic admission, watermark-driven preemption, and deterministic
recompute-resume (README "Admission & preemption").

The acceptance contract pinned here:
- optimistic admission charges prompt + headroom, not prompt + max_new;
- under forced pool exhaustion (``chaos_page_pressure``) no request
  deadlocks, errors, or leaks pages — victims preempt, requeue at the
  head, and recompute-resume;
- under greedy decoding a preempted-and-resumed request produces
  byte-identical output to an unpreempted run;
- the starvation guard re-admits a much-preempted request under full
  worst-case reservation and exempts it from further preemption.

Everything runs on CPU: ``chaos_page_pressure`` holds real pages out of
the pool, making exhaustion deterministic without a trace or a TPU.
"""

import threading

import pytest

from tests._leak import assert_pool_clean
from tpu_inference.config import EngineConfig, tiny_llama
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.scheduler import EngineScheduler

MODEL = tiny_llama(vocab_size=128)

PROMPTS = [[1, 2, 3, 4, 5, 6, 7, 8, 9, 10], [11, 12, 13, 14],
           [21, 22, 23, 24, 25, 26], [31, 32, 33]]


def _ecfg(**kw) -> EngineConfig:
    base = dict(page_size=8, num_pages=40, max_pages_per_seq=16,
                max_batch_size=4, prefill_buckets=(16, 32),
                decode_steps_per_call=4)
    base.update(kw)
    return EngineConfig(**base)


def _run_scheduler(ecfg, max_new=24, prompts=PROMPTS, timeout=60.0):
    """Submit ``prompts`` through a real scheduler; returns (per-request
    token lists, finish reasons, engine) after every request finishes."""
    engine = InferenceEngine(MODEL, ecfg, seed=0)
    sched = EngineScheduler(engine).start()
    outs, reasons, events = {}, {}, []
    try:
        for i, p in enumerate(prompts):
            ev = threading.Event()
            events.append(ev)
            seq = Sequence(request_id=i, prompt_tokens=list(p),
                           max_new_tokens=max_new)
            sched.submit(
                seq,
                lambda s, t: outs.setdefault(s.request_id, []).append(t),
                lambda s, ev=ev: (reasons.__setitem__(s.request_id,
                                                      s.finish_reason),
                                  ev.set()))
        for ev in events:
            assert ev.wait(timeout), "request did not finish (deadlock?)"
    finally:
        sched.stop(drain=True, timeout=10.0)
    return outs, reasons, engine


# ------------------------------------------------------------ admission


def test_optimistic_admission_charges_prompt_plus_headroom():
    ecfg = _ecfg(admission="optimistic", optimistic_headroom_pages=2)
    eng = InferenceEngine(MODEL, ecfg, seed=0)
    seq = Sequence(request_id=0, prompt_tokens=list(range(1, 13)),
                   max_new_tokens=100)
    # Worst case: 12 + 100 tokens = 14 pages, capped at max_pages 16.
    assert eng._pages_reserved(seq) == 14
    # Optimistic: 2 prompt pages + 2 headroom.
    assert eng._pages_for_admission(seq) == 4
    # The starvation guard escalates to the full reservation.
    seq.preemptions = ecfg.preempt_max_per_request
    assert eng._pages_for_admission(seq) == eng._pages_reserved(seq)

    # Reserve mode never charges less than worst case.
    eng2 = InferenceEngine(MODEL, _ecfg(), seed=0)
    seq2 = Sequence(request_id=1, prompt_tokens=list(range(1, 13)),
                    max_new_tokens=100)
    assert eng2._pages_for_admission(seq2) == eng2._pages_reserved(seq2)


def test_admission_mode_validated():
    with pytest.raises(ValueError, match="admission"):
        InferenceEngine(MODEL, _ecfg(admission="yolo"), seed=0)


# ----------------------------------------- engine-level recompute-resume


def test_preempt_recompute_resume_token_identical():
    """A sequence preempted mid-decode and re-prefilled resumes its
    token stream exactly (greedy), reusing prefix-cache pages published
    at preemption time."""
    prompt = list(range(1, 13))
    baseline = InferenceEngine(MODEL, _ecfg(), seed=0).generate(
        [prompt], max_new_tokens=16)[0]

    eng = InferenceEngine(MODEL, _ecfg(admission="optimistic"), seed=0)
    seq = Sequence(request_id=0, prompt_tokens=list(prompt),
                   max_new_tokens=16)
    eng.prefill(seq)
    while len(seq.generated) < 6:
        eng.decode_steps(max_steps=1)
    pre_preempt = list(seq.generated)

    eng.preempt(seq)
    assert seq.slot == -1 and not seq.pages and seq.ctx_len == 0
    assert seq.preemptions == 1
    assert seq.generated == pre_preempt          # host state kept
    assert eng.take_preempted() == [seq]
    assert eng.slots == [None] * eng.engine_cfg.max_batch_size

    # Recompute-resume: re-prefill prompt + generated, decode to done.
    eng.prefill(seq)
    # The pages published at preemption serve the resume from cache.
    assert seq.cached_tokens > 0
    assert eng.resumes_total == 1
    while not seq.done:
        eng.decode_steps()
    assert seq.generated == baseline
    assert seq.finish_reason == "length"
    eng.release(seq)
    assert_pool_clean(eng)


def test_double_preemption_still_identical():
    prompt = list(range(40, 52))
    baseline = InferenceEngine(MODEL, _ecfg(), seed=0).generate(
        [prompt], max_new_tokens=20)[0]
    eng = InferenceEngine(MODEL, _ecfg(admission="optimistic"), seed=0)
    seq = Sequence(request_id=0, prompt_tokens=list(prompt),
                   max_new_tokens=20)
    eng.prefill(seq)
    for cut in (5, 11):
        while len(seq.generated) < cut:
            eng.decode_steps(max_steps=1)
        eng.preempt(seq)
        eng.take_preempted()
        eng.prefill(seq)
    while not seq.done:
        eng.decode_steps()
    assert seq.generated == baseline
    assert seq.preemptions == 2 and eng.resumes_total == 2
    eng.release(seq)
    assert_pool_clean(eng)


# ------------------------------------- scheduler path under chaos pressure


@pytest.mark.parametrize("depth", [1, 3])
def test_chaos_page_pressure_preempts_never_fails(depth):
    """With chaos_page_pressure forcing exhaustion, the full scheduler
    path preempts + recompute-resumes: every request finishes cleanly
    (never "oom"/"error"), streams are byte-identical to an unpressured
    reserve run, and the pool returns to fully free."""
    b_outs, b_reasons, b_eng = _run_scheduler(_ecfg())
    assert all(r == "length" for r in b_reasons.values())
    assert_pool_clean(b_eng)

    ecfg = _ecfg(admission="optimistic", optimistic_headroom_pages=1,
                 preempt_watermark_pages=4, chaos_page_pressure=28,
                 decode_pipeline_depth=depth)
    outs, reasons, engine = _run_scheduler(ecfg)
    assert all(r == "length" for r in reasons.values()), reasons
    assert engine.preemptions_total > 0, \
        "pressure never triggered a preemption — test lost its teeth"
    assert engine.resumes_total == engine.preemptions_total
    assert outs == b_outs, \
        "preempted/resumed streams must be byte-identical under greedy"
    assert_pool_clean(engine)


def test_reserve_mode_untouched_by_pressure_knobs():
    """admission="reserve" (the default) never preempts: worst-case
    reservation at admission makes exhaustion impossible."""
    outs, reasons, engine = _run_scheduler(_ecfg())
    assert engine.preemptions_total == 0
    assert all(r == "length" for r in reasons.values())
    assert_pool_clean(engine)


# ------------------------------------------------------ starvation guard


def test_starvation_guard_exempts_and_finishes():
    """A sequence at its preemption budget is never chosen as a victim
    and re-admits under full reservation, so it provably finishes."""
    ecfg = _ecfg(admission="optimistic", preempt_max_per_request=1)
    eng = InferenceEngine(MODEL, ecfg, seed=0)
    s1 = Sequence(request_id=0, prompt_tokens=[1, 2, 3],
                  max_new_tokens=8)
    s2 = Sequence(request_id=1, prompt_tokens=[4, 5, 6],
                  max_new_tokens=8)
    eng.prefill(s1)
    eng.prefill(s2)
    s1.preemptions = 1                     # guard reached
    # Victim selection must pick s2 (later admitted is preferred anyway)
    # and, with s2 excluded, find nothing rather than evict s1.
    assert eng._preempt_victim([s1, s2]) is s2
    assert eng._preempt_victim([s1]) is None
    # _starved on a guarded sequence fails it (reserve semantics) rather
    # than preempting forever.
    eng._starved(s1)
    assert s1.done and s1.finish_reason == "oom"
    eng.release(s1)
    eng.release(s2)
    assert_pool_clean(eng)


def test_starvation_guard_end_to_end():
    """preempt_max_per_request=1 under sustained pressure: every request
    still finishes cleanly and token-identically."""
    b_outs, _, _ = _run_scheduler(_ecfg())
    ecfg = _ecfg(admission="optimistic", optimistic_headroom_pages=1,
                 preempt_watermark_pages=4, chaos_page_pressure=28,
                 preempt_max_per_request=1)
    outs, reasons, engine = _run_scheduler(ecfg)
    assert all(r == "length" for r in reasons.values()), reasons
    assert all(s.preemptions <= 1 for s in engine.slots if s is not None)
    assert outs == b_outs
    assert_pool_clean(engine)


# ------------------------------------------------- observability surface


def test_preemption_metrics_exposed():
    ecfg = _ecfg(admission="optimistic", optimistic_headroom_pages=1,
                 preempt_watermark_pages=4, chaos_page_pressure=28)
    outs, reasons, engine = _run_scheduler(ecfg)
    from tpu_inference import telemetry
    from tpu_inference.engine.scheduler import SchedulerStats
    snap = SchedulerStats().snapshot(engine)
    assert snap["admission"] == "optimistic"
    assert snap["preemptions"] == engine.preemptions_total > 0
    assert snap["recompute_resumes"] == engine.resumes_total
    assert 0.0 <= snap["pool_pressure"] <= 1.0
    if engine.telemetry.enabled:
        text = telemetry.render_prometheus(
            [({}, engine.telemetry.registry)])
        assert "tpu_inf_preemptions_total" in text
        assert "tpu_inf_recompute_resumes_total" in text
        assert "tpu_inf_kv_pool_pressure" in text
    assert_pool_clean(engine)


def test_router_prefers_unpressured_replica():
    from tpu_inference.config import ServerConfig
    from tpu_inference.server.replicas import EngineGroup

    ecfg = _ecfg(admission="optimistic")
    engines = [InferenceEngine(MODEL, ecfg, seed=0),
               InferenceEngine(MODEL, ecfg, seed=0)]
    group = EngineGroup(engines, ServerConfig(model_name="t"))
    # Equal load: the first replica would win the min() tie...
    assert group._least_loaded() is group.schedulers[0]
    # ...until it comes under pool pressure.
    engines[0].set_page_pressure(ecfg.num_pages - 2)
    assert engines[0].under_pressure
    assert group._least_loaded() is group.schedulers[1]
    snap = group.health_snapshot()
    assert snap["replicas"][0]["under_pressure"] is True
    assert snap["replicas"][1]["under_pressure"] is False
    assert "preemptions" in snap["supervision"]
    engines[0].set_page_pressure(0)


# ------------------------------------------------ drain-deadline shutdown


def test_stop_drain_deadline_cancels_stragglers():
    """stop(drain=True) past its deadline cancels queued AND running
    requests with finish_reason="shutdown" — terminal callbacks fire,
    streams end, nothing hangs."""
    # chaos_step_wedge_s slows every dispatch so the running request is
    # provably unfinished at the 0.3s drain deadline.
    ecfg = _ecfg(max_batch_size=1, chaos_step_wedge_s=0.25)
    engine = InferenceEngine(MODEL, ecfg, seed=0)
    sched = EngineScheduler(engine).start()
    finished, ev_running, ev_queued = {}, threading.Event(), \
        threading.Event()
    got_token = threading.Event()

    running = Sequence(request_id=0, prompt_tokens=[1, 2, 3],
                       max_new_tokens=64)
    sched.submit(running, lambda s, t: got_token.set(),
                 lambda s: (finished.__setitem__(0, s.finish_reason),
                            ev_running.set()))
    assert got_token.wait(30)
    # One decode slot: this one can never be admitted before the stop.
    queued = Sequence(request_id=1, prompt_tokens=[4, 5, 6],
                      max_new_tokens=100000)
    sched.submit(queued, lambda s, t: None,
                 lambda s: (finished.__setitem__(1, s.finish_reason),
                            ev_queued.set()))

    sched.stop(drain=True, timeout=0.3)
    assert ev_running.wait(10), "running request never got on_finish"
    assert ev_queued.wait(10), "queued request never got on_finish"
    assert finished == {0: "shutdown", 1: "shutdown"}
    assert_pool_clean(engine)


# ------------------------------------------------- leak invariant mixes


def test_page_leak_invariant_across_request_mixes():
    """finish + cancel + chaos failure + preemption in one scheduler
    run: the allocator must return to fully free."""
    ecfg = _ecfg(admission="optimistic", optimistic_headroom_pages=1,
                 preempt_watermark_pages=4, chaos_page_pressure=28)
    engine = InferenceEngine(MODEL, ecfg, seed=0)
    sched = EngineScheduler(engine).start()
    events = []
    try:
        for i, p in enumerate(PROMPTS):
            ev = threading.Event()
            events.append(ev)
            sched.submit(
                Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=24),
                lambda s, t: None, lambda s, ev=ev: ev.set())
        # Cancel one mid-flight, fail one step via chaos, let the rest
        # run (preempting under pressure).
        sched.cancel(2)
        engine.chaos_step_failure_rate = 1.0
        import time as _t
        _t.sleep(0.05)
        engine.chaos_step_failure_rate = 0.0
        for i, ev in enumerate(events):
            if i != 2:                     # cancelled: no finish event
                assert ev.wait(60), f"request {i} never finished"
    finally:
        sched.stop(drain=True, timeout=10.0)
    assert_pool_clean(engine)
