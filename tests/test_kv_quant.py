"""int8 KV-cache quantization (engine/kv_cache.py quantize_kv + kernels).

The pool stores int8 codes with per-(token, kv-head) scales; dequant is
in-kernel for the Pallas decode/prefill kernels and at-gather for the
dense path. The reference has no KV cache at all (client-only, SURVEY.md
§0); this is the memory-bandwidth tier of the server its external
endpoint provided. Tests pin: quantization error bounds, write/gather
roundtrip through the paged pool, cross-backend token equality (dense
gather vs Pallas in-kernel dequant read the same codes, so greedy tokens
must match exactly), TP-sharded equality, and spec-decode compatibility.
"""

import dataclasses

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from tpu_inference.config import (
    EngineConfig,
    ParallelConfig,
    tiny_llama,
    tiny_mixtral,
)
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import InferenceEngine

BASE = dict(num_pages=64, max_batch_size=2, prefill_buckets=(64,),
            max_new_tokens=16)
PROMPTS = [list(range(1, 20)), list(range(5, 40))]


def test_quantize_kv_error_bounded():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 3, 16)) * 2.0
    q, scale = kvc.quantize_kv(x)
    assert q.dtype == jnp.int8 and scale.shape == (2, 5, 3)
    err = jnp.abs(q.astype(jnp.float32) * scale[..., None] - x)
    assert bool((err <= scale[..., None] / 2 + 1e-6).all())


def test_write_gather_roundtrip_quantized():
    cfg = tiny_llama()
    ecfg = EngineConfig(**BASE, kv_quant="int8")
    kv = kvc.alloc_kv_pages(cfg, ecfg)
    assert kv.quantized and kv.k.dtype == jnp.int8
    k_new = jax.random.normal(jax.random.PRNGKey(1),
                              (1, 4, cfg.n_kv_heads, cfg.head_dim))
    v_new = jax.random.normal(jax.random.PRNGKey(2), k_new.shape)
    bt = jnp.zeros((1, ecfg.max_pages_per_seq), jnp.int32).at[0, 0].set(3)
    positions = jnp.arange(4)[None]
    valid = jnp.ones((1, 4), bool)
    slots = kvc.slot_mapping(bt, positions, valid, ecfg.page_size)
    kv = kvc.write_kv(kv, 0, k_new, v_new, slots)
    k_got, v_got = kvc.gather_kv(kv, 0, bt)
    # Dequantized readback within the per-row quantization envelope.
    _, ks = kvc.quantize_kv(k_new)
    np.testing.assert_allclose(np.asarray(k_got[0, :4]),
                               np.asarray(k_new[0], np.float32),
                               atol=float(ks.max()) / 2 + 1e-6)
    _, vs = kvc.quantize_kv(v_new)
    np.testing.assert_allclose(np.asarray(v_got[0, :4]),
                               np.asarray(v_new[0], np.float32),
                               atol=float(vs.max()) / 2 + 1e-6)


def test_unquantized_pool_unchanged():
    cfg = tiny_llama()
    kv = kvc.alloc_kv_pages(cfg, EngineConfig(**BASE))
    assert not kv.quantized and kv.k_scale is None


def test_dense_and_pallas_token_equal_kv_int8():
    """Both backends read the SAME int8 codes; greedy tokens must agree
    exactly (in-kernel dequant == gather dequant)."""
    cfg = tiny_llama()
    dense = InferenceEngine(cfg, EngineConfig(**BASE, kv_quant="int8"),
                            seed=0).generate(PROMPTS, max_new_tokens=10)
    pallas = InferenceEngine(
        cfg, EngineConfig(**BASE, kv_quant="int8", attn_backend="pallas"),
        seed=0).generate(PROMPTS, max_new_tokens=10)
    assert dense == pallas


def test_kv_int8_close_to_full_precision():
    cfg = tiny_llama()
    fp = InferenceEngine(cfg, EngineConfig(**BASE),
                         seed=0).generate(PROMPTS, max_new_tokens=10)
    kv8 = InferenceEngine(cfg, EngineConfig(**BASE, kv_quant="int8"),
                          seed=0).generate(PROMPTS, max_new_tokens=10)
    # Greedy drift is bounded: the first tokens (short context) agree.
    assert fp[0][:4] == kv8[0][:4]


def test_tp_sharded_kv_int8_matches_unsharded():
    from tpu_inference.parallel.mesh import build_mesh
    cfg = tiny_llama()
    ecfg = EngineConfig(**BASE, kv_quant="int8", attn_backend="pallas")
    base = InferenceEngine(cfg, ecfg, seed=0).generate(PROMPTS,
                                                       max_new_tokens=10)
    mesh = build_mesh(ParallelConfig(tp=2))
    tp_eng = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
    assert tp_eng.kv.k_scale.sharding.spec == \
        jax.sharding.PartitionSpec(None, None, None, "tp")
    assert base == tp_eng.generate(PROMPTS, max_new_tokens=10)


def test_mixtral_kv_int8():
    cfg = tiny_mixtral()
    out = InferenceEngine(cfg, EngineConfig(**BASE, kv_quant="int8"),
                          seed=0).generate([PROMPTS[0]], max_new_tokens=8)
    assert len(out[0]) == 8


@pytest.mark.slow   # spec x kv-int8 combination; each covered separately
def test_spec_decode_with_kv_int8():
    cfg = tiny_llama()
    draft = dataclasses.replace(cfg, n_layers=1, name="draft")
    ecfg = EngineConfig(**BASE, kv_quant="int8", num_speculative_tokens=2,
                        enable_prefix_cache=False)
    eng = InferenceEngine(cfg, ecfg, seed=0, draft_cfg=draft)
    assert eng.draft_kv.quantized
    out = eng.generate([PROMPTS[0]], max_new_tokens=6)
    assert len(out[0]) == 6


@pytest.mark.slow   # int8 x kv-int8 x pallas combination sweep
def test_both_quant_tiers_together():
    """Weights int8 + KV int8 — the full memory-bandwidth configuration."""
    cfg = tiny_llama()
    ecfg = EngineConfig(**BASE, quant="int8", kv_quant="int8",
                        attn_backend="pallas")
    out = InferenceEngine(cfg, ecfg, seed=0).generate(PROMPTS,
                                                      max_new_tokens=8)
    assert all(len(t) == 8 for t in out)
    assert all(0 <= tok < cfg.vocab_size for t in out for tok in t)


def test_quantize_kv_int4_roundtrip_and_bounds():
    """Nibble pack/unpack is lossless on the codes; dequant error stays
    inside the per-row quantization envelope (scale/2 per element)."""
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 5, 3, 16)) * 2.0
    packed, scale = kvc.quantize_kv_int4(x)
    assert packed.dtype == jnp.uint8 and packed.shape == (2, 5, 3, 8)
    codes = kvc.unpack_int4_kv(packed)
    assert codes.shape == x.shape
    assert int(jnp.max(jnp.abs(codes))) <= 7
    err = jnp.abs(codes.astype(jnp.float32) * scale[..., None] - x)
    assert bool((err <= scale[..., None] / 2 + 1e-6).all())


def test_kv_int4_pool_alloc():
    cfg = tiny_llama()
    kv = kvc.alloc_kv_pages(cfg, EngineConfig(**BASE, kv_quant="int4"))
    assert kv.quantized and kv.packed_int4
    assert kv.k.dtype == jnp.uint8
    assert kv.k.shape[-1] == cfg.head_dim // 2
    assert kv.k_scale.shape[-1] == cfg.n_kv_heads
    odd = dataclasses.replace(cfg, d_model=120, n_heads=4, n_kv_heads=2,
                              head_dim_override=15)
    with pytest.raises(ValueError, match="even head_dim"):
        kvc.alloc_kv_pages(odd, EngineConfig(**BASE, kv_quant="int4"))


def test_dense_and_pallas_token_equal_kv_int4():
    """Both backends read the SAME packed nibbles; greedy tokens must
    agree exactly (in-kernel unpack+dequant == gather unpack+dequant)."""
    cfg = tiny_llama()
    dense = InferenceEngine(cfg, EngineConfig(**BASE, kv_quant="int4"),
                            seed=0).generate(PROMPTS, max_new_tokens=10)
    pallas = InferenceEngine(
        cfg, EngineConfig(**BASE, kv_quant="int4", attn_backend="pallas"),
        seed=0).generate(PROMPTS, max_new_tokens=10)
    assert dense == pallas


def test_kv_int4_dequant_error_bounded_at_pool_scale():
    """Full write->gather through the paged pool at realistic shapes:
    int4 dequant error stays in its expected band (~10% relative for
    7-level symmetric on standard-normal data) and strictly below a
    hard ceiling. Token-level closeness vs full precision is NOT
    asserted: on a random-init tiny model greedy argmax margins are
    smaller than honest int4 noise (int8 is the accuracy-safe tier;
    the cross-backend exact-equality test pins implementation
    correctness instead)."""
    cfg = tiny_llama()
    ecfg = EngineConfig(**BASE, kv_quant="int4")
    kv = kvc.alloc_kv_pages(cfg, ecfg)
    k_new = jax.random.normal(jax.random.PRNGKey(1),
                              (1, 16, cfg.n_kv_heads, cfg.head_dim))
    v_new = jax.random.normal(jax.random.PRNGKey(2), k_new.shape)
    bt = jnp.zeros((1, ecfg.max_pages_per_seq), jnp.int32).at[0, 0].set(3)
    slots = kvc.slot_mapping(bt, jnp.arange(16)[None],
                             jnp.ones((1, 16), bool), ecfg.page_size)
    kv = kvc.write_kv(kv, 0, k_new, v_new, slots)
    k_got, v_got = kvc.gather_kv(kv, 0, bt)
    for got, ref in ((k_got, k_new), (v_got, v_new)):
        rel = float(jnp.linalg.norm(got[0, :16] - ref[0])
                    / jnp.linalg.norm(ref[0]))
        assert rel < 0.15, rel


def test_tp_sharded_kv_int4_matches_unsharded():
    """The packed pool (trailing dim D/2) shards on the kv-head dim like
    every other pool; TP generation is token-equal to unsharded."""
    from tpu_inference.parallel.mesh import build_mesh
    cfg = tiny_llama()
    ecfg = EngineConfig(**BASE, kv_quant="int4", attn_backend="pallas")
    base = InferenceEngine(cfg, ecfg, seed=0).generate(PROMPTS,
                                                       max_new_tokens=10)
    mesh = build_mesh(ParallelConfig(tp=2))
    tp_eng = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
    assert tp_eng.kv.k.dtype == jnp.uint8
    assert base == tp_eng.generate(PROMPTS, max_new_tokens=10)


def test_unknown_kv_quant_mode_rejected():
    import pytest
    cfg = tiny_llama()
    with pytest.raises(ValueError, match="unknown kv_quant"):
        InferenceEngine(cfg, EngineConfig(**BASE, kv_quant="fp8"), seed=0)


def test_prefix_cache_reuses_quantized_pages():
    """Cached pages hold int8 codes + scales; a second request sharing
    the prefix must reuse them and produce the same tokens as a cold
    run (cache hits are output-invisible, quantized or not)."""
    cfg = tiny_llama()
    ecfg = EngineConfig(**BASE, kv_quant="int8")
    eng = InferenceEngine(cfg, ecfg, seed=0)
    cold = eng.generate([PROMPTS[1]], max_new_tokens=8)
    hits_before = eng.prefix_cache.hits_hbm.value
    warm = eng.generate([PROMPTS[1]], max_new_tokens=8)
    assert eng.prefix_cache.hits_hbm.value > hits_before
    assert cold == warm


@pytest.mark.slow   # sp x kv-int8 combination; each covered separately
def test_sp_ring_prefill_with_kv_int8():
    """sp>1 ring-attention prefill writes the chunk's KV into the
    quantized pool; decode then reads int8 codes — token-equal to the
    unsharded int8-KV engine."""
    from tpu_inference.parallel.mesh import build_mesh
    cfg = tiny_llama()
    ecfg = EngineConfig(**BASE, kv_quant="int8")
    prompt = [list(range(1, 33))]                 # 32 % sp == 0
    base = InferenceEngine(cfg, ecfg, seed=0).generate(prompt,
                                                       max_new_tokens=8)
    mesh = build_mesh(ParallelConfig(tp=2, sp=2))
    eng = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
    assert eng.sp == 2
    assert base == eng.generate(prompt, max_new_tokens=8)
