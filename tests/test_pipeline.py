"""Pipeline parallelism (parallel/pipeline.py): layer-stage sharding +
GPipe micro-batch schedule vs the unsharded forward oracle."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tpu_inference.config import tiny_llama
from tpu_inference.models import build_model, common, llama
from tpu_inference.parallel.pipeline import pp_forward


def _case(n_layers=2, vocab=128, sliding_window=0):
    cfg = dataclasses.replace(tiny_llama(vocab_size=vocab),
                              n_layers=n_layers,
                              sliding_window=sliding_window)
    params, _ = build_model(cfg, seed=0)
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(0, vocab, (4, 9)))
    pos = jnp.broadcast_to(jnp.arange(9), (4, 9))
    want, _ = llama.forward(params, cfg, toks, pos, None,
                            common.make_dense_attn(cfg.sliding_window))
    return cfg, params, toks, pos, want


@pytest.mark.parametrize("pp,n_micro", [(2, 2), (2, 4), (4, 2)])
def test_pp_forward_matches_unsharded(pp, n_micro):
    """Stages own disjoint layer slabs; activations ppermute through the
    pipe; logits equal the single-device forward for fill (n_micro=pp),
    oversubscribed (n_micro>pp), and deep-pipe (pp=4) schedules."""
    cfg, params, toks, pos, want = _case(n_layers=4)
    mesh = Mesh(np.array(jax.devices()[:pp]), ("pp",))
    got = pp_forward(params, cfg, toks, pos, mesh, n_micro=n_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_pp_forward_swa_dialect():
    """The window mask and micro-batched positions compose (a Mistral-
    class model through the pipe)."""
    cfg, params, toks, pos, want = _case(n_layers=2, sliding_window=4)
    mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
    got = pp_forward(params, cfg, toks, pos, mesh, n_micro=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_pp_forward_quantized_slabs():
    """PP composes with weight quantization: QuantizedArray layer slabs
    (codes + per-channel scales, both [L, ...]) shard their layer axis
    across stages like plain weights — the memory story for serving a
    model that only fits quantized AND staged."""
    from tpu_inference.models.quant import quantize_params

    cfg, params, toks, pos, _ = _case(n_layers=2)
    qp = quantize_params(params, "int8")
    want, _ = llama.forward(qp, cfg, toks, pos, None,
                            common.make_dense_attn())
    mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
    got = pp_forward(qp, cfg, toks, pos, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_pp_forward_rejects_bad_shapes():
    cfg, params, toks, pos, _ = _case(n_layers=2)
    mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
    with pytest.raises(ValueError, match="n_layers"):
        pp_forward(params, dataclasses.replace(cfg, n_layers=3),
                   toks, pos, mesh)
    with pytest.raises(ValueError, match="n_micro"):
        pp_forward(params, cfg, toks, pos, mesh, n_micro=3)
