"""Continuous-batching scheduler: admission, streaming callbacks, cancel."""

import threading
import time

import numpy as np
import pytest

from tpu_inference import config as cfgs
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.scheduler import EngineScheduler
from tpu_inference.models import build_model


@pytest.fixture(scope="module")
def engine():
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    engine_cfg = cfgs.EngineConfig(
        page_size=8, num_pages=128, max_pages_per_seq=8, max_batch_size=4,
        prefill_buckets=(16, 32))
    params, _ = build_model(model_cfg, seed=0)
    return InferenceEngine(model_cfg, engine_cfg, params=params)


def _submit_and_wait(sched, seqs, timeout=120.0):
    events = {s.request_id: [] for s in seqs}
    done = {s.request_id: threading.Event() for s in seqs}

    for s in seqs:
        sched.submit(
            s,
            on_token=lambda sq, t: events[sq.request_id].append(t),
            on_finish=lambda sq: done[sq.request_id].set())
    for s in seqs:
        assert done[s.request_id].wait(timeout), f"request {s.request_id} hung"
    return events


def test_scheduler_streams_all_requests(engine):
    sched = EngineScheduler(engine).start()
    rng = np.random.default_rng(0)
    seqs = [Sequence(request_id=i,
                     prompt_tokens=rng.integers(0, 256, size=5 + i).tolist(),
                     max_new_tokens=6) for i in range(6)]  # > max_batch_size
    events = _submit_and_wait(sched, seqs)
    for s in seqs:
        assert events[s.request_id] == s.generated
        assert len(s.generated) == 6
        assert s.finish_reason == "length"
    stats = sched.stats.snapshot(engine)
    assert stats["requests_finished"] == 6
    # Released pages may stay in the prefix cache; in-use minus evictable
    # must be zero (nothing is leaked, everything reclaimable).
    assert (stats["kv_pages_in_use"]
            == stats["prefix_cache"]["evictable"])
    sched.stop()


def test_scheduler_queue_overflow(engine):
    ecfg = engine.engine_cfg
    sched = EngineScheduler(engine)   # not started: queue only fills
    finished = []
    for i in range(ecfg.max_queue_len + 3):
        s = Sequence(request_id=1000 + i, prompt_tokens=[1, 2, 3],
                     max_new_tokens=1)
        sched.submit(s, on_token=lambda *a: None,
                     on_finish=lambda sq: finished.append(sq))
    assert len(finished) == 3
    assert all(s.finish_reason == "queue_full" for s in finished)
    assert sched.stats.requests_rejected == 3


def test_scheduler_rejects_too_large(engine):
    """A request that can never fit must be rejected, not block the queue."""
    sched = EngineScheduler(engine)
    finished = []
    s = Sequence(request_id=500, prompt_tokens=[1] * 10,
                 max_new_tokens=10**6)
    s2 = Sequence(request_id=501, prompt_tokens=[1] * 200 * 8,
                  max_new_tokens=1)        # prompt alone exceeds the pool
    for seq in (s, s2):
        sched.submit(seq, on_token=lambda *a: None,
                     on_finish=lambda sq: finished.append(sq))
    # request 500 is admittable (need capped at max_pages_per_seq=8);
    # request 501's prompt alone busts the 127-page pool? No: prompt is
    # clamped to max_context on prefill, so reservation caps too — both fit.
    assert all(f.finish_reason != "too_large" for f in finished)
    small = EngineScheduler(
        __import__("tpu_inference.engine.engine", fromlist=["InferenceEngine"])
        .InferenceEngine(engine.model_cfg,
                         cfgs.EngineConfig(page_size=8, num_pages=4,
                                           max_pages_per_seq=64,
                                           max_batch_size=2,
                                           prefill_buckets=(16,)),
                         params=engine.params))
    s3 = Sequence(request_id=502, prompt_tokens=[1] * 10, max_new_tokens=512)
    small.submit(s3, on_token=lambda *a: None,
                 on_finish=lambda sq: finished.append(sq))
    assert s3.finish_reason == "too_large"


def test_scheduler_cancel_queued(engine):
    sched = EngineScheduler(engine)   # not started
    s = Sequence(request_id=77, prompt_tokens=[1, 2], max_new_tokens=5)
    sched.submit(s, on_token=lambda *a: None, on_finish=lambda *a: None)
    sched.cancel(77)
    assert s.finish_reason == "cancelled"
    # Starting afterwards must not execute the cancelled request.
    sched.start()
    time.sleep(0.3)
    assert s.generated == []
    sched.stop()


def test_pipelined_decode_error_recovery():
    """A decode-dispatch exception with calls in flight must not poison
    later requests: the pipeline is aborted, the victims error out, and a
    fresh request through the reused slots completes correctly."""
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=128, max_pages_per_seq=8,
                             max_batch_size=2, prefill_buckets=(16,),
                             decode_steps_per_call=4,
                             decode_pipeline_depth=2,
                             # Force the pipelined path even for a lone
                             # request (latency mode would bypass it).
                             latency_decode_threshold=0,
                             # The same engine serves the reference
                             # generate below; no warm-prefill crosstalk.
                             enable_prefix_cache=False)
    params, _ = build_model(model_cfg, seed=0)
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    # Same engine supplies the reference (generate leaves no state).
    want = engine.generate([[5, 6, 7]], max_new_tokens=6)[0]

    real = engine._decode_multi_jit
    state = {"calls": 0}

    def flaky(*a, **kw):
        state["calls"] += 1
        if state["calls"] == 2:
            raise RuntimeError("injected decode failure")
        return real(*a, **kw)

    engine._decode_multi_jit = flaky
    sched = EngineScheduler(engine).start()
    try:
        victim = Sequence(request_id=1, prompt_tokens=[1, 2, 3],
                          max_new_tokens=12)
        events = _submit_and_wait(sched, [victim])
        assert victim.finish_reason == "error"
        assert not engine.pipeline_pending

        engine._decode_multi_jit = real
        fresh = Sequence(request_id=2, prompt_tokens=[5, 6, 7],
                         max_new_tokens=6)
        _submit_and_wait(sched, [fresh])
        assert fresh.finish_reason == "length"
        assert fresh.generated == want
    finally:
        sched.stop(drain=False)


def test_chunked_prefill_interleaves_with_decode():
    """A multi-chunk prompt prefills one chunk per loop iteration, so a
    running request keeps decoding in between; both outputs equal the
    non-interleaved reference."""
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=128, max_pages_per_seq=16,
                             max_batch_size=4, prefill_buckets=(16, 32),
                             enable_prefix_cache=False)
    params, _ = build_model(model_cfg, seed=0)
    rng = np.random.default_rng(21)
    short = rng.integers(0, 256, size=6).tolist()
    long = rng.integers(0, 256, size=90).tolist()   # 3 chunks of <=32

    # One engine serves both the reference generates and the scheduler:
    # generate() leaves no state behind, so the second compile of an
    # identical engine would be pure waste on this single-core box.
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    want_short = engine.generate([short], max_new_tokens=20)[0]
    want_long = engine.generate([long], max_new_tokens=8)[0]

    sched = EngineScheduler(engine).start()
    try:
        s1 = Sequence(request_id=1, prompt_tokens=short, max_new_tokens=20)
        s2 = Sequence(request_id=2, prompt_tokens=long, max_new_tokens=8)
        events = _submit_and_wait(sched, [s1, s2])
    finally:
        sched.stop(drain=False)
    assert events[1] == want_short
    assert events[2] == want_long
    assert s2.finish_reason == "length"


def test_latency_mode_matches_fused_tokens():
    """A lone request served through the single-step latency graph must
    produce exactly the fused-K tokens (same math, shorter scan)."""
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    params, _ = build_model(model_cfg, seed=0)
    base = dict(page_size=8, num_pages=128, max_pages_per_seq=8,
                max_batch_size=4, prefill_buckets=(16, 32))
    prompt = list(range(3, 17))

    def run(threshold):
        eng = InferenceEngine(
            model_cfg,
            cfgs.EngineConfig(**base, latency_decode_threshold=threshold),
            params=params)
        sched = EngineScheduler(eng).start()
        seq = Sequence(request_id=0, prompt_tokens=prompt, max_new_tokens=10)
        events = _submit_and_wait(sched, [seq])
        sched.stop()
        return events[0]

    fused = run(threshold=0)      # always the fused-K path
    latency = run(threshold=4)    # always the single-step path
    assert fused == latency and len(fused) == 10


def test_admission_during_incremental_prefill_no_slot_collision():
    """A request admitted WHILE a multi-chunk prefill is mid-flight must
    not be handed the prefilling sequence's slot (the slot binds at
    prefill_begin; before the fix, free_slots still listed it and the
    finishing prefill overwrote the newcomer, orphaning its stream)."""
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=128, max_pages_per_seq=16,
                             max_batch_size=2,     # only 2 slots: collision-prone
                             prefill_buckets=(16,),
                             chunked_prefill_size=16)
    params, _ = build_model(model_cfg, seed=0)
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    sched = EngineScheduler(engine).start()
    try:
        rng = np.random.default_rng(3)
        # 100-token prompt = 7 chunks of 16: many loop iterations mid-prefill.
        long_seq = Sequence(request_id=1,
                            prompt_tokens=rng.integers(
                                0, 256, size=100).tolist(),
                            max_new_tokens=4)
        shorts = [Sequence(request_id=10 + i,
                           prompt_tokens=rng.integers(0, 256, size=6).tolist(),
                           max_new_tokens=4) for i in range(3)]
        events = _submit_and_wait(sched, [long_seq] + shorts, timeout=120.0)
        for s in [long_seq] + shorts:
            assert s.finish_reason == "length", (s.request_id, s.finish_reason)
            assert len(events[s.request_id]) == 4
    finally:
        sched.stop(drain=False)
