"""Draft-free n-gram speculation (README "Speculative decoding",
spec_mode="ngram").

The load-bearing claims: greedy output is byte-identical to plain decode
(speculation is a scheduling decision, never a behavior change) through
the engine AND through the scheduler at every ladder rung, with
dispatch-ahead staging, with the repetition penalty applied, and across
preemption/recompute-resume; the adaptive-γ throttle converges to γ=0 on
adversarial (echo-free) streams so spec can never lose; the host KV tier
and the decode ladder stay ACTIVE under ngram mode (unlike draft mode);
warmup covers (every rung) x (every verify width) so no XLA compile ever
lands mid-serving; and the pool-leak invariant holds across spec rounds.
"""

import logging
import threading

import numpy as np
import pytest

from tpu_inference import config as cfgs
from tpu_inference.engine import engine as engine_mod
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.scheduler import EngineScheduler
from tpu_inference.engine.speculative import ngram_propose
from tpu_inference.models import build_model
from tests._leak import assert_pool_clean

VOCAB = 256


@pytest.fixture(scope="module")
def model_setup():
    model_cfg = cfgs.tiny_llama(vocab_size=VOCAB)
    params, _ = build_model(model_cfg, seed=0)
    return model_cfg, params


def _ecfg(**kw):
    base = dict(page_size=8, num_pages=512, max_pages_per_seq=16,
                max_batch_size=4, prefill_buckets=(16, 32, 64))
    base.update(kw)
    return cfgs.EngineConfig(**base)


def _ngram_kw(gamma=4, **kw):
    return dict(spec_mode="ngram", num_speculative_tokens=gamma, **kw)


def _submit_and_wait(sched, seqs, timeout=180.0, start=False):
    events = {s.request_id: [] for s in seqs}
    done = {s.request_id: threading.Event() for s in seqs}
    for s in seqs:
        sched.submit(
            s, on_token=lambda sq, t: events[sq.request_id].append(t),
            on_finish=lambda sq: done[sq.request_id].set())
    if start:
        sched.start()
    for s in seqs:
        assert done[s.request_id].wait(timeout), f"request {s.request_id} hung"
    return events


# ---------------------------------------------------------------- proposer

def test_ngram_propose_basics():
    # Suffix [1,2,3] matched one period back: proposal continues the
    # cycle, TILING past the end of history (the repetition-loop steady
    # state would otherwise truncate to one period).
    assert ngram_propose([1, 2, 3] * 6, 5, 3).tolist() == [1, 2, 3, 1, 2]
    # 1-gram fallback when no longer match exists.
    assert ngram_propose([5, 9, 5], 4, 3).tolist() == [9, 5, 9, 5]
    # Most RECENT match wins (recency beats the conversation opener).
    assert ngram_propose([7, 1, 7, 2, 7], 1, 1).tolist() == [2]
    # No match / too-short histories propose nothing.
    assert ngram_propose([1, 2, 3, 4, 5], 4, 3).size == 0
    assert ngram_propose([9], 4, 3).size == 0
    assert ngram_propose([], 4, 3).size == 0
    assert ngram_propose([1, 1, 1], 0, 3).size == 0


# ------------------------------------------------------- byte identity

def test_greedy_byte_identity_engine(model_setup):
    """ngram-spec greedy output == plain greedy output, token for token,
    and the pool comes back clean after speculative rounds."""
    model_cfg, params = model_setup
    plain = InferenceEngine(model_cfg, _ecfg(), params=params)
    ng = InferenceEngine(model_cfg, _ecfg(**_ngram_kw()), params=params)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, VOCAB, size=n).tolist()
               for n in (5, 13, 22, 40)]
    want = plain.generate(prompts, max_new_tokens=48)
    got = ng.generate(prompts, max_new_tokens=48)
    assert got == want
    assert ng.spec_drafted > 0 and ng.spec_accepted > 0
    assert ng.spec_rounds_total > 0
    assert_pool_clean(ng)


def test_ngram_keeps_ladder_and_host_tier(model_setup):
    """Unlike draft-model spec, ngram mode keeps the decode ladder (no
    single-rung collapse) and the host KV tier (no draft pool to
    desync) — the gates PRs 6-7 built stay active."""
    model_cfg, params = model_setup
    eng = InferenceEngine(
        model_cfg, _ecfg(max_batch_size=16, decode_ladder=(4, 8, 16),
                         host_cache_pages=32, **_ngram_kw()),
        params=params)
    assert eng.ladder == (4, 8, 16)
    assert eng.host_pool is not None
    assert eng.spec_ngram and not eng.spec_draft
    # Verify graph widths: the full γ+1 round plus the narrow probe.
    assert eng._spec_widths == [2, 5]


def test_greedy_byte_identity_through_scheduler_every_rung(model_setup):
    """The same request set served by the plain base-rung engine and by
    ngram spec over the full ladder must stream byte-identical greedy
    tokens — and the ladder must demonstrably climb, so every rung's
    verify graph really served traffic."""
    model_cfg, params = model_setup
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, VOCAB, size=6).tolist() for _ in range(12)]

    def run(ecfg):
        engine = InferenceEngine(model_cfg, ecfg, params=params)
        sched = EngineScheduler(engine)
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=24) for i, p in enumerate(prompts)]
        events = _submit_and_wait(sched, seqs, start=True)
        sched.stop(drain=True, timeout=20)
        assert_pool_clean(engine)
        return events, engine

    base_events, _ = run(_ecfg(max_batch_size=4, decode_ladder=(),
                               max_pages_per_seq=8))
    spec_events, eng = run(_ecfg(max_batch_size=16, max_pages_per_seq=8,
                                 decode_ladder=(4, 8, 16), **_ngram_kw()))
    assert base_events == spec_events
    assert eng.rung_peak == 16
    assert eng.spec_drafted > 0


def test_greedy_byte_identity_dispatch_ahead(model_setup):
    """Spec rounds staged into the dispatch-ahead pipeline (depth > 1,
    sync-then-stage) emit the same greedy bytes as plain decode, and the
    pipeline drains clean at shutdown."""
    model_cfg, params = model_setup
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, VOCAB, size=8).tolist() for _ in range(6)]

    plain = InferenceEngine(model_cfg, _ecfg(), params=params)
    want = plain.generate(prompts, max_new_tokens=32)

    engine = InferenceEngine(
        model_cfg, _ecfg(decode_pipeline_depth=2,
                         latency_decode_threshold=0, **_ngram_kw()),
        params=params)
    sched = EngineScheduler(engine)
    seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                     max_new_tokens=32) for i, p in enumerate(prompts)]
    events = _submit_and_wait(sched, seqs, start=True)
    sched.stop(drain=True, timeout=20)
    assert [events[i] for i in range(len(prompts))] == want
    assert engine.spec_rounds_total > 0
    assert_pool_clean(engine)


def test_repeat_penalty_composes(model_setup, monkeypatch):
    """The repetition penalty applies inside the verify round (each
    position penalized against the window rolled with its accepted
    prefix), so penalized greedy ngram output == penalized plain output
    — the PR drops the server's 'ignored under spec' warning for this
    mode. Draft mode still zeroes the penalty.

    Two passes: the REAL proposer (the penalty suppresses the tiny
    model's cycles, so proposals mostly reject — the rejection/
    correction path must still match the penalized argmax), then an
    ORACLE proposer feeding the plain arm's own continuation — those
    proposals verify only if the verify-phase distribution is penalized
    exactly like sequential decode, so high acceptance here IS the
    penalty-composition proof."""
    model_cfg, params = model_setup
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, VOCAB, size=9).tolist() for _ in range(3)]

    def run(ecfg):
        eng = InferenceEngine(model_cfg, ecfg, params=params)
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=32, repeat_penalty=1.3,
                         repeat_last_n=32)
                for i, p in enumerate(prompts)]
        for s in seqs:
            eng.prefill(s)
        while eng.active_sequences():
            eng.decode_steps()
        out = [list(s.generated) for s in seqs]
        for s in seqs:
            eng.release(s)
        assert_pool_clean(eng)
        return out, eng

    # K=1 keeps the mixed-batch gate out of the way: this test pins the
    # penalty math, and partial-proposal rounds must actually dispatch
    # verifies for the rejection path to run.
    want, _ = run(_ecfg(decode_steps_per_call=1))
    got, eng = run(_ecfg(decode_steps_per_call=1, **_ngram_kw()))
    assert got == want
    assert eng.spec_drafted > 0      # verify rounds genuinely ran

    # Oracle pass: propose the penalized plain continuation itself.
    ref = {tuple(p): w for p, w in zip(prompts, want)}

    def oracle(hist, gamma, max_n, min_n=1):
        for p, w in ref.items():
            if tuple(hist[:len(p)]) == p:
                done = len(hist) - len(p)
                return np.asarray(w[done:done + gamma], np.int32)
        return np.empty((0,), np.int32)

    monkeypatch.setattr(engine_mod, "ngram_propose", oracle)
    got2, eng2 = run(_ecfg(decode_steps_per_call=1, **_ngram_kw()))
    assert got2 == want
    # An unpenalized verify distribution would argmax-reject these
    # proposals; near-total acceptance proves the penalty landed.
    assert eng2.spec_accepted >= 0.8 * eng2.spec_drafted > 0
    # Engine-side contract the server warning logic keys on:
    seq = Sequence(request_id=99, prompt_tokens=[1], max_new_tokens=1,
                   repeat_penalty=1.3, repeat_last_n=32)
    assert eng2._penalty_arrays(seq) == (1.3, 32)


# ------------------------------------------------------ adaptive gamma

def test_adaptive_gamma_throttles_adversarial_stream(model_setup,
                                                     monkeypatch):
    """An adversarial proposer (every proposal wrong) must converge to
    γ=0: the EWMA throttles the lane, subsequent rounds degrade to the
    plain fused-K graph (fallback), probes stay on the narrow verify
    width, and greedy output remains byte-identical throughout — spec
    never loses."""
    model_cfg, params = model_setup
    plain = InferenceEngine(model_cfg, _ecfg(), params=params)
    prompt = [1, 2, 3, 4, 5, 6]
    want = plain.generate([prompt], max_new_tokens=50)[0]

    eng = InferenceEngine(
        model_cfg, _ecfg(**_ngram_kw(spec_probe_every=8)), params=params)
    monkeypatch.setattr(
        engine_mod, "ngram_propose",
        lambda hist, gamma, max_n, min_n=1: np.full((gamma,), 7, np.int32))
    s = Sequence(request_id=0, prompt_tokens=list(prompt),
                 max_new_tokens=50)
    eng.prefill(s)
    while eng.active_sequences():
        eng.decode_steps()
    eng.release(s)
    assert s.generated == want
    assert s.spec_gamma == 0                      # converged to throttle
    assert s.spec_accept_ewma < 0.35
    assert eng.spec_throttles_total >= 1
    assert eng.spec_fallback_rounds >= 1          # plain rounds took over
    assert eng.spec_accepted == 0
    # Backoff engaged: failed probes doubled the re-check interval.
    assert s.spec_probe_interval >= 8
    assert_pool_clean(eng)


def test_probe_uses_narrow_width(model_setup):
    """A probe round (single-token proposals) picks the compiled narrow
    verify width instead of paying the full γ+1 forward."""
    model_cfg, params = model_setup
    eng = InferenceEngine(model_cfg, _ecfg(**_ngram_kw(gamma=5)),
                          params=params)
    assert eng._spec_widths == [2, 6]
    assert eng._spec_width_for({0: np.array([9], np.int32)}) == 2
    assert eng._spec_width_for({0: np.array([9, 9], np.int32)}) == 6
    # A throttled sequence's probe proposes exactly one token.
    s = Sequence(request_id=0, prompt_tokens=[1], max_new_tokens=4,
                 spec_gamma=0, spec_probe_countdown=1,
                 spec_probe_interval=48)
    assert eng._seq_spec_gamma(s) == 1


def test_mixed_batch_gate(model_setup):
    """Fused-K batches (K > 1): a lone low-confidence proposer must not
    drag bystander lanes into 1-token verify rounds — the gate degrades
    the round to plain fused decode unless the proposers' expected
    accepted tokens cover one token per bystander. K == 1 has no
    bystander deficit, so the gate stays open."""
    model_cfg, params = model_setup
    eng = InferenceEngine(
        model_cfg, _ecfg(decode_steps_per_call=8, **_ngram_kw(gamma=5)),
        params=params)
    seqs = []
    for i in range(4):
        s = Sequence(request_id=i, prompt_tokens=[1 + i, 2, 3],
                     max_new_tokens=8)
        eng.prefill(s)
        seqs.append(s)
    lone = {seqs[0].slot: np.array([7], np.int32)}
    seqs[0].spec_accept_ewma = 0.5
    # 0.5 expected < 3 bystanders: degrade to plain.
    assert eng._gate_mixed_batch(seqs, lone) == {}
    # Every lane proposing (no bystanders): always dispatch.
    full = {s.slot: np.array([7, 7, 7], np.int32) for s in seqs}
    assert eng._gate_mixed_batch(seqs, full) == full
    # Confident proposers can carry bystanders.
    seqs[0].spec_accept_ewma = 1.0
    rich = {seqs[0].slot: np.array([7] * 5, np.int32)}
    assert eng._gate_mixed_batch(seqs, rich) == rich
    # K == 1: no gate (a verify round strictly dominates a 1-step call).
    eng1 = InferenceEngine(
        model_cfg, _ecfg(decode_steps_per_call=1, **_ngram_kw(gamma=5)),
        params=params)
    s1 = Sequence(request_id=0, prompt_tokens=[1, 2, 3], max_new_tokens=8,
                  spec_accept_ewma=0.01)
    eng1.prefill(s1)
    s2 = Sequence(request_id=1, prompt_tokens=[4, 5, 6], max_new_tokens=8)
    eng1.prefill(s2)
    lone1 = {s1.slot: np.array([7], np.int32)}
    assert eng1._gate_mixed_batch([s1, s2], lone1) == lone1
    for e, group in ((eng, seqs), (eng1, [s1, s2])):
        for s in group:
            s.done = True
            e.release(s)
        assert_pool_clean(e)


def test_adaptive_gamma_recovers_on_echo(model_setup):
    """A throttled sequence re-earns its γ: one clean probe lifts the
    EWMA back over the threshold and restores the full depth."""
    model_cfg, params = model_setup
    eng = InferenceEngine(model_cfg, _ecfg(**_ngram_kw(gamma=4)),
                          params=params)
    s = Sequence(request_id=0, prompt_tokens=[1], max_new_tokens=4,
                 spec_gamma=1, spec_accept_ewma=0.1,
                 spec_probe_interval=48)
    eng._spec_update_adaptive(s, drafted=1, accepted=1)
    assert s.spec_gamma == 4
    assert s.spec_probe_interval == 0


# ------------------------------------------- preemption / recompute-resume

def test_preemption_recompute_resume_composes(model_setup):
    """A tight pool under optimistic admission with ngram spec AND the
    host tier: watermark preemption fires against in-flight spec
    sequences, recompute-resume finishes every request, greedy outputs
    match the uncontended plain run, and the pool invariant holds."""
    model_cfg, params = model_setup
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, VOCAB, size=8).tolist() for _ in range(12)]

    ref = InferenceEngine(model_cfg, _ecfg(max_batch_size=4,
                                           max_pages_per_seq=8),
                          params=params)
    want = {i: toks for i, toks in
            enumerate(ref.generate(prompts, max_new_tokens=16))}

    ecfg = _ecfg(max_batch_size=8, decode_ladder=(2, 4, 8),
                 max_pages_per_seq=8, num_pages=16,
                 admission="optimistic", optimistic_headroom_pages=1,
                 preempt_watermark_pages=4, host_cache_pages=64,
                 **_ngram_kw())
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    assert engine.host_pool is not None      # tier live under ngram spec
    sched = EngineScheduler(engine)
    seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                     max_new_tokens=16) for i, p in enumerate(prompts)]
    try:
        events = _submit_and_wait(sched, seqs, start=True)
    finally:
        sched.stop(drain=True, timeout=30)
    for i, s in enumerate(seqs):
        assert s.finish_reason == "length", (i, s.finish_reason)
        assert events[i] == want[i]
    assert engine.preemptions_total >= 1
    assert_pool_clean(engine)


# ------------------------------------------------------- zero compile

def test_warmup_covers_rungs_and_widths_no_midserve_compile(model_setup):
    """Extends the test_ladder.py zero-compile pin to ngram spec: after
    the first served request, a burst that climbs the whole ladder —
    speculating all the way — must find every verify width AND every
    plain fallback graph warm. No XLA compile mid-serving."""
    import jax

    model_cfg, params = model_setup
    engine = InferenceEngine(
        model_cfg, _ecfg(max_batch_size=16, decode_ladder=(4, 8, 16),
                         max_pages_per_seq=8, decode_steps_per_call=4,
                         **_ngram_kw()),
        params=params)
    engine.warmup()

    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    loggers = [logging.getLogger(n)
               for n in ("jax._src.interpreters.pxla", "jax._src.dispatch")]
    jax.config.update("jax_log_compiles", True)
    for lg in loggers:
        lg.addHandler(handler)
        lg.setLevel(logging.DEBUG)
    rng = np.random.default_rng(11)
    try:
        sched = EngineScheduler(engine).start()
        try:
            _submit_and_wait(sched, [Sequence(
                request_id=0,
                prompt_tokens=rng.integers(0, VOCAB, size=6).tolist(),
                max_new_tokens=4)])
            records.clear()
            seqs = [Sequence(request_id=1 + i,
                             prompt_tokens=rng.integers(
                                 0, VOCAB, size=6).tolist(),
                             max_new_tokens=16 + (i % 3))
                    for i in range(15)]
            _submit_and_wait(sched, seqs)
        finally:
            sched.stop(drain=True, timeout=20)
    finally:
        jax.config.update("jax_log_compiles", False)
        for lg in loggers:
            lg.removeHandler(handler)
    assert engine.rung_peak == 16       # the burst really climbed
    assert engine.spec_rounds_total > 0  # and really speculated
    compiles = [m for m in records if m.startswith("Compiling ")]
    assert not compiles, (
        f"XLA compiled {len(compiles)} graph(s) after the first served "
        f"request under ngram spec: {compiles[:4]}")
    assert_pool_clean(engine)


# --------------------------------------------------------- validation

def test_spec_config_validation():
    from tpu_inference.config import validate_spec_config

    validate_spec_config("ngram", 4, 3, has_draft_model=False)
    validate_spec_config("draft", 4, 3, has_draft_model=True)
    with pytest.raises(ValueError, match="draft-model"):
        validate_spec_config("ngram", 4, 3, has_draft_model=True)
    with pytest.raises(ValueError, match="num-speculative-tokens"):
        validate_spec_config("ngram", 0, 3, has_draft_model=False)
    with pytest.raises(ValueError, match="num-speculative-tokens"):
        validate_spec_config("ngram", 17, 3, has_draft_model=False)
    with pytest.raises(ValueError, match="ngram-window"):
        validate_spec_config("ngram", 4, 0, has_draft_model=False)
    with pytest.raises(ValueError, match="ngram-window"):
        validate_spec_config("ngram", 4, 9, has_draft_model=False)
    with pytest.raises(ValueError, match="spec-mode"):
        validate_spec_config("banana", 4, 3, has_draft_model=False)


def test_engine_rejects_bad_spec_config(model_setup):
    model_cfg, params = model_setup
    with pytest.raises(ValueError, match="spec_mode"):
        InferenceEngine(model_cfg, _ecfg(spec_mode="banana"),
                        params=params)
    with pytest.raises(ValueError, match="num-speculative-tokens"):
        InferenceEngine(model_cfg,
                        _ecfg(spec_mode="ngram",
                              num_speculative_tokens=0),
                        params=params)
    # ngram + a draft model is a contradiction, not a silent pick.
    import dataclasses
    draft = dataclasses.replace(model_cfg, n_layers=1, name="draft")
    with pytest.raises(ValueError, match="draft-model"):
        InferenceEngine(model_cfg, _ecfg(**_ngram_kw()), params=params,
                        draft_cfg=draft)


def test_spec_stats_snapshot(model_setup):
    """Scheduler stats expose the speculative block (mode/γ/counters)
    and /metrics exposes the spec series."""
    from tpu_inference import telemetry as tm

    model_cfg, params = model_setup
    engine = InferenceEngine(model_cfg, _ecfg(**_ngram_kw()),
                             params=params)
    sched = EngineScheduler(engine)
    out = engine.generate([[1, 2, 3] * 4], max_new_tokens=12)
    assert len(out[0]) == 12
    snap = sched.stats.snapshot(engine)
    spec = snap["speculative"]
    assert spec["mode"] == "ngram" and spec["gamma"] == 4
    assert spec["drafted"] >= spec["accepted"] >= 0
    assert spec["rounds"] + spec["fallback_rounds"] > 0
    text = tm.render_prometheus([({}, engine.telemetry.registry)])
    for name in ("tpu_inf_spec_drafted_total",
                 "tpu_inf_spec_accepted_total",
                 "tpu_inf_spec_acceptance_rate",
                 "tpu_inf_spec_gamma",
                 "tpu_inf_spec_rounds_total",
                 "tpu_inf_spec_fallback_rounds_total",
                 "tpu_inf_spec_throttles_total"):
        assert f"\n{name}" in text or text.startswith(name), name
