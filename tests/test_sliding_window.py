"""Sliding-window attention (Mistral-style SWA).

The reference's endpoint served `mistral` — whose signature architecture
feature is a sliding attention window (each token attends to itself and
the window-1 tokens before it). Tests pin: the mask semantics against a
naive numpy oracle, engine serving equality with a windowed full-forward
oracle (prefill + paged decode both windowed), the HF config mapping,
and the window-aware Pallas kernels (decode + prefill) against the
dense reference on both KV tiers."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_inference import config as cfgs
from tpu_inference.engine.engine import InferenceEngine
from tpu_inference.models import build_model, common


def _naive_swa(q, k, v, window):
    """O(S^2) numpy oracle: causal + window mask, per head."""
    b, s, h, d = q.shape
    out = np.zeros_like(q)
    for bi in range(b):
        for hi in range(h):
            for i in range(s):
                lo = max(0, i - window + 1) if window else 0
                ks = k[bi, lo:i + 1, hi]
                sc = (q[bi, i, hi] @ ks.T) / np.sqrt(d)
                p = np.exp(sc - sc.max())
                p /= p.sum()
                out[bi, i, hi] = p @ v[bi, lo:i + 1, hi]
    return out


def test_window_mask_matches_naive_oracle():
    rng = np.random.default_rng(0)
    b, s, h, d = 1, 12, 2, 8
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    for window in (0, 1, 4, 12, 100):
        got = common.dense_causal_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            sliding_window=window)
        want = _naive_swa(q, k, v, window)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"window={window}")


def _swa_cfg(window):
    base = cfgs.tiny_llama(vocab_size=256)
    import dataclasses

    return dataclasses.replace(base, name="tiny-swa",
                               sliding_window=window)


# Shared geometry for the window-8 serving tests; the module-scoped
# dense engine below serves every test that only needs plain windowed
# generate() (tokens are geometry-invariant given the same params).
SWA_KW = dict(page_size=8, num_pages=96, max_pages_per_seq=8,
              max_batch_size=2, prefill_buckets=(16, 32))


@pytest.fixture(scope="module")
def swa8():
    cfg = _swa_cfg(8)
    params, mod = build_model(cfg, seed=0)
    return cfg, params, mod


@pytest.fixture(scope="module")
def swa8_dense_engine(swa8):
    # attn_backend pinned: "auto" would resolve to pallas on a real TPU
    # backend and make the dense-vs-pallas parity test vacuous.
    cfg, params, _ = swa8
    return InferenceEngine(cfg, cfgs.EngineConfig(**SWA_KW,
                                                  attn_backend="dense"),
                           params=params)


def test_engine_matches_windowed_oracle(swa8, swa8_dense_engine):
    """Greedy serving (bucketed prefill + paged decode) == repeated
    windowed full forwards: the window must hold across the
    prefill/decode boundary and as decode slides past it."""
    cfg, params, mod = swa8
    engine = swa8_dense_engine
    rng = np.random.default_rng(3)
    # Prompts shorter and longer than the window; enough new tokens that
    # decode positions slide well past it.
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 20)]
    got = engine.generate(prompts, max_new_tokens=12)

    # reference_greedy honors cfg.sliding_window (shared-compile oracle).
    from tests.test_engine import reference_greedy
    for prompt, gen in zip(prompts, got):
        want = reference_greedy(params, mod, cfg, prompt, 12)
        assert gen == want, f"prompt len {len(prompt)}"


def test_windowed_differs_from_full_attention():
    """Sanity that the window actually changes behavior: same weights,
    window on vs off, long-enough prompt -> different logits."""
    cfg_full = cfgs.tiny_llama(vocab_size=256)
    params, mod = build_model(cfg_full, seed=0)
    toks = jnp.asarray(np.arange(1, 25)[None] % 256)
    pos = jnp.broadcast_to(jnp.arange(24), (1, 24))
    full, _ = mod.forward(params, cfg_full, toks, pos, None,
                          common.make_dense_attn())
    swa, _ = mod.forward(params, cfg_full, toks, pos, None,
                         common.make_dense_attn(sliding_window=4))
    assert not np.allclose(np.asarray(full[0, -1]), np.asarray(swa[0, -1]))


def test_config_from_hf_reads_mistral_sliding_window(tmp_path):
    from tpu_inference.models.weights import config_from_hf

    hf = {"model_type": "mistral", "vocab_size": 32000,
          "hidden_size": 128, "num_hidden_layers": 2,
          "num_attention_heads": 4, "num_key_value_heads": 2,
          "intermediate_size": 256, "max_position_embeddings": 4096,
          "sliding_window": 1024}
    (tmp_path / "config.json").write_text(json.dumps(hf))
    cfg = config_from_hf(str(tmp_path))
    assert cfg.family == "llama" and cfg.sliding_window == 1024

    hf["sliding_window"] = None          # v0.2+ spelling for "no window"
    (tmp_path / "config.json").write_text(json.dumps(hf))
    assert config_from_hf(str(tmp_path)).sliding_window == 0


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_windowed_paged_decode_kernel_matches_dense(kv_quant):
    """The Pallas decode kernel's O(window) page walk (relative-page
    grid + offset index maps) == the window-masked dense reference, for
    ragged kv_lens crossing page boundaries, GQA, and the int8 pool."""
    import jax

    from tpu_inference.engine import kv_cache as kvc
    from tpu_inference.kernels.paged_attention import paged_attention

    rng = np.random.default_rng(11)
    page, mp, hq, hkv, d, window = 8, 6, 4, 2, 16, 11
    b = 3
    n_pages = 32
    kv_lens = np.array([5, 17, 41], np.int32)      # <W, >W, >>W
    k_pool = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    v_pool = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    bt = rng.permutation(np.arange(1, 1 + b * mp)).reshape(b, mp).astype(
        np.int32)
    q = rng.standard_normal((b, hq, d)).astype(np.float32)

    ks = vs = None
    if kv_quant == "int8":
        kq, ks_ = kvc.quantize_kv(jnp.asarray(k_pool))
        vq, vs_ = kvc.quantize_kv(jnp.asarray(v_pool))
        k_in, v_in, ks, vs = kq, vq, ks_, vs_
        # Dense reference sees the dequantized pool.
        k_pool = np.asarray(kq, np.float32) * np.asarray(ks_)[..., None]
        v_pool = np.asarray(vq, np.float32) * np.asarray(vs_)[..., None]
    else:
        k_in, v_in = jnp.asarray(k_pool), jnp.asarray(v_pool)

    got = paged_attention(jnp.asarray(q), k_in, v_in, jnp.asarray(bt),
                          jnp.asarray(kv_lens), ks, vs,
                          sliding_window=window, interpret=True)

    # Dense reference: gather each sequence's pages, window-masked
    # attention with the query at position kv_len-1.
    for i in range(b):
        n = int(kv_lens[i])
        flat = np.concatenate([k_pool[bt[i, j]] for j in range(mp)])[:n]
        flatv = np.concatenate([v_pool[bt[i, j]] for j in range(mp)])[:n]
        want = common.dense_causal_attention(
            jnp.asarray(q[i][None, None]),                 # [1, 1, Hq, D]
            jnp.asarray(flat[None]), jnp.asarray(flatv[None]),
            q_offset=n - 1, kv_len=n, sliding_window=window)
        np.testing.assert_allclose(np.asarray(got[i]),
                                   np.asarray(want[0, 0]),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"seq {i} kv_len {n}")


def test_swa_pallas_engine_matches_dense_engine(swa8, swa8_dense_engine):
    """Serving on the full windowed Pallas path (flash prefill + paged
    decode) produces exactly the dense backend's tokens."""
    cfg, params, _ = swa8
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (6, 21)]

    want = swa8_dense_engine.generate(prompts, max_new_tokens=14)
    pallas = InferenceEngine(cfg, cfgs.EngineConfig(**SWA_KW,
                                                    attn_backend="pallas"),
                             params=params)
    got = pallas.generate(prompts, max_new_tokens=14)
    assert got == want


@pytest.mark.parametrize("sp_attn", ["ring", "ulysses"])
def test_swa_sp_engine_matches_unsharded(sp_attn, swa8, swa8_dense_engine):
    """SWA composes with sequence parallelism (VERDICT r4 item 5): a
    sliding-window model served on an sp=2 mesh — prompts long enough to
    span both sequence shards, window smaller than the prompt so the
    mask binds — produces exactly the unsharded engine's tokens, for
    both SP prefill algorithms."""
    from tpu_inference.config import ParallelConfig
    from tpu_inference.parallel.mesh import build_mesh

    cfg, params, _ = swa8
    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (21, 13)]

    want = swa8_dense_engine.generate(prompts, max_new_tokens=10)

    # Ulysses needs n_kv_heads (2) divisible by tp*sp, so it runs tp=1;
    # the ring composes with tp=2 head sharding.
    tp = 2 if sp_attn == "ring" else 1
    mesh = build_mesh(ParallelConfig(tp=tp, sp=2))
    eng = InferenceEngine(cfg, cfgs.EngineConfig(**SWA_KW, sp_attn=sp_attn),
                          params=params, mesh=mesh)
    assert eng.sp == 2 and eng.swa_evict
    got = eng.generate(prompts, max_new_tokens=10)
    assert got == want


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_windowed_paged_prefill_kernel_matches_dense(kv_quant):
    """The windowed Pallas prefill (per-query-block relative pages) ==
    the window-masked dense reference, including a chunked-prefill
    q_offset > 0 and the int8 pool."""
    from tpu_inference.engine import kv_cache as kvc
    from tpu_inference.kernels.prefill_attention import (
        paged_prefill_attention)

    rng = np.random.default_rng(13)
    page, mp, hq, hkv, d, window = 8, 8, 4, 2, 16, 10
    b, s = 2, 24                 # current chunk length
    q_off = np.array([0, 16], np.int32)      # fresh + continued chunk
    kv_lens = q_off + s
    n_pages = 40
    k_pool = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    v_pool = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    bt = rng.permutation(np.arange(1, 1 + b * mp)).reshape(b, mp).astype(
        np.int32)
    q = rng.standard_normal((b, s, hq, d)).astype(np.float32)

    ks = vs = None
    if kv_quant == "int8":
        kq, ks_ = kvc.quantize_kv(jnp.asarray(k_pool))
        vq, vs_ = kvc.quantize_kv(jnp.asarray(v_pool))
        k_in, v_in, ks, vs = kq, vq, ks_, vs_
        k_pool = np.asarray(kq, np.float32) * np.asarray(ks_)[..., None]
        v_pool = np.asarray(vq, np.float32) * np.asarray(vs_)[..., None]
    else:
        k_in, v_in = jnp.asarray(k_pool), jnp.asarray(v_pool)

    got = paged_prefill_attention(
        jnp.asarray(q), k_in, v_in, jnp.asarray(bt), jnp.asarray(kv_lens),
        jnp.asarray(q_off), ks, vs, block_q=8, sliding_window=window,
        interpret=True)

    for i in range(b):
        n = int(kv_lens[i])
        flat = np.concatenate([k_pool[bt[i, j]] for j in range(mp)])[:n]
        flatv = np.concatenate([v_pool[bt[i, j]] for j in range(mp)])[:n]
        want = common.dense_causal_attention(
            jnp.asarray(q[i][None]), jnp.asarray(flat[None]),
            jnp.asarray(flatv[None]), q_offset=int(q_off[i]), kv_len=n,
            sliding_window=window)
        np.testing.assert_allclose(np.asarray(got[i]), np.asarray(want[0]),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"seq {i} q_off {q_off[i]}")


def test_swa_disables_prefix_cache():
    """SWA + prefix caching don't compose (evicted holes in cached
    prefixes); the engine makes the vLLM-style exclusion and turns on
    behind-window eviction instead."""
    eng = InferenceEngine(_swa_cfg(8), cfgs.EngineConfig(
        page_size=8, num_pages=32, max_pages_per_seq=4, max_batch_size=2,
        prefill_buckets=(16,), enable_prefix_cache=True), seed=0)
    assert eng.prefix_cache is None
    assert eng.swa_evict


def test_swa_exclusions_gated_on_window_binding():
    """When max_context <= window the mask can never bind (behavior is
    identical to full attention), so the SWA exclusions don't apply: the
    prefix cache stays on and eviction stays off (ADVICE r4)."""
    # window 64 vs max_context 4 pages x 8 = 32: never binds.
    eng = InferenceEngine(_swa_cfg(64), cfgs.EngineConfig(
        page_size=8, num_pages=32, max_pages_per_seq=4, max_batch_size=2,
        prefill_buckets=(16,), enable_prefix_cache=True), seed=0)
    assert eng.prefix_cache is not None
    assert not eng.swa_evict


def test_swa_eviction_bounds_live_pages_and_preserves_tokens():
    """A sequence decoding far past its window holds O(window) live KV
    pages (behind-window pages return to the pool mid-flight), and the
    tokens still match the windowed full-forward oracle."""
    from tpu_inference.engine.engine import Sequence

    window, page = 8, 8
    cfg = _swa_cfg(window)
    ecfg = cfgs.EngineConfig(page_size=page, num_pages=64,
                             max_pages_per_seq=16, max_batch_size=2,
                             prefill_buckets=(16, 32))
    params, mod = build_model(cfg, seed=0)
    engine = InferenceEngine(cfg, ecfg, params=params)
    assert engine.swa_evict

    rng = np.random.default_rng(9)
    prompt = rng.integers(0, 256, size=20).tolist()
    seq = Sequence(request_id=0, prompt_tokens=prompt, max_new_tokens=40)
    free_at_prefill = engine.allocator.num_free
    engine.prefill(seq)
    max_live = 0
    while engine.active_sequences():
        engine.decode_step()
        live = sum(1 for p in seq.pages if p)
        max_live = max(max_live, live)
    # Window spans at most ceil(W/page)+1 pages; +1 more for the page
    # being written at the head.
    assert max_live <= -(-window // page) + 2, max_live
    # Behind-window pages really went back to the pool mid-flight: at
    # the end the sequence holds far fewer than its ctx would need.
    assert sum(1 for p in seq.pages if p) < (seq.ctx_len // page)

    got = list(seq.generated)
    engine.release(seq)
    assert engine.allocator.num_free == free_at_prefill

    # Token equality with the windowed no-cache oracle (shared-compile).
    from tests.test_engine import reference_greedy
    assert got == reference_greedy(params, mod, cfg, prompt, 40)


def test_mistral_preset_registered():
    """'mistral' is what the reference's endpoint served; the preset
    carries its sliding window into the windowed serving path."""
    cfg = cfgs.PRESETS["mistral-7b"]()
    assert cfg.sliding_window == 4096 and cfg.family == "llama"
    from tpu_inference.engine.autosize import auto_size

    # And it sizes onto one 16 GB chip with int8 (the reference's
    # Ollama served it quantized too).
    sz = auto_size(cfg, hbm_bytes=16e9, quant="int8", kv_quant="int8")
    assert sz.max_batch_size >= 8


def test_spec_decode_serves_swa_target(swa8, swa8_dense_engine):
    """Speculative decoding with a window-less draft over an SWA target:
    emitted tokens must equal the plain SWA engine's (the verify pass
    windows the target's logits; rejection sampling is exact)."""
    import dataclasses

    cfg, params, _ = swa8
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (6, 18)]
    want = swa8_dense_engine.generate(prompts, max_new_tokens=12)

    draft_cfg = dataclasses.replace(cfg, name="draft", n_layers=1,
                                    sliding_window=0)
    draft_params, _ = build_model(draft_cfg, seed=9)
    spec = InferenceEngine(
        cfg, cfgs.EngineConfig(**SWA_KW, num_speculative_tokens=3),
        params=params, draft_cfg=draft_cfg, draft_params=draft_params)
    assert not spec.swa_evict        # window-less draft reads full ctx
    got = spec.generate(prompts, max_new_tokens=12)
    assert got == want


def test_swa_admission_reserves_window_not_generation():
    """Admission must charge an SWA-evict sequence its true peak (full
    prompt at prefill, O(window) during decode) — not prompt+max_new.
    A long-generation Mistral-style request fits a small pool."""
    from tpu_inference.engine.engine import Sequence

    cfg = _swa_cfg(8)
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=16, max_pages_per_seq=8,
                             max_batch_size=1, prefill_buckets=(16,),
                             max_new_tokens=512)
    eng = InferenceEngine(cfg, ecfg, seed=0)
    seq = Sequence(request_id=0, prompt_tokens=list(range(1, 11)),
                   max_new_tokens=500)     # 510 tokens = 64 pages naively
    assert eng._pages_reserved(seq) <= 5   # window span + margins
    assert eng.can_ever_admit(seq)
    # And it actually serves to completion inside the 15-page pool.
    eng.prefill(seq)
    while eng.active_sequences():
        eng.decode_steps()
    assert seq.finish_reason in ("stop", "length"), seq.finish_reason
    assert len(seq.generated) > 50         # decoded far past the pool's
    eng.release(seq)                       # naive capacity
