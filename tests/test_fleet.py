"""Process fleet (README "Process fleet"): router + engine-worker
processes with KV page migration.

Covers the subsystem at three levels:

- pure units: the RPC frame codec, JSON config transport, and the
  migration wire format (bit-exact host-page round-trips for every
  kv_quant layout) — no processes, no jax device work beyond an engine.
- engine-level: host-tier import (capacity, LRU-for-imports, tier
  invariant, leak cleanliness).
- REAL processes: a module-scoped dp=2 subprocess fleet exercised for
  backend equivalence (byte-identical greedy outputs vs the in-process
  EngineGroup), ``kill -9``-a-worker-mid-decode chaos (requests fail
  over from the router's token record and complete byte-identically;
  the fleet restarts the worker; survivors' pools stay leak-free), the
  SIGTERM drain-and-migrate path (admission on the destination becomes
  a swap-in-resume), and metrics-label hygiene across restarts (stable
  ``replica="i"`` label, no counter resets, no duplicate series).
- P/D disaggregation (README "P/D disaggregation"): live-sequence KV
  handoff export/adopt at the engine level for every kv_quant mode
  (including the partial final page the drain path would recompute),
  the malformed-blob fallback to recompute-resume, and a second
  module-scoped 1-prefill+1-decode fleet pinning handoff routing,
  role observability, and a handoff racing a decode-worker ``kill -9``
  (stale-blob fallback, byte-identical).
"""

import hashlib
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from tests._leak import assert_arena_clean
from tpu_inference.config import (EngineConfig, FrameworkConfig,
                                  ParallelConfig, ServerConfig,
                                  framework_config_from_dict,
                                  framework_config_to_dict, tiny_llama)
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import InferenceEngine, Sequence

# One geometry for every fleet test: small enough to boot a worker in
# seconds, host tier on so drain migration has somewhere to land.
ENGINE_KW = dict(page_size=8, num_pages=64, max_pages_per_seq=8,
                 max_batch_size=2, prefill_buckets=(16,),
                 host_cache_pages=32)


def _cfg(dp=2, **server_kw) -> FrameworkConfig:
    server_kw.setdefault("fleet", "subprocess")
    server_kw.setdefault("worker_restart_max", 10)
    server_kw.setdefault("worker_restart_backoff_s", 0.1)
    server_kw.setdefault("drain_timeout_s", 8.0)
    return FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(**ENGINE_KW),
        parallel=ParallelConfig(dp=dp),
        server=ServerConfig(model_name="t", tokenizer="byte",
                            warmup=False, **server_kw))


# ------------------------------------------------------------- units


def test_frame_codec_roundtrip():
    """Length-prefixed JSON + binary attachment round-trips through a
    real socketpair, including interleaved frames and empty blobs."""
    import socket

    from tpu_inference.server.worker import recv_frame, send_frame

    a, b = socket.socketpair()
    rfile = b.makefile("rb")
    send_frame(a, {"id": 1, "verb": "hello"})
    send_frame(a, {"ev": "token", "t": 42}, blob=b"\x00\x01\xffbytes")
    obj, blob = recv_frame(rfile)
    assert obj == {"id": 1, "verb": "hello"} and blob == b""
    obj, blob = recv_frame(rfile)
    assert obj["t"] == 42 and blob == b"\x00\x01\xffbytes"
    a.close()
    with pytest.raises(ConnectionError):
        recv_frame(rfile)
    b.close()


def test_config_json_transport_roundtrip():
    """The router->worker config envelope survives JSON: dtypes by
    name, tuples, nested dataclasses, fleet knobs."""
    cfg = _cfg(dp=3)
    cfg2 = framework_config_from_dict(
        json.loads(json.dumps(framework_config_to_dict(cfg))))
    assert cfg2.model == cfg.model
    assert cfg2.engine == cfg.engine
    assert cfg2.parallel == cfg.parallel
    assert cfg2.server == cfg.server
    assert cfg2.engine.prefill_buckets == (16,)
    assert cfg2.model.dtype == cfg.model.dtype


@pytest.mark.parametrize("quant", ["none", "int8", "int4"])
def test_host_page_serialization_bit_exact(quant):
    """The migration wire format round-trips every kv_quant host-page
    layout bit-exactly (the PR-6 stored layout, serialized)."""
    rng = np.random.default_rng(7)
    if quant == "none":
        mk = lambda: rng.standard_normal((2, 8, 2, 16)).astype(np.float32)
        pages = [kvc.HostKVPage(mk(), mk()) for _ in range(3)]
    else:
        code_dt = np.uint8 if quant == "int4" else np.int8
        d = 8 if quant == "int4" else 16
        mk = lambda: rng.integers(0, 255, (2, 8, 2, d)).astype(code_dt)
        sc = lambda: rng.standard_normal((2, 8, 2)).astype(np.float32)
        pages = [kvc.HostKVPage(mk(), mk(), sc(), sc()) for _ in range(3)]
    blob = kvc.serialize_host_pages(pages)
    back = kvc.deserialize_host_pages(blob)
    assert len(back) == len(pages)
    for orig, got in zip(pages, back):
        np.testing.assert_array_equal(orig.k, got.k)
        np.testing.assert_array_equal(orig.v, got.v)
        if orig.k_scale is None:
            assert got.k_scale is None
        else:
            np.testing.assert_array_equal(orig.k_scale, got.k_scale)
            np.testing.assert_array_equal(orig.v_scale, got.v_scale)
        assert orig.nbytes == got.nbytes
    assert kvc.deserialize_host_pages(kvc.serialize_host_pages([])) == []


def test_import_host_capacity_and_tier_invariant():
    """Engine-level migration import: entries land in the host tier
    (newest-LRU), duplicates of either tier are skipped, imports evict
    the tier's own oldest warmth to fit, overflow drops the remainder,
    and the leak invariant holds after a clear."""
    from tests._leak import assert_pool_clean

    engine = InferenceEngine(tiny_llama(vocab_size=512),
                             EngineConfig(**{**ENGINE_KW,
                                             "host_cache_pages": 4}))
    cache, pool = engine.prefix_cache, engine.host_pool

    def entry(tag: int):
        k = np.full((2, 8, 2, 16), tag, np.float32)
        return kvc.HostKVPage(k, k.copy())

    d = [bytes([i]) * 16 for i in range(8)]
    assert cache.import_host([(d[0], entry(0)), (d[1], entry(1))]) == 2
    assert pool.used == 2 and pool.imported_total == 2
    # Duplicate digest: skipped, not double-resident.
    assert cache.import_host([(d[0], entry(9))]) == 0
    # Fill to capacity, then one more: the OLDEST host entry evicts.
    assert cache.import_host([(d[2], entry(2)), (d[3], entry(3))]) == 2
    assert cache.import_host([(d[4], entry(4))]) == 1
    assert pool.used == 4 and d[0] not in cache._host
    assert d[4] in cache._host
    # Offering more than capacity drops the tail (never over-fills).
    added = cache.import_host([(d[i], entry(i)) for i in range(5, 8)])
    assert pool.used == 4 and added <= 3
    # Apply-queue path (the worker's import-kv RPC marshals through the
    # engine loop): queued entries adopt on apply, event fires.
    done = engine.request_import_host([(b"z" * 16, entry(42))])
    engine.apply_pending_imports()
    assert done.is_set()
    assert engine.migrate_in_pages >= 1
    assert_pool_clean(engine)


# ------------------------------------------------- real process fleet


def _submit(group, rid, prompt, max_new, timeout=180.0):
    toks, done, box = [], threading.Event(), {}
    seq = Sequence(request_id=rid, prompt_tokens=list(prompt),
                   max_new_tokens=max_new)
    group.submit(seq, lambda s, t: toks.append(t),
                 lambda s: (box.update(seq=s), done.set()))
    return toks, done, box


def _finish(done, box, timeout=180.0):
    assert done.wait(timeout), "request did not finish"
    return box["seq"]


def _wait_states(group, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(h.state == "up" for h in group.workers):
            return
        time.sleep(0.1)
    raise AssertionError(
        f"fleet never healed: {[h.state for h in group.workers]}")


@pytest.fixture(scope="module")
def fleet():
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(dp=2))
    group.start()
    yield group
    group.stop(drain=False)


@pytest.fixture(scope="module")
def oracle():
    """In-process engine with the same seed/geometry as every worker:
    greedy outputs must match the fleet's byte for byte."""
    return InferenceEngine(tiny_llama(vocab_size=512),
                           EngineConfig(**ENGINE_KW), seed=0)


def test_fleet_basic_and_surfaces(fleet, oracle):
    toks, done, box = _submit(fleet, 0, [1, 2, 3, 4, 5], 12)
    fin = _finish(done, box)
    assert fin.finish_reason == "length"
    assert toks == oracle.generate([[1, 2, 3, 4, 5]],
                                   max_new_tokens=12)[0]
    assert fin.routed_replica in (0, 1)

    hs = fleet.health_snapshot()
    assert hs["status"] == "ok" and hs["fleet"] == "subprocess"
    assert len(hs["replicas"]) == 2
    for r in hs["replicas"]:
        assert r["pid"] and "restarts" in r and "routing" in r
        assert "pool_pressure" in r and "host_cache" in r
    ss = fleet.stats_snapshot()
    assert ss["dp"] == 2 and ss["tokens_generated"] >= 12
    assert "phases" in ss and "supervision" in ss
    pt = fleet.prometheus_text()
    assert 'replica="0"' in pt and 'replica="1"' in pt
    assert "tpu_inf_worker_up" in pt
    assert "tpu_inf_fleet_migrations_total" in pt
    # /debug/requests analogue: merged recent timelines.
    recent = fleet.recent_snapshot(10)
    assert recent and recent[-1]["finish_reason"] == "length"


def test_backend_equivalence_pinned_mix(fleet):
    """Satellite: the same pinned greedy mix through --fleet in-process
    and --fleet subprocess produces byte-identical outputs
    (outputs_sha256), identical finish reasons, and matching
    route/telemetry counter shapes."""
    from tpu_inference.server.http import build_engine_group

    prompts = [[1, 2, 3], [9, 8, 7, 6], [5, 5, 5, 5, 5], [2, 4, 6]]
    budgets = [10, 14, 8, 200]          # 200 hits the context cap

    def run(group):
        outs, reasons = [], []
        pend = [_submit(group, 1000 + i, p, b)
                for i, (p, b) in enumerate(zip(prompts, budgets))]
        for toks, done, box in pend:
            fin = _finish(done, box)
            outs.append(list(toks))
            reasons.append(fin.finish_reason)
        h = hashlib.sha256()
        for o in outs:
            h.update(np.asarray(o, np.int32).tobytes() + b"|")
        return h.hexdigest(), reasons, group.stats_snapshot()

    cfg = _cfg(dp=2, fleet="in-process")
    inproc = build_engine_group(cfg).start()
    try:
        sha_in, reasons_in, stats_in = run(inproc)
    finally:
        inproc.stop(drain=False)
    sha_sub, reasons_sub, stats_sub = run(fleet)

    assert sha_sub == sha_in
    assert reasons_sub == reasons_in
    # Counter-shape parity: every in-process supervision counter exists
    # in the subprocess fleet's view, and the aggregated stats share
    # the core serving keys.
    assert set(stats_in["supervision"]) <= set(stats_sub["supervision"])
    core = {"steps", "prefills", "tokens_generated", "requests_finished",
            "preemptions", "recompute_resumes", "swap_in_resumes",
            "migrate_out_pages", "migrate_in_pages", "kv_pages_total",
            "decode_ladder", "phases", "replicas", "dp", "supervision"}
    assert core <= set(stats_in) and core <= set(stats_sub)
    # Route stats per replica share the same shape.
    h_in = inproc.health_snapshot()["replicas"][0]["routing"]
    h_sub = fleet.health_snapshot()["replicas"][0]["routing"]
    assert set(h_in) == set(h_sub)


def test_kill9_chaos_failover(fleet, oracle):
    """Acceptance: kill -9 a worker mid-decode. In-flight requests on
    the killed worker fail over (router token record, recompute-resume
    on the survivor) and COMPLETE byte-identically; /healthz shows the
    restart; no KV pages leak on the survivors."""
    _wait_states(fleet)
    failovers0 = fleet.failovers
    # Two long streams: the cold-prompt rotating tie-break spreads them
    # across both workers, so SOME worker holds a mid-decode stream.
    a = _submit(fleet, 2000, [7, 8, 9], 40)
    b = _submit(fleet, 2001, [3, 1, 4, 1, 5], 40)
    deadline = time.monotonic() + 60
    while (len(a[0]) < 4 or len(b[0]) < 4) and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(a[0]) >= 4 and len(b[0]) >= 4
    with fleet._lock:
        victim_idx = fleet._tracked[2000].worker.replica
    r = fleet.apply_chaos({"replica": victim_idx, "kill": "kill9"})
    assert r["killed"] == "kill9"

    fin_a = _finish(a[1], a[2])
    fin_b = _finish(b[1], b[2])
    assert fin_a.finish_reason == "length"
    assert fin_b.finish_reason == "length"
    # Byte-identity: the failover resume replays the streamed prefix
    # and continues exactly where the dead worker left off (greedy).
    assert a[0] == oracle.generate([[7, 8, 9]], max_new_tokens=40)[0]
    assert b[0] == oracle.generate([[3, 1, 4, 1, 5]],
                                   max_new_tokens=40)[0]
    assert fleet.failovers > failovers0

    # The fleet restarts the worker under the same replica label.
    _wait_states(fleet)
    hs = fleet.health_snapshot()
    assert hs["replicas"][victim_idx]["restarts"] >= 1
    assert hs["supervision"]["worker_restarts"] >= 1

    # Leak invariant on the survivors (worker-side debug snapshot: the
    # tests/_leak checks, evaluated in the worker process after
    # clearing its cache references).
    for h in fleet.workers:
        snap = h.client.rpc("debug", clear=True)
        assert not snap["pipeline_pending"]
        assert snap["preempted_uncollected"] == 0
        assert snap["slots_bound"] == 0
        assert snap["num_free"] == snap["num_pages"] - 1, snap
        assert snap["refs_held"] == 0 and snap["evictable_count"] == 0
        assert snap["host_used"] == 0
        assert snap.get("tier_overlap", 0) == 0


def test_sigterm_drain_migrates_kv(fleet, oracle):
    """Tentpole proof: graceful drain (SIGTERM) exports the in-flight
    sequence's KV pages over the migration channel; the router imports
    them into the destination's host tier and resubmission becomes a
    swap-in-resume — tokens byte-identical, migrated pages > 0, and the
    destination records a swap_in_resume."""
    _wait_states(fleet)
    migrations0 = fleet.migrations
    pages0 = fleet.migrated_pages
    prompt = [11, 12, 13, 14, 15, 16, 17]
    toks, done, box = _submit(fleet, 3000, prompt, 48)
    deadline = time.monotonic() + 60
    # Wait until a couple of FULL pages of KV exist (page_size=8).
    while len(toks) < 18 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(toks) >= 18
    with fleet._lock:
        src_idx = fleet._tracked[3000].worker.replica
    fleet.apply_chaos({"replica": src_idx, "kill": "sigterm"})

    fin = _finish(done, box)
    assert fin.finish_reason == "length"
    assert toks == oracle.generate([prompt], max_new_tokens=48)[0]
    assert fleet.migrations > migrations0
    assert fleet.migrated_pages > pages0
    assert fleet.resume_reused_tokens > 0
    sup = fleet.supervision_counters()
    assert sup["swap_in_resumes"] >= 1
    assert sup["migrated_bytes"] > 0
    _wait_states(fleet)


def test_metrics_label_stable_across_restart(fleet):
    """Satellite: per-worker series keep the stable replica="i" label
    across a restart, fleet-level counters never reset (restart carry),
    and no series is double-reported in the aggregated scrape."""
    from tests import _prom

    _wait_states(fleet)
    # Traffic so worker counters are non-zero, then force the periodic
    # metrics cache (the carry source) to be fresh.
    toks, done, box = _submit(fleet, 4000, [2, 7, 1, 8], 10)
    _finish(done, box)
    fleet._refresh_caches()

    def scrape():
        _, samples = _prom.parse(fleet.prometheus_text())
        out = {}
        for name, labels, value in samples:
            key = (name, tuple(sorted(labels.items())))
            assert key not in out, f"duplicate series {key}"
            out[key] = value
        return out

    before = scrape()

    def series(samples, name):
        return {labels: v for (n, labels), v in samples.items()
                if n == name}

    tok_before = series(before, "tpu_inf_tokens_generated_total")
    replicas = {dict(labels).get("replica") for labels in tok_before}
    assert replicas == {"0", "1"}
    # build_info: one info series per replica + one fleet-level, all
    # value 1 with config-pure labels.
    binfo_before = series(before, "tpu_inf_build_info")
    assert len(binfo_before) == 3
    assert all(v == 1.0 for v in binfo_before.values())

    # Restart worker 0 gracefully (drain carries the final dump).
    fleet.apply_chaos({"replica": 0, "kill": "sigterm"})
    deadline = time.monotonic() + 60
    while fleet.workers[0].state == "up" and time.monotonic() < deadline:
        time.sleep(0.05)
    _wait_states(fleet)

    after = scrape()                 # scrape() re-asserts no duplicates
    tok_after = series(after, "tpu_inf_tokens_generated_total")
    assert set(tok_after) == set(tok_before)
    for labels, v in tok_before.items():
        # Monotone across the restart: the carry folds the dead
        # incarnation's total under the same replica label.
        assert tok_after[labels] >= v, (labels, v, tok_after[labels])
    # Fleet-side restart counter moved under the stable label.
    restarts = series(after, "tpu_inf_worker_restarts_total")
    assert restarts[(("replica", "0"),)] >= 1
    # build_info label stability: the restarted worker re-minted the
    # IDENTICAL labelset (values are pure config), so the series set is
    # unchanged — no new series, none vanished, still all value 1.
    binfo_after = series(after, "tpu_inf_build_info")
    assert set(binfo_after) == set(binfo_before)
    assert all(v == 1.0 for v in binfo_after.values())


# ------------------------------------------- P/D disaggregation (live
# KV handoff): engine-level export/adopt, then a real 1p+1d fleet.

# 13 tokens: two KV pages at page_size=8, the second PARTIAL — the
# case the drain-time migrate path recomputes and the live handoff
# must move verbatim.
PD_PROMPT = [5, 9, 2, 7, 3, 8, 1, 6, 4, 2, 9, 1, 7]


def _run_sched(engine, seq, hook=None, timeout=180.0):
    """One request through a real EngineScheduler; returns
    (streamed tokens, finished seq, scheduler) after a hard stop."""
    from tpu_inference.engine.scheduler import EngineScheduler

    sched = EngineScheduler(engine)
    if hook is not None:
        sched.on_prefill_handoff = hook
    sched.start()
    toks, done, box = [], threading.Event(), {}
    try:
        sched.submit(seq, lambda s, t: toks.append(t),
                     lambda s: (box.update(seq=s), done.set()))
        assert done.wait(timeout), "request did not finish"
    finally:
        sched.stop(drain=False)
    return toks, box["seq"], sched


def _pd_engine(quant, role):
    return InferenceEngine(
        tiny_llama(vocab_size=512),
        EngineConfig(**{**ENGINE_KW, "kv_quant": quant, "role": role}),
        seed=0)


@pytest.mark.parametrize("quant", ["none", "int8", "int4"])
def test_live_handoff_export_adopt_bit_exact(quant):
    """Satellite: a LIVE (in-flight, not draining) sequence's KV
    exports on a prefill-role engine — including the partial final
    page — crosses the wire format, and adopts on a decode-role engine
    with ZERO prefill dispatches and zero recomputed tokens; the
    continued greedy stream is byte-identical to a mixed engine, for
    every kv_quant layout."""
    from tests._leak import assert_pool_clean

    src = _pd_engine(quant, "prefill")
    captured = {}

    def hook(s):
        digests, pages, ctx = src.export_sequence_kv_live(s)
        if not pages:
            return False
        captured["blob"] = kvc.serialize_host_pages(pages)
        captured["ctx"] = ctx
        captured["digests"] = digests
        return True

    seq = Sequence(request_id=1, prompt_tokens=list(PD_PROMPT),
                   max_new_tokens=24)
    seq.handoff_after_prefill = True
    toks_src, fin_src, _ = _run_sched(src, seq, hook)
    # The prefill settled, streamed exactly the first token, and
    # finished locally as a handoff.
    assert fin_src.finish_reason == "handoff"
    assert len(toks_src) == 1
    assert src.handoffs_out == 1
    # The export covers EVERY page holding ctx_len tokens — the final
    # one partial (13 % 8 != 0) — while chain digests cover only the
    # full pages (a chain digest is defined on full pages).
    assert captured["ctx"] == len(PD_PROMPT)
    pages = kvc.deserialize_host_pages(captured["blob"])
    assert len(pages) == 2 and len(captured["digests"]) == 1

    dst = _pd_engine(quant, "decode")
    seq2 = Sequence(request_id=2, prompt_tokens=list(PD_PROMPT),
                    max_new_tokens=24)
    seq2.generated = list(toks_src)
    seq2.resume_base = len(toks_src)
    seq2.adopt_kv = (pages, captured["ctx"])
    toks_dst, fin_dst, sched_dst = _run_sched(dst, seq2)
    assert fin_dst.finish_reason == "length"
    # Clean-handoff path: the adoption restored KV instead of
    # prefilling — nothing recomputed on the decode side.
    assert sched_dst.stats.prefills == 0
    assert dst.adoptions_in == 1 and dst.swap_in_resumes == 1
    assert fin_dst.cached_tokens == len(PD_PROMPT) + 1

    mixed = _pd_engine(quant, "mixed")
    want = mixed.generate([list(PD_PROMPT)], max_new_tokens=24)[0]
    assert toks_src + toks_dst == want
    assert_pool_clean(src)
    assert_pool_clean(dst)


def test_handoff_adopt_malformed_blob_recomputes():
    """A handoff blob that doesn't match its ctx_len (truncated page
    list) must NOT stick: adoption fails, the scheduler clears the
    adoption state and recompute-resumes through the ordinary prefill
    path — byte-identical, with the recompute visible in stats."""
    from tests._leak import assert_pool_clean

    src = _pd_engine("none", "prefill")
    captured = {}

    def hook(s):
        _, pages, ctx = src.export_sequence_kv_live(s)
        captured["pages"], captured["ctx"] = pages, ctx
        return bool(pages)

    seq = Sequence(request_id=3, prompt_tokens=list(PD_PROMPT),
                   max_new_tokens=16)
    seq.handoff_after_prefill = True
    toks_src, _, _ = _run_sched(src, seq, hook)

    dst = _pd_engine("none", "decode")
    seq2 = Sequence(request_id=4, prompt_tokens=list(PD_PROMPT),
                    max_new_tokens=16)
    seq2.generated = list(toks_src)
    seq2.resume_base = len(toks_src)
    # Truncated: one page short of what ctx_len needs.
    seq2.adopt_kv = (captured["pages"][:-1], captured["ctx"])
    toks_dst, fin_dst, sched_dst = _run_sched(dst, seq2)
    assert fin_dst.finish_reason == "length"
    assert dst.adoptions_in == 0
    assert dst.adopt_fallbacks == 1           # counted, not silent
    assert sched_dst.stats.prefills == 1      # the recompute-resume
    mixed = _pd_engine("none", "mixed")
    want = mixed.generate([list(PD_PROMPT)], max_new_tokens=16)[0]
    assert toks_src + toks_dst == want
    assert_pool_clean(dst)


@pytest.fixture(scope="module")
def pd_fleet():
    """1 prefill + 1 decode worker: the smallest disaggregated
    topology (README "P/D disaggregation")."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(
        _cfg(dp=2, worker_roles=("prefill", "decode")))
    group.start()
    yield group
    group.stop(drain=False)


def test_pd_fleet_handoff_byte_identity_and_surfaces(pd_fleet, oracle):
    """Tentpole proof at process level: new prompts admit to the
    prefill worker, settle, hand off, and decode on the decode worker
    — outputs byte-identical to a mixed engine, zero handoff
    recomputes, with roles/backlog/occupancy/handoff counters visible
    in /healthz, stats, and the Prometheus scrape."""
    _wait_states(pd_fleet)
    handoffs0 = pd_fleet.pd_handoffs
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [4, 4, 4, 4]]
    pend = [_submit(pd_fleet, 6000 + i, p, 16)
            for i, p in enumerate(prompts)]
    for (toks, done, box), p in zip(pend, prompts):
        fin = _finish(done, box)
        assert fin.finish_reason == "length"
        assert toks == oracle.generate([p], max_new_tokens=16)[0]
    assert pd_fleet.pd_handoffs >= handoffs0 + len(prompts)
    assert pd_fleet.pd_handoff_recomputes == 0

    # stats_snapshot refreshes each worker's cached stats, so the
    # supervision view's adoption sum is current.
    sup = pd_fleet.stats_snapshot()["supervision"]
    assert sup["roles"] == ["prefill", "decode"]
    assert sup["pd_handoffs"] >= len(prompts)
    assert sup["pd_adoptions"] >= len(prompts)
    # The handoff-wall histogram rides supervision as a diffable phase
    # snapshot (one observation per routed handoff).
    assert sup["phases"]["pd_handoff_s"]["count"] >= len(prompts)
    assert sup["phases"]["pd_handoff_s"]["p95"] is not None
    hs = pd_fleet.health_snapshot()
    roles = [r["role"] for r in hs["replicas"]]
    assert roles == ["prefill", "decode"]
    for r in hs["replicas"]:
        assert "prefill_backlog" in r and "ladder_occupancy" in r
    # The decode worker did the adopting; the prefill worker the
    # handing-off.
    assert hs["replicas"][0]["pd_handoffs"] >= len(prompts)
    assert hs["replicas"][1]["pd_adoptions"] >= len(prompts)
    pt = pd_fleet.prometheus_text()
    assert 'tpu_inf_worker_role_info{replica="0",role="prefill"}' in pt
    assert 'tpu_inf_worker_role_info{replica="1",role="decode"}' in pt
    assert "tpu_inf_pd_handoffs_total" in pt
    assert "tpu_inf_pd_handoff_seconds_bucket" in pt
    # Relay plane (no --kv-plane shm): the arena invariant checker is
    # a documented no-op, and no handoff blob leaked a tracked slab.
    assert_arena_clean(pd_fleet)


@pytest.mark.slow   # ~77s of restart-backoff waits; the handoff fallback
                    # path it races is covered fast by the malformed-blob
                    # recompute test and pd byte-identity stays tier-1
def test_pd_handoff_races_decode_restart(pd_fleet, oracle):
    """Satellite: kill -9 the decode worker AFTER it adopted a handoff
    and streamed tokens. The kept handoff blob is stale (decode
    advanced past the export), so the failover falls back to
    recompute-resume — on the prefill worker, since no decode worker
    is routable — and the stream completes byte-identically; the
    supervisor restarts the decode worker."""
    _wait_states(pd_fleet)
    recomputes0 = pd_fleet.pd_handoff_recomputes
    prompt = [8, 1, 8, 2, 8, 3]
    toks, done, box = _submit(pd_fleet, 7000, prompt, 40)
    deadline = time.monotonic() + 60
    # Wait until decode is well past the handoff point (1 token).
    while len(toks) < 6 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(toks) >= 6
    with pd_fleet._lock:
        holder = pd_fleet._tracked[7000].worker.replica
    assert holder == 1        # the decode worker owns the stream
    pd_fleet.apply_chaos({"replica": 1, "kill": "kill9"})

    fin = _finish(done, box)
    assert fin.finish_reason == "length"
    assert toks == oracle.generate([prompt], max_new_tokens=40)[0]
    # The stale-export fallback fired: the blob was dropped, not
    # adopted (adopting it would fork the stream).
    assert pd_fleet.pd_handoff_recomputes > recomputes0
    _wait_states(pd_fleet)
    assert pd_fleet.health_snapshot()["replicas"][1]["restarts"] >= 1


def test_handoff_trace_id_in_worker_logs(oracle, tmp_path):
    """Trace-id satellite, pinned at the OS level: the id a client
    sends appears in BOTH workers' structured logs for a handed-off
    request — the prefill worker's request_finish (reason "handoff")
    and the decode worker's terminal request_finish. The fleet spawns
    with fd 2 redirected to a file (workers inherit it for life) and
    TPU_INF_LOG=info, so the assertion reads the workers' REAL stderr
    stream, not an in-process shim."""
    import os

    from tpu_inference.server.fleet import ProcessEngineGroup

    log_path = tmp_path / "workers.stderr"
    log_fd = os.open(str(log_path), os.O_CREAT | os.O_WRONLY, 0o600)
    saved = os.dup(2)
    prior = os.environ.get("TPU_INF_LOG")
    os.environ["TPU_INF_LOG"] = "info"
    try:
        os.dup2(log_fd, 2)
        try:
            group = ProcessEngineGroup(
                _cfg(dp=2, worker_roles=("prefill", "decode")))
            group.start()
        finally:
            os.dup2(saved, 2)
    finally:
        os.close(saved)
        os.close(log_fd)
        if prior is None:
            os.environ.pop("TPU_INF_LOG", None)
        else:
            os.environ["TPU_INF_LOG"] = prior
    tid = "cli-e2e-7f3a"
    try:
        _wait_states(group)
        toks, done, box = [], threading.Event(), {}
        seq = Sequence(request_id=8000, prompt_tokens=list(PD_PROMPT),
                       max_new_tokens=12, trace_id=tid)
        group.submit(seq, lambda s, t: toks.append(t),
                     lambda s: (box.update(seq=s), done.set()))
        fin = _finish(done, box)
        assert fin.finish_reason == "length"
        assert toks == oracle.generate([list(PD_PROMPT)],
                                       max_new_tokens=12)[0]
        deadline = time.monotonic() + 30
        reasons = set()
        while time.monotonic() < deadline:
            lines = [l for l in log_path.read_text().splitlines()
                     if '"request_finish"' in l and tid in l]
            reasons = {json.loads(l)["reason"] for l in lines}
            if {"handoff", "length"} <= reasons:
                break
            time.sleep(0.1)
        assert {"handoff", "length"} <= reasons, \
            log_path.read_text()[-2000:]
        for line in lines:
            assert json.loads(line)["request_id"] == tid
        # /debug/requests on both workers: one timeline per side, both
        # under the client's id.
        recent = [t for t in group.recent_snapshot(50)
                  if t["trace_id"] == tid]
        assert {t["finish_reason"] for t in recent} \
            == {"handoff", "length"}
    finally:
        group.stop(drain=False)


def test_handoff_span_tree_three_processes(pd_fleet, oracle):
    """Tentpole, end to end across three OS processes: the router
    assembles ONE span tree under the client's trace id with router +
    prefill-worker + decode-worker spans, the handoff export/adopt
    spans adjacent and non-overlapping with prefill/decode."""
    _wait_states(pd_fleet)
    tid = "cli-span-9b1c"
    toks, done, box = [], threading.Event(), {}
    seq = Sequence(request_id=8200, prompt_tokens=list(PD_PROMPT),
                   max_new_tokens=12, trace_id=tid)
    pd_fleet.submit(seq, lambda s, t: toks.append(t),
                    lambda s: (box.update(seq=s), done.set()))
    fin = _finish(done, box)
    assert fin.finish_reason == "length"
    assert toks == oracle.generate([list(PD_PROMPT)],
                                   max_new_tokens=12)[0]

    # The assembled span tree: one trace id, three processes.
    snap = pd_fleet.trace_snapshot(tid)
    assert snap is not None
    assert snap["replicas"] == [-1, 0, 1]
    spans = {s["name"]: s for s in snap["spans"]}
    for name in ("request", "route", "handoff", "prefill",
                 "handoff_export", "handoff_adopt", "decode"):
        assert name in spans, (name, sorted(spans))
    assert spans["prefill"]["replica"] == 0
    assert spans["handoff_export"]["replica"] == 0
    assert spans["handoff_adopt"]["replica"] == 1
    assert spans["decode"]["replica"] == 1
    assert snap["tree"]["name"] == "request"

    def end(s):
        return s["ts"] + s["dur"]

    # Adjacent + non-overlapping: prefill -> export (same process,
    # exact) -> adopt (cross-process, 5 ms anchor tolerance) -> decode
    # (same process, exact by construction).
    assert end(spans["prefill"]) <= spans["handoff_export"]["ts"] + 1e-6
    assert end(spans["handoff_export"]) \
        <= spans["handoff_adopt"]["ts"] + 5e-3
    assert end(spans["handoff_adopt"]) <= spans["decode"]["ts"] + 1e-6

    # The pull path agrees with the event-frame assembly: the decode
    # worker's trace verb serves its half of the same trace.
    h1 = pd_fleet.workers[1]
    pulled = h1.client.rpc("trace", timeout=10.0, trace=tid)["spans"]
    assert {"handoff_adopt", "decode"} <= {s["name"] for s in pulled}


def test_pd_fleet_scrape_catalog_slo_and_build_info(pd_fleet):
    """Satellite: a LIVE dp=2 P/D fleet's aggregated scrape parses
    under the strict exposition parser, has no duplicate series across
    fleet aggregation, and carries the new slo / build_info series with
    correct types — per replica AND fleet-level."""
    from tests import _prom

    _wait_states(pd_fleet)
    # Traffic so the SLO windows hold data, then refresh the cached
    # worker stats the fleet-level pooled gauges read.
    toks, done, box = _submit(pd_fleet, 8100, [3, 1, 4, 1, 5], 8)
    _finish(done, box)
    pd_fleet._refresh_caches()

    meta, samples = _prom.parse(pd_fleet.prometheus_text())
    seen = set()
    for name, labels, _ in samples:
        key = (name, tuple(sorted(labels.items())))
        assert key not in seen, f"duplicate series {key}"
        seen.add(key)

    assert meta["tpu_inf_slo_ttft_seconds"]["type"] == "gauge"
    assert meta["tpu_inf_slo_tpot_seconds"]["type"] == "gauge"
    assert meta["tpu_inf_slo_breaches_total"]["type"] == "counter"
    assert meta["tpu_inf_build_info"]["type"] == "gauge"

    def rows(name):
        return [(labels, v) for n, labels, v in samples if n == name]

    slo = rows("tpu_inf_slo_ttft_seconds")
    # 2 quantiles x (2 replicas + 1 fleet-pooled).
    assert len(slo) == 6
    assert {l.get("q") for l, _ in slo} == {"0.5", "0.95"}
    fleet_p95 = next(v for l, v in slo
                     if "replica" not in l and l["q"] == "0.95")
    assert fleet_p95 > 0                      # pooled window has data
    binfo = rows("tpu_inf_build_info")
    assert len(binfo) == 3                    # 2 replicas + fleet
    for labels, v in binfo:
        assert v == 1.0
        assert labels["fleet"] == "subprocess"
        assert set(labels) >= {"version", "backend", "kv_quant",
                               "spec_mode", "routing"}
    assert len(rows("tpu_inf_slo_breaches_total")) == 6  # 2 kinds x 3


def test_worker_profile_rpc_captures_trace(pd_fleet, tmp_path):
    """Satellite surface: the profile RPC verb runs jax.profiler on a
    live worker (serving continues) and returns the trace dir under the
    operator's profile_dir."""
    import os

    _wait_states(pd_fleet)
    r = pd_fleet.capture_profile(1, seconds=0.3)
    assert r["replica"] == 1 and r["seconds"] == 0.3
    assert r["dir"].endswith("replica1")
    assert os.path.isdir(r["dir"])
    # jax wrote a plugins/profile capture under the dir.
    assert any(os.scandir(r["dir"]))


_WARMUP_COMPILE_COUNTER = """
import logging, sys
records = []
handler = logging.Handler()
handler.emit = lambda rec: records.append(rec.getMessage())
import jax
jax.config.update("jax_log_compiles", True)
for n in ("jax._src.interpreters.pxla", "jax._src.dispatch"):
    lg = logging.getLogger(n)
    lg.addHandler(handler)
    lg.setLevel(logging.DEBUG)
from tpu_inference.config import EngineConfig, tiny_llama
from tpu_inference.engine.engine import InferenceEngine
kw = dict(page_size=8, num_pages=64, max_pages_per_seq=8,
          max_batch_size=2, prefill_buckets=(16,), host_cache_pages=32)
engine = InferenceEngine(tiny_llama(vocab_size=512),
                         EngineConfig(**kw, role=sys.argv[1]), seed=0)
n0 = len(records)          # boot/param compiles, not warmup's
engine.warmup()
print("COMPILES", len(records) - n0)
"""


@pytest.mark.slow   # ~44s subprocess compile-census sweep; role validation
                    # and role-aware serving stay tier-1
def test_role_specialized_warmup_shrinks_compile_set():
    """Tentpole claim: a prefill-role warmup compiles only the prefill
    side and a decode-role warmup only the decode side, so each
    specialized role boots on a strictly smaller compile set than
    mixed while the two together still cover it. Each warmup runs in a
    FRESH python process: in-process jax shares a global pjit cache
    across engines, so a second engine's identical graphs never
    recompile and in-process counts compare nothing."""
    import subprocess

    def warmup_compiles(role):
        out = subprocess.run(
            [sys.executable, "-c", _WARMUP_COMPILE_COUNTER, role],
            capture_output=True, text=True, timeout=240,
            cwd=os.path.dirname(os.path.dirname(__file__)))
        assert out.returncode == 0, out.stderr[-2000:]
        return int(out.stdout.split("COMPILES")[1].strip())

    n_mixed = warmup_compiles("mixed")
    n_prefill = warmup_compiles("prefill")
    n_decode = warmup_compiles("decode")
    assert 0 < n_prefill < n_mixed
    assert 0 < n_decode < n_mixed
    # Specialization drops the OTHER phase's graphs, never its own:
    # the two role sets together cover at least the mixed set (shared
    # helper ops may double-count, so >=, not ==).
    assert n_prefill + n_decode >= n_mixed


def test_peek_fanout_deadline_and_cold_fallback():
    """Satellite: candidate peeks fan out CONCURRENTLY with a short
    deadline — one stalled worker no longer adds its full round-trip
    to every admission; it scores with the cold fallback while the
    fast sibling's real peek is used."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(dp=2, route_peek_timeout_s=0.3))
    fast = {"hbm": 3, "host": 1, "load": 2, "pressure": False,
            "occupancy": 0.5, "backlog": 0, "role": "mixed"}

    def fake_peek(h, digests, timeout=10.0):
        if h.replica == 1:
            time.sleep(5.0)       # a wedged worker's round-trip
        return dict(fast)

    group._peek = fake_peek
    try:
        t0 = time.monotonic()
        peeks = group._peek_many(group.workers, [b"\x00" * 8])
        dt = time.monotonic() - t0
        assert dt < 2.0, f"fan-out waited on the straggler ({dt:.2f}s)"
        assert peeks[0] == fast
        assert peeks[1] == group._cold_peek(group.workers[1])
        # Single candidate short-circuits the pool (no thread hop).
        assert group._peek_many([group.workers[0]], []) == [fast]
    finally:
        group.stop(drain=False)


def test_worker_roles_resolution_and_guards():
    """Role-axis config contract: resolve_worker_roles expands/
    validates, pd_worker_roles sizes the split, and the in-process
    backend refuses phase roles (the handoff needs worker
    processes)."""
    from tpu_inference.config import resolve_worker_roles
    from tpu_inference.engine.autosize import pd_worker_roles
    from tpu_inference.server.http import build_engine_group

    assert resolve_worker_roles(3, ()) == ("mixed",) * 3
    assert resolve_worker_roles(2, (), default_role="prefill") == \
        ("prefill", "prefill")
    assert resolve_worker_roles(2, ("prefill", "decode")) == \
        ("prefill", "decode")
    with pytest.raises(ValueError, match="one role per dp replica"):
        resolve_worker_roles(3, ("prefill", "decode"))
    with pytest.raises(ValueError, match="unknown worker role"):
        resolve_worker_roles(1, ("chonk",))

    assert pd_worker_roles(4, "1:1") == ("prefill",) * 2 + ("decode",) * 2
    assert pd_worker_roles(4, "1:3") == ("prefill",) + ("decode",) * 3
    # auto with the BurstGPT-shaped default mix: prefill share =
    # 512 / (512 + 4*128) = 0.5.
    assert pd_worker_roles(4, "auto") == \
        ("prefill",) * 2 + ("decode",) * 2
    # Heavily decode-weighted observed mix: prefill floors at one.
    assert pd_worker_roles(4, "auto", prompt_token_rate=10,
                           decode_token_rate=1000) == \
        ("prefill",) + ("decode",) * 3
    with pytest.raises(ValueError, match="dp >= 2"):
        pd_worker_roles(1, "auto")
    with pytest.raises(ValueError, match="'auto' or 'P:D'"):
        pd_worker_roles(2, "half")
    with pytest.raises(ValueError, match=">= 1"):
        pd_worker_roles(2, "0:2")

    with pytest.raises(ValueError, match="subprocess"):
        build_engine_group(_cfg(dp=2, fleet="in-process",
                                worker_roles=("prefill", "decode")))


def test_draining_worker_refuses_submit_routes_to_sibling(fleet, oracle):
    """A request submitted while one worker drains lands on the
    sibling (the draining worker's refusal re-routes, not errors)."""
    _wait_states(fleet)
    fleet.apply_chaos({"replica": 1, "kill": "sigterm"})
    toks, done, box = _submit(fleet, 5000, [6, 6, 6], 8)
    fin = _finish(done, box)
    assert fin.finish_reason == "length"
    assert toks == oracle.generate([[6, 6, 6]], max_new_tokens=8)[0]
    _wait_states(fleet)


# -------------------------------------- Byzantine transport (PR "RPC
# fault injection, end-to-end KV integrity, poison quarantine"): the
# codec/chaos units live in test_transport.py; these drive REAL worker
# processes through frame corruption, wedged connections, garbage
# bytes, and poison-request quarantine.


def test_chaos_rpc_corruption_byte_identity(fleet, oracle):
    """Seeded frame corruption on the worker->router event stream:
    every corrupted frame is rejected by CRC (counted), the router
    reconnects WITHOUT restarting the worker process, resyncs the
    victims, and completions stay byte-identical to the oracle —
    zero silent corruptions."""
    _wait_states(fleet)
    frame_errors0 = fleet.frame_errors
    reconnects0 = fleet.reconnects
    restarts0 = sum(h.restarts for h in fleet.workers)
    r = fleet.apply_chaos({"rpc": {"seed": 42, "corrupt_rate": 0.1,
                                   "verbs": ["token"],
                                   "direction": "recv"}})
    assert r["rpc"]["corrupt_rate"] == 0.1
    try:
        a = _submit(fleet, 7000, [7, 1, 7], 48)
        b = _submit(fleet, 7001, [2, 7, 2, 7], 48)
        fin_a = _finish(a[1], a[2])
        fin_b = _finish(b[1], b[2])
    finally:
        fleet.apply_chaos({"rpc": {"corrupt_rate": 0.0}})
    assert fin_a.finish_reason == "length"
    assert fin_b.finish_reason == "length"
    assert a[0] == oracle.generate([[7, 1, 7]], max_new_tokens=48)[0]
    assert b[0] == oracle.generate([[2, 7, 2, 7]], max_new_tokens=48)[0]
    # Verified rejection happened (the acceptance counter) and was
    # healed at the CONNECTION level, not by process restart.
    assert fleet.frame_errors > frame_errors0
    assert fleet.reconnects > reconnects0
    assert sum(h.restarts for h in fleet.workers) == restarts0
    sup = fleet.supervision_counters()
    assert sup["frame_errors"] >= fleet.frame_errors - frame_errors0
    assert sup["worker_reconnects"] >= 1
    _wait_states(fleet)


def test_worker_survives_garbage_bytes(fleet, oracle):
    """Codec fuzz against a LIVE worker: a rogue connection spewing
    garbage (bad magic, torn frames, absurd lengths) is dropped with a
    typed error — the worker process neither crashes nor hangs nor
    over-allocates, and keeps serving its real connection."""
    import socket as _socket
    import struct as _struct

    _wait_states(fleet)
    h = fleet.workers[0]
    restarts0 = h.restarts
    for payload in (b"GARBAGE" * 64,
                    _struct.pack(">IIII", 0x54504631, 0xFFFFFF,
                                 0xFFFFFFFF, 0) + b"x" * 32,
                    _struct.pack(">IIII", 0x54504631, 8, 0, 0)):
        s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        s.settimeout(10.0)
        s.connect(h.socket_path)
        s.sendall(payload)
        s.shutdown(_socket.SHUT_WR)
        # The worker must close OUR connection (clean typed rejection),
        # not wedge on it.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                if not s.recv(4096):
                    break
            except OSError:
                break
        s.close()
    # Worker still up and serving (no restart burned).
    assert h.client.rpc("healthz")["ok"]
    assert h.restarts == restarts0
    toks, done, box = _submit(fleet, 7100, [9, 9, 9], 8)
    _finish(done, box)
    assert toks == oracle.generate([[9, 9, 9]], max_new_tokens=8)[0]


@pytest.fixture(scope="module")
def byz_fleet(tmp_path_factory):
    """Dedicated fleet for wedge + poison: fast RPC deadlines (the
    wedge detector), a 2-worker poison budget, and a blackbox dir for
    the router's flight recorder."""
    from tpu_inference.server.fleet import ProcessEngineGroup

    root = str(tmp_path_factory.mktemp("byz-blackbox"))
    group = ProcessEngineGroup(_cfg(dp=2, rpc_deadline_fast_s=2.0,
                                    rpc_deadline_slow_s=4.0,
                                    poison_max_workers=2,
                                    blackbox_dir=root))
    group.start()
    yield group
    group.stop(drain=False)


def test_wedged_connection_recycled_not_restarted(byz_fleet, oracle):
    """A connection that goes silent (wedge: open socket, writes
    swallowed) is detected by per-verb deadlines — structured
    rpc_timeout events, counter moves — and recycled; the request
    re-routes and completes byte-identically. The worker process is
    never restarted for a transport fault."""
    _wait_states(byz_fleet)
    timeouts0 = byz_fleet.rpc_timeouts
    restarts0 = sum(h.restarts for h in byz_fleet.workers)
    byz_fleet.apply_chaos({"rpc": {"seed": 9, "wedge_after": 1,
                                   "wedge_replica": 0,
                                   "direction": "send"}})
    try:
        # Submits to replica 0 vanish into the wedge until the deadline
        # watchdog recycles the connection; the attempt re-routes.
        pend = [_submit(byz_fleet, 7200 + i, [3, 3, 3 + i], 10)
                for i in range(3)]
        fins = [_finish(done, box, timeout=120.0)
                for _, done, box in pend]
    finally:
        byz_fleet.apply_chaos({"rpc": {"wedge_after": 0}})
    for i, (fin, (toks, _, _)) in enumerate(zip(fins, pend)):
        assert fin.finish_reason == "length"
        assert toks == oracle.generate([[3, 3, 3 + i]],
                                       max_new_tokens=10)[0]
    assert byz_fleet.rpc_timeouts > timeouts0
    assert sum(h.restarts for h in byz_fleet.workers) == restarts0
    _wait_states(byz_fleet)


def test_poison_request_quarantined(byz_fleet):
    """Acceptance: a request whose attempts crash poison_max_workers=2
    DISTINCT workers fails terminally with finish_reason="poison"
    (worth a structured 500 at the HTTP layer) after exactly 2 burned
    workers, the counter moves, the router's flight recorder captures
    the event, and the fleet heals and keeps serving."""
    _wait_states(byz_fleet)
    poison0 = byz_fleet.poison_requests
    rid = 7300
    toks, done, box = _submit(byz_fleet, rid, [8, 4, 8, 4], 200)
    deadline = time.monotonic() + 60
    while len(toks) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(toks) >= 2
    with byz_fleet._lock:
        first = byz_fleet._tracked[rid].worker.replica
    byz_fleet.apply_chaos({"replica": first, "kill": "kill9"})
    # Wait for the failover onto the OTHER worker to start streaming.
    deadline = time.monotonic() + 60
    second = None
    while time.monotonic() < deadline:
        with byz_fleet._lock:
            e = byz_fleet._tracked.get(rid)
            w = e.worker if e is not None else None
            second = w.replica if w is not None else None
        if second is not None and second != first:
            break
        time.sleep(0.05)
    assert second is not None and second != first
    byz_fleet.apply_chaos({"replica": second, "kill": "kill9"})

    fin = _finish(done, box, timeout=120.0)
    assert fin.finish_reason == "poison"
    assert byz_fleet.poison_requests == poison0 + 1
    sup = byz_fleet.supervision_counters()
    assert sup["poison_requests"] >= 1
    # Flight-recorder evidence: a router-side (replica--1) capture with
    # the poison trigger.
    idx = byz_fleet.blackbox_index()
    triggers = [c["trigger"] for c in idx["captures"]
                if c["replica"] == -1]
    assert "poison_request" in triggers
    # The fleet heals (both workers restart) and keeps serving.
    _wait_states(byz_fleet)
    toks2, done2, box2 = _submit(byz_fleet, 7301, [1, 2, 1], 8)
    fin2 = _finish(done2, box2)
    assert fin2.finish_reason == "length"
