"""Cross-feature soak: randomized concurrent requests through one server.

Every per-request option the wire supports (temperature, top-k/p, seed,
stop, repeat penalty, context continuation, streaming on/off) mixed in
the same continuous batch, plus embeddings interleaved — with both int8
quantization tiers active. Pins the invariants
that matter across ANY mix: every request completes, schemas stay
coherent, context round-trips, and seeded requests reproduce.

The reference's only integration test replayed 6 requests against a live
endpoint by hand (reference notebooks/test.ipynb); this is the hermetic,
adversarial version of that.
"""

import asyncio
import json
import random

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpu_inference.config import (
    EngineConfig,
    FrameworkConfig,
    ServerConfig,
    tiny_llama,
)
from tpu_inference.server.http import InferenceServer


@pytest.fixture(scope="module")
def soak_server():
    cfg = FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(page_size=8, num_pages=256, max_pages_per_seq=8,
                            max_batch_size=4, prefill_buckets=(16, 32, 64),
                            quant="int8", kv_quant="int8",
                            decode_steps_per_call=4),
        server=ServerConfig(model_name="tiny-llama", tokenizer="byte"))
    return InferenceServer(cfg)


def _request_body(rng: random.Random, i: int, prior_context):
    body = {"model": "m", "prompt": f"soak request {i} " + "x" * rng.randint(0, 40),
            "stream": rng.random() < 0.5,
            "max_tokens": rng.randint(1, 12)}
    opts = {}
    roll = rng.random()
    if roll < 0.3:
        opts["temperature"] = 0.0
    else:
        opts["temperature"] = round(rng.uniform(0.3, 1.5), 2)
        if rng.random() < 0.5:
            opts["seed"] = rng.randint(0, 10000)
        if rng.random() < 0.3:
            opts["top_k"] = rng.randint(1, 50)
        if rng.random() < 0.3:
            opts["top_p"] = round(rng.uniform(0.5, 1.0), 2)
    if rng.random() < 0.3:
        opts["repeat_penalty"] = round(rng.uniform(1.05, 1.9), 2)
        opts["repeat_last_n"] = rng.choice([-1, 0, 4, 64])
    if rng.random() < 0.2:
        opts["stop"] = ["$$"]
    if prior_context and rng.random() < 0.25:
        body["context"] = prior_context
    body["options"] = opts
    return body


async def _one(client, body):
    resp = await client.post("/api/generate", json=body)
    assert resp.status == 200, await resp.text()
    if body["stream"]:
        lines = [json.loads(l) for l in (await resp.read()).splitlines()]
        assert lines, "empty stream"
        final = lines[-1]
        assert all(not l["done"] for l in lines[:-1])
    else:
        final = await resp.json()
    assert final["done"] is True
    assert final["done_reason"] in ("stop", "length")
    assert final["eval_count"] >= 1
    assert len(final["context"]) == (final["prompt_eval_count"]
                                     + final["eval_count"])
    return final


@pytest.mark.slow   # randomized soak sweep
def test_randomized_option_soak(soak_server):
    rng = random.Random(7)

    async def go(client):
        prior = []
        finals = []
        for wave in range(4):
            bodies = [_request_body(rng, wave * 8 + j,
                                    prior[-1] if prior else None)
                      for j in range(8)]
            if wave % 2 == 1:
                # Interleave embeddings with generation load.
                bodies.append(None)
            tasks = []
            for b in bodies:
                if b is None:
                    tasks.append(client.post("/api/embed",
                                             json={"input": "soak embed"}))
                else:
                    tasks.append(_one(client, b))
            results = await asyncio.gather(*tasks)
            for b, r in zip(bodies, results):
                if b is None:
                    assert r.status == 200, await r.text()
                    emb = await r.json()
                    assert len(emb["embeddings"][0]) == 128
                else:
                    finals.append((b, r))
            prior.append(finals[-1][1]["context"])
        # Seeded non-greedy requests reproduce exactly when re-sent.
        seeded = [(b, r) for b, r in finals
                  if b["options"].get("seed") is not None
                  and "context" not in b]
        assert seeded, "soak produced no seeded requests"
        b, r = seeded[0]
        r2 = await _one(client, b)
        assert r2["context"] == r["context"]

    async def wrapper():
        app = soak_server.make_app()
        async with TestClient(TestServer(app)) as client:
            await go(client)

    asyncio.run(wrapper())
