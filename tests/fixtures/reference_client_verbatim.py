import os
from time import perf_counter
import json
# import argparse
import asyncio
import aiohttp
import numpy as np
import pandas as pd

# from langchain_ollama import ChatOllama


class SteadyUser:
    def __init__(self, name: str, req_freq: float, duration: float, delay_start: float = 0.0):
        self.name = name
        self.req_freq = req_freq
        self.duration = duration
        self.delay_start = delay_start
    
    def get_timestamps(self) -> list[float]:
        timestamps = []
        interval = 1.0 / self.req_freq
        t = 0.0
        while t <= self.duration:
            timestamps.append(t + self.delay_start)
            t += interval
        return timestamps


class BurstUser:
    def __init__(self, name: str, n_req: int, time: float):
        self.name = name
        self.n_req = n_req
        self.time = time
    
    def get_timestamps(self) -> list[float]:
        return [self.time] * self.n_req


class DataLoader:
    def __init__(self, config=None):
        self.config = config

    @staticmethod
    def load_json_from_path(file_path: str):
        with open(file_path, "r") as f:
            return json.load(f)
    
    def get_data_from_path(self, data_path: str) -> list[tuple]:
        data = self.load_json_from_path(data_path)
        return [(d["prompt"], d["len_prompt"], d["len_output"], d['output']) for d in data.values()]

class Scheduler:
    def __init__(self, config=None):
        self.config = config

    def get_schedule_from_trace(self, trace_path: str, max_trace: int) -> pd.DataFrame:
        return pd.read_csv(
            trace_path,
            nrows=max_trace,
            dtype={
                "Timestamp": float,
                "Request tokens": int,
                "Response tokens": int
            }
        )

    def get_schedule_from_users(self, users: list[SteadyUser | BurstUser]) -> pd.DataFrame:
        REQUEST_TOKENS = 500
        RESPONSE_TOKENS = 500
        
        dfs = []
        for user in users:
            timestamps = user.get_timestamps()
            dfs.append(pd.DataFrame(
                {
                    'Timestamp': timestamps,
                    'Request tokens': [REQUEST_TOKENS] * len(timestamps),
                    'Response tokens': [RESPONSE_TOKENS] * len(timestamps),
                    'User': [user.name] * len(timestamps)
                }
            ))

        return pd.concat(dfs).reset_index(drop=True)

class Query:
    def __init__(self, inputs: list, schedule: pd.DataFrame):
        self.inputs = inputs
        self.schedule = schedule.sort_values(by='Timestamp').reset_index(drop=True)
        self.query_id = -1
        self.query_time = 0
        self.max_prompt_len = MAX_PROMPT_LEN
        self.max_gen_len = MAX_GEN_LEN
        self.prefill_idx = self.get_prefill_idx()

    @staticmethod
    def _fill_missing_idx(arr, missing):
        n = len(arr)
        
        dist_to_left = [n] * n
        i = 0
        while i < n and arr[i] == missing:
            i += 1
        # if all missings then just return
        if i == n:
            return
        for j in range(i, n):
            if arr[j] == missing:
                dist += 1
            else:
                dist = 0
            dist_to_left[j] = dist
        
        dist_to_right = [n] * n
        i = n - 1
        while arr[i] == missing:
            i -= 1
        for j in range(i, -1, -1):
            if arr[j] == missing:
                dist += 1
            else:
                dist = 0
            dist_to_right[j] = dist
            
        for i in range(n):
            if dist_to_left[i] <= dist_to_right[i]:
                arr[i] = arr[i - dist_to_left[i]]
            else:
                arr[i] = arr[i + dist_to_right[i]]

    def get_prefill_idx(self):
        prefill_idx = np.ones((self.max_prompt_len+1, self.max_gen_len+1), dtype=int) * (-1)
        prompt_exist = np.zeros(self.max_prompt_len+1, dtype=bool)

        # prefill record
        for idx, data in enumerate(self.inputs):
            len_prompt = data[1]
            len_output = data[2]
            if len_prompt <= self.max_prompt_len and len_output <= self.max_gen_len:
                prefill_idx[len_prompt, len_output] = idx
                prompt_exist[len_prompt] = True

        # fill in missing row values
        for idx_ii in np.where(prompt_exist)[0]:
            self._fill_missing_idx(prefill_idx[idx_ii], missing=-1)
        
        # fill in missing rows
        row_idx_arr = prompt_exist * np.arange(self.max_prompt_len+1)
        self._fill_missing_idx(row_idx_arr, missing=0)

        missing_row_idx_arr = np.where(~prompt_exist)[0]
        prefill_idx[missing_row_idx_arr] = prefill_idx[row_idx_arr[missing_row_idx_arr]]

        return prefill_idx

    def get_query(self):
        # Use the trace
        self.query_id += 1

        self.query_time = self.schedule.at[self.query_id, 'Timestamp'].item()

        sampled_prompt_len = self.schedule.at[self.query_id, 'Request tokens'].item()
        sampled_prompt_len = min(sampled_prompt_len, self.max_prompt_len)
        sampled_output_len = self.schedule.at[self.query_id, 'Response tokens'].item()
        sampled_output_len = min(sampled_output_len, self.max_gen_len)

        sampled = self.inputs[self.prefill_idx[sampled_prompt_len][sampled_output_len]]

        return [
            sampled[0], # prompt
            sampled[1], # prompt input length
            sampled[2], # prompr output length
            self.query_id,
            self.query_time
        ]
    
    def reset(self):
        self.query_id = -1
        self.query_time = 0

    def __len__(self):
        return len(self.schedule)

class MetricCollector:
    def __init__(self):
        self.trace_config = TraceConfig()
        self.metrics = {}
    
    def save(self, path):
        with open(path, 'w') as f:
            json.dump(self.metrics, f)

class TraceConfig(aiohttp.TraceConfig):
    def __init__(self):
        super().__init__()
        self.on_request_start.append(self.on_request_start_callback)
        self.on_request_end.append(self.on_request_end_callback)
        self.on_request_exception.append(self.on_request_exception_callback)

    async def on_request_start_callback(self, session, ctx, params):
        # request start
        logger = ctx.trace_request_ctx['logger']
        query_id = ctx.trace_request_ctx['query_id']
        request_start_time = perf_counter() - logger.session_start_timestamp

        logger.metrics[query_id]['request_start_time'] = request_start_time

        print(f"[START] ID: {query_id}, Start: {request_start_time:.1f}")

    async def on_request_end_callback(self, session, ctx, params):
        # response status line and headers received
        logger = ctx.trace_request_ctx['logger']
        query_id = ctx.trace_request_ctx['query_id']

        logger.metrics[query_id]['response_headers_received_time'] = perf_counter() - logger.session_start_timestamp
    
    async def on_request_exception_callback(self, session, ctx, params):
        # request exception raised
        query_id = ctx.trace_request_ctx['query_id']
        logger.metrics[query_id]['response_headers_received_time'] = None

        print(f"[ERROR] ID: {query_id}, Request Exception")

# sending token rate  = (number of tokens sent / ackknowledge time)
# time to first token
# check queue is on the server side, need to check acknowledge time from server.
# 


class TrafficGenerator:
    """Generates LLM inference traffic and send it to inference endpoint"""
    def __init__(self, data: list, schedule: pd.DataFrame, config: dict, logger: MetricCollector):
        self.queries = Query(inputs=data, schedule=schedule)
        self.config = config
        self.logger = logger

        print(self.queries.schedule)

    async def inference_call(self, session, prompt, sleep_time, query_id):
        # Single inference call
        payload = {
            "model": self.config['model'],
            "prompt": prompt,
            "temperature": self.config['temperature'],
            "max_tokens": self.config['max_tokens'],
            "stream": STREAM
        }
        url = self.config['url']
        trace_request_ctx = {'query_id':query_id, 'logger':self.logger}

        success = False
        response_end_time = None
        first_token_arrive_time = None

        await asyncio.sleep(sleep_time)
        try:
            async with session.post(url, json=payload, trace_request_ctx=trace_request_ctx) as resp:
                resp.raise_for_status()
                first = True
                async for _ in resp.content:
                    if first:
                        first_token_arrive_time = perf_counter() - self.logger.session_start_timestamp
                        first = False
            success = True
            response_end_time = perf_counter() - self.logger.session_start_timestamp

            print(f"[END] ID: {query_id}, End: {response_end_time:.1f}, turnaround: {response_end_time - self.logger.metrics[query_id]['request_start_time']:.1f}")

        except aiohttp.ClientResponseError as e:
            print(f"ClientResponseError: {e}")
        except aiohttp.ClientConnectionError as e:
            print(f"ClientConnectionError: {e}")

        self.logger.metrics[query_id]['first_token_arrive_time'] = first_token_arrive_time
        self.logger.metrics[query_id]['response_end_time'] = response_end_time
        self.logger.metrics[query_id]['scheduled_start_time'] = sleep_time
        self.logger.metrics[query_id]['success'] = success

    async def issue_queries(self):
        # Multiple concurrent inference call
        async with aiohttp.ClientSession(trace_configs=[self.logger.trace_config]) as session:
            task_list = []
            for _ in range(len(self.queries)):
                prompt, in_num, out_num, query_id, sleep_time = self.queries.get_query()
                task_list.append(self.inference_call(session, prompt, sleep_time, query_id))
                
                self.logger.metrics[query_id] = {} # initialise
                self.logger.metrics[query_id]['number_of_input_tokens'] = in_num
            self.logger.session_start_timestamp = perf_counter()
            await asyncio.gather(*task_list)

    def start_profile(self):
        self.queries.reset()
        asyncio.run(self.issue_queries())



MAX_PROMPT_LEN = 1024
MAX_GEN_LEN = 1024
STREAM = True

config = {
    'trace_path': '../data/trace1.csv',
    'data_path': '../data/conversations.json',
    'max_trace': 100,
    'url': 'http://10.215.130.20:11434/api/generate', # OR 172.25.149.93
    'no_proxy': '10.215.130.20',
    'model': 'mistral',
    'temperature': 0.7,
    'max_tokens': 200,
    'save_log': False,
    'log_path': '../logs/log.json'
}

if __name__ == "__main__":
    # os.environ["NO_PROXY"] = config['no_proxy']

    data = DataLoader().get_data_from_path(data_path=config['data_path'])

    schedule = Scheduler().get_schedule_from_trace(trace_path=config['trace_path'], max_trace=config['max_trace'])

    logger = MetricCollector()

    # user1 = SteadyUser(name='u1', req_freq=1.0, duration=10.0, delay_start=0.0)
    # user2 = SteadyUser(name='u2', req_freq=1.0, duration=10.0, delay_start=0.3)
    # user3 = SteadyUser(name='u3', req_freq=1.0, duration=10.0, delay_start=0.6)
    # user4 = BurstUser(name='u4', n_req=5, time=5.5)
    # user5 = BurstUser(name='u5', n_req=5, time=2.5)
    # users = [user1, user2, user3, user4, user5]
    # schedule = Scheduler().get_schedule_from_users(users=users)

    # llm = ChatOllama(
    #     model=config['model'],
    #     base_url=config['host'],
    #     temperature=config['temperature'],
    #     num_predict=config['max_token']
    # )

    generator = TrafficGenerator(data=data, schedule=schedule, config=config, logger=logger)
    generator.start_profile()

    print(logger.metrics)
    logger.save(path=config['log_path'])