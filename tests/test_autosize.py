"""HBM-aware auto-sizing (engine/autosize.py) — pure arithmetic, no
devices needed. Pins the sizing decisions VERDICT r3 asked for: a 1B
model on a 16 GB chip must serve well above batch 8, an 8B bf16 model
must refuse to pretend it fits, and int8 levers must buy the expected
capacity."""

import jax.numpy as jnp
import pytest

from tpu_inference.config import ModelConfig, tiny_llama, tiny_mixtral
from tpu_inference.engine import autosize


def llama_1b():
    return ModelConfig(name="llama-1b", family="llama", vocab_size=32000,
                       d_model=2048, n_layers=22, n_heads=32, n_kv_heads=4,
                       d_ff=5632, max_seq_len=2048, dtype=jnp.bfloat16)


def llama_8b():
    return ModelConfig(name="llama-8b", family="llama", vocab_size=128256,
                       d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
                       d_ff=14336, max_seq_len=8192, dtype=jnp.bfloat16)


def test_param_estimate_close_to_known_sizes():
    # TinyLlama-1.1B and Llama-3-8B: estimates within 10% of the names.
    assert 0.9e9 < autosize.estimate_param_count(llama_1b()) < 1.3e9
    assert 7e9 < autosize.estimate_param_count(llama_8b()) < 9e9


def test_moe_params_count_all_experts():
    dense = autosize.estimate_param_count(tiny_llama())
    moe = autosize.estimate_param_count(tiny_mixtral())
    assert moe > dense * 1.5  # 4 experts' FFNs vs 1


def test_1b_on_v5e_serves_well_above_batch_8():
    sz = autosize.auto_size(llama_1b(), hbm_bytes=16e9)
    assert sz.max_batch_size >= 16        # the VERDICT r3 complaint
    assert sz.max_batch_size <= 32        # default cap
    assert sz.num_pages > 512             # pool sized by HBM, not default
    # Residents actually fit inside the stated budget.
    assert (sz.weight_bytes_per_chip + sz.kv_pool_bytes_per_chip
            < 0.85 * 16e9)


def test_8b_bf16_refuses_single_v5e():
    with pytest.raises(ValueError, match="int8"):
        autosize.auto_size(llama_8b(), hbm_bytes=16e9)


def test_8b_int8_fits_single_v5e():
    sz = autosize.auto_size(llama_8b(), hbm_bytes=16e9, quant="int8",
                            kv_quant="int8")
    assert sz.max_batch_size >= 8
    assert (sz.weight_bytes_per_chip + sz.kv_pool_bytes_per_chip
            < 0.85 * 16e9)


def test_8b_bf16_fits_with_tp4():
    sz = autosize.auto_size(llama_8b(), hbm_bytes=16e9, tp=4)
    assert sz.max_batch_size >= 8


def test_int8_kv_roughly_doubles_pool_tokens():
    a = autosize.auto_size(llama_8b(), hbm_bytes=16e9, quant="int8")
    b = autosize.auto_size(llama_8b(), hbm_bytes=16e9, quant="int8",
                           kv_quant="int8")
    assert b.num_pages > 1.8 * a.num_pages


def test_pool_floor_one_full_sequence():
    # A budget too small for even one max-length sequence must raise,
    # not deadlock admission later.
    with pytest.raises(ValueError, match="pages"):
        autosize.auto_size(llama_1b(), hbm_bytes=3.5e9,
                           max_pages_per_seq=4096)


def test_target_ctx_shapes_batch():
    wide = autosize.auto_size(llama_1b(), hbm_bytes=16e9, batch_cap=512,
                              target_ctx=512)
    narrow = autosize.auto_size(llama_1b(), hbm_bytes=16e9, batch_cap=512,
                                target_ctx=2048)
    assert wide.max_batch_size > narrow.max_batch_size


def test_swa_model_batches_by_window_not_context():
    """Behind-window eviction caps live KV at ~window tokens, so auto
    sizing serves a bigger batch for an SWA model than for the same
    architecture with full attention."""
    import dataclasses

    from tpu_inference.config import PRESETS

    mistral = PRESETS["mistral-7b"]()
    full = dataclasses.replace(mistral, sliding_window=0)
    # Long-context serving geometry (target ctx 8192 > the 4096 window):
    # full attention must budget the whole context per sequence, SWA
    # only the window.
    kw = dict(hbm_bytes=16e9, quant="int8", kv_quant="int8",
              max_pages_per_seq=1024, batch_cap=256)
    swa_sz = autosize.auto_size(mistral, **kw)
    full_sz = autosize.auto_size(full, **kw)
    assert swa_sz.max_batch_size > full_sz.max_batch_size
    assert swa_sz.target_ctx <= mistral.sliding_window + 32


def test_swa_clamp_off_under_speculative_decoding():
    """Spec decode disables behind-window eviction (the window-less
    draft reads full context), so the SWA batch clamp must not apply."""
    import dataclasses

    from tpu_inference.config import PRESETS

    mistral = PRESETS["mistral-7b"]()
    full = dataclasses.replace(mistral, sliding_window=0)
    kw = dict(hbm_bytes=16e9, quant="int8", kv_quant="int8",
              max_pages_per_seq=1024, batch_cap=256)
    spec_sz = autosize.auto_size(mistral, speculative=True, **kw)
    full_sz = autosize.auto_size(full, **kw)
    assert spec_sz.max_batch_size == full_sz.max_batch_size


def test_decode_ladder_rungs_shapes():
    """The compiled-graph ladder: doubling rungs from 8 strictly below
    the top, plus the top itself; tops at or under the base collapse to
    the single legacy rung."""
    assert autosize.decode_ladder_rungs(32) == (8, 16, 32)
    assert autosize.decode_ladder_rungs(64) == (8, 16, 32, 64)
    assert autosize.decode_ladder_rungs(24) == (8, 16, 24)
    assert autosize.decode_ladder_rungs(8) == (8,)
    assert autosize.decode_ladder_rungs(4) == (4,)
    with pytest.raises(ValueError, match="positive"):
        autosize.decode_ladder_rungs(0)


def test_ladder_from_auto_sizing_is_engine_valid():
    """The ladder derived from an auto-sized top must pass the engine's
    validation shape: strictly increasing, ending at the top."""
    sz = autosize.auto_size(llama_1b(), hbm_bytes=16e9)
    rungs = autosize.decode_ladder_rungs(sz.max_batch_size)
    assert rungs[-1] == sz.max_batch_size
    assert list(rungs) == sorted(set(rungs))
    assert len(rungs) >= 2                # a 1B/v5e top is 16+ (above)


def test_detect_peak_flops_has_default():
    """CPU/unknown chips report the v5e peak so the MFU estimate always
    renders (same stance as DEFAULT_HBM_BYTES)."""
    assert autosize.detect_peak_flops() > 0


def test_int_or_auto_argparse_type():
    import argparse

    assert autosize.int_or_auto("auto") == "auto"
    assert autosize.int_or_auto("16") == 16
    with pytest.raises(argparse.ArgumentTypeError, match="auto"):
        autosize.int_or_auto("8x")


def test_resolve_sizing_args_noop_on_ints():
    """No 'auto' -> no model resolution, no device probe: the values
    pass through untouched (the CLI fast path)."""
    import types

    args = types.SimpleNamespace(max_batch_size=8, num_pages=512)
    assert autosize.resolve_sizing_args(args) == (8, 512)
