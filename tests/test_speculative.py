"""Speculative decoding: output must be exactly the target model's.

The defining property of draft-verify rejection sampling: the emitted
token stream is distributed exactly as the target model alone (greedy =
token-for-token identical), regardless of draft quality. Draft quality
only moves the acceptance rate / speed. (BASELINE.json config 4.)
"""

import dataclasses

import numpy as np
import pytest

from tpu_inference import config as cfgs
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.models import build_model


@pytest.fixture(scope="module")
def models():
    target_cfg = cfgs.tiny_llama(vocab_size=256)
    draft_cfg = cfgs.ModelConfig(
        name="draft", family="llama", vocab_size=256, d_model=64,
        n_layers=1, n_heads=2, n_kv_heads=2, d_ff=128, max_seq_len=1024,
        rope_theta=10000.0, dtype=target_cfg.dtype)
    params, _ = build_model(target_cfg, seed=0)
    draft_params, _ = build_model(draft_cfg, seed=9)
    return target_cfg, params, draft_cfg, draft_params


def _ecfg(gamma, **kw):
    base = dict(page_size=8, num_pages=64, max_pages_per_seq=16,
                max_batch_size=4, prefill_buckets=(16, 32, 64),
                num_speculative_tokens=gamma)
    base.update(kw)
    return cfgs.EngineConfig(**base)


@pytest.fixture(scope="module")
def plain_engine(models):
    """Shared no-spec reference engine (generate leaves no state behind,
    so read-only token-equality tests reuse one compile)."""
    target_cfg, params, _, _ = models
    return InferenceEngine(target_cfg, _ecfg(0), params=params)


@pytest.fixture(scope="module")
def spec_engine(models):
    """Shared gamma=3 spec engine (counters are cumulative across tests;
    assert deltas or > 0, never exact totals)."""
    target_cfg, params, draft_cfg, draft_params = models
    return InferenceEngine(target_cfg, _ecfg(3), params=params,
                           draft_cfg=draft_cfg, draft_params=draft_params)


def test_spec_greedy_matches_target(models, plain_engine, spec_engine):
    """Greedy spec output == greedy plain output, any draft model."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 256, size=n).tolist() for n in (5, 13, 22)]

    want = plain_engine.generate(prompts, max_new_tokens=15)
    got = spec_engine.generate(prompts, max_new_tokens=15)
    assert got == want
    assert spec_engine.spec_drafted > 0


def test_spec_perfect_draft_accepts_everything(models, plain_engine):
    """Draft == target: every draft token accepted, gamma+1 tokens/round."""
    target_cfg, params, _, _ = models
    gamma = 3
    spec = InferenceEngine(target_cfg, _ecfg(gamma), params=params,
                           draft_cfg=target_cfg, draft_params=params)
    prompt = list(range(40, 52))
    out = spec.generate([prompt], max_new_tokens=12)[0]
    assert len(out) == 12
    assert spec.spec_accepted == spec.spec_drafted  # 100% acceptance

    assert out == plain_engine.generate([prompt], max_new_tokens=12)[0]


def test_spec_eos_and_budget(models, plain_engine, spec_engine):
    prompt = list(range(7))
    ref = plain_engine.generate([prompt], max_new_tokens=10)[0]
    # EOS = a token whose FIRST occurrence is mid-stream (tiny random
    # models repeat; picking ref[k] blindly could stop earlier).
    k = max(i for i in range(len(ref)) if ref[i] not in ref[:i])
    eos = ref[k]

    spec = spec_engine
    s = Sequence(request_id=0, prompt_tokens=prompt, max_new_tokens=10,
                 eos_token_id=eos)
    spec.prefill(s)
    while spec.active_sequences():
        spec.decode_steps()
    # Stream truncated exactly at EOS even when EOS landed mid-round.
    assert s.generated == ref[:k + 1]
    assert s.finish_reason == "stop"

    spec.release(s)          # shared engine: free the slot for later tests

    s2 = Sequence(request_id=1, prompt_tokens=prompt, max_new_tokens=7)
    spec.prefill(s2)
    while spec.active_sequences():
        spec.decode_steps()
    assert len(s2.generated) == 7               # budget exact, no overshoot
    assert s2.generated == ref[:7]
    assert s2.finish_reason == "length"
    spec.release(s2)


def test_spec_sampled_runs(models):
    """Temperature sampling through spec: right count, valid ids."""
    target_cfg, params, draft_cfg, draft_params = models
    spec = InferenceEngine(target_cfg, _ecfg(2), params=params,
                           draft_cfg=draft_cfg, draft_params=draft_params)
    out = spec.generate([list(range(9))], max_new_tokens=20,
                        temperature=0.8)[0]
    assert len(out) == 20
    assert all(0 <= t < 256 for t in out)


def test_spec_continuous_batching_join(models, plain_engine, spec_engine):
    """Sequences join mid-flight in spec mode without perturbing others."""
    rng = np.random.default_rng(2)
    p1 = rng.integers(0, 256, size=9).tolist()
    p2 = rng.integers(0, 256, size=17).tolist()
    w1 = plain_engine.generate([p1], max_new_tokens=12)[0]
    w2 = plain_engine.generate([p2], max_new_tokens=8)[0]

    spec = spec_engine
    s1 = Sequence(request_id=3, prompt_tokens=p1, max_new_tokens=12)
    s2 = Sequence(request_id=4, prompt_tokens=p2, max_new_tokens=8)
    spec.prefill(s1)
    spec.decode_steps()
    spec.prefill(s2)            # joins while s1 mid-generation
    while spec.active_sequences():
        spec.decode_steps()
    assert s1.generated == w1
    assert s2.generated == w2
    spec.release(s1)
    spec.release(s2)            # shared engine: leave all slots free


def test_spec_composes_with_prefix_cache():
    """Prefix caching is live under speculative decoding: the draft pool
    is a positional twin of the target pool (same tokens at the same
    block-table slots), so a cached page carries a valid draft twin.
    A repeated greedy request must hit the cache and emit identical
    tokens to the cold run."""
    cfg = cfgs.tiny_llama(vocab_size=256)
    draft = dataclasses.replace(cfg, n_layers=1, name="draft")
    ecfg = cfgs.EngineConfig(page_size=8, num_pages=128, max_pages_per_seq=8,
                             max_batch_size=2, prefill_buckets=(16, 32),
                             num_speculative_tokens=2,
                             enable_prefix_cache=True)
    eng = InferenceEngine(cfg, ecfg, seed=0, draft_cfg=draft)
    assert eng.prefix_cache is not None          # no longer excluded
    prompt = [list(range(3, 20))]
    cold = eng.generate(prompt, max_new_tokens=8)
    cold_acc = (eng.spec_accepted, eng.spec_drafted)
    hits0 = eng.prefix_cache.hits_hbm.value
    warm = eng.generate(prompt, max_new_tokens=8)
    assert eng.prefix_cache.hits_hbm.value > hits0
    assert cold == warm
    # The real twin property: a cache hit reuses valid DRAFT rows too,
    # so the warm run's greedy acceptance pattern matches the cold run
    # exactly. (Output equality alone can't see a corrupted draft twin —
    # verify corrects any proposal; acceptance rate is where it shows.)
    warm_acc = (eng.spec_accepted - cold_acc[0],
                eng.spec_drafted - cold_acc[1])
    assert warm_acc == cold_acc
