"""Tier-1 CPU lane for ``benchmarks/replay.py --smoke``.

The bench-side consumer of the metrics pipeline (HTTP /metrics scrape ->
phase_breakdown artifact) must not rot between chip windows, so this
exercises the whole path end-to-end on CPU: server boot + warmup, trace
replay through the vendored traffic generator, a real-HTTP Prometheus
scrape, and the committed artifact's phase_breakdown with its sum-check.
"""

import importlib.util
import json
import os
import sys

import pytest


def _load_bench(name):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        f"{name}_smoke_mod", os.path.join(root, "benchmarks", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return root, mod


def _load_replay():
    return _load_bench("replay")


def test_replay_smoke_commits_phase_breakdown(tmp_path, monkeypatch):
    root, replay = _load_replay()
    out = tmp_path / "replay_smoke.json"
    monkeypatch.chdir(root)                 # trace/data paths repo-relative
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--out", str(out)])
    summary = replay.main()

    # Every smoke request succeeded and produced tokens.
    assert summary["succeeded"] == summary["requests"] > 0
    assert summary["output_tokens"] > 0

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    pb = art["summary"]["phase_breakdown"]
    # The roofline-attribution phases all carry data + percentiles.
    for key in ("decode_dispatch_s", "dispatch_bubble_s", "queue_wait_s",
                "prefill_dispatch_s", "e2e_s"):
        assert pb[key]["count"] > 0, f"{key} never observed"
        assert pb[key]["p50"] is not None
        assert pb[key]["p95"] is not None
        assert pb[key]["p99"] is not None
        assert pb[key]["p50"] <= pb[key]["p99"]
    # Sum-check: queue + prefill + decode == e2e (identical server-side
    # timestamps; rounding only).
    sc = pb["sum_check"]
    assert sc["ratio"] is not None
    assert abs(sc["ratio"] - 1.0) < 0.01
    # The Prometheus scrape went over real HTTP and parsed.
    prom = art["summary"]["prometheus_scrape"]
    assert prom["content_type"].startswith("text/plain; version=0.0.4")
    assert prom["families"] >= 10
    assert prom["samples"] > 50
    # The step-attribution block rode along (live /debug/steps path;
    # the committed artifact's copy is graded in test_step_ledger.py).
    att = art["summary"]["step_attribution"]
    assert att["enabled"] and att["records"] > 0
    assert att["verdicts"] and att["mfu"]["ledger"] is not None


def test_replay_smoke_compare_admission(tmp_path, monkeypatch):
    """Tier-1 preemption smoke (CPU): the reserve-vs-optimistic
    comparison lane boots both servers against a burst of the smoke
    trace with a pool tight enough that worst-case reservation binds.
    Optimistic admission must exercise watermark preemption +
    recompute-resume through the full HTTP path, finish every request,
    and land the win (higher occupancy, or matched throughput at no
    worse shed rate) in the committed artifact."""
    root, replay = _load_replay()
    out = tmp_path / "replay_admission.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-admission",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    for mode in ("reserve", "optimistic"):
        s = art[mode]
        # No deadlocks, no errors: every request in both arms finished.
        assert s["succeeded"] == s["requests"] > 0, (mode, s)
        assert s["admission"]["mode"] == mode
    # The optimistic arm actually hit the preemption path (otherwise
    # this smoke proves nothing about it).
    assert cmp["preemptions"] >= 1
    assert cmp["recompute_resumes"] == cmp["preemptions"]
    assert art["reserve"]["admission"]["preemptions"] == 0
    assert cmp["optimistic_wins"], cmp


def test_replay_smoke_compare_hybrid(tmp_path, monkeypatch):
    """Tier-1 hybrid-stepping smoke (CPU): the serial-vs-hybrid lane
    replays a pinned mix — one 8-chunk long prompt plus three shorts
    that decode through its prefill — through the full HTTP path, twice.
    The committed artifact must show the serial arm stalling decode
    lanes behind chunk dispatches and the hybrid arm fusing every chunk
    (structurally zero stall samples, so its p95 is <= serial's), with
    identical greedy token counts across arms."""
    root, replay = _load_replay()
    out = tmp_path / "replay_hybrid.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-hybrid",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    for mode in ("serial", "hybrid"):
        s = art[mode]
        assert s["succeeded"] == s["requests"] > 0, (mode, s)
        # Artifact schema: the stall histogram and hybrid counters are
        # present in both arms' summaries.
        assert "decode_stall_during_prefill_s" in s["phase_breakdown"]
        assert set(s["hybrid"]) >= {"enabled", "hybrid_steps",
                                    "decode_stall_count",
                                    "decode_stall_p95_s"}
    assert art["serial"]["hybrid"]["enabled"] is False
    assert art["hybrid"]["hybrid"]["enabled"] is True
    # The serial arm demonstrably stalled decode lanes behind chunks...
    assert cmp["decode_stall_count_serial"] >= 1
    assert cmp["decode_stall_p95_serial_s"] > 0
    # ...and the hybrid arm fused them instead.
    assert cmp["hybrid_steps"] >= 1
    assert cmp["decode_stall_count_hybrid"] == 0
    assert (cmp["decode_stall_p95_hybrid_s"]
            <= cmp["decode_stall_p95_serial_s"])
    # Greedy + identical prompts: same token counts in both arms.
    assert cmp["output_tokens_hybrid"] == cmp["output_tokens_serial"]
    assert cmp["hybrid_wins"], cmp


def test_replay_smoke_compare_ladder(tmp_path, monkeypatch):
    """Tier-1 batch-ladder smoke (CPU): the fixed-bs8 vs compiled-
    ladder comparison lane serves the pinned greedy burst through the
    full HTTP path three times (bs8 / ladder / ladder with staging
    reuse off). Live assertions are the DETERMINISTIC claims — byte-
    identical outputs across every batch shape, the ladder actually
    climbing to its top rung and switching graphs, and strictly higher
    aggregate tok/s than the fixed bs=8 graph; the latency/throughput
    magnitudes are graded on the committed artifact (the tiering/
    routing lanes' stance: wall-clock on a loaded CI box swings)."""
    root, replay = _load_replay()
    out = tmp_path / "replay_ladder.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-ladder",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for arm in ("bs8", "ladder", "ladder_rebuild"):
        s = art[arm]
        assert s["requests"] > 0 and s["output_tokens"] > 0, (arm, s)
    # The ladder demonstrably climbed to the top rung, switching graphs.
    assert art["ladder"]["decode_ladder"] == [8, 16, 32]
    assert cmp["rung_peak"] == 32
    assert cmp["rung_switches"] >= 1
    assert art["bs8"]["rung_peak"] == 8
    # Byte-identity across batch shapes: graph width is never a
    # behavior change (greedy, identical weights/seed).
    assert cmp["outputs_identical"], cmp
    # The concurrency win, live: strictly higher aggregate tok/s.
    assert cmp["tokens_per_s_ladder"] > cmp["tokens_per_s_bs8"], cmp
    assert cmp["ladder_wins"], cmp
    # The staging micro-measure is deterministic enough to grade live:
    # reuse must beat rebuild-per-dispatch.
    micro = cmp["stage_us_per_dispatch"]
    assert micro["reuse_us"] < micro["rebuild_us"], micro

    # The committed artifact carries the full acceptance claim: >=2x
    # aggregate tok/s at the bs=32 rung vs the bs=8 baseline on the CPU
    # lane, per-stream latency within 1.5x, byte-identity, and the
    # host-bubble drop the staging reuse buys.
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_ladder.json")).read())
    c = committed["comparison"]
    assert c["ladder_wins"] and c["outputs_identical"]
    assert c["tok_s_ratio"] >= 2.0
    assert c["per_stream_latency_ratio"] <= 1.5
    assert c["rung_peak"] == 32
    assert c["bubble_p95_improved"]
    assert (c["stage_us_per_dispatch"]["reuse_us"]
            < c["stage_us_per_dispatch"]["rebuild_us"])


def test_replay_smoke_compare_spec(tmp_path, monkeypatch):
    """Tier-1 draft-free-speculation smoke (CPU): the plain vs ngram
    comparison lane serves a pinned echo-heavy greedy multi-turn mix
    (where self-drafting wins) and an adversarial no-echo sampled mix
    (where adaptive γ must throttle) through the full HTTP path, four
    boots total. Live assertions are the DETERMINISTIC claims —
    byte-identical greedy outputs across arms (speculation is never a
    behavior change), real accepted speculation on the echo mix, and
    the throttle engaging on the adversarial mix; the >=1.3x /
    >=0.95x magnitudes are graded on the committed artifact (the
    ladder/tiering lanes' stance: wall-clock on a loaded CI box
    swings)."""
    root, replay = _load_replay()
    out = tmp_path / "replay_spec.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-spec",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for arm in ("echo_plain", "echo_ngram", "adversarial_plain",
                "adversarial_ngram"):
        s = art[arm]
        assert s["requests"] > 0 and s["output_tokens"] > 0, (arm, s)
    # The plain arms really ran plain and the ngram arms really
    # speculated.
    assert art["echo_plain"]["speculative"] is None
    espec = art["echo_ngram"]["speculative"]
    assert espec["mode"] == "ngram"
    # Byte-identity on the greedy echo mix: speculation is a scheduling
    # decision, never a behavior change.
    assert cmp["outputs_identical"], cmp
    # Real speculation happened and mostly verified (greedy + pinned
    # weights/seed make the acceptance rate deterministic).
    assert cmp["spec_drafted"] > 0
    assert cmp["acceptance_rate"] > 0.3, cmp
    # The adversarial mix engaged the never-lose machinery: lanes
    # throttled to gamma=0 and rounds degraded to plain fused decode.
    assert (cmp["adversarial_throttles"] or 0) >= 1
    assert (cmp["adversarial_fallback_rounds"] or 0) >= 1
    assert (cmp["adversarial_acceptance_rate"] or 0) < 0.3
    assert cmp["spec_wins"], cmp

    # The committed artifact carries the full acceptance claim: >=1.3x
    # per-stream decode tok/s on the echo mix with byte-identical
    # outputs, and the adaptive-gamma arm >=0.95x plain on the
    # adversarial mix (spec never loses).
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_spec.json")).read())
    c = committed["comparison"]
    assert c["spec_wins"] and c["outputs_identical"]
    assert c["per_stream_ratio"] >= 1.3
    assert c["acceptance_rate"] > 0.5
    assert c["adversarial_ratio"] >= 0.95
    assert c["spec_never_loses"]


@pytest.mark.slow   # heaviest chaos lane (~90s); fleet kill/drain behavior
                    # stays tier-1 in test_fleet.py, the committed artifact
                    # in benchmarks/results/replay_fleet.json
def test_replay_smoke_compare_fleet(tmp_path, monkeypatch):
    """Tier-1 process-fleet smoke (CPU, dp=2): the in-process vs
    subprocess comparison lane serves a pinned greedy burst through the
    full HTTP path on both fleet backends, then with a worker
    SIGKILLed mid-decode, then the pinned drain scenario twice
    (migration vs resubmission) — five boots, eight real worker
    processes total. Live assertions are the DETERMINISTIC claims:
    byte-identical outputs across every arm (the fleet backend — and a
    kill -9 — is a placement/supervision decision, never a behavior
    change), the killed worker's in-flight requests failing over and
    completing with the worker restarted, and drain-time migration
    recording swap-in-resumes with strictly fewer recomputed tokens
    than plain resubmission. Throughput magnitudes are reported, not
    graded (loaded-CI-box stance)."""
    root, replay = _load_replay()
    out = tmp_path / "replay_fleet.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-fleet",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for arm in ("in_process", "subprocess", "subprocess_kill",
                "drain_migrate", "drain_resubmit"):
        s = art[arm]
        assert s["requests"] > 0 and s["output_tokens"] > 0, (arm, s)
    assert art["in_process"]["fleet"] == "in-process"
    assert art["subprocess"]["fleet"] == "subprocess"
    # Byte-identity across backends and chaos arms.
    assert cmp["outputs_identical"], cmp
    # The kill arm really killed a worker mid-decode, its requests
    # failed over and completed, and the supervisor restarted it.
    assert cmp["kill_chaos_fired"]
    assert cmp["failover_count"] >= 1
    assert cmp["kill_worker_restarts"] >= 1
    assert cmp["failover_wins"], cmp
    # The drain arms really drained, the migration arm moved KV pages
    # and swap-in-resumed, and it recomputed strictly fewer tokens
    # than the resubmission arm.
    assert cmp["migrations"] >= 1
    assert cmp["migrated_pages"] >= 1 and cmp["migrated_bytes"] > 0
    assert cmp["swap_in_resumes"] >= 1
    assert (cmp["recomputed_tokens_migrate"]
            < cmp["recomputed_tokens_resubmit"]), cmp
    assert cmp["migration_wins"], cmp

    # The committed artifact carries the same acceptance claims.
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_fleet.json")).read())
    c = committed["comparison"]
    assert c["outputs_identical"] and c["failover_wins"]
    assert c["migration_wins"]
    assert c["swap_in_resumes"] >= 1
    assert (c["recomputed_tokens_migrate"]
            < c["recomputed_tokens_resubmit"])


def test_replay_smoke_compare_chaos_rpc(tmp_path, monkeypatch):
    """Tier-1 Byzantine-transport smoke (CPU, dp=2): the chaos-rpc
    lane serves the pinned greedy burst through a clean subprocess
    fleet and again under seeded frame-level fault injection — byte
    corruption + delays on every router<->worker frame in both
    directions, plus one wedged connection as the burst opens. Live
    assertions are the DETERMINISTIC claims: byte-identical outputs
    (zero silent corruptions — every corrupt frame was CRC-rejected
    and the connection recycled+resynced), frame errors and RPC
    timeouts actually counted, reconnects with ZERO worker process
    restarts (transport faults are repaired at the connection), and
    p95 inflation bounded. Throughput magnitudes are reported, not
    graded (loaded-CI-box stance)."""
    root, replay = _load_replay()
    out = tmp_path / "replay_chaos_rpc.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-chaos-rpc",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for arm in ("clean", "chaos_rpc"):
        s = art[arm]
        assert s["requests"] > 0 and s["output_tokens"] > 0, (arm, s)
    # The clean arm saw no injected faults.
    assert art["clean"]["frame_errors"] == 0
    assert art["clean"]["worker_reconnects"] == 0
    # The chaos arm really injected, detected, and recovered.
    assert cmp["chaos_fired"]
    assert cmp["outputs_identical"], cmp
    assert cmp["silent_corruptions"] == 0
    assert cmp["frame_errors"] >= 1, cmp
    assert cmp["rpc_timeouts"] >= 1, cmp
    assert cmp["worker_reconnects"] >= 1, cmp
    # Connection-level failover, never a process restart.
    assert cmp["worker_restarts_chaos"] == 0, cmp
    assert cmp["p95_inflation_bounded"], cmp
    assert cmp["chaos_wins"], cmp

    # The committed artifact carries the same acceptance claims.
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_chaos_rpc.json")).read())
    c = committed["comparison"]
    assert c["chaos_wins"] and c["outputs_identical"]
    assert c["silent_corruptions"] == 0
    assert c["frame_errors"] >= 1 and c["rpc_timeouts"] >= 1
    assert c["worker_reconnects"] >= 1
    assert c["worker_restarts_chaos"] == 0
    assert c["p95_inflation_bounded"]


def test_replay_smoke_compare_elastic(tmp_path, monkeypatch):
    """Tier-1 elastic-fleet smoke (CPU): the fixed vs elastic lane
    replays the pinned mini-diurnal (>= 20x offered-load swing, mixed
    X-Priority classes) through one fixed subprocess worker and
    through the autoscaled fleet — which must scale up on the
    sustained SLO breach, preempt the batch lane for interactives
    instead of shedding them, survive a rolling upgrade fired mid-
    burst over HTTP with zero failed requests, and scale back down in
    the quiet tail. Live assertions are the DETERMINISTIC claims:
    interactive TTFT p95 holds the SLO in the elastic arm, batch
    preemptions > 0 with interactive shed == 0, scale-up AND
    scale-down events visible in /metrics and /debug/trace, the
    rollout replacing every worker with none failed, and byte-
    identical greedy outputs across arms; throughput magnitudes are
    reported, not graded (loaded-CI-box stance)."""
    root, replay = _load_replay()
    out = tmp_path / "replay_elastic.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-elastic",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for arm in ("fixed", "elastic"):
        s = art[arm]
        assert s["requests"] > 0 and s["output_tokens"] > 0, (arm, s)
    assert art["fixed"]["elastic"] is False
    assert art["elastic"]["elastic"] is True
    # The diurnal really swung >= 20x trough-to-peak.
    assert cmp["load_swing"] >= 20.0
    # Interactive held the SLO under the peak; batch absorbed the
    # slack (real preemptions, nothing interactive shed or failed).
    assert cmp["interactive_slo_held_elastic"], cmp
    assert cmp["batch_preemptions_elastic"] >= 1
    assert cmp["interactive_shed_elastic"] == 0
    assert cmp["elastic_completed_all"], cmp
    # The fleet scaled up on the breach AND back down in the lull,
    # with events in /metrics and /debug/trace.
    assert cmp["scale_ups"] >= 1 and cmp["scale_downs"] >= 1
    assert cmp["scale_events_in_metrics"]
    assert cmp["scale_events_in_trace"]
    # The mid-burst rolling upgrade replaced every worker, failed
    # none, and left a trace span.
    assert cmp["rollout_replaced"] >= 1
    assert cmp["rollout_failed"] == 0
    assert cmp["rollout_in_trace"]
    # Byte-identity across arms on every commonly-completed request:
    # elasticity is a capacity decision, never a behavior change.
    assert cmp["common_requests"] >= 1
    assert cmp["outputs_identical_common"], cmp
    assert cmp["elastic_wins"], cmp

    # The committed artifact carries the same acceptance claims.
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_elastic.json")).read())
    c = committed["comparison"]
    assert c["elastic_wins"] and c["outputs_identical_common"]
    assert c["load_swing"] >= 20.0
    assert c["interactive_slo_held_elastic"]
    assert c["batch_preemptions_elastic"] >= 1
    assert c["interactive_shed_elastic"] == 0
    assert c["scale_ups"] >= 1 and c["scale_downs"] >= 1
    assert c["rollout_replaced"] >= 1 and c["rollout_failed"] == 0


def test_replay_smoke_compare_pd(tmp_path, monkeypatch):
    """Tier-1 P/D-disaggregation smoke (CPU, dp=2, three subprocess
    topologies): the pinned long-prompt burst runs unloaded then
    loaded through mixed, hybrid, and 1-prefill+1-decode arms. Live
    assertions are the DETERMINISTIC claims: byte-identical outputs
    across every arm AND phase (the topology — and a live KV handoff —
    is a placement decision, never a behavior change), handoffs > 0
    with every one adopted cleanly (zero handoff recomputes, zero
    recomputed tokens), and a genuinely 10x-plus prefill burst. The
    TPOT-isolation magnitudes (pd flat within 10%, hybrid degrading)
    are graded on the committed artifact, not re-timed on a loaded CI
    box (the routing/fleet artifacts' stance)."""
    root, replay = _load_replay()
    out = tmp_path / "replay_pd.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-pd",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for arm in ("mixed", "hybrid", "pd"):
        s = art[arm]
        assert s["output_tokens"] > 0, (arm, s)
        assert s["outputs_phases_identical"], arm
        assert s["fleet_status"] == "ok", (arm, s)
    assert art["pd"]["roles"] == ["prefill", "decode"]
    assert art["mixed"]["roles"] == ["mixed", "mixed"]
    assert art["hybrid"]["hybrid_prefill"] is True
    # Byte-identity across the three topologies and both phases.
    assert cmp["outputs_identical"], cmp
    # The pd arm really disaggregated: every prompt prefilled on the
    # prefill worker and moved to the decode worker as a live handoff,
    # every handoff adopted cleanly — nothing recomputed.
    assert cmp["pd_handoffs"] > 0
    assert cmp["pd_adoptions"] > 0
    assert cmp["pd_handoff_recomputes"] == 0
    assert cmp["pd_recomputed_tokens"] == 0
    assert cmp["pd_clean_handoffs"], cmp
    # The loaded phase offered >= 10x the unloaded phase's prefill.
    assert cmp["prefill_load_ratio"] >= 10.0

    # Distributed tracing (README "Observability"): the lane committed
    # a Chrome trace-event artifact next to --out, and THIS run's pd
    # arm produced >= 1 handed-off request whose spans appear under one
    # trace id across router + prefill worker + decode worker pids,
    # export/adopt adjacent and non-overlapping with prefill/decode.
    trace_path = tmp_path / "replay_pd_trace.json"
    assert trace_path.exists()
    chrome = json.loads(trace_path.read_text())
    assert isinstance(chrome["traceEvents"], list) and chrome["traceEvents"]
    assert all({"name", "ph", "pid"} <= set(e)
               for e in chrome["traceEvents"])
    grading = chrome["otherData"]
    assert grading["handoff_traces_3pid"] >= 1
    assert grading["handoff_traces_clean"] >= 1
    assert grading["adjacency_ok"], grading
    assert cmp["trace"]["handoff_traces_3pid"] >= 1
    # Rolling SLO gauges tracked the replay: real targets were set, the
    # windowed p95 exists, and the gauge-vs-client ratio is recorded
    # (the within-10% magnitude is graded on the committed artifact —
    # a loaded CI box skews client-side timing).
    slo = art["pd"]["slo"]
    assert slo["ttft_target_s"] == 2.0 and slo["tpot_target_s"] == 0.2
    assert slo["ttft_p95_s"] is not None and slo["ttft_p95_s"] > 0
    assert art["pd"]["client_ttft_p95_s"] > 0
    assert art["pd"]["slo_ttft_p95_tracking_ratio"] is not None

    # The committed artifact carries the acceptance magnitudes: decode
    # TPOT p95 flat (within 10% of the arm's own unloaded baseline)
    # under the burst on the pd split, degrading on hybrid.
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_pd.json")).read())
    c = committed["comparison"]
    assert c["pd_wins"] and c["outputs_identical"]
    assert c["pd_clean_handoffs"] and c["pd_handoffs"] > 0
    assert c["prefill_load_ratio"] >= 10.0
    assert c["decode_tpot_p95_ratio"]["pd"] <= 1.10
    assert c["decode_tpot_p95_ratio"]["hybrid"] >= 1.25
    assert (c["decode_tpot_p95_ratio"]["hybrid"]
            > c["decode_tpot_p95_ratio"]["pd"])


def test_committed_pd_trace_artifact():
    """The committed Chrome-trace artifact
    (benchmarks/results/replay_pd_trace.json, from the --compare-pd
    lane) is valid trace-event JSON carrying the acceptance claims: a
    handed-off request's spans under ONE trace id across three pids
    (router=0, prefill worker, decode worker) with export/adopt
    adjacent and non-overlapping with prefill/decode, and the rolling
    SLO TTFT p95 gauge tracking the replay-measured p95 within 10%."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    chrome = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_pd_trace.json")).read())
    evs = chrome["traceEvents"]
    assert isinstance(evs, list) and len(evs) > 10
    x = [e for e in evs if e.get("ph") == "X"]
    assert all({"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
               for e in x)
    # One handed-off request spanning three pids, verified from the
    # raw events (not just the recorded grading).
    by_trace = {}
    for e in x:
        tid = e["args"].get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    three_pid = [
        tid for tid, es in by_trace.items()
        if len({e["pid"] for e in es}) >= 3
        and {"handoff_export", "handoff_adopt", "prefill",
             "decode"} <= {e["name"] for e in es}]
    assert three_pid, "no handed-off request spans three pids"
    assert 0 in {e["pid"] for e in by_trace[three_pid[0]]}  # the router
    g = chrome["otherData"]
    assert g["handoff_traces_3pid"] >= 1 and g["adjacency_ok"]
    # SLO tracking: gauge p95 within 10% of the replay-measured p95.
    assert g["slo_tracks_within_10pct"], g
    assert abs(g["slo_ttft_p95_tracking_ratio"] - 1.0) <= 0.10
    assert g["slo"]["ttft_breaches"] >= 1      # targets actually bound


def test_replay_smoke_compare_tiering(tmp_path, monkeypatch):
    """Tier-1 tiered-KV-cache smoke (CPU, tiny model): the host-tier
    off-vs-on comparison lane replays the pinned multi-turn mix with the
    HBM pool sized well below the conversations' KV working set, twice.
    The tiered arm must serve STRICTLY more cached tokens (evictions
    demote instead of destroy; returning turns swap back in) with real
    demote/restore traffic, and greedy outputs must be byte-identical
    across arms — tiering is a memory-placement decision, never a
    behavior change. The repo-committed artifact must carry the full
    win (cached tokens AND returning-turn TTFT p95)."""
    root, multiturn = _load_bench("multiturn")
    out = tmp_path / "multiturn_tiering.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["multiturn.py", "--smoke", "--compare-tiering",
                         "--out", str(out)])
    cmp = multiturn.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for mode in ("hbm_only", "tiered"):
        s = art[mode]
        assert s["requests"] > 0 and s["output_tokens"] > 0, (mode, s)
    # The pool was genuinely oversubscribed — the comparison measured
    # churn, not an idle cache.
    assert cmp["working_set_over_pool"] > 1.5
    # The HBM-only arm demonstrably destroyed KV on eviction...
    assert art["hbm_only"]["prefix_cache"].get("offloaded_pages", 0) == 0
    # ...while the tiered arm demoted and swapped back in.
    assert cmp["offloaded_pages"] > 0
    assert cmp["restored_pages"] > 0
    assert cmp["cached_tokens_tiered"] > cmp["cached_tokens_hbm_only"]
    # Byte-identity across arms (greedy, identical weights/seed).
    assert cmp["outputs_identical"], cmp
    assert cmp["tiering_wins"], cmp

    # The committed artifact carries the full acceptance claim,
    # including the returning-turn latency win (graded on the artifact,
    # not re-timed on a loaded CI box — the routing artifact's stance).
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "multiturn_tiering.json")).read())
    c = committed["comparison"]
    assert c["tiering_wins"] and c["outputs_identical"]
    assert c["ttft_returning_p95_improved"]
    assert c["cached_tokens_tiered"] > c["cached_tokens_hbm_only"]
    assert (c["ttft_returning_p95_tiered_s"]
            < c["ttft_returning_p95_hbm_only_s"])
    assert c["working_set_over_pool"] >= 3.0


def test_replay_smoke_compare_routing(tmp_path, monkeypatch):
    """Tier-1 cache-aware-routing smoke (CPU, dp=2, tiny model): the
    least-loaded vs prefix-affinity comparison lane runs the pinned
    multi-turn mix through the full dp=2 HTTP path, twice. The affinity
    arm must route strictly more cached prefix pages (the deterministic
    claim), with byte-identical greedy outputs across both routing
    modes — routing is a placement decision, never a behavior change.
    The repo-committed artifact must carry the full win (hit pages AND
    TTFT p95)."""
    root, multiturn = _load_bench("multiturn")
    out = tmp_path / "multiturn_routing.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["multiturn.py", "--smoke", "--compare-routing",
                         "--out", str(out)])
    cmp = multiturn.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    assert cmp["dp"] == 2
    for mode in ("least_loaded", "prefix_affinity"):
        s = art[mode]
        assert s["requests"] > 0 and s["output_tokens"] > 0, (mode, s)
        assert s["routing"]["mode"] == mode and s["routing"]["dp"] == 2
    # The affinity arm demonstrably routed conversations back to their
    # warm replica (peeked pages + server-side cache reuse both higher).
    assert cmp["route_warm_dispatches_prefix_affinity"] >= 1
    assert (cmp["route_hit_pages_prefix_affinity"]
            > cmp["route_hit_pages_least_loaded"])
    assert (cmp["cached_prompt_pages_prefix_affinity"]
            > cmp["cached_prompt_pages_least_loaded"])
    # Byte-identity across routing modes (greedy, identical replicas).
    assert cmp["outputs_identical"], cmp
    assert cmp["affinity_wins"], cmp

    # The committed artifact carries the full acceptance claim,
    # including the latency win (graded on the artifact, not re-timed
    # on a loaded CI box — replay's tok_s_within_5pct stance).
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "multiturn_routing.json")).read())
    c = committed["comparison"]
    assert c["affinity_wins"] and c["outputs_identical"]
    assert c["ttft_p95_improved"]
    assert (c["cached_prompt_pages_prefix_affinity"]
            > c["cached_prompt_pages_least_loaded"])
    assert (c["ttft_p95_prefix_affinity_s"]
            < c["ttft_p95_least_loaded_s"])


def test_replay_smoke_compare_fabric(tmp_path, monkeypatch):
    """Tier-1 fleet-KV-fabric smoke (CPU, dp=2, three subprocess
    fleets): the fabric lane replays the shared-system-prompt multi-
    user mix with the router-side fabric pool off, on, and on with a
    mid-run scale-up whose new worker boots fabric-warm. Live
    assertions are the DETERMINISTIC claims: byte-identical greedy
    outputs across all three arms (the fabric is a placement/transport
    decision, never a behavior change), the shared prefix prefilled
    ONCE fleet-wide in the fabric arms (replica B's first turn is
    fabric-warm with zero recomputed prefix tokens, adopting >=
    prefix-size pooled pages), the warmboot worker entering service
    with pooled pages already resident and serving its first request
    with fabric hits > 0, and zero integrity rejections. The TTFT
    ratio is graded on the committed artifact, not re-timed on a
    loaded CI box (replay's tok_s_within_5pct stance)."""
    root, replay = _load_replay()
    out = tmp_path / "replay_fabric.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-fabric",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for arm in ("fabric_off", "fabric_on", "fabric_warmboot"):
        s = art[arm]
        assert s["requests"] > 0, (arm, s)
        assert s["kv_integrity_rejections"] == 0, (arm, s)
    assert art["fabric_off"]["fabric"]["capacity_pages"] == 0
    assert art["fabric_on"]["fabric"]["capacity_pages"] > 0
    # Byte-identity across all three arms.
    assert cmp["outputs_identical"], cmp
    # The shared prefix was prefilled ONCE fleet-wide: the fabric arm
    # re-prefilled zero prefix tokens while the off arm re-prefilled
    # the whole prefix once per returning user, and the cross-replica
    # first turn adopted the full pooled prefix.
    assert cmp["prefix_prefilled_once"], cmp
    assert cmp["prefix_recomputed_tokens_on"] == 0
    assert (cmp["prefix_recomputed_tokens_off"]
            >= cmp["prefix_tokens"])
    assert cmp["cross_replica_turns_on"] >= 1
    assert (cmp["cross_fabric_hit_pages_on"]
            * art["config"]["page_size"] >= cmp["prefix_tokens"])
    # The scaled-up worker booted fabric-warm and served its first
    # request from pooled pages, recomputing nothing.
    assert cmp["warmboot_wins"], cmp
    assert cmp["warmboot_host_pages"] >= 1
    assert cmp["warmboot_first_hit_pages"] >= 1
    assert cmp["fabric_wins"], cmp

    # The committed artifact carries the same claims PLUS the latency
    # win: returning-user TTFT p95 at least 1.3x better fabric-on.
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_fabric.json")).read())
    c = committed["comparison"]
    assert c["fabric_wins"] and c["outputs_identical"]
    assert c["prefix_prefilled_once"] and c["warmboot_wins"]
    assert c["prefix_recomputed_tokens_on"] == 0
    assert c["returning_ttft_ratio"] >= 1.3
    assert c["fabric_ttft_wins"]


def test_replay_smoke_compare_kv_plane(tmp_path, monkeypatch):
    """Tier-1 zero-copy KV data plane smoke (CPU, 1 prefill + 1 decode
    subprocess fleet, both planes): the kv-plane lane replays the same
    handoff-heavy burst with KV payloads relayed through router frames
    vs handed worker-to-worker through the shared-memory page arena.
    Live assertions are the DETERMINISTIC claims (README "KV data
    plane"): byte-identical greedy outputs across the planes AND
    through each arm's kill -9 wave (the plane moves the same bytes),
    the shm arm relaying ZERO KV payload bytes through router frames
    on every verb while the relay arm moved every handoff through the
    router twice plus every fabric publish, the mid-handoff kill -9
    reclaiming the dead incarnation's slabs via the region epoch bump
    with every caught-out request recompute-resumed, and zero
    integrity rejections anywhere. The handoff-wall latency ratio is
    graded on the committed artifact, not re-timed on a loaded CI box
    (replay's tok_s_within_5pct stance)."""
    root, replay = _load_replay()
    out = tmp_path / "replay_kv_plane.json"
    monkeypatch.chdir(root)
    monkeypatch.setattr(sys, "argv",
                        ["replay.py", "--smoke", "--compare-kv-plane",
                         "--out", str(out)])
    cmp = replay.main()

    art = json.loads(out.read_text())
    assert art["config"]["smoke"] is True
    for arm in ("relay", "shm"):
        s = art[arm]
        assert s["requests"] > 0, (arm, s)
        assert s["kv_integrity_rejections"] == 0, (arm, s)
        # Every measured request handed off prefill->decode and the
        # kill wave ran to completion in both arms.
        assert s["pd_handoffs_measured"] > 0, (arm, s)
        assert s["kill_wave_requests"] == art["config"]["kvp_users"]
        assert s["worker_restarts"] >= 1, (arm, s)
    # Byte-identity across planes, including the kill waves.
    assert cmp["outputs_identical"], cmp
    # The zero-copy claim: no KV payload byte traversed a router frame
    # in the shm arm's measured phase, on ANY verb — while the relay
    # arm's books show the handoff event in, the dispatch out, and the
    # fabric publishes.
    assert cmp["shm_zero_copy"], cmp
    assert sum(cmp["rpc_blob_bytes_measured_shm"].values()) == 0
    assert cmp["rpc_blob_bytes_measured_relay"]["handoff"] > 0
    assert cmp["rpc_blob_bytes_measured_relay"]["submit"] > 0
    assert cmp["rpc_blob_bytes_measured_relay"]["fabric_put"] > 0
    # Kill -9 mid-handoff: slabs reclaimed (epoch bump), worker
    # respawned, nothing lost.
    assert cmp["kill_recovered"], cmp
    assert cmp["shm_reclaims"] >= 1
    assert cmp["kv_plane_wins"], cmp

    # The committed artifact carries the same claims PLUS the latency
    # win: handoff+adopt wall p95 at least 1.5x better on the shm
    # plane (export-span END on the prefill worker — serialized
    # payload in hand — to adopt-span end on the decode worker,
    # sequential measured series; the export itself is identical
    # prefill-side compute on either plane).
    committed = json.loads(open(os.path.join(
        root, "benchmarks", "results", "replay_kv_plane.json")).read())
    c = committed["comparison"]
    assert c["kv_plane_wins"] and c["outputs_identical"]
    assert c["shm_zero_copy"] and c["kill_recovered"]
    assert sum(c["rpc_blob_bytes_measured_shm"].values()) == 0
    assert c["shm_reclaims"] >= 1
    assert c["handoff_p95_ratio"] >= 1.5
    assert c["shm_handoff_wins"]
