"""Numerical parity of the pure-JAX model families against HuggingFace.

Strategy (replaces the reference's manual notebook testing, SURVEY.md §4):
instantiate a tiny random HF model in-process (no network), convert its state
dict with models/weights.py, and compare full-sequence logits. This pins
every architectural detail (RoPE pairing, GQA expansion, norm epsilon
placement, GELU flavor, MoE routing normalization) to the de-facto standard
implementation.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_inference import config as cfgs
from tpu_inference.models import common, gpt2, llama, mixtral, weights

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _compare_logits(ours: np.ndarray, theirs: np.ndarray, atol: float = 2e-3):
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=1e-3)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


def _tokens(rng, vocab, b=2, s=17):
    return rng.integers(0, vocab, size=(b, s), dtype=np.int64)


def test_llama_matches_hf(rng):
    cfg = cfgs.tiny_llama(vocab_size=128)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads, num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_seq_len, rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rope_theta, attn_implementation="eager",
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    params = weights.convert_state_dict(cfg, hf.state_dict())
    toks = _tokens(rng, cfg.vocab_size)
    positions = np.broadcast_to(np.arange(toks.shape[1]), toks.shape)

    ours, _ = llama.forward(params, cfg, jnp.asarray(toks),
                            jnp.asarray(positions), None,
                            common.make_dense_attn())
    with torch.no_grad():
        theirs = hf(torch.from_numpy(toks)).logits.numpy()
    _compare_logits(np.asarray(ours), theirs)


def test_gpt2_matches_hf(rng):
    cfg = cfgs.tiny_gpt2(vocab_size=128)
    hf_cfg = transformers.GPT2Config(
        vocab_size=cfg.vocab_size, n_positions=cfg.max_seq_len,
        n_embd=cfg.d_model, n_layer=cfg.n_layers, n_head=cfg.n_heads,
        n_inner=cfg.d_ff, layer_norm_epsilon=cfg.norm_eps,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()

    params = weights.convert_state_dict(cfg, hf.state_dict())
    toks = _tokens(rng, cfg.vocab_size)
    positions = np.broadcast_to(np.arange(toks.shape[1]), toks.shape)

    ours, _ = gpt2.forward(params, cfg, jnp.asarray(toks),
                           jnp.asarray(positions), None,
                           common.make_dense_attn())
    with torch.no_grad():
        theirs = hf(torch.from_numpy(toks)).logits.numpy()
    _compare_logits(np.asarray(ours), theirs)


def test_qwen2_matches_hf(rng):
    """Qwen2 dialect: q/k/v projection bias on top of the Llama block."""
    cfg = cfgs.tiny_qwen2(vocab_size=128)
    hf_cfg = transformers.Qwen2Config(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads, num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_seq_len, rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rope_theta, attn_implementation="eager",
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    sd = hf.state_dict()
    assert "model.layers.0.self_attn.q_proj.bias" in sd

    # HF inits the biases to zero; randomize so the test actually pins
    # the bias term, then convert the updated state dict.
    gen = torch.Generator().manual_seed(1)
    with torch.no_grad():
        for i in range(cfg.n_layers):
            for proj in ("q_proj", "k_proj", "v_proj"):
                b = hf.model.layers[i].self_attn.__getattr__(proj).bias
                b.copy_(torch.randn(b.shape, generator=gen) * 0.1)
    params = weights.convert_state_dict(cfg, hf.state_dict())
    toks = _tokens(rng, cfg.vocab_size)
    positions = np.broadcast_to(np.arange(toks.shape[1]), toks.shape)

    ours, _ = llama.forward(params, cfg, jnp.asarray(toks),
                            jnp.asarray(positions), None,
                            common.make_dense_attn())
    with torch.no_grad():
        theirs = hf(torch.from_numpy(toks)).logits.numpy()
    _compare_logits(np.asarray(ours), theirs)


def test_gemma_matches_hf(rng):
    """Gemma dialect: +1 norm offset, GeGLU, sqrt(d)-scaled embeddings,
    tied unembedding, head_dim decoupled from d_model/n_heads."""
    cfg = cfgs.tiny_gemma(vocab_size=128)
    assert cfg.head_dim * cfg.n_heads != cfg.d_model  # the decoupled case
    hf_cfg = transformers.GemmaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads, num_key_value_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, max_position_embeddings=cfg.max_seq_len,
        rms_norm_eps=cfg.norm_eps, rope_theta=cfg.rope_theta,
        hidden_act="gelu_pytorch_tanh",
        hidden_activation="gelu_pytorch_tanh",
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.GemmaForCausalLM(hf_cfg).eval()

    params = weights.convert_state_dict(cfg, hf.state_dict())
    toks = _tokens(rng, cfg.vocab_size)
    positions = np.broadcast_to(np.arange(toks.shape[1]), toks.shape)

    ours, _ = llama.forward(params, cfg, jnp.asarray(toks),
                            jnp.asarray(positions), None,
                            common.make_dense_attn())
    with torch.no_grad():
        theirs = hf(torch.from_numpy(toks)).logits.numpy()
    _compare_logits(np.asarray(ours), theirs)


def test_llama31_rope_scaling_matches_hf(rng):
    """Llama-3.1 "llama3" rope rescale: original_max_position_embeddings
    (32) is chosen so that, at head_dim 32 / theta 10000, the frequency
    table spans all three regimes — untouched high-frequency channels,
    factor-8-slowed low-frequency channels, and the interpolated band."""
    cfg = dataclasses.replace(
        cfgs.tiny_llama(vocab_size=128),
        rope_scaling=cfgs.RopeScaling(factor=8.0, low_freq_factor=1.0,
                                      high_freq_factor=4.0,
                                      original_max_len=32))
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads, num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_seq_len, rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rope_theta, attn_implementation="eager",
        tie_word_embeddings=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32},
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    params = weights.convert_state_dict(cfg, hf.state_dict())
    toks = _tokens(rng, cfg.vocab_size)
    positions = np.broadcast_to(np.arange(toks.shape[1]), toks.shape)

    ours, _ = llama.forward(params, cfg, jnp.asarray(toks),
                            jnp.asarray(positions), None,
                            common.make_dense_attn())
    with torch.no_grad():
        theirs = hf(torch.from_numpy(toks)).logits.numpy()
    _compare_logits(np.asarray(ours), theirs)

    # The rescale must actually bind at these dims — identical logits
    # with scaling dropped would mean the test pinned nothing.
    unscaled, _ = llama.forward(
        params, dataclasses.replace(cfg, rope_scaling=None),
        jnp.asarray(toks), jnp.asarray(positions), None,
        common.make_dense_attn())
    assert not np.allclose(np.asarray(unscaled), theirs, atol=2e-3)


def test_phi3_matches_hf(rng):
    """Phi-3 dialect: fused qkv_proj / gate_up_proj checkpoints split at
    conversion, plus a BINDING sliding window (window 8 < seq 17) — this
    pins our window convention (self + window-1 prior tokens) against
    HF's eager-path Phi3 mask, not just the projection split."""
    cfg = cfgs.tiny_phi3(vocab_size=128)
    assert cfg.sliding_window == 8
    hf_cfg = transformers.Phi3Config(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads, num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_seq_len, rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rope_theta, sliding_window=cfg.sliding_window,
        attn_implementation="eager", tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(0)
    hf = transformers.Phi3ForCausalLM(hf_cfg).eval()
    sd = hf.state_dict()
    assert "model.layers.0.self_attn.qkv_proj.weight" in sd

    params = weights.convert_state_dict(cfg, sd)
    toks = _tokens(rng, cfg.vocab_size)  # s=17 > window: the mask binds
    positions = np.broadcast_to(np.arange(toks.shape[1]), toks.shape)

    ours, _ = llama.forward(params, cfg, jnp.asarray(toks),
                            jnp.asarray(positions), None,
                            common.make_dense_attn(cfg.sliding_window))
    with torch.no_grad():
        theirs = hf(torch.from_numpy(toks)).logits.numpy()
    _compare_logits(np.asarray(ours), theirs)


def test_mixtral_matches_hf(rng):
    cfg = cfgs.tiny_mixtral(vocab_size=128)
    hf_cfg = transformers.MixtralConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads, num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_seq_len, rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rope_theta, num_local_experts=cfg.n_experts,
        num_experts_per_tok=cfg.n_experts_per_tok,
        attn_implementation="eager", tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.MixtralForCausalLM(hf_cfg).eval()

    params = weights.convert_state_dict(cfg, hf.state_dict())
    # Ample capacity so no tokens drop (HF computes all routed tokens).
    toks = _tokens(rng, cfg.vocab_size, b=1, s=13)
    positions = np.broadcast_to(np.arange(toks.shape[1]), toks.shape)

    ours, _ = mixtral.forward(params, cfg, jnp.asarray(toks),
                              jnp.asarray(positions), None,
                              common.make_dense_attn())
    with torch.no_grad():
        theirs = hf(torch.from_numpy(toks)).logits.numpy()
    _compare_logits(np.asarray(ours), theirs)


def test_dense_attention_is_causal():
    """Changing a future token must not affect earlier logits."""
    cfg = cfgs.tiny_llama(vocab_size=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    toks = np.zeros((1, 8), dtype=np.int64)
    toks2 = toks.copy()
    toks2[0, -1] = 5
    positions = np.broadcast_to(np.arange(8), toks.shape)

    out1, _ = llama.forward(params, cfg, jnp.asarray(toks),
                            jnp.asarray(positions), None,
                            common.make_dense_attn())
    out2, _ = llama.forward(params, cfg, jnp.asarray(toks2),
                            jnp.asarray(positions), None,
                            common.make_dense_attn())
    np.testing.assert_allclose(np.asarray(out1)[:, :-1],
                               np.asarray(out2)[:, :-1], atol=1e-6)


def test_orbax_native_checkpoint_roundtrip(tmp_path):
    """Orbax save/restore preserves the params pytree exactly."""
    import numpy as np

    from tpu_inference import config as cfgs
    from tpu_inference.models import build_model
    from tpu_inference.models.weights import load_native, save_native

    cfg = cfgs.tiny_llama(vocab_size=128)
    params, _ = build_model(cfg, seed=3)
    path = str(tmp_path / "ckpt")
    save_native(params, path)
    restored = load_native(path, params)
    import jax
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_orbax_roundtrip_quantized_params(tmp_path):
    """Checkpoint/resume composes with weight quantization: a
    QuantizedArray pytree (codes + scales custom node) survives Orbax
    save/restore bit-exactly, node types included — restart-after-
    failure never has to re-quantize from a bf16 source."""
    import numpy as np

    from tpu_inference import config as cfgs
    from tpu_inference.models import build_model
    from tpu_inference.models.quant import QuantizedArray, quantize_params
    from tpu_inference.models.weights import load_native, save_native

    cfg = cfgs.tiny_llama(vocab_size=128)
    params, _ = build_model(cfg, seed=3)
    qp = quantize_params(params, "int8")
    path = str(tmp_path / "qckpt")
    save_native(qp, path)
    restored = load_native(path, qp)
    assert isinstance(restored["blocks"]["wq"], QuantizedArray)
    import jax
    for a, b in zip(jax.tree.leaves(qp), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
