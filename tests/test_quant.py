"""Weight-only int8 quantization (models/quant.py).

The reference has no quantization tier (no model code at all, SURVEY.md
§0); its external Ollama endpoint served quantized GGUF models — this is
the TPU-native equivalent (int8 weights + per-channel scales, XLA fusing
the dequant into the matmul). Tests pin: quantization error bounds, the
qdot/qeinsum contraction helpers, end-to-end engine serving parity, and
TP-sharded quantized params matching the unsharded quantized tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_inference.config import (
    EngineConfig,
    ParallelConfig,
    tiny_gpt2,
    tiny_llama,
    tiny_mixtral,
)
from tpu_inference.engine.engine import InferenceEngine
from tpu_inference.models.quant import (
    QUANT_KEYS,
    QuantizedArray,
    dequantize,
    qdot,
    qeinsum,
    quantize_array,
    quantize_params,
)


def test_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.05
    qa = quantize_array(w)
    assert qa.q.dtype == jnp.int8
    assert qa.scale.shape == (1, 32)
    # Symmetric rounding: |w - dq(q(w))| <= scale/2 per output channel.
    err = jnp.abs(dequantize(qa) - w)
    assert bool((err <= qa.scale / 2 + 1e-7).all())


def test_qdot_matches_dequantized_product():
    # The contraction invariant: qdot(x, qa) == x @ dequantize(qa) — the
    # scale factors out of the contraction exactly (it scales the output
    # channel, which is never summed over).
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(32, 16)) * 0.05, jnp.float32)
    x = jnp.asarray(rng.normal(size=(4, 32)), jnp.float32)
    qa = quantize_array(w)
    np.testing.assert_allclose(np.asarray(qdot(x, qa)),
                               np.asarray(x @ dequantize(qa)),
                               rtol=1e-5, atol=1e-6)
    # Plain-array passthrough.
    np.testing.assert_allclose(qdot(x, w), x @ w, rtol=1e-6)


def test_qeinsum_expert_contractions():
    rng = np.random.default_rng(1)
    e, c, d, f = 2, 3, 8, 16
    a = jnp.asarray(rng.normal(size=(e, c, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(e, d, f)) * 0.02, jnp.float32)
    qa = quantize_array(w)
    got = qeinsum("ecd,edf->ecf", a, qa)
    want = jnp.einsum("ecd,edf->ecf", a, dequantize(qa))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-6)


def test_quantize_params_selects_matmul_weights_only():
    from tpu_inference.models.registry import build_model
    cfg = tiny_llama()
    params, _ = build_model(cfg, seed=0)
    qp = quantize_params(params)
    assert isinstance(qp["blocks"]["wq"], QuantizedArray)
    assert isinstance(qp["blocks"]["w_down"], QuantizedArray)
    # Norms, embeddings stay full precision.
    assert not isinstance(qp["blocks"]["attn_norm"], QuantizedArray)
    assert not isinstance(qp["embed"], QuantizedArray)
    # Stacked-layer leaves keep the leading L axis on q and scale.
    assert qp["blocks"]["wq"].q.shape[0] == cfg.n_layers
    assert qp["blocks"]["wq"].scale.shape == (cfg.n_layers, 1,
                                              qp["blocks"]["wq"].q.shape[-1])


def test_quantized_forward_close_to_full_precision():
    from tpu_inference.models.common import make_dense_attn
    from tpu_inference.models.registry import build_model, get_model_fns
    cfg = tiny_llama()
    params, _ = build_model(cfg, seed=0)
    mod = get_model_fns(cfg)
    toks = jnp.arange(1, 17, dtype=jnp.int32)[None]
    pos = jnp.arange(16, dtype=jnp.int32)[None]
    full, _ = mod.forward(params, cfg, toks, pos, None, make_dense_attn())
    quant, _ = mod.forward(quantize_params(params), cfg, toks, pos, None,
                           make_dense_attn())
    # Per-channel int8 keeps logits within a tight relative envelope.
    denom = jnp.abs(full).max()
    assert float(jnp.abs(quant - full).max() / denom) < 0.05


@pytest.mark.parametrize("cfg_fn", [tiny_llama, tiny_mixtral, tiny_gpt2])
def test_engine_serves_int8(cfg_fn):
    cfg = cfg_fn()
    ecfg = EngineConfig(num_pages=64, max_batch_size=2,
                        prefill_buckets=(64,), max_new_tokens=16,
                        quant="int8")
    engine = InferenceEngine(cfg, ecfg, seed=0)
    out = engine.generate([list(range(1, 20)), list(range(5, 40))],
                          max_new_tokens=8)
    assert all(len(t) == 8 for t in out)
    assert all(0 <= tok < cfg.vocab_size for t in out for tok in t)


def test_tp_sharded_int8_matches_unsharded():
    from tpu_inference.parallel.mesh import build_mesh
    cfg = tiny_llama()
    ecfg = EngineConfig(num_pages=64, max_batch_size=2,
                        prefill_buckets=(64,), max_new_tokens=16,
                        quant="int8")
    prompts = [list(range(1, 20)), list(range(5, 40))]
    base = InferenceEngine(cfg, ecfg, seed=0).generate(prompts,
                                                       max_new_tokens=10)
    mesh = build_mesh(ParallelConfig(tp=2))
    tp = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh).generate(
        prompts, max_new_tokens=10)
    assert base == tp


@pytest.mark.slow   # EP x int8 combination sweep; EP and int8 each covered separately
def test_tp_sharded_int8_mixtral_ep():
    from tpu_inference.parallel.mesh import build_mesh
    cfg = tiny_mixtral()
    ecfg = EngineConfig(num_pages=64, max_batch_size=2,
                        prefill_buckets=(64,), max_new_tokens=16,
                        quant="int8")
    prompts = [list(range(1, 16))]
    base = InferenceEngine(cfg, ecfg, seed=0).generate(prompts,
                                                       max_new_tokens=8)
    mesh = build_mesh(ParallelConfig(tp=2))
    tp = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh).generate(
        prompts, max_new_tokens=8)
    assert base == tp


def test_scale_sharding_unshards_reduced_dim():
    """wo shards its input (contraction) dim on tp; the scale's size-1
    contraction dim must come out unsharded or device_put would fail."""
    from jax.sharding import PartitionSpec as P

    from tpu_inference.models.registry import build_model
    from tpu_inference.parallel import shardings as shd
    from tpu_inference.parallel.mesh import build_mesh
    cfg = tiny_llama()
    params, _ = build_model(cfg, seed=0)
    qp = quantize_params(params)
    mesh = build_mesh(ParallelConfig(tp=2))
    sh = shd.param_shardings(cfg, mesh, qp)
    wo = sh["blocks"]["wo"]
    assert wo.q.spec == P(None, "tp", None)
    assert wo.scale.spec == P(None, None, None)
    placed = shd.shard_params(qp, cfg, mesh)
    assert placed["blocks"]["wo"].q.sharding.spec == P(None, "tp", None)


def test_check_numerics_passes_on_quantized_params():
    cfg = tiny_llama()
    ecfg = EngineConfig(num_pages=64, max_batch_size=2,
                        prefill_buckets=(64,), quant="int8")
    InferenceEngine(cfg, ecfg, seed=0).check_numerics()


def test_unknown_quant_mode_rejected():
    with pytest.raises(ValueError, match="unknown quant mode"):
        quantize_params({}, "fp4")


def test_quant_keys_cover_all_families():
    # Every family's big matmul weights are in QUANT_KEYS (drift guard).
    from tpu_inference.models.registry import build_model
    for cfg_fn in (tiny_llama, tiny_mixtral, tiny_gpt2):
        cfg = cfg_fn()
        params, _ = build_model(cfg, seed=0)
        qp = quantize_params(params)
        n_quant = sum(isinstance(x, QuantizedArray)
                      for x in jax.tree.leaves(
                          qp, is_leaf=lambda x: isinstance(x, QuantizedArray))
                      if isinstance(x, QuantizedArray))
        assert n_quant >= 4, f"{cfg.name}: only {n_quant} quantized leaves"


def test_init_quantized_params_structure_and_determinism():
    """Leaf-by-leaf quantized init (the 8B-on-16GB path) produces the
    same tree structure as init-then-quantize — QuantizedArray at every
    QUANT_KEYS leaf, same shapes/dtypes — and is deterministic per
    seed."""
    import jax

    from tpu_inference.models.quant import (QuantizedArray,
                                            init_quantized_params,
                                            quantize_params)
    from tpu_inference.models.registry import build_model

    cfg = tiny_llama()
    a = init_quantized_params(cfg, seed=0)
    b = init_quantized_params(cfg, seed=0)
    ref = quantize_params(build_model(cfg, seed=0)[0])

    ra = jax.tree_util.tree_structure(a)
    assert ra == jax.tree_util.tree_structure(ref)
    for la, lb, lr in zip(jax.tree.leaves(a), jax.tree.leaves(b),
                          jax.tree.leaves(ref)):
        assert la.shape == lr.shape and la.dtype == lr.dtype
        assert (la == lb).all()      # deterministic per seed
    # The quantized leaves really are quantized (int8 codes).
    flat = jax.tree_util.tree_flatten_with_path(a)[0]
    n_q = sum(1 for p, _ in flat if any(
        getattr(k, "name", "") == "q" for k in p))
    assert n_q >= 8  # wq wk wv wo gate up down lm_head


def test_engine_random_init_quant_decodes():
    """An engine that initializes its own int8 params (params=None)
    serves tokens — the BENCH_MODEL=8b lane's construction path."""
    cfg = tiny_llama()
    ecfg = EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=8,
                        max_batch_size=2, prefill_buckets=(16,),
                        quant="int8")
    eng = InferenceEngine(cfg, ecfg, seed=0)
    out = eng.generate([[1, 2, 3, 4]], max_new_tokens=6, temperature=0.0)
    assert len(out[0]) == 6


# ---------------------------------------------------------------------
# int4 (group-quantized) tier — quarter weight traffic vs bf16; the
# reference's Ollama endpoint served a 4-bit Mistral by default, so this
# is the tier its numbers actually came from.
# ---------------------------------------------------------------------

def test_int4_pack_unpack_roundtrip():
    """Nibble packing is lossless over the full code range, including
    sign extension of negative nibbles from both byte halves."""
    from tpu_inference.models.quant import pack_int4, unpack_int4

    codes = jnp.tile(jnp.arange(-7, 8, dtype=jnp.int8), 30)[:448]
    codes = codes.reshape(56, 8)              # even contraction dim
    packed = pack_int4(codes)
    assert packed.dtype == jnp.int8 and packed.shape == (28, 8)
    np.testing.assert_array_equal(np.asarray(unpack_int4(packed)),
                                  np.asarray(codes))


def test_int4_roundtrip_grouped():
    from tpu_inference.models.quant import GROUP_SIZE

    w = jax.random.normal(jax.random.PRNGKey(2),
                          (2 * GROUP_SIZE, 32)) * 0.05
    qa = quantize_array(w, "int4")
    # Codes are nibble-packed two-per-byte (no sub-byte dtype persists
    # across jit boundaries — the axon device_put re-layout recursion).
    assert qa.q.dtype == jnp.int8
    assert qa.q.shape == (GROUP_SIZE, 32)     # half the contraction dim
    assert qa.scale.shape == (2, 32)          # one scale per (group, col)
    # Per-group symmetric rounding error bound.
    err = jnp.abs(dequantize(qa) - w).reshape(2, GROUP_SIZE, 32)
    bound = qa.scale[:, None, :] / 2 + 1e-7
    assert bool((err <= bound).all())
    # Indivisible contraction dims degrade to one whole-column group.
    qa1 = quantize_array(jax.random.normal(jax.random.PRNGKey(3),
                                           (96, 8)), "int4")
    assert qa1.scale.shape == (1, 8)


def test_int4_qdot_and_qeinsum_match_dequantized():
    # Grouped contraction invariant: folding per-group partials with
    # their scales == contracting against the dequantized weight.
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.normal(size=(256, 16)) * 0.05, jnp.float32)
    x = jnp.asarray(rng.normal(size=(4, 256)), jnp.float32)
    qa = quantize_array(w, "int4")
    assert qa.scale.shape[-2] == 2            # really grouped
    np.testing.assert_allclose(np.asarray(qdot(x, qa)),
                               np.asarray(x @ dequantize(qa)),
                               rtol=1e-4, atol=1e-5)
    we = jnp.asarray(rng.normal(size=(2, 256, 8)) * 0.02, jnp.float32)
    a = jnp.asarray(rng.normal(size=(2, 3, 256)), jnp.float32)
    qe = quantize_array(we, "int4")
    assert qe.scale.shape == (2, 2, 8)
    got = qeinsum("ecd,edf->ecf", a, qe)
    want = jnp.einsum("ecd,edf->ecf", a, dequantize(qe))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_int4_grouped_bf16_activations():
    """bf16 activations through the grouped paths (the real-checkpoint
    serving dtype). XLA:CPU can't execute batched bf16 dots, so the
    grouped contraction upcasts off-TPU (_contract_dtype) — this is the
    regression test for the int4 CPU-smoke failure."""
    rng = np.random.default_rng(6)
    w = jnp.asarray(rng.normal(size=(256, 16)) * 0.05, jnp.bfloat16)
    x = jnp.asarray(rng.normal(size=(4, 256)), jnp.bfloat16)
    qa = quantize_array(w, "int4")
    assert qa.scale.shape[-2] == 2
    got = jax.jit(qdot)(x, qa)               # must compile AND execute
    want = x.astype(jnp.float32) @ dequantize(qa)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-2, atol=5e-2)
    we = jnp.asarray(rng.normal(size=(2, 256, 8)) * 0.02, jnp.bfloat16)
    a = jnp.asarray(rng.normal(size=(2, 3, 256)), jnp.bfloat16)
    qe = quantize_array(we, "int4")
    got = jax.jit(lambda a_, w_: qeinsum("ecd,edf->ecf", a_, w_))(a, qe)
    want = jnp.einsum("ecd,edf->ecf", a.astype(jnp.float32),
                      dequantize(qe))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("cfg_fn", [tiny_llama, tiny_mixtral])
def test_engine_serves_int4(cfg_fn):
    """End-to-end serving with int4 weights (w_down's 256-dim contraction
    exercises the truly-grouped path inside the engine graphs)."""
    cfg = cfg_fn()
    ecfg = EngineConfig(num_pages=64, max_batch_size=2,
                        prefill_buckets=(64,), max_new_tokens=16,
                        quant="int4")
    engine = InferenceEngine(cfg, ecfg, seed=0)
    out = engine.generate([list(range(1, 20)), list(range(5, 40))],
                          max_new_tokens=8)
    assert all(len(t) == 8 for t in out)
    assert all(0 <= tok < cfg.vocab_size for t in out for tok in t)


def test_tp_sharded_int4_matches_unsharded():
    """TP token equality for int4 — w_down shards its 256-dim contraction
    over tp, so the grouped scale must shard its group axis alongside
    (shardings._scale_spec)."""
    from tpu_inference.parallel.mesh import build_mesh
    cfg = tiny_llama()
    ecfg = EngineConfig(num_pages=64, max_batch_size=2,
                        prefill_buckets=(64,), max_new_tokens=16,
                        quant="int4")
    prompts = [list(range(1, 20)), list(range(5, 40))]
    base = InferenceEngine(cfg, ecfg, seed=0).generate(prompts,
                                                       max_new_tokens=10)
    mesh = build_mesh(ParallelConfig(tp=2))
    tp = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh).generate(
        prompts, max_new_tokens=10)
    assert base == tp


def test_int4_scale_sharding_follows_contraction_dim():
    """Grouped scales keep the weight's contraction-dim sharding (each
    chip holds the scales for its own weight shard); int8's size-1 scale
    dim stays replicated."""
    from jax.sharding import PartitionSpec as P

    from tpu_inference.models.registry import build_model
    from tpu_inference.parallel import shardings as shd
    from tpu_inference.parallel.mesh import build_mesh
    cfg = tiny_llama()
    params, _ = build_model(cfg, seed=0)
    qp = quantize_params(params, "int4")
    mesh = build_mesh(ParallelConfig(tp=2))
    sh = shd.param_shardings(cfg, mesh, qp)
    # w_down [L, d_ff=256, d_model] shards the contraction dim -> its
    # G=2 scale groups shard with it.
    wd = sh["blocks"]["w_down"]
    assert qp["blocks"]["w_down"].scale.shape[-2] == 2
    assert wd.q.spec == wd.scale.spec
    placed = shd.shard_params(qp, cfg, mesh)
    assert placed["blocks"]["w_down"].scale.sharding.spec == wd.scale.spec
