"""Hermetic HTTP server tests: the exact wire contract the benchmark
harness depends on (SURVEY.md §2c), served by a tiny random-init model."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

import _prom
from tpu_inference.config import (EngineConfig, FrameworkConfig, ServerConfig,
                                  tiny_llama)
from tpu_inference.server.http import InferenceServer

FINAL_FIELDS = {"model", "created_at", "response", "done", "done_reason",
                "context", "total_duration", "load_duration",
                "prompt_eval_count", "prompt_eval_duration", "eval_count",
                "eval_duration"}


@pytest.fixture(scope="module")
def profile_dir(tmp_path_factory):
    return str(tmp_path_factory.mktemp("jax-trace"))


@pytest.fixture(scope="module")
def server(profile_dir):
    cfg = FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(page_size=8, num_pages=128, max_pages_per_seq=8,
                            max_batch_size=4, prefill_buckets=(16, 32, 64)),
        server=ServerConfig(model_name="tiny-llama", tokenizer="byte",
                            enable_debug=True, profile_dir=profile_dir))
    return InferenceServer(cfg)


def _run(server, coro_fn):
    async def wrapper():
        app = server.make_app()
        async with TestClient(TestServer(app)) as client:
            return await coro_fn(client)

    return asyncio.run(wrapper())


def test_streaming_ndjson_contract(server):
    async def go(client):
        resp = await client.post("/api/generate", json={
            "model": "tiny-llama", "prompt": "Hello TPU",
            "temperature": 0.0, "max_tokens": 8, "stream": True})
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("application/x-ndjson")
        raw = await resp.read()
        lines = [json.loads(l) for l in raw.splitlines()]
        assert len(lines) >= 2
        for line in lines[:-1]:
            assert line["done"] is False
            assert set(line) == {"model", "created_at", "response", "done"}
            assert line["model"] == "tiny-llama"
        final = lines[-1]
        assert final["done"] is True
        assert FINAL_FIELDS <= set(final)
        assert final["eval_count"] == 8 or final["done_reason"] == "stop"
        assert final["prompt_eval_count"] == len("Hello TPU") + 1  # +BOS
        assert final["prompt_eval_duration"] > 0
        assert final["total_duration"] > 0
        assert len(final["context"]) == final["prompt_eval_count"] + final["eval_count"]
        return lines

    _run(server, go)


def test_non_streaming_single_object(server):
    async def go(client):
        resp = await client.post("/api/generate", json={
            "prompt": "abc", "stream": False, "max_tokens": 5})
        assert resp.status == 200
        body = await resp.json()
        assert body["done"] is True
        assert isinstance(body["response"], str)
        assert FINAL_FIELDS <= set(body)
        return body

    _run(server, go)


def test_options_num_predict_honored(server):
    """Ollama-placement options.num_predict must control generation length."""
    async def go(client):
        resp = await client.post("/api/generate", json={
            "prompt": "xyz", "stream": False, "max_tokens": 99,
            "options": {"num_predict": 3, "temperature": 0.0}})
        body = await resp.json()
        assert body["eval_count"] == 3 or body["done_reason"] == "stop"
        return body

    _run(server, go)


def test_greedy_is_deterministic(server):
    async def go(client):
        outs = []
        for _ in range(2):
            resp = await client.post("/api/generate", json={
                "prompt": "determinism", "stream": False, "max_tokens": 6,
                "temperature": 0.0})
            outs.append((await resp.json())["context"])
        assert outs[0] == outs[1]

    _run(server, go)


def test_options_seed_reproducible(server):
    """options.seed makes temperature sampling reproducible across
    requests (and across different engine key states)."""
    async def go(client):
        outs = []
        for _ in range(2):
            resp = await client.post("/api/generate", json={
                "prompt": "seeded run", "stream": False, "max_tokens": 8,
                "options": {"temperature": 1.0, "seed": 1234}})
            outs.append((await resp.json())["context"])
        assert outs[0] == outs[1]
        # Different seed should (overwhelmingly) differ.
        resp = await client.post("/api/generate", json={
            "prompt": "seeded run", "stream": False, "max_tokens": 8,
            "options": {"temperature": 1.0, "seed": 99}})
        other = (await resp.json())["context"]
        assert other != outs[0]

    _run(server, go)


def test_options_top_k_one_is_greedy(server):
    """top_k=1 at high temperature degenerates to the greedy tokens."""
    async def go(client):
        greedy = await (await client.post("/api/generate", json={
            "prompt": "topk probe", "stream": False, "max_tokens": 6,
            "temperature": 0.0})).json()
        topk1 = await (await client.post("/api/generate", json={
            "prompt": "topk probe", "stream": False, "max_tokens": 6,
            "options": {"temperature": 5.0, "top_k": 1}})).json()
        assert topk1["context"] == greedy["context"]

    _run(server, go)


def test_stop_sequences(server):
    """options.stop truncates the response before the stop string, ends
    the request with done_reason=stop, in both unary and streaming."""
    async def go(client):
        # Discover the greedy continuation, then stop on a substring of it.
        base = await (await client.post("/api/generate", json={
            "prompt": "stop probe", "stream": False, "max_tokens": 12,
            "temperature": 0.0})).json()
        text = base["response"]
        assert len(text) >= 3
        stop_s = text[2:4]

        unary = await (await client.post("/api/generate", json={
            "prompt": "stop probe", "stream": False, "max_tokens": 12,
            "temperature": 0.0, "options": {"stop": [stop_s]}})).json()
        assert unary["done_reason"] == "stop"
        assert unary["response"] == text[:text.find(stop_s)]
        assert stop_s not in unary["response"]

        resp = await client.post("/api/generate", json={
            "prompt": "stop probe", "stream": True, "max_tokens": 12,
            "temperature": 0.0, "options": {"stop": stop_s}})
        lines = [json.loads(l) for l in (await resp.read()).splitlines()]
        assert lines[-1]["done"] and lines[-1]["done_reason"] == "stop"
        streamed = "".join(l.get("response", "") for l in lines[:-1])
        assert streamed == text[:text.find(stop_s)]

    _run(server, go)


def test_stop_matcher_unit():
    from tpu_inference.server.tokenizer import StopMatcher

    m = StopMatcher(["END"])
    assert m.push("hello ") == ("hello ", False)
    assert m.push("E") == ("", False)           # possible prefix: hold
    assert m.push("X") == ("EX", False)         # disambiguated: release
    out, stopped = m.push("abcENDxyz")
    assert (out, stopped) == ("abc", True)

    m = StopMatcher(["END"])                     # split across pushes
    assert m.push("aE") == ("a", False)
    assert m.push("N") == ("", False)
    assert m.push("D tail") == ("", True)

    m = StopMatcher([])
    assert m.push("anything") == ("anything", False)


def test_bad_requests(server):
    async def go(client):
        r1 = await client.post("/api/generate", data=b"{not json")
        assert r1.status == 400
        r2 = await client.post("/api/generate", json={"model": "x"})
        assert r2.status == 400
        # Malformed sampling options -> structured 400, not a 500.
        r3 = await client.post("/api/generate", json={
            "prompt": "x", "options": {"stop": 5}})
        assert r3.status == 400
        r4 = await client.post("/api/generate", json={
            "prompt": "x", "options": {"top_k": "lots"}})
        assert r4.status == 400
        r5 = await client.post("/api/generate", json={
            "prompt": "x", "options": "fast"})
        assert r5.status == 400
        return r1, r2

    _run(server, go)


def test_seed_edge_values(server):
    """64-bit seeds are accepted (clamped into int32 on device) and
    seed=-1 means unseeded (requests differ across retries)."""
    async def go(client):
        big = {"prompt": "edge", "stream": False, "max_tokens": 6,
               "options": {"temperature": 1.0, "seed": 2**40 + 123}}
        a = await (await client.post("/api/generate", json=big)).json()
        b = await (await client.post("/api/generate", json=big)).json()
        assert a["done"] and a["context"] == b["context"]
        outs = set()
        for _ in range(4):
            r = await (await client.post("/api/generate", json={
                "prompt": "edge", "stream": False, "max_tokens": 6,
                "options": {"temperature": 5.0, "seed": -1}})).json()
            outs.add(tuple(r["context"]))
        assert len(outs) > 1

    _run(server, go)


def test_aux_routes(server):
    async def go(client):
        assert (await client.get("/healthz")).status == 200
        tags = await (await client.get("/api/tags")).json()
        assert tags["models"][0]["name"] == "tiny-llama"
        metrics = await (await client.get("/metrics?format=json")).json()
        assert "kv_pages_in_use" in metrics
        version = await (await client.get("/api/version")).json()
        assert "version" in version
        show = await (await client.post("/api/show",
                                        json={"model": "m"})).json()
        assert show["details"]["family"] == "llama"
        info = show["model_info"]
        assert info["llama.context_length"] > 0
        assert info["general.parameter_count"] > 0
        # SWA composition rules surface here (full-attention model:
        # window 0, no eviction, prefix cache on).
        assert info["llama.attention.sliding_window"] == 0
        assert info["serving.swa_eviction"] is False
        assert info["serving.prefix_cache"] is True
        # Ollama GET /api/ps: the one loaded model, never unloading.
        # size/size_vram are ONE model copy (not x dp — ADVICE r5); the
        # replica count is a separate additive field, and details carry
        # Ollama-shaped values ("3.2M"/"8.0B" parameter_size, "F32"/
        # "Q8_0"-style quantization_level).
        ps = await (await client.get("/api/ps")).json()
        (entry,) = ps["models"]
        assert entry["name"] == "tiny-llama"
        assert entry["size"] > 0 and entry["size_vram"] == entry["size"]
        assert entry["replicas"] == 1
        det = entry["details"]
        assert det["parameter_size"].endswith(("B", "M", "K"))
        assert det["quantization_level"] in ("F32", "F16", "BF16",
                                             "Q8_0", "Q4_0")
        assert entry["expires_at"].startswith("0001-01-01")

    _run(server, go)


def test_concurrent_requests_interleave(server):
    """Multiple in-flight requests (continuous batching through HTTP)."""
    async def go(client):
        async def one(i):
            resp = await client.post("/api/generate", json={
                "prompt": f"request {i}", "stream": False, "max_tokens": 6})
            return await resp.json()

        bodies = await asyncio.gather(*[one(i) for i in range(6)])
        for b in bodies:
            assert b["done"] is True
            assert b["eval_count"] >= 1
        return bodies

    _run(server, go)


def test_debug_requests_and_profile(server, profile_dir):
    """Observability endpoints: request timelines + profiler control."""

    async def scenario(client):
        resp = await client.post("/api/generate", json={
            "model": "m", "prompt": "observe me", "temperature": 0,
            "max_tokens": 6, "stream": False})
        assert resp.status == 200

        resp = await client.get("/debug/requests")
        timelines = await resp.json()
        assert len(timelines) >= 1
        t = timelines[-1]
        assert t["output_tokens"] == 6
        assert t["finish_reason"] == "length"
        assert t["queue_wait_s"] >= 0 and t["decode_s"] >= 0
        assert t["tpot_s"] > 0

        resp = await client.get("/metrics?format=json")
        stats = await resp.json()
        assert stats["model_params"] > 0
        assert stats["approx_flops_per_token"] == 2 * stats["model_params"]

        import os
        # Client-supplied "dir" is ignored: traces land only in the
        # server-configured profile_dir (unauthenticated endpoint must
        # not take filesystem paths from the wire).
        resp = await client.post("/debug/profile",
                                 json={"action": "start", "dir": "/etc"})
        assert resp.status == 200
        assert (await resp.json())["dir"] == profile_dir
        resp = await client.post("/debug/profile", json={"action": "stop"})
        assert resp.status == 200
        assert any(os.scandir(profile_dir))     # trace artifacts written
        resp = await client.post("/debug/profile", json={"action": "bogus"})
        assert resp.status == 400

    _run(server, scenario)


def test_debug_disabled_by_default():
    """Without enable_debug the /debug routes are not registered."""
    cfg = FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(page_size=8, num_pages=32, max_pages_per_seq=4,
                            max_batch_size=2, prefill_buckets=(16,)),
        server=ServerConfig(model_name="t", tokenizer="byte",
                            warmup=False))   # routes-only test: no compile
    srv = InferenceServer(cfg)

    async def scenario(client):
        assert (await client.get("/debug/requests")).status == 404
        assert (await client.post("/debug/profile",
                                  json={"action": "start"})).status == 404
        assert (await client.get("/healthz")).status == 200

    _run(srv, scenario)


def test_chat_endpoint(server):
    """Ollama /api/chat: message records, counters, streaming + unary."""

    async def scenario(client):
        msgs = [{"role": "system", "content": "be brief"},
                {"role": "user", "content": "hi"}]
        resp = await client.post("/api/chat", json={
            "model": "m", "messages": msgs, "stream": False,
            "options": {"num_predict": 6, "temperature": 0}})
        assert resp.status == 200
        rec = await resp.json()
        assert rec["done"] and rec["message"]["role"] == "assistant"
        assert "context" not in rec and "response" not in rec
        assert rec["eval_count"] == 6

        resp = await client.post("/api/chat", json={
            "model": "m", "messages": msgs, "stream": True,
            "options": {"num_predict": 6, "temperature": 0}})
        lines = [json.loads(l) for l in (await resp.read()).splitlines() if l]
        assert all("message" in l for l in lines)
        assert lines[-1]["done"] and lines[-1]["eval_count"] == 6

        # Empty messages = the Ollama chat-model preload probe: an
        # immediate load ack, not a 400 (clients use this to warm up).
        resp = await client.post("/api/chat", json={"model": "m",
                                                    "messages": []})
        assert resp.status == 200
        ping = await resp.json()
        assert ping["done"] and ping["done_reason"] == "load"
        # Malformed (non-list / bad entries) still 400s.
        resp = await client.post("/api/chat", json={"model": "m",
                                                    "messages": "nope"})
        assert resp.status == 400

    _run(server, scenario)


def test_chaos_injection():
    """chaos_failure_rate=1.0 rejects every request with 503."""
    from tpu_inference.config import (EngineConfig, FrameworkConfig,
                                      ServerConfig, tiny_llama)
    from tpu_inference.server.http import InferenceServer

    cfg = FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(page_size=8, num_pages=32, max_pages_per_seq=4,
                            max_batch_size=2, prefill_buckets=(16,)),
        server=ServerConfig(model_name="t", tokenizer="byte",
                            chaos_failure_rate=1.0,
                            warmup=False))   # 503s pre-engine: no compile
    srv = InferenceServer(cfg)

    async def scenario(client):
        resp = await client.post("/api/generate", json={
            "model": "m", "prompt": "x", "max_tokens": 2})
        assert resp.status == 503

    _run(srv, scenario)


@pytest.mark.parametrize("quant,kv_quant", [
    ("none", "none"),
    # The quantized-replica combination re-proves what test_quant and
    # test_kv_quant cover per-component; slow-marked as a sweep.
    pytest.param("int8", "int8", marks=pytest.mark.slow)])
def test_dp_replica_serving(quant, kv_quant):
    """dp=2 builds two replica engines on disjoint submeshes; concurrent
    requests spread across them and all succeed (least-loaded routing).
    Parametrized over the quantization tiers: each replica carries its
    own (possibly int8) weights + KV pool, and /metrics reports the
    modes."""
    from tpu_inference.config import ParallelConfig
    from tpu_inference.server.http import build_engine_group

    cfg = FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=4,
                            max_batch_size=2, prefill_buckets=(16,),
                            quant=quant, kv_quant=kv_quant),
        parallel=ParallelConfig(dp=2, tp=2),
        server=ServerConfig(model_name="t", tokenizer="byte"))
    group = build_engine_group(cfg)
    assert len(group.engines) == 2
    d0 = {d for d in group.engines[0].mesh.devices.flat}
    d1 = {d for d in group.engines[1].mesh.devices.flat}
    assert d0.isdisjoint(d1)
    if quant == "int8":
        from tpu_inference.models.quant import QuantizedArray
        for eng in group.engines:
            assert isinstance(eng.params["blocks"]["wq"], QuantizedArray)
            assert eng.kv.quantized
    srv = InferenceServer(cfg, group=group)

    async def scenario(client):
        async def one(i):
            resp = await client.post("/api/generate", json={
                "prompt": f"replica probe {i}", "stream": False,
                "max_tokens": 5})
            return await resp.json()

        bodies = await asyncio.gather(*[one(i) for i in range(6)])
        assert all(b["done"] and b["eval_count"] >= 1 for b in bodies)
        stats = await (await client.get("/metrics?format=json")).json()
        assert stats["dp"] == 2
        assert stats["quant"] == quant
        assert stats["kv_quant"] == kv_quant
        # Both replicas did work under concurrent load.
        assert all(r["requests_finished"] >= 1 for r in stats["replicas"])
        # Fleet phase histograms merge across replicas (not replica 0's
        # copy masquerading): every request shows up in the e2e count.
        assert stats["phases"]["e2e_s"]["count"] == sum(
            r["phases"]["e2e_s"]["count"] for r in stats["replicas"])
        # Prometheus exposition separates replicas by label: the same
        # family carries one series per replica, plus fleet-level
        # supervision series without a replica label.
        meta, samples = _prom.parse(
            await (await client.get("/metrics")).text())
        steps = {l.get("replica"): v for n, l, v in samples
                 if n == "tpu_inf_steps_total"}
        assert set(steps) == {"0", "1"}
        assert any(n == "tpu_inf_replicas" and "replica" not in l
                   for n, l, _ in samples)

    _run(srv, scenario)



def test_metrics_prometheus_exposition(server):
    """GET /metrics (default format) is standards-compliant Prometheus
    text: correct content type, HELP/TYPE for every family, histogram
    buckets cumulative-monotone with le="+Inf" == _count, and the step-
    phase metric names the round-6 dashboards will scrape."""
    async def go(client):
        resp = await client.post("/api/generate", json={
            "prompt": "scrape me", "stream": False, "max_tokens": 6,
            "temperature": 0.0})
        assert resp.status == 200

        resp = await client.get("/metrics")
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        meta, samples = _prom.parse(await resp.text())

        # Every sample belongs to a declared family with HELP and TYPE.
        for name, labels, value in samples:
            fam = _prom.family(name, meta)
            assert "type" in meta[fam], f"no TYPE for {name}"
            assert "help" in meta[fam], f"no HELP for {name}"
        names = {_prom.family(n, meta) for n, _, _ in samples}
        for expected in ("tpu_inf_decode_dispatch_seconds",
                         "tpu_inf_prefill_dispatch_seconds",
                         "tpu_inf_dispatch_bubble_seconds",
                         "tpu_inf_tokens_per_dispatch",
                         "tpu_inf_queue_wait_seconds",
                         "tpu_inf_e2e_seconds",
                         "tpu_inf_kv_pages_in_use",
                         "tpu_inf_kv_page_allocs_total",
                         "tpu_inf_tokens_generated_total",
                         "tpu_inf_requests_finished_total"):
            assert expected in names, f"{expected} missing from /metrics"

        # Histogram contract per labelset: buckets monotone in le, last
        # le=+Inf, +Inf bucket == _count, and _sum present.
        counts = {(n[:-len("_count")], tuple(sorted(l.items()))): v
                  for n, l, v in samples if n.endswith("_count")}
        sums = {(n[:-len("_sum")], tuple(sorted(l.items()))): v
                for n, l, v in samples if n.endswith("_sum")}
        checked = 0
        for fam, info in meta.items():
            if info.get("type") != "histogram":
                continue
            for key, buckets in _prom.histogram_series(samples,
                                                       fam).items():
                vals = [v for _, v in buckets]
                assert vals == sorted(vals), f"{fam} not cumulative"
                assert buckets[-1][0] == float("inf")
                assert counts[(fam, key)] == vals[-1]
                assert sums[(fam, key)] >= 0
                checked += 1
        assert checked >= 5

        # The decode phase actually ran: non-zero observations.
        series = _prom.histogram_series(
            samples, "tpu_inf_decode_dispatch_seconds")
        assert any(b[-1][1] > 0 for b in series.values())
        # Per-reason finish counter carries a label.
        assert any(n == "tpu_inf_requests_finished_total"
                   and l.get("reason") == "length"
                   for n, l, _ in samples)
        # JSON mode is preserved and still carries the legacy keys.
        js = await (await client.get("/metrics?format=json")).json()
        assert "kv_pages_in_use" in js and "phases" in js

    _run(server, go)


def test_request_id_propagation_and_span_accounting(server):
    """X-Request-Id flows ingress -> engine -> response header, terminal
    record, and the /debug/requests span; the span's queue + prefill +
    decode phases sum to E2E (same clock stamps), and the new dispatch-
    wall/bubble phases are populated."""
    async def go(client):
        resp = await client.post("/api/generate", json={
            "prompt": "trace this request", "stream": False,
            "max_tokens": 6, "temperature": 0.0},
            headers={"X-Request-Id": "trace-me-42"})
        assert resp.status == 200
        assert resp.headers["X-Request-Id"] == "trace-me-42"
        rec = await resp.json()
        assert rec["request_id"] == "trace-me-42"

        timelines = await (await client.get("/debug/requests")).json()
        spans = [t for t in timelines if t.get("trace_id") == "trace-me-42"]
        assert spans, "span for the traced request must be recorded"
        t = spans[-1]
        assert t["attempt"] == 0
        # Phase sum-check: identical timestamps on both sides, so the
        # identity holds to rounding noise.
        phase_sum = t["queue_wait_s"] + t["prefill_s"] + t["decode_s"]
        assert abs(phase_sum - t["e2e_s"]) < 1e-3
        assert t["ttft_s"] >= t["queue_wait_s"]
        assert t["dispatch_wall_s"] > 0
        assert t["bubble_s"] >= 0
        # Dispatch exposure can't exceed the request's wall clock.
        assert t["dispatch_wall_s"] <= t["e2e_s"] + 1e-3

        # Streaming + no client id: the server mints one and echoes it.
        resp = await client.post("/api/generate", json={
            "prompt": "minted id", "stream": True, "max_tokens": 4,
            "temperature": 0.0})
        assert resp.status == 200
        minted = resp.headers.get("X-Request-Id")
        assert minted
        lines = [json.loads(l) for l in (await resp.read()).splitlines()]
        assert lines[-1]["request_id"] == minted

    _run(server, go)


def test_context_continuation_hits_prefix_cache(server):
    """A continuation request (prior response's context + new prompt) is
    a strict prefix extension, so its prefill must reuse the cached KV
    pages of the first request (tokens_prefix_cached grows)."""
    async def go(client):
        resp = await client.post("/api/generate", json={
            "prompt": "cache me please", "stream": False, "max_tokens": 10,
            "temperature": 0.0})
        assert resp.status == 200
        first = await resp.json()
        before = (await (await client.get("/metrics?format=json")).json()
                  )["tokens_prefix_cached"]
        cont = await (await client.post("/api/generate", json={
            "prompt": " keep going", "stream": False, "max_tokens": 4,
            "temperature": 0.0, "context": first["context"]})).json()
        assert cont["done"]
        after = (await (await client.get("/metrics?format=json")).json()
                 )["tokens_prefix_cached"]
        assert after > before

    _run(server, go)


def test_sampling_warnings_surface(server):
    """Options accepted but not honored exactly are reported in a
    terminal-record ``warnings`` list (ADVICE r3): repeat_last_n beyond
    the static penalty window is clamped — the client learns instead of
    silently getting different sampling. Honored options add no field."""
    async def go(client):
        rec = await (await client.post("/api/generate", json={
            "prompt": "hi", "stream": False, "max_tokens": 4,
            "temperature": 0.0,
            "options": {"repeat_penalty": 1.1, "repeat_last_n": 512}})).json()
        assert rec["done"]
        assert any("repeat_last_n" in w and "clamped" in w
                   for w in rec["warnings"])

        clean = await (await client.post("/api/generate", json={
            "prompt": "hi", "stream": False, "max_tokens": 4,
            "temperature": 0.0,
            "options": {"repeat_penalty": 1.1, "repeat_last_n": 32}})).json()
        assert "warnings" not in clean

    _run(server, go)


def test_context_ids_validate_against_model_vocab(server):
    """An id the model cannot embed must 400 — the XLA gather would
    clamp it silently into a wrong embedding (ADVICE r3). tiny-llama
    model vocab is 512; the byte tokenizer's is smaller."""
    async def go(client):
        resp = await client.post("/api/generate", json={
            "prompt": "hi", "stream": False, "max_tokens": 2,
            "temperature": 0.0, "context": [0, 511]})
        assert resp.status == 200
        resp = await client.post("/api/generate", json={
            "prompt": "hi", "stream": False, "max_tokens": 2,
            "temperature": 0.0, "context": [512]})
        assert resp.status == 400
        assert "out of range" in (await resp.json())["error"]

    _run(server, go)


def test_boot_rejects_tokenizer_model_vocab_mismatch():
    """A tokenizer that can emit ids the model cannot embed must fail at
    boot (one loud error), not clamp embeddings one request at a time:
    the byte tokenizer needs 258 ids, so a 200-entry model vocab is a
    broken deployment."""
    cfg = FrameworkConfig(
        model=tiny_llama(vocab_size=200),
        engine=EngineConfig(page_size=8, num_pages=32, max_pages_per_seq=4,
                            max_batch_size=2, prefill_buckets=(16,)),
        server=ServerConfig(tokenizer="byte"))
    with pytest.raises(ValueError, match="tokenizer vocab"):
        InferenceServer(cfg)


def test_spec_decode_repeat_penalty_warning():
    """With a draft model configured, a request asking for repeat_penalty
    gets a warning that the penalty is ignored (rejection sampling needs
    the unmodified target distribution) — never a silent divergence."""
    import dataclasses

    from tpu_inference.engine.engine import InferenceEngine
    from tpu_inference.models import build_model

    target = tiny_llama(vocab_size=512)
    # Derive the draft from the target (same idiom as test_kv_quant) so
    # the configs can't drift apart.
    draft = dataclasses.replace(target, name="draft", n_layers=1)
    params, _ = build_model(target, seed=0)
    dparams, _ = build_model(draft, seed=9)
    ecfg = EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=8,
                        max_batch_size=2, prefill_buckets=(16, 32),
                        num_speculative_tokens=2)
    eng = InferenceEngine(target, ecfg, params=params,
                          draft_cfg=draft, draft_params=dparams)
    srv = InferenceServer(FrameworkConfig(
        model=target, engine=ecfg, server=ServerConfig(tokenizer="byte")),
        engine=eng)

    async def go(client):
        rec = await (await client.post("/api/generate", json={
            "prompt": "hi", "stream": False, "max_tokens": 4,
            "temperature": 0.0, "options": {"repeat_penalty": 1.2}})).json()
        assert rec["done"]
        assert any("speculative" in w for w in rec["warnings"])

    _run(srv, go)
