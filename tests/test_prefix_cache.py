"""Prefix cache: KV page reuse must be invisible to generation output.

The invariant under test: a request served with prefix-cache hits
generates exactly the tokens it would generate cold — page sharing is an
optimization, never a behavior change (BASELINE.json config 3 multi-turn
target; the reference has no KV reuse, SURVEY.md §2b).
"""

import numpy as np
import pytest

from tpu_inference import config as cfgs
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.kv_cache import PageAllocator
from tpu_inference.engine.prefix_cache import PrefixCache, _chain_hashes
from tpu_inference.models import build_model


@pytest.fixture(scope="module")
def setup():
    model_cfg = cfgs.tiny_llama(vocab_size=256)
    params, mod = build_model(model_cfg, seed=0)
    return model_cfg, params, mod


def _ecfg(**kw):
    base = dict(page_size=8, num_pages=64, max_pages_per_seq=16,
                max_batch_size=4, prefill_buckets=(16, 32, 64),
                decode_steps_per_call=4, enable_prefix_cache=True)
    base.update(kw)
    return cfgs.EngineConfig(**base)


@pytest.fixture(scope="module")
def warm_engine(setup):
    """Shared cache-on engine. Cache state is cumulative across tests:
    each test uses its own distinct prompts and asserts >=/>0, so
    earlier entries can't change any outcome."""
    model_cfg, params, _ = setup
    return InferenceEngine(model_cfg, _ecfg(), params=params)


@pytest.fixture(scope="module")
def cold_engine(setup):
    model_cfg, params, _ = setup
    return InferenceEngine(model_cfg, _ecfg(enable_prefix_cache=False),
                           params=params)


def test_chain_hash_full_pages_only():
    hs = _chain_hashes(list(range(20)), 8)
    assert len(hs) == 2                      # 20 tokens -> 2 full pages
    # Chain property: same block after a different prefix hashes differently.
    other = _chain_hashes(list(range(1, 21)), 8)
    assert hs[0] != other[0] and hs[1] != other[1]
    assert _chain_hashes(list(range(16)), 8)[:2] == hs[:2]


def test_chain_hash_sensitive_to_every_token():
    """Micro-assert for the packed-int32 encoding: flipping ANY single
    token — including values that would collide under a sloppier
    serialization (0 vs 00, adjacent-block bleed) — changes that page's
    digest and every digest after it."""
    base = list(range(100, 116))             # 2 full pages of 8
    ref = _chain_hashes(base, 8)
    for i in range(len(base)):
        mutated = list(base)
        mutated[i] += 1
        got = _chain_hashes(mutated, 8)
        page = i // 8
        assert got[page] != ref[page], f"token {i} did not change page {page}"
        assert got[page:] != ref[page:]
        # Chain property: pages BEFORE the mutated one are untouched.
        assert got[:page] == ref[:page]
    # Fixed-width packing is injective where str-joins could collide:
    # [1, 21] vs [12, 1] concatenate identically as digit strings.
    assert _chain_hashes([1, 21], 2) != _chain_hashes([12, 1], 2)
    # Large ids (real vocabs are ~128k) survive the int32 packing.
    big = _chain_hashes([2**30 + 7] * 8, 8)
    assert big and big != _chain_hashes([2**30 + 8] * 8, 8)


def test_prefix_cache_unit():
    alloc = PageAllocator(16)
    cache = PrefixCache(alloc, page_size=4)
    tokens = list(range(10))                 # 2 full pages + tail
    pages = alloc.allocate(3)
    assert cache.insert(tokens, pages) == 2
    assert alloc.refcount(pages[0]) == 2     # seq + cache
    alloc.free(pages)                        # seq done
    assert cache.evictable == 2

    got, host, n = cache.lookup(tokens)
    assert got == pages[:2] and n == 8 and host == []
    assert alloc.refcount(pages[0]) == 2     # cache + new lookup ref
    # max_tokens caps the match (engine recomputes the final token).
    got2, _, n2 = cache.lookup(tokens, max_tokens=8)
    assert n2 == 8 and len(got2) == 2
    got3, _, n3 = cache.lookup(tokens, max_tokens=7)
    assert n3 == 4 and len(got3) == 1
    alloc.free(got + got2 + got3)

    # Eviction frees only cache-held pages, LRU first (no host tier
    # attached: classic free-on-evict).
    freed = cache.evict(10)
    assert freed == 2
    assert alloc.num_free == 15
    got, host, n = cache.lookup(tokens)
    assert n == 0 and got == [] and host == []


def test_peek_is_side_effect_free():
    """The router's peek must neither promote (LRU order), pin
    (refcounts), nor perturb hit/miss accounting — only count."""
    alloc = PageAllocator(16)
    cache = PrefixCache(alloc, page_size=4)
    old = list(range(8))                     # 2 full pages
    new = list(range(50, 58))
    p_old, p_new = alloc.allocate(2), alloc.allocate(2)
    cache.insert(old, p_old)
    cache.insert(new, p_new)
    alloc.free(p_old)
    alloc.free(p_new)                        # cache holds the only refs

    refs_before = [alloc.refcount(p) for p in p_old + p_new]
    hits = (cache.hits_hbm.value, cache.hits_host.value)
    misses = cache.misses.value
    assert cache.peek(old) == 2
    assert cache.peek(old, max_tokens=7) == 1
    assert cache.peek(list(range(99, 107))) == 0
    # No refcount share, no stat movement, only the peek counter —
    # which now IS the telemetry Counter /metrics scrapes (one set of
    # numbers; same torn-update-tolerant stance as telemetry.py).
    assert [alloc.refcount(p) for p in p_old + p_new] == refs_before
    assert (cache.hits_hbm.value, cache.hits_host.value) == hits
    assert cache.misses.value == misses
    assert cache.peeks.value == 3

    # No promotion: `old` was peeked last, but eviction still takes it
    # first (insertion order = LRU order untouched by peeks).
    cache.evict(2)
    assert cache.peek(old) == 0
    assert cache.peek(new) == 2

    # lookup agreement: peek's count matches what a real lookup takes.
    got, _, n = cache.lookup(new)
    assert len(got) == cache.peek(new) == 2 and n == 8
    alloc.free(got)
    cache.clear()
    assert alloc.num_free == 15              # page 0 = trash page


def test_stale_peek_tolerated_under_eviction(setup):
    """A routing decision counts pages that pressure may evict before
    the request prefills: the prefill must re-check via lookup and
    recompute the difference — never trust the peek — and generation
    output stays byte-identical. The pool comes back clean after the
    churn (tests/_leak.py invariant)."""
    model_cfg, params, _ = setup
    engine = InferenceEngine(model_cfg, _ecfg(num_pages=32), params=params)
    prompt = list(range(30, 62))             # 4 full pages of 8
    want = engine.generate([prompt], max_new_tokens=6)[0]

    hit, prompt_pages = engine.peek_prefix_pages(prompt)
    assert prompt_pages == 4
    assert hit == 3                          # final token always recomputed
    # Pressure evicts EVERYTHING the router just counted on.
    assert engine.prefix_cache.evict(32) > 0
    assert engine.peek_prefix_pages(prompt)[0] == 0
    # The request routed on the stale peek still admits and matches.
    assert engine.generate([prompt], max_new_tokens=6)[0] == want

    # Refcount/eviction invariants under churn: interleave peeks with
    # admissions and evictions, then require a fully reclaimable pool.
    for i in range(6):
        mix = [(7 * i + j) % 256 for j in range(24)]
        engine.peek_prefix_pages(mix)
        engine.generate([mix], max_new_tokens=4)
        engine.prefix_cache.evict(i)
        engine.peek_prefix_pages(prompt)
    from tests._leak import assert_pool_clean
    assert_pool_clean(engine)


def test_warm_request_matches_cold(warm_engine, cold_engine):
    prompt = np.random.default_rng(0).integers(0, 256, 37).tolist()

    want = cold_engine.generate([prompt], max_new_tokens=12)[0]

    warm = warm_engine
    first = warm.generate([prompt], max_new_tokens=12)[0]
    assert first == want
    assert warm.prefix_cache.stats()["entries"] > 0
    # Second identical request hits the cache and still matches.
    second = warm.generate([prompt], max_new_tokens=12)[0]
    assert second == want
    assert warm.prefix_cache.hits_hbm.value >= 1


def test_multi_turn_conversation_reuse(warm_engine, cold_engine):
    """Turn 2 resends turn 1's history: its full pages must be reused."""
    engine = warm_engine
    rng = np.random.default_rng(1)
    turn1 = rng.integers(0, 256, 20).tolist()
    reply1 = engine.generate([turn1], max_new_tokens=8)[0]
    history = turn1 + reply1[:-1] + [7, 7]   # user follow-up

    s = Sequence(request_id=9, prompt_tokens=history, max_new_tokens=4)
    engine.prefill(s)
    # 20 + 7 in-KV tokens = 3 full pages of 8 cached.
    assert s.cached_tokens == 24
    while engine.active_sequences():
        engine.decode_steps()
    warm_out = list(s.generated)
    engine.release(s)

    assert warm_out == cold_engine.generate([history],
                                            max_new_tokens=4)[0]


def test_cache_eviction_under_pressure(setup):
    """A big request evicts cached pages instead of failing admission."""
    model_cfg, params, _ = setup
    ecfg = _ecfg(num_pages=9, max_pages_per_seq=8, max_batch_size=1)
    engine = InferenceEngine(model_cfg, ecfg, params=params)
    p1 = list(range(100, 124))               # 3 pages
    engine.generate([p1], max_new_tokens=8)  # finishes -> pages cached
    assert engine.prefix_cache.evictable > 0

    s = Sequence(request_id=1, prompt_tokens=list(range(40)),
                 max_new_tokens=8)           # needs 5 pages for prefill
    assert engine.can_admit(s)
    engine.prefill(s)
    while engine.active_sequences():
        engine.decode_steps()
    assert len(s.generated) == 8
    engine.release(s)


def test_shared_pages_never_written(warm_engine):
    """Running a warm request must not corrupt the cached prefix for a
    concurrent cold request using the same pages."""
    engine = warm_engine
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 256, 16).tolist()   # exactly 2 full pages
    base = engine.generate([prompt], max_new_tokens=10)[0]

    # Two warm requests sharing the cached pages, decoding concurrently.
    s1 = Sequence(request_id=1, prompt_tokens=prompt, max_new_tokens=10)
    s2 = Sequence(request_id=2, prompt_tokens=prompt + [9],
                  max_new_tokens=10)
    engine.prefill(s1)
    engine.prefill(s2)
    assert s1.cached_tokens == 8             # page 2 is full but capped
    assert s2.cached_tokens == 16
    while engine.active_sequences():
        engine.decode_steps()
    assert s1.generated == base
    engine.release(s1)
    engine.release(s2)
