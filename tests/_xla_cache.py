"""Persistent XLA compilation cache for the test suite.

The suite is ~70% XLA:CPU compile time on a single-core box, and the
graphs are identical run to run, so the compiled executables are cached
on disk (keyed by HLO + compile options + jaxlib version). Shared by
tests/conftest.py and the bare-subprocess tests/_multihost_worker.py so
the knobs cannot drift.

``jax_persistent_cache_enable_xla_caches="all"`` is required for XLA:CPU
executable reuse (the default scope caches nothing useful on CPU).
Reusing an executable on the same machine triggers a cosmetic
cpu_aot_loader machine-feature warning per load (XLA's pseudo-features
like +prefer-no-scatter are absent from the host-feature string), so
TF_CPP_MIN_LOG_LEVEL silences C++ logging below FATAL; tests assert via
Python exceptions, not glog. Numeric parity tests would catch a
genuinely bad cached executable; delete the dir to force recompiles.

Debugging knobs (ADVICE r5 — a blanket log gag must never survive into
a debugging run):
- ``TPU_INF_NO_XLA_CACHE=1`` opts out of the cache entirely AND skips
  the log suppression, so a debugging run gets full XLA logs.
- ``TPU_INF_XLA_LOGS=1`` keeps the (fast) cache but skips the
  suppression — full logs without paying cold recompiles.
"""

import os


def enable(jax) -> None:
    if os.environ.get("TPU_INF_NO_XLA_CACHE"):
        # No cache -> no cosmetic reuse warning to hide, so the blanket
        # TF_CPP_MIN_LOG_LEVEL suppression is skipped too: debugging
        # runs see every XLA warning/error.
        return
    if not os.environ.get("TPU_INF_XLA_LOGS"):
        os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("TPU_INF_XLA_CACHE",
                                     "/tmp/tpu_inference_xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
