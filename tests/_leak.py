"""Page-leak invariant checker (test helper).

After every request mix — clean finishes, cancels, chaos step failures,
preemptions, recompute-resumes — the KV pool must return to its
fully-free state once the prefix cache releases its references and any
chaos page pressure is disarmed. A page that doesn't come back is a
leak: under production load the pool ratchets down until the server
sheds everything.
"""

from __future__ import annotations


def assert_pool_clean(engine) -> None:
    """Assert the allocator is fully reclaimable: disarm chaos page
    pressure, drop the prefix cache's references, then require every
    page free with zero refcounts (page 0, the trash page, excepted)."""
    assert not engine.pipeline_pending, \
        "dispatch-ahead calls still in flight; drain before checking"
    assert not engine._preempted_out, \
        "preempted sequences never collected (take_preempted)"
    engine.set_page_pressure(0)
    cache = engine.prefix_cache
    if cache is not None and cache.host_pool is not None:
        # Host-tier accounting invariant BEFORE the clear: the pool's
        # page/byte counters must agree with the entries actually
        # resident, and no digest may live in both tiers at once.
        pool = cache.host_pool
        assert pool.used == len(cache._host), (
            f"host-tier page accounting drifted: pool says {pool.used}, "
            f"table holds {len(cache._host)}")
        assert pool.bytes_resident == sum(
            e.nbytes for e in cache._host.values()), \
            "host-tier byte accounting drifted"
        assert 0 <= pool.used <= pool.capacity, (
            f"host pool over capacity: {pool.used}/{pool.capacity}")
        overlap = set(cache._host) & set(cache._table)
        assert not overlap, \
            f"digests resident in BOTH tiers: {len(overlap)}"
    if cache is not None:
        cache.clear()
        if cache.host_pool is not None:
            assert cache.host_pool.used == 0, \
                "host pool pages leaked after clear"
            assert cache.host_pool.bytes_resident == 0, \
                "host pool bytes leaked after clear"
    alloc = engine.allocator
    expected = alloc.num_pages - 1          # page 0 = trash page
    leaked = [p for p in range(1, alloc.num_pages) if alloc._refs[p] > 0]
    assert alloc.num_free == expected, (
        f"KV page leak: {expected - alloc.num_free} pages never freed "
        f"(refs held on pages {leaked[:16]})")
    assert not leaked, f"pages with stale refcounts: {leaked[:16]}"
    assert alloc.evictable_count == 0, (
        f"evictable counter drifted: {alloc.evictable_count} after clear")
    bound = [i for i, s in enumerate(engine.slots) if s is not None]
    assert not bound, f"decode slots still bound after drain: {bound}"


def assert_fabric_clean(pool) -> None:
    """Fleet-fabric accounting invariant (server/kv_fabric.FabricPool):
    the page/byte counters must agree with the entries actually
    resident, occupancy must respect capacity, and clear() must return
    the pool to empty — a pooled blob that outlives its accounting is
    router-process memory that ratchets until OOM."""
    with pool._lock:
        entries = dict(pool._entries)
    assert pool.used == len(entries), (
        f"fabric page accounting drifted: pool says {pool.used}, "
        f"table holds {len(entries)}")
    assert pool.bytes_used == sum(e.nbytes for e in entries.values()), \
        "fabric byte accounting drifted"
    assert pool.bytes_used == sum(
        len(e.blob) if e.blob is not None else int(e.desc["len"])
        for e in entries.values()), \
        "fabric entry nbytes disagrees with its blob/descriptor"
    assert 0 <= pool.used <= max(pool.capacity, 0), (
        f"fabric pool over capacity: {pool.used}/{pool.capacity}")
    snap = pool.snapshot()
    assert snap["pages_used"] == pool.used
    assert snap["bytes_used"] == pool.bytes_used
    pool.clear()
    assert pool.used == 0, "fabric pages leaked after clear"
    assert pool.bytes_used == 0, "fabric bytes leaked after clear"


def assert_arena_clean(group) -> None:
    """Shared-memory arena invariant (server/shm_arena, zero-copy KV
    plane): after the fabric pool and every in-flight handoff released
    their slabs, the router's SlabDirectory must hold nothing live — a
    tracked slab with no consumer is arena memory that ratchets until
    the region is full and every publish relays. No-op on the relay
    plane (no arena). Call AFTER assert_fabric_clean/clear: pool
    entries legitimately hold live slabs."""
    arena = getattr(group, "arena", None)
    adir = getattr(group, "_arena_dir", None)
    if arena is None or adir is None:
        return
    live = adir.slabs_live
    assert live == 0, (
        f"arena slab leak: {live} slabs still registered with no "
        "releasing consumer")
    # Pending frees are fine (they drain on the next stats tick) but
    # the books must balance: released + reclaimed covers everything
    # ever registered minus the live set (== 0 here).
    assert adir.slabs_tracked >= 0
    for rg in range(arena.regions):
        assert arena.epoch(rg) >= 1, f"region {rg} epoch word clobbered"
