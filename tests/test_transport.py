"""Byzantine transport units (README "Failure model"): the checksummed
frame codec, its fuzz surface, the deterministic chaos shim, and the
end-to-end KV blob digest.

Everything here is process-free — codec bytes in, typed errors out.
The fleet-level consequences (reconnect+resync, poison quarantine,
worker survival under garbage) live in test_fleet.py against real
worker processes.
"""

import io
import socket
import struct

import numpy as np
import pytest

from tpu_inference import integrity
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.server import transport
from tpu_inference.server.transport import (ChaosPolicy, ChaosTransport,
                                            FrameError, encode_frame,
                                            recv_frame, send_frame)

# ------------------------------------------------------------- crc32c


def test_crc32c_reference_vector():
    """The canonical CRC-32C check value (RFC 3720 appendix B.4)."""
    assert integrity.crc32c(b"123456789") == 0xE3069283
    assert integrity.crc32c(b"") == 0
    # Chainable: feeding in two chunks equals one pass.
    whole = integrity.crc32c(b"123456789")
    assert integrity.crc32c(b"456789",
                            integrity.crc32c(b"123")) == whole


def test_crc32c_accelerated_matches_pure_python():
    """Whichever backend `crc32c` resolved to (the optional C
    extension or the table walk), it must be bit-identical to the
    pure-Python reference — including chaining — or stored descriptors
    and frames stop verifying across differently-provisioned hosts."""
    import random
    rng = random.Random(42)
    for n in (0, 1, 7, 64, 1337, 65536):
        data = bytes(rng.getrandbits(8) for _ in range(n))
        assert integrity.crc32c(data) == integrity._crc32c_py(data)
        cut = n // 3
        assert integrity.crc32c(
            data[cut:], integrity.crc32c(data[:cut])) == \
            integrity._crc32c_py(data)


# -------------------------------------------------------- frame codec


def _recv_bytes(data: bytes):
    return recv_frame(io.BytesIO(data))


def test_frame_roundtrip_and_clean_eof():
    a, b = socket.socketpair()
    rfile = b.makefile("rb")
    send_frame(a, {"id": 1, "verb": "hello"})
    send_frame(a, {"ev": "token", "t": 42}, blob=b"\x00\x01\xffbytes")
    obj, blob = recv_frame(rfile)
    assert obj == {"id": 1, "verb": "hello"} and blob == b""
    obj, blob = recv_frame(rfile)
    assert obj["t"] == 42 and blob == b"\x00\x01\xffbytes"
    a.close()
    # Clean EOF at a frame boundary: plain ConnectionError, NOT a
    # FrameError — the stream was valid to its end.
    with pytest.raises(ConnectionError) as ei:
        recv_frame(rfile)
    assert not isinstance(ei.value, FrameError)
    b.close()


def test_frame_truncated_header_typed_eof():
    frame = encode_frame({"id": 7})
    for cut in (1, 3, 7, 15):
        with pytest.raises(FrameError) as ei:
            _recv_bytes(frame[:cut])
        assert ei.value.reason == "eof"


def test_frame_mid_payload_eof():
    frame = encode_frame({"id": 7, "verb": "x"}, blob=b"y" * 100)
    with pytest.raises(FrameError) as ei:
        _recv_bytes(frame[:len(frame) - 30])
    assert ei.value.reason == "eof"


def test_frame_bad_magic_desync():
    frame = bytearray(encode_frame({"id": 7}))
    frame[0] ^= 0xFF
    with pytest.raises(FrameError) as ei:
        _recv_bytes(bytes(frame))
    assert ei.value.reason == "magic"


def test_frame_garbage_lengths_no_allocation():
    """A garbage header must fail BEFORE any payload allocation: the
    reader below holds only these 16 bytes, so an attempted multi-GB
    read would raise eof — the typed 'oversized' proves the bounds
    check came first."""
    hdr = struct.pack(">IIII", 0x54504631, transport.MAX_JSON + 1,
                      0, 0xDEADBEEF)
    with pytest.raises(FrameError) as ei:
        _recv_bytes(hdr)
    assert ei.value.reason == "oversized"
    hdr = struct.pack(">IIII", 0x54504631, 2,
                      0xFFFFFFFF, 0xDEADBEEF)
    with pytest.raises(FrameError) as ei:
        _recv_bytes(hdr + b"{}")
    assert ei.value.reason == "oversized"


def test_frame_crc_rejects_any_flipped_byte():
    frame = encode_frame({"id": 9, "verb": "submit"}, blob=b"kvkvkv")
    # Flip every byte past the length words, one at a time: each must
    # be caught (CRC field, JSON, or blob corruption).
    for off in range(12, len(frame)):
        buf = bytearray(frame)
        buf[off] ^= 0x01
        with pytest.raises(FrameError) as ei:
            _recv_bytes(bytes(buf))
        assert ei.value.reason == "crc"


def test_frame_bad_json_typed():
    payload = b"{not json"
    lens = struct.pack(">II", len(payload), 0)
    crc = integrity.crc32c(payload, integrity.crc32c(lens))
    raw = struct.pack(">IIII", 0x54504631, len(payload), 0, crc) + payload
    with pytest.raises(FrameError) as ei:
        _recv_bytes(raw)
    assert ei.value.reason == "json"


def test_frame_error_is_connection_error():
    """Every existing 'peer died' handler catches ConnectionError; the
    typed codec errors must route through the same recycling path."""
    assert issubclass(FrameError, ConnectionError)


# -------------------------------------------------------- chaos shim


def _schedule(policy_kw, n=200, verb="submit", direction="send"):
    t = ChaosTransport(ChaosPolicy(**policy_kw))
    return [t.decide(verb, direction) for _ in range(n)]


def test_chaos_deterministic_schedule():
    """Same seed => identical fault schedule, different seed => a
    different one (the replay lane's reproducibility contract)."""
    kw = dict(seed=1234, corrupt_rate=0.1, drop_rate=0.05,
              delay_rate=0.2, truncate_rate=0.05)
    s1, s2 = _schedule(kw), _schedule(kw)
    assert s1 == s2
    assert set(s1) >= {"pass", "delay", "corrupt"}
    assert _schedule({**kw, "seed": 99}) != s1


def test_chaos_verb_and_direction_filters():
    kw = dict(seed=7, drop_rate=1.0)
    assert _schedule(kw, n=3) == ["drop"] * 3
    assert _schedule({**kw, "verbs": ("cancel",)}, n=3) == ["pass"] * 3
    assert _schedule({**kw, "verbs": ("submit",)}, n=3) == ["drop"] * 3
    assert _schedule({**kw, "direction": "recv"}, n=3) == ["pass"] * 3


def test_chaos_wedge_one_shot():
    """The wedge fires once per policy: after wedge_after eligible
    frames the connection goes mute for ALL traffic; a replacement
    transport on the same policy serves clean (liveness)."""
    pol = ChaosPolicy(seed=0, wedge_after=3)
    t = ChaosTransport(pol)
    assert [t.decide("submit", "send") for _ in range(3)] == ["pass"] * 3
    assert t.decide("submit", "send") == "wedge"
    # Mute even for frames the filters would skip.
    assert t.decide("healthz", "recv") == "wedge"
    assert pol.wedge_spent
    t2 = ChaosTransport(pol)
    assert [t2.decide("submit", "send") for _ in range(10)] \
        == ["pass"] * 10


def test_chaos_corrupted_send_rejected_by_reader():
    """corrupt-rate 1.0 through a real socketpair: the reader's CRC
    rejects every frame as a typed crc error — never bad data."""
    a, b = socket.socketpair()
    rfile = b.makefile("rb")
    chaos = ChaosTransport(ChaosPolicy(seed=5, corrupt_rate=1.0))
    send_frame(a, {"id": 1, "verb": "submit"}, blob=b"z" * 64,
               chaos=chaos, verb="submit")
    with pytest.raises(FrameError) as ei:
        recv_frame(rfile)
    assert ei.value.reason == "crc"
    a.close(), b.close()


def test_chaos_drop_and_truncate_raise_connection_error():
    for kw in (dict(drop_rate=1.0), dict(truncate_rate=1.0)):
        a, b = socket.socketpair()
        chaos = ChaosTransport(ChaosPolicy(seed=3, **kw))
        with pytest.raises(ConnectionError):
            send_frame(a, {"id": 1, "verb": "submit"},
                       chaos=chaos, verb="submit")
        a.close(), b.close()


# ----------------------------------------------------- KV blob digest


def _pages(n=2):
    rng = np.random.default_rng(11)
    mk = lambda: rng.standard_normal((2, 8, 2, 16)).astype(np.float32)
    return [kvc.HostKVPage(mk(), mk()) for _ in range(n)]


def test_kv_blob_digest_roundtrip_and_corruption():
    blob = kvc.serialize_host_pages(_pages())
    assert kvc.deserialize_host_pages(blob)   # clean blob passes
    assert kvc.verify_host_pages_blob(blob) is None
    # One flipped body byte: rejected, typed, never adopted.
    buf = bytearray(blob)
    buf[-1] ^= 0x01
    with pytest.raises(integrity.KVIntegrityError):
        kvc.deserialize_host_pages(bytes(buf))
    assert kvc.verify_host_pages_blob(bytes(buf)) is not None


def test_kv_blob_truncation_rejected():
    blob = kvc.serialize_host_pages(_pages())
    for cut in (1, 3, len(blob) // 2, len(blob) - 1):
        with pytest.raises(integrity.KVIntegrityError):
            kvc.deserialize_host_pages(blob[:cut])
        assert kvc.verify_host_pages_blob(blob[:cut]) is not None


def test_kv_blob_predigest_compat():
    """A blob serialized WITHOUT the digest key (an older peer) still
    deserializes — integrity is enforced when the digest is present,
    not retroactively."""
    import json as _json
    blob = kvc.serialize_host_pages(_pages())
    hlen = struct.unpack(">I", blob[:4])[0]
    meta = _json.loads(blob[4:4 + hlen].decode())
    meta.pop("crc32c")
    hdr = _json.dumps(meta).encode()
    legacy = struct.pack(">I", len(hdr)) + hdr + blob[4 + hlen:]
    assert len(kvc.deserialize_host_pages(legacy)) == 2
    assert kvc.verify_host_pages_blob(legacy) is None
