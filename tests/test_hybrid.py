"""Hybrid prefill-decode steps (EngineConfig.hybrid_prefill).

While a multi-chunk prompt prefills, each chunk fuses into the same
device dispatch as the batch's fused decode steps, so running lanes keep
producing tokens instead of stalling a chunk wall per chunk. These tests
pin the contract that makes the fusion shippable:

- greedy outputs are BYTE-IDENTICAL to the serial scheduler under mixed
  arrivals, with and without dispatch-ahead chaining and the per-step
  token budget;
- mid-prefill cancel, watermark preemption of decode lanes, and drain
  shutdown all keep their serial-path semantics;
- the KV pool comes back clean after every mix (tests/_leak.py).
"""

import threading
import time

import numpy as np
import pytest

from tpu_inference import config as cfgs
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.scheduler import EngineScheduler
from tpu_inference.models import build_model

from tests._leak import assert_pool_clean

VOCAB = 256


@pytest.fixture(scope="module")
def model_and_params():
    model_cfg = cfgs.tiny_llama(vocab_size=VOCAB)
    params, _ = build_model(model_cfg, seed=0)
    return model_cfg, params


BASE = dict(page_size=8, num_pages=128, max_pages_per_seq=16,
            max_batch_size=4, prefill_buckets=(16, 32),
            chunked_prefill_size=16, enable_prefix_cache=False)


def _submit_and_wait(sched, seqs, timeout=180.0):
    events = {s.request_id: [] for s in seqs}
    done = {s.request_id: threading.Event() for s in seqs}
    for s in seqs:
        sched.submit(
            s,
            on_token=lambda sq, t: events[sq.request_id].append(t),
            on_finish=lambda sq: done[sq.request_id].set())
    for s in seqs:
        assert done[s.request_id].wait(timeout), \
            f"request {s.request_id} hung"
    return events


def _mixed_prompts():
    rng = np.random.default_rng(21)
    short = rng.integers(0, VOCAB, size=6).tolist()
    long = rng.integers(0, VOCAB, size=90).tolist()   # 6 chunks of 16
    return short, long


@pytest.mark.parametrize("depth,budget", [(1, 0), (2, 0), (1, 24)],
                         ids=["sync", "dispatch-ahead", "token-budget"])
def test_hybrid_byte_equality_mixed_arrivals(model_and_params, depth,
                                             budget):
    """Greedy outputs through hybrid stepping must be byte-identical to
    the non-interleaved reference, across the sync path, dispatch-ahead
    chaining (depth 2), and a binding step token budget."""
    model_cfg, params = model_and_params
    short, long = _mixed_prompts()
    ref = InferenceEngine(model_cfg, cfgs.EngineConfig(**BASE),
                          params=params)
    want_short = ref.generate([short], max_new_tokens=20)[0]
    want_long = ref.generate([long], max_new_tokens=8)[0]

    eng = InferenceEngine(
        model_cfg,
        cfgs.EngineConfig(**BASE, hybrid_prefill=True,
                          decode_pipeline_depth=depth,
                          step_token_budget=budget),
        params=params)
    sched = EngineScheduler(eng).start()
    try:
        s1 = Sequence(request_id=1, prompt_tokens=short, max_new_tokens=20)
        s2 = Sequence(request_id=2, prompt_tokens=long, max_new_tokens=8)
        events = _submit_and_wait(sched, [s1, s2])
    finally:
        sched.stop(drain=False)
    assert events[1] == want_short
    assert events[2] == want_long
    assert s2.finish_reason == "length"
    # The long prompt's chunks actually rode fused dispatches.
    assert eng.hybrid_steps_total > 0
    assert_pool_clean(eng)


def test_hybrid_matches_serial_scheduler(model_and_params):
    """Serial and hybrid schedulers, identical mixed workload: token
    streams must match request for request (the scheduler-level
    byte-equality pin, not just engine-level)."""
    model_cfg, params = model_and_params
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, VOCAB, size=n).tolist()
               for n in (5, 80, 9, 50)]
    budgets = [12, 6, 10, 7]

    def run(hybrid):
        eng = InferenceEngine(
            model_cfg,
            cfgs.EngineConfig(**BASE, hybrid_prefill=hybrid),
            params=params)
        sched = EngineScheduler(eng).start()
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=b)
                for i, (p, b) in enumerate(zip(prompts, budgets))]
        try:
            events = _submit_and_wait(sched, seqs)
        finally:
            sched.stop(drain=False)
        assert_pool_clean(eng)
        return events, eng

    serial_events, serial_eng = run(hybrid=False)
    hybrid_events, hybrid_eng = run(hybrid=True)
    assert serial_eng.hybrid_steps_total == 0
    assert hybrid_events == serial_events
    for i, b in enumerate(budgets):
        assert len(hybrid_events[i]) == b


def test_hybrid_mid_prefill_cancel(model_and_params):
    """Cancelling the long prompt while its chunks are mid-hybrid-flight
    must terminate it cleanly (finish_reason=cancelled, no token ever
    delivered) without disturbing the decoding lanes or leaking its
    already-allocated pages."""
    model_cfg, params = model_and_params
    short, long = _mixed_prompts()
    long = long * 2          # 180 tokens -> truncated to 127, 8 chunks
    eng = InferenceEngine(
        model_cfg,
        cfgs.EngineConfig(**BASE, hybrid_prefill=True,
                          decode_pipeline_depth=2),
        params=params)
    want_short = eng.generate([short], max_new_tokens=30)[0]
    sched = EngineScheduler(eng).start()
    try:
        events = {1: [], 2: []}
        done = {1: threading.Event(), 2: threading.Event()}
        s1 = Sequence(request_id=1, prompt_tokens=short, max_new_tokens=30)
        s2 = Sequence(request_id=2, prompt_tokens=long, max_new_tokens=8)
        for s in (s1, s2):
            sched.submit(
                s,
                on_token=lambda sq, t: events[sq.request_id].append(t),
                on_finish=lambda sq: done[sq.request_id].set())
        # Wait until the long prompt is demonstrably mid-prefill, then
        # cancel it between chunks.
        deadline = time.time() + 60
        while s2.prefill_offset == 0 and time.time() < deadline:
            time.sleep(0.002)
        sched.cancel(2)
        assert done[2].wait(60), "cancelled request never finished"
        assert done[1].wait(120), "survivor hung after cancel"
    finally:
        sched.stop(drain=False)
    assert s2.finish_reason == "cancelled"
    assert events[2] == []               # no token from a cancelled prefill
    assert events[1] == want_short       # survivor byte-identical
    assert_pool_clean(eng)


def test_hybrid_mid_prefill_preemption(model_and_params):
    """Watermark preemption under optimistic admission composes with
    hybrid stepping: decode lanes evicted for pool pressure while a long
    prompt chunk-prefills recompute-resume to byte-identical greedy
    output, and the pool comes back clean."""
    model_cfg, params = model_and_params
    rng = np.random.default_rng(11)
    shorts = [rng.integers(0, VOCAB, size=6).tolist() for _ in range(3)]
    long = rng.integers(0, VOCAB, size=90).tolist()
    base = dict(BASE, num_pages=48, max_pages_per_seq=16,
                admission="optimistic", preempt_watermark_pages=6,
                optimistic_headroom_pages=1)
    ref = InferenceEngine(model_cfg, cfgs.EngineConfig(**BASE),
                          params=params)
    want = ([ref.generate([p], max_new_tokens=40)[0] for p in shorts]
            + [ref.generate([long], max_new_tokens=8)[0]])

    # Pool math: long needs 12 prompt pages + 1 decode; shorts grow to 6
    # pages each (6 prompt+40 gen tokens at page_size 8). Total demand 31
    # pages against 47 - 20 = 27 available -> exhaustion is guaranteed,
    # and optimistic admission must preempt (not fail) to finish.
    eng = InferenceEngine(
        model_cfg,
        cfgs.EngineConfig(**base, hybrid_prefill=True,
                          chaos_page_pressure=20),
        params=params)
    sched = EngineScheduler(eng).start()
    try:
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=40)
                for i, p in enumerate(shorts)]
        seqs.append(Sequence(request_id=3, prompt_tokens=long,
                             max_new_tokens=8))
        events = _submit_and_wait(sched, seqs, timeout=240.0)
    finally:
        sched.stop(drain=False)
    for i in range(3):
        assert events[i] == want[i], f"short {i} diverged after preemption"
    assert events[3] == want[3]
    # The pool really was tight enough to exercise the safety net.
    assert eng.preemptions_total >= 1
    assert eng.resumes_total == eng.preemptions_total
    assert eng.hybrid_steps_total > 0
    assert_pool_clean(eng)


def test_hybrid_drain_shutdown(model_and_params):
    """stop(drain=True) with a hybrid prefill and decode lanes in flight:
    every submitted request gets exactly one terminal callback — finished
    normally or cancelled with finish_reason=shutdown — and nothing
    leaks."""
    model_cfg, params = model_and_params
    rng = np.random.default_rng(5)
    eng = InferenceEngine(
        model_cfg,
        cfgs.EngineConfig(**BASE, hybrid_prefill=True,
                          decode_pipeline_depth=2),
        params=params)
    sched = EngineScheduler(eng).start()
    finished = []
    s_short = Sequence(request_id=1,
                       prompt_tokens=rng.integers(0, VOCAB, 6).tolist(),
                       max_new_tokens=500)      # can't finish in time
    s_long = Sequence(request_id=2,
                      prompt_tokens=rng.integers(0, VOCAB, 120).tolist(),
                      max_new_tokens=500)
    for s in (s_short, s_long):
        sched.submit(s, on_token=lambda *a: None,
                     on_finish=lambda sq: finished.append(sq))
    # Let the mix get airborne (short decoding, long mid-chunks).
    deadline = time.time() + 60
    while not s_short.generated and time.time() < deadline:
        time.sleep(0.002)
    sched.stop(drain=True, timeout=0.3)   # deadline forces shutdown cancels
    assert {s.request_id for s in finished} == {1, 2}
    for s in finished:
        assert s.finish_reason in ("length", "stop", "shutdown"), \
            (s.request_id, s.finish_reason)
    # The engine thread is stopped; settle any in-flight calls, then the
    # pool must be fully reclaimable.
    eng.drain_pipeline()
    assert_pool_clean(eng)


def test_hybrid_prefill_liveness_under_sustained_pressure(model_and_params):
    """Sustained watermark pressure (preempt_watermark > pool, so
    under_pressure never clears) must not starve a mid-prefill prompt
    while decode lanes stay busy: the pressure branch advances one chunk
    serially per iteration (its pages were all allocated at
    prefill_begin), keeping TTFT bounded like serial mode. Regression:
    the chunk was deferred until every decode lane drained."""
    model_cfg, params = model_and_params
    eng = InferenceEngine(
        model_cfg,
        cfgs.EngineConfig(**BASE, hybrid_prefill=True,
                          admission="optimistic",
                          preempt_watermark_pages=10_000),
        params=params)
    sched = EngineScheduler(eng).start()
    try:
        rng = np.random.default_rng(9)
        short = Sequence(request_id=1,
                         prompt_tokens=rng.integers(0, VOCAB, 6).tolist(),
                         max_new_tokens=500)   # context cap ends it ~121
        long = Sequence(request_id=2,
                        prompt_tokens=rng.integers(0, VOCAB, 90).tolist(),
                        max_new_tokens=4)
        done = {1: threading.Event(), 2: threading.Event()}
        long_first = threading.Event()
        short_done_at_long_first = []
        sched.submit(short, on_token=lambda *a: None,
                     on_finish=lambda s: done[1].set())
        deadline = time.time() + 60
        while not short.generated and time.time() < deadline:
            time.sleep(0.002)          # the short is decoding first

        def on_long_token(s, t):
            if not long_first.is_set():
                short_done_at_long_first.append(short.done)
                long_first.set()

        sched.submit(long, on_token=on_long_token,
                     on_finish=lambda s: done[2].set())
        assert long_first.wait(120), "long prompt starved under pressure"
        sched.cancel(1)
        for ev in done.values():
            assert ev.wait(60)
    finally:
        sched.stop(drain=False)
    # The long prompt's first token arrived while the short was still
    # decoding — the prefill stayed live under sustained pressure.
    assert short_done_at_long_first == [False]
    assert_pool_clean(eng)


def test_hybrid_chunk_only_call_then_decode_staging(model_and_params):
    """A chunk-only pipeline call (no decode lane could advance — its
    decode half is None) must not poison later staging: the in-flight
    carry fold skips it, so a lane that becomes stageable afterwards
    dispatches normally. Regression: jnp.where(None, ...) raised
    TypeError and errored out the whole batch."""
    model_cfg, params = model_and_params
    eng = InferenceEngine(
        model_cfg,
        cfgs.EngineConfig(**BASE, hybrid_prefill=True,
                          decode_pipeline_depth=4),
        params=params)
    k = eng.engine_cfg.decode_steps_per_call
    rng = np.random.default_rng(3)
    s1 = Sequence(request_id=1,
                  prompt_tokens=rng.integers(0, VOCAB, 5).tolist(),
                  max_new_tokens=k)        # one staged call covers it
    eng.prefill(s1)
    long = Sequence(request_id=2,
                    prompt_tokens=rng.integers(0, VOCAB, 90).tolist(),
                    max_new_tokens=4)
    eng.prefill_begin(long)
    eng.hybrid_step_pipelined(long)        # decode grant + chunk 1
    eng.hybrid_step_pipelined(long)        # s1 fully covered: chunk-only
    assert any(c["outs"] is None for c in eng._inflight), \
        "setup failed to produce a chunk-only call"
    # A fresh lane becomes stageable with the chunk-only call still in
    # flight — staging must skip its None decode half, not crash.
    s3 = Sequence(request_id=3,
                  prompt_tokens=rng.integers(0, VOCAB, 5).tolist(),
                  max_new_tokens=12)
    eng.prefill(s3)
    eng.hybrid_step_pipelined(long)        # would raise before the fix
    for _ in range(50):
        eng.drain_pipeline()
        if long.prefill_prompt is None:
            break
        eng.hybrid_step_pipelined(long)
    assert long.prefill_prompt is None and long.generated
    eng.drain_pipeline()
    for s in list(eng.slots):
        if s is not None:
            eng.release(s)
    assert_pool_clean(eng)


def test_hybrid_drain_error_keeps_engine_loop_alive(model_and_params):
    """A device error surfacing only at drain/sync time (async dispatch
    on real TPU) must fail the affected requests with
    finish_reason="error" — not propagate out of run() and kill the
    engine thread. Regression: the cancel-path drains ran outside the
    run loop's try/except."""
    model_cfg, params = model_and_params
    eng = InferenceEngine(
        model_cfg,
        cfgs.EngineConfig(**BASE, hybrid_prefill=True,
                          decode_pipeline_depth=2),
        params=params)
    sched = EngineScheduler(eng).start()
    real = eng.drain_pipeline
    state = {"armed": False, "fired": False}

    def flaky():
        if state["armed"] and not state["fired"]:
            state["fired"] = True
            eng.abort_pipeline()        # mimic poisoned in-flight state
            raise RuntimeError("injected sync failure")
        return real()

    eng.drain_pipeline = flaky
    try:
        rng = np.random.default_rng(13)
        short = Sequence(request_id=1,
                         prompt_tokens=rng.integers(0, VOCAB, 6).tolist(),
                         max_new_tokens=40)
        long = Sequence(request_id=2,
                        prompt_tokens=rng.integers(0, VOCAB, 90).tolist(),
                        max_new_tokens=6)
        done = {i: threading.Event() for i in (1, 2, 3)}
        for s in (short, long):
            sched.submit(s, on_token=lambda *a: None,
                         on_finish=lambda sq: done[sq.request_id].set())
        deadline = time.time() + 60
        while long.prefill_offset == 0 and time.time() < deadline:
            time.sleep(0.002)
        state["armed"] = True
        sched.cancel(2)       # cancel mid-prefill -> a drain path fires
        assert done[2].wait(60), "cancelled request never finished"
        assert done[1].wait(120), "batch-mate never finished"
        assert state["fired"]
        # The loop survived: a fresh request completes normally.
        eng.drain_pipeline = real
        fresh = Sequence(request_id=3,
                         prompt_tokens=rng.integers(0, VOCAB, 6).tolist(),
                         max_new_tokens=5)
        sched.submit(fresh, on_token=lambda *a: None,
                     on_finish=lambda sq: done[3].set())
        assert done[3].wait(60), "engine thread died after drain error"
        assert fresh.finish_reason == "length"
    finally:
        sched.stop(drain=False)
    assert_pool_clean(eng)


def test_hybrid_chunk_cap_budget_math(model_and_params):
    """step_token_budget splits each fused step between the decode
    tokens actually granted and the chunk, floored at page_size so the
    prefill always advances."""
    model_cfg, params = model_and_params
    eng = InferenceEngine(
        model_cfg,
        cfgs.EngineConfig(**BASE, hybrid_prefill=True,
                          step_token_budget=40),
        params=params)
    k = eng.engine_cfg.decode_steps_per_call
    # No decode tokens granted: the whole budget is the chunk's
    # (capped by the configured chunk size).
    assert eng._hybrid_chunk_cap(0) == min(16, 40)
    # Budget minus the granted decode tokens...
    assert eng._hybrid_chunk_cap(2 * k) == min(16, max(8, 40 - 2 * k))
    # ...but never below a page of progress.
    assert eng._hybrid_chunk_cap(800) == eng.engine_cfg.page_size
    # An over-large CLI chunked_prefill_size clamps to the largest
    # compiled bucket (a bigger chunk fits no prefill graph).
    big = cfgs.EngineConfig(**{**BASE, "chunked_prefill_size": 10_000})
    assert big.chunk_tokens_cap == big.prefill_buckets[-1]
