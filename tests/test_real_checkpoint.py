"""End-to-end: a real-format HF checkpoint dir (sharded safetensors +
config.json + trained BPE tokenizer.json) served through the full stack —
config_from_hf -> streaming loader -> HFTokenizer -> HTTP contract.

This is the serving path a user coming from the reference exercises: point
the server at a model directory, no hand-written preset (reference bar:
its external endpoint served `mistral` end-to-end, logs/log.json)."""

import asyncio
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_inference.config import ModelConfig

pytest.importorskip("torch")
pytest.importorskip("transformers")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def real_dir():
    """Real-format checkpoint dir, cached across suite runs.

    Building it (subprocess: jax+torch import, BPE training, sharded
    safetensors write) costs ~30 s — the single most expensive fixture in
    the suite — and its output is a pure function of the builder script +
    args + corpus, so it is cached in /tmp keyed by a hash of exactly
    those inputs. A builder or corpus edit changes the key and rebuilds.

    Concurrency/crash safety: the build lands in a unique sibling temp
    dir (same filesystem — os.rename never crosses a mount), the
    .complete marker is written BEFORE the atomic rename, and a lost
    rename race (ENOTEMPTY/EEXIST: another run published first) falls
    back to the winner's dir. A complete cache dir is never deleted.
    """
    import hashlib
    import shutil

    builder = os.path.join(REPO, "benchmarks/make_real_model.py")
    data = os.path.join(REPO, "data/conversations.json")
    args = ["--size", "tiny", "--vocab-size", "1024", "--data", data]
    h = hashlib.sha256()
    for path in (builder, data):
        with open(path, "rb") as f:
            h.update(f.read())
    h.update(" ".join(args).encode())
    cached = f"/tmp/tpu_inference_test_real_model_{h.hexdigest()[:16]}"
    marker = os.path.join(cached, ".complete")
    if os.path.exists(marker):
        return cached
    tmp = f"{cached}.tmp{os.getpid()}"
    try:
        subprocess.run(
            [sys.executable, builder, "--out", tmp, *args],
            check=True, cwd=REPO, capture_output=True)
        with open(os.path.join(tmp, ".complete"), "w") as f:
            f.write("ok")
        os.rename(tmp, cached)
    except OSError:
        # Lost the publish race (ENOTEMPTY: another run renamed first).
        shutil.rmtree(tmp, ignore_errors=True)
        if not os.path.exists(marker):
            raise
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)  # failed build: no orphans
        raise
    return cached


def test_config_from_hf(real_dir):
    from tpu_inference.models.weights import config_from_hf

    cfg = config_from_hf(real_dir)
    assert isinstance(cfg, ModelConfig)
    assert cfg.family == "llama" and cfg.d_model == 128
    assert cfg.vocab_size % 128 == 0


def test_hf_tokenizer_roundtrip(real_dir):
    from tpu_inference.server.tokenizer import (HFTokenizer,
                                                IncrementalDecoder)

    tok = HFTokenizer(real_dir)
    text = "Hello there, how is the weather today? éèê"
    ids = tok.encode(text)
    assert ids[0] == tok.bos_token_id
    assert tok.decode(ids) == text
    # Incremental decoding re-assembles the same text chunkwise.
    dec = IncrementalDecoder(tok)
    streamed = "".join(dec.push(i) for i in ids) + dec.flush()
    assert streamed == text


def test_serve_hf_checkpoint_dir(real_dir):
    """build_server(model=<dir>, tokenizer='auto') serves the checkpoint
    with real text in/out and the Ollama wire contract."""
    from aiohttp.test_utils import TestClient, TestServer

    from tpu_inference.server.http import build_server

    srv = build_server(model=real_dir, tokenizer="auto",
                       page_size=8, num_pages=128, max_pages_per_seq=8,
                       max_batch_size=2, prefill_buckets=(16, 32))
    assert srv.engine.model_cfg.family == "llama"
    assert srv.tokenizer.__class__.__name__ == "HFTokenizer"

    async def go():
        app = srv.make_app()
        async with TestClient(TestServer(app)) as client:
            resp = await client.post("/api/generate", json={
                "model": "real", "prompt": "How many users", "stream": False,
                "max_tokens": 8, "temperature": 0.0})
            assert resp.status == 200
            body = await resp.json()
            assert body["done"] and body["eval_count"] >= 1
            assert isinstance(body["response"], str)
            # Weight check: params came from the checkpoint files, not
            # random init — compare one leaf against the safetensors dir.
            from tpu_inference.models.weights import (_CheckpointFiles,
                                                      config_from_hf)
            files = _CheckpointFiles(real_dir)
            want = np.asarray(
                files.get_slice("model.norm.weight")[:]).astype(np.float32)
            got = np.asarray(srv.engine.params["final_norm"], np.float32)
            np.testing.assert_array_equal(got, want)

    asyncio.run(go())
