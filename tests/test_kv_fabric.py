"""Fleet KV fabric (README "KV fabric"): the router-side shared
prefix-page pool and its fourth routing temperature.

Covers the subsystem at three levels:

- pure pool units: capacity/LRU bounds with byte accounting, digest
  dedup (re-publish stores once, a stale entry is superseded by fresh
  bytes), contiguous-from-page-0 match depth, MRU-first hot set for
  warm worker boot, capacity-0 no-op, and crc32c integrity on get for
  every kv_quant host-page layout (a corrupt pooled blob is dropped +
  counted + treated as a miss, never adopted silently).
- shared scoring formulas: the four cache temperatures order HBM-warm
  < host-warm < fabric-warm < cold, the pressure shift preserves
  relative order but puts a fully-warm pressured replica behind a cold
  idle one, and the fabric term only covers pages beyond a candidate's
  own warm depth.
- engine publish hook: settled prefix pages ship to the armed publish
  callable once — steady traffic over the same prompt dedups.
- BOTH fleet backends end-to-end: a prefix prefilled on replica A is
  pulled from the pool by a prefill routed to replica B (page pressure
  on A stands in for saturation), byte-identically, with the same
  supervision/healthz accounting under --fleet in-process and
  subprocess.
"""

import threading
import time

import numpy as np
import pytest

from tests._leak import assert_arena_clean, assert_fabric_clean
from tpu_inference.config import (EngineConfig, FrameworkConfig,
                                  ParallelConfig, ServerConfig, tiny_llama)
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.server import kv_fabric
from tpu_inference.server.kv_fabric import FabricPool

# Same tiny worker geometry as test_fleet, except the preempt
# watermark is raised so chaos page pressure (holding every free page)
# drops free+evictable below it even while the pressured replica's own
# prefix cache stays resident — the deterministic stand-in for a
# saturated replica the routed tests steer around.
ENGINE_KW = dict(page_size=8, num_pages=64, max_pages_per_seq=8,
                 max_batch_size=2, prefill_buckets=(16,),
                 host_cache_pages=32, preempt_watermark_pages=40)
FABRIC_KW = dict(fabric_cache_pages=64, fabric_publish_min_pages=1)

# 33 tokens = 4 full pages of shared prefix (digest cap (33-1)//8) + a
# straggler token, under vocab 512.
PROMPT = [(3 * i + 1) % 500 for i in range(33)]


def _cfg(dp=2, **server_kw) -> FrameworkConfig:
    server_kw.setdefault("fleet", "subprocess")
    server_kw.setdefault("worker_restart_max", 10)
    server_kw.setdefault("worker_restart_backoff_s", 0.1)
    return FrameworkConfig(
        model=tiny_llama(vocab_size=512),
        engine=EngineConfig(**ENGINE_KW),
        parallel=ParallelConfig(dp=dp),
        server=ServerConfig(model_name="t", tokenizer="byte",
                            warmup=False, **server_kw))


def _page(quant: str, tag: int) -> kvc.HostKVPage:
    rng = np.random.default_rng(100 + tag)
    if quant == "none":
        mk = lambda: rng.standard_normal((2, 8, 2, 16)).astype(np.float32)
        return kvc.HostKVPage(mk(), mk())
    code_dt = np.uint8 if quant == "int4" else np.int8
    d = 8 if quant == "int4" else 16
    mk = lambda: rng.integers(0, 255, (2, 8, 2, d)).astype(code_dt)
    sc = lambda: rng.standard_normal((2, 8, 2)).astype(np.float32)
    return kvc.HostKVPage(mk(), mk(), sc(), sc())


def _digests(n: int):
    return [bytes([i]) * 16 for i in range(n)]


# ------------------------------------------------------------ pool units


def test_pool_capacity_lru_and_accounting():
    """The pool never exceeds its page capacity: overflow evicts LRU
    entries (a get refreshes recency), and page/byte accounting stays
    exact through the churn."""
    pool = FabricPool(4)
    d = _digests(6)
    for i in range(4):
        pool.put_blob(d[i], kvc.serialize_host_pages([_page("none", i)]))
    assert pool.used == 4 and pool.puts == 4 and pool.evictions == 0
    # Touch d[0]: it becomes MRU, so the next overflow evicts d[1].
    got = pool.get_pages([d[0]])
    assert len(got) == 1 and got[0][0] == d[0] and pool.hits == 1
    pool.put_blob(d[4], kvc.serialize_host_pages([_page("none", 4)]))
    assert pool.used == 4 and pool.evictions == 1
    assert pool.match_depth([d[1]]) == 0, "LRU victim should be d[1]"
    assert pool.match_depth([d[0]]) == 1
    # MRU-first hot set for warm worker boot.
    hot = pool.hot_set(2)
    assert [h[0] for h in hot] == [d[4], d[0]]
    assert pool.hot_set(0) == []
    assert_fabric_clean(pool)


def test_pool_dedup_and_supersede():
    """Re-publishing a digest stores ONE entry (second replica
    publishing the same prefix costs nothing extra), and a fresh blob
    supersedes a stale one — a later get returns the new bytes."""
    pool = FabricPool(8)
    d = _digests(1)[0]
    page_a, page_b = _page("none", 1), _page("none", 2)
    blob_a = kvc.serialize_host_pages([page_a])
    blob_b = kvc.serialize_host_pages([page_b])
    pool.put_blob(d, blob_a)
    pool.put_blob(d, blob_a)
    assert pool.used == 1 and pool.superseded == 1
    assert pool.bytes_used == len(blob_a)
    pool.put_blob(d, blob_b)
    assert pool.used == 1 and pool.superseded == 2
    got = pool.get_pages([d])
    np.testing.assert_array_equal(got[0][1].k, page_b.k)
    np.testing.assert_array_equal(got[0][1].v, page_b.v)
    assert_fabric_clean(pool)


def test_pool_match_depth_contiguous_and_side_effect_free():
    """match_depth counts only the contiguous run from page 0 (a chain
    with a hole is warm only up to the hole) and never touches the
    hit/miss counters — it is the router's per-candidate scoring peek."""
    pool = FabricPool(8)
    d = _digests(4)
    pool.put_blob(d[0], kvc.serialize_host_pages([_page("none", 0)]))
    pool.put_blob(d[2], kvc.serialize_host_pages([_page("none", 2)]))
    assert pool.match_depth(d[:3]) == 1          # hole at d[1]
    assert pool.match_depth([d[0]]) == 1
    assert pool.match_depth([d[3]]) == 0
    assert pool.match_depth([]) == 0
    assert pool.hits == 0 and pool.misses == 0
    assert_fabric_clean(pool)


def test_pool_capacity_zero_noop():
    """fabric_cache_pages=0 (the default) disables the pool without a
    special case at any call site: puts drop, lookups miss clean."""
    pool = FabricPool(0)
    d = _digests(1)[0]
    pool.put_blob(d, kvc.serialize_host_pages([_page("none", 0)]))
    assert pool.used == 0 and pool.puts == 0
    assert pool.match_depth([d]) == 0
    assert pool.get_pages([]) == []
    assert pool.hot_set(4) == []
    assert pool.snapshot()["capacity_pages"] == 0
    assert_fabric_clean(pool)


@pytest.mark.parametrize("quant", ["none", "int8", "int4"])
def test_pool_get_rejects_corrupt_blob(quant):
    """Integrity on the read path, pinned per kv_quant layout: a pooled
    blob corrupted in router memory fails its crc32c on get, is
    dropped + counted (kv_rejections) + treated as a miss, and the
    clean entries still round-trip bit-exactly."""
    pool = FabricPool(8)
    d = _digests(3)
    pages = [_page(quant, i) for i in range(3)]
    assert pool.put_pages(list(zip(d, pages))) == 3
    # Flip one payload byte of the middle entry, in place.
    with pool._lock:
        e = pool._entries[d[1]]
    raw = bytearray(e.blob)
    raw[len(raw) // 2] ^= 0xFF
    e.blob = bytes(raw)
    got = pool.get_pages(d)
    assert [g[0] for g in got] == [d[0]], \
        "corrupt entry must end the run, not be adopted"
    assert pool.kv_rejections == 1 and pool.misses == 1
    assert pool.used == 2 and pool.match_depth(d) == 1
    np.testing.assert_array_equal(got[0][1].k, pages[0].k)
    np.testing.assert_array_equal(got[0][1].v, pages[0].v)
    if quant != "none":
        np.testing.assert_array_equal(got[0][1].k_scale, pages[0].k_scale)
    # The untouched later entry is still servable on its own chain.
    pool.reject(d[2])
    assert pool.kv_rejections == 2 and pool.used == 1
    assert_fabric_clean(pool)


# ------------------------------------------------------ scoring helpers


def test_routing_score_four_temperatures():
    """THE shared formulas (both backends import these): warmth
    discounts order HBM < host < fabric < cold; the pressure shift
    keeps relative order but puts a fully-warm pressured replica
    behind a cold idle one; the fabric term covers only pages beyond a
    candidate's own warm depth."""
    cfg = ServerConfig(model_name="t", tokenizer="byte")
    pp = 8

    def score(hbm=0, host=0, fabric=0, load=0.0, pressured=False):
        return kv_fabric.prefill_route_score(
            cfg, prompt_pages=pp, hbm=hbm, host=host, fabric=fabric,
            load=load, pressured=pressured)

    hbm_s, host_s = score(hbm=pp), score(host=pp)
    fab_s, cold_s = score(fabric=pp), score()
    assert hbm_s < host_s < fab_s < cold_s
    # Pressure: order-preserving shift, and warm+pressured loses to
    # cold+idle at the default weights.
    assert score(hbm=pp, pressured=True) < score(host=pp, pressured=True)
    assert score(hbm=pp, pressured=True) > cold_s
    # Load blends in page units.
    assert score(load=2.0) > score(load=1.0) > score()

    assert kv_fabric.fabric_extra_pages(10, 3, 8) == 5
    assert kv_fabric.fabric_extra_pages(2, 5, 8) == 0
    assert kv_fabric.fabric_extra_pages(50, 0, 8) == 8
    assert kv_fabric.fabric_extra_pages(0, 0, 8) == 0

    dec = lambda **kw: kv_fabric.decode_route_score(
        cfg, **{"hbm": 0, "host": 0, "fabric": 0, "load": 0.0,
                "occupancy": 0.0, "pressured": False, **kw})
    assert dec(hbm=4) < dec(host=4) < dec(fabric=4) < dec()
    assert dec(pressured=True) > dec()
    assert kv_fabric.cold_route_key(False, 5.0) \
        < kv_fabric.cold_route_key(True, 0.0)


# -------------------------------------------------- engine publish hook


def test_engine_publish_hook_and_dedup():
    """The engine ships settled full prefix pages to the armed publish
    callable exactly once per digest: a second pass over the same
    prompt publishes nothing new, and fabric_published_pages tracks
    the total."""
    engine = InferenceEngine(tiny_llama(vocab_size=512),
                             EngineConfig(**ENGINE_KW), seed=0)
    published = []
    engine.fabric_publish = published.extend
    engine.fabric_publish_min_pages = 2
    out1 = engine.generate([list(PROMPT)], max_new_tokens=8)[0]
    assert len(published) >= 4, "full prompt prefix pages must publish"
    digests = [d for d, _ in published]
    assert len(set(digests)) == len(digests)
    for _, p in published:
        assert isinstance(p, kvc.HostKVPage)
    n1 = len(published)
    assert engine.fabric_published_pages == n1
    out2 = engine.generate([list(PROMPT)], max_new_tokens=8)[0]
    assert out2 == out1
    assert len(published) == n1, "republish of the same prefix"
    # A short prompt below fabric_publish_min_pages never publishes.
    engine.generate([[5, 6, 7]], max_new_tokens=4)
    assert len(published) == n1


# ---------------------------------------------- both backends end-to-end


def _submit(group, rid, prompt, max_new):
    toks, done, box = [], threading.Event(), {}
    seq = Sequence(request_id=rid, prompt_tokens=list(prompt),
                   max_new_tokens=max_new)
    group.submit(seq, lambda s, t: toks.append(t),
                 lambda s: (box.update(seq=s), done.set()))
    return toks, done, box


def _finish(done, box, timeout=180.0):
    assert done.wait(timeout), "request did not finish"
    return box["seq"]


def _wait(predicate, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _fabric_flow(group, *, pressure, unpressure, is_pressured):
    """The cross-replica warm-once flow both backends must serve
    identically: prefill the prefix on whichever replica the router
    picks, saturate that replica, then prove the SAME prompt served by
    the other replica adopts pooled pages (route_fabric_hit_pages) and
    stays byte-identical."""
    toks1, done, box = _submit(group, 9100, PROMPT, 8)
    fin1 = _finish(done, box)
    assert fin1.finish_reason == "length"
    seed_replica = fin1.routed_replica
    assert seed_replica in (0, 1)
    _wait(lambda: group.fabric.used >= 4, msg="fabric publish")

    pressure(seed_replica)
    _wait(lambda: is_pressured(seed_replica),
          msg="pressured replica visible")
    try:
        toks2, done, box = _submit(group, 9101, PROMPT, 8)
        fin2 = _finish(done, box)
    finally:
        unpressure(seed_replica)
    assert fin2.routed_replica == 1 - seed_replica, \
        "wave must route AROUND the pressured prefiller"
    assert fin2.route_fabric_hit_pages >= 1, \
        "the cross-replica turn must adopt pooled pages"
    assert fin2.route_hit_pages >= fin2.route_fabric_hit_pages
    assert toks2 == toks1, "fabric restore must be byte-identical"

    sup = group.supervision_counters()
    assert sup["route_fabric_hits"] >= 1
    assert sup["fabric_puts"] >= 4 and sup["fabric_hits"] >= 1
    hs = group.health_snapshot()
    snap = hs["fabric"]
    assert snap["capacity_pages"] == 64
    assert snap["pages_used"] >= 4 and snap["kv_rejections"] == 0
    assert set(snap) == set(group.fabric.snapshot())
    return seed_replica


@pytest.fixture(scope="module")
def fabric_fleet():
    from tpu_inference.server.fleet import ProcessEngineGroup

    group = ProcessEngineGroup(_cfg(dp=2, **FABRIC_KW))
    group.start()
    yield group
    group.stop(drain=False)


def test_fabric_warm_once_subprocess(fabric_fleet):
    group = fabric_fleet
    _wait(lambda: all(h.state == "up" for h in group.workers),
          timeout=60.0, msg="fleet up")

    def is_pressured(i):
        reps = group.health_snapshot()["replicas"]
        return bool(reps[i].get("under_pressure"))

    seed = _fabric_flow(
        group,
        pressure=lambda i: group.apply_chaos(
            {"replica": i, "page_pressure": 64}),
        unpressure=lambda i: group.apply_chaos(
            {"replica": i, "page_pressure": 0}),
        is_pressured=is_pressured)
    # The publisher's own accounting is visible in /healthz.
    reps = group.health_snapshot()["replicas"]
    assert reps[seed].get("fabric_published_pages", 0) >= 4

    # Metric surface: fabric series exported once (no duplicate
    # series/labels), pool gauges live.
    from tests import _prom

    _, samples = _prom.parse(group.prometheus_text())
    seen = {}
    for name, labels, value in samples:
        key = (name, tuple(sorted(labels.items())))
        assert key not in seen, f"duplicate series {key}"
        seen[key] = value
    for name in ("tpu_inf_fabric_pages_used", "tpu_inf_fabric_bytes_used",
                 "tpu_inf_fabric_puts_total", "tpu_inf_fabric_hits_total",
                 "tpu_inf_fabric_misses_total",
                 "tpu_inf_fabric_evictions_total",
                 "tpu_inf_route_fabric_hits_total"):
        assert any(k[0] == name for k in seen), f"missing {name}"
    # Relay plane: no arena exists, and the invariant checker says so.
    assert_arena_clean(group)


def test_fabric_warm_once_in_process():
    from tpu_inference.server.http import build_engine_group

    group = build_engine_group(
        _cfg(dp=2, fleet="in-process", **FABRIC_KW)).start()
    try:
        def pressure(i):
            group.schedulers[i].engine.request_page_pressure(64)

        def unpressure(i):
            group.schedulers[i].engine.request_page_pressure(0)

        _fabric_flow(
            group, pressure=pressure, unpressure=unpressure,
            is_pressured=lambda i:
                group.schedulers[i].engine.under_pressure)
        assert_fabric_clean(group.fabric)
    finally:
        group.stop(drain=False)
