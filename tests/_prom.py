"""Minimal Prometheus text-format (0.0.4) parser for test assertions.

Independent of tpu_inference/telemetry.py's renderer on purpose: the
exposition tests are parser-level — they must catch a renderer bug, so
they cannot share its code. Strictness matches what real scrapers
enforce: metric/label name charsets, quoted escaped label values, one
value per line, HELP/TYPE comment grammar.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')


def _unescape(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt,
                                                             "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse(text: str) -> Tuple[Dict[str, dict], List[tuple]]:
    """-> (meta, samples): meta[name] = {"type", "help"}, samples =
    [(name, labels dict, float value)]. Raises AssertionError on any
    line that is not valid exposition format."""
    meta: Dict[str, dict] = {}
    samples: List[tuple] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, help_ = line[len("# HELP "):].split(" ", 1)
            meta.setdefault(name, {})["help"] = help_
        elif line.startswith("# TYPE "):
            name, kind = line[len("# TYPE "):].split(" ", 1)
            assert kind.strip() in ("counter", "gauge", "histogram",
                                    "summary", "untyped"), line
            meta.setdefault(name, {})["type"] = kind.strip()
        elif line.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable sample line: {line!r}"
            raw = m.group(2) or ""
            labels = {lm.group(1): _unescape(lm.group(2))
                      for lm in _LABEL_RE.finditer(raw)}
            # The label section must be nothing but well-formed pairs.
            stripped = _LABEL_RE.sub("", raw).replace(",", "").strip()
            assert stripped == "", f"malformed labels in: {line!r}"
            v = m.group(3)
            value = float("inf") if v == "+Inf" else float(v)
            samples.append((m.group(1), labels, value))
    return meta, samples


def family(name: str, meta: Dict[str, dict]) -> str:
    """Map a histogram series name (_bucket/_sum/_count) back to its
    declared family; plain names map to themselves."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix) and name[:-len(suffix)] in meta:
            return name[:-len(suffix)]
    return name


def histogram_series(samples: List[tuple], name: str) -> Dict[tuple, list]:
    """Group ``name_bucket`` samples by non-le labelset; each value is
    the (le, cumulative count) list sorted by le."""
    out: Dict[tuple, list] = {}
    for n, labels, v in samples:
        if n != name + "_bucket":
            continue
        key = tuple(sorted((k, val) for k, val in labels.items()
                           if k != "le"))
        le = labels["le"]
        out.setdefault(key, []).append(
            (float("inf") if le == "+Inf" else float(le), v))
    for key in out:
        out[key].sort()
    return out
