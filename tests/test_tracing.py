"""Distributed request tracing + rolling SLO gauges (README
"Observability"): SpanRecorder units, wallclock anchoring, Chrome
export, tree assembly, the scheduler's span emission, EngineGroup
cross-replica assembly, SLO windows/breaches, and the build_info gauge.
Everything here is CPU-hermetic and in-process; the cross-PROCESS half
(worker event transport, trace RPC verb) lives in tests/test_fleet.py.
"""

import threading
import time

import pytest

import _prom
from tpu_inference import telemetry
from tpu_inference.config import (EngineConfig, ServerConfig, tiny_llama)
from tpu_inference.telemetry import (RollingWindow, SLOTracker,
                                     SpanRecorder, assemble_trace,
                                     pooled_quantile, pooled_slo,
                                     spans_to_chrome)

ENGINE_KW = dict(page_size=8, num_pages=64, max_pages_per_seq=8,
                 max_batch_size=2, prefill_buckets=(16,))


# ------------------------------------------------------------- units


def test_span_recorder_add_seal_export():
    rec = SpanRecorder(enabled=True, replica=3)
    t0 = time.perf_counter()
    rec.add("prefill", "t1", t0, t0 + 0.5, cached_tokens=4)
    rec.add("decode", "t1", t0 + 0.5, t0 + 1.0)
    assert rec.export_open("t1") and rec.export_recent("t1") == []
    rec.seal("t1")
    spans = rec.export_recent("t1")
    assert [s["name"] for s in spans] == ["prefill", "decode"]
    assert all(s["replica"] == 3 and s["trace"] == "t1" for s in spans)
    assert spans[0]["attrs"]["cached_tokens"] == 4
    # Wallclock anchoring: a perf_counter start maps to ~now in unix.
    assert abs(spans[0]["ts"] - time.time()) < 5.0
    assert spans[0]["dur"] == pytest.approx(0.5, abs=1e-6)
    # Export after seal keeps the ring copy (trace verb re-reads it).
    assert rec.get_trace("t1") is not None
    assert rec.recent_traces(10) == {"t1": spans}


def test_span_recorder_caps_and_disabled():
    rec = SpanRecorder(enabled=True)
    t = time.perf_counter()
    for i in range(rec.MAX_SPANS_PER_TRACE + 10):
        rec.add("prefill_chunk", "big", t, t + 0.001)
    assert len(rec.export_open("big")) == rec.MAX_SPANS_PER_TRACE
    assert rec.spans_dropped == 10
    # Unsealed traces (engine-direct callers) can never grow without
    # bound: the open table evicts oldest-first at MAX_TRACES.
    for i in range(rec.MAX_TRACES + 5):
        rec.add("prefill", f"open-{i}", t, t + 0.001)
    assert rec.export_open("big") == []          # evicted
    off = SpanRecorder(enabled=False)
    off.add("prefill", "x", t, t + 1)
    off.add_maintenance("kv_swap_out", t, t + 1)
    off.seal("x")
    assert off.get_trace("x") is None and off.maintenance_spans() == []


def test_span_recorder_ingest_after_seal():
    """A worker's finish-frame spans can arrive after the router sealed
    the trace (handoff traces span two connections): they must still
    join the sealed trace, not a fresh open one."""
    rec = SpanRecorder(enabled=True, replica=-1)
    t = time.perf_counter()
    rec.add("request", "h1", t, t + 1.0, parent="")
    rec.seal("h1")
    rec.ingest("h1", [{"name": "prefill", "trace": "h1", "parent":
                       "request", "ts": time.time(), "dur": 0.2,
                       "replica": 0}])
    names = {s["name"] for s in rec.get_trace("h1")}
    assert names == {"request", "prefill"}


def test_assemble_trace_parent_rules():
    now = time.time()

    def span(name, parent, ts, dur, replica=0):
        return {"name": name, "trace": "t", "parent": parent,
                "ts": ts, "dur": dur, "replica": replica}

    spans = [
        span("request", "", now, 2.0, replica=-1),
        span("queue_wait", "request", now + 0.0, 0.1),
        span("prefill", "request", now + 0.1, 0.5),
        span("prefill_chunk", "prefill", now + 0.1, 0.2),
        span("prefill_chunk", "prefill", now + 0.3, 0.2),
        span("decode", "request", now + 0.6, 1.0, replica=1),
        span("orphan_name", "no_such_parent", now + 0.2, 0.1),
    ]
    tree = assemble_trace("t", spans)
    assert tree["trace_id"] == "t" and tree["n_spans"] == 7
    assert tree["replicas"] == [-1, 0, 1]
    root = tree["tree"]
    assert root["name"] == "request" and "synthetic" not in root
    kids = [c["name"] for c in root["children"]]
    assert kids == ["queue_wait", "prefill", "orphan_name", "decode"]
    prefill = next(c for c in root["children"] if c["name"] == "prefill")
    assert [c["name"] for c in prefill["children"]] == \
        ["prefill_chunk", "prefill_chunk"]
    # No root span at all -> synthetic envelope covering everything.
    tree2 = assemble_trace("t", spans[1:3])
    assert tree2["tree"]["synthetic"] is True
    assert len(tree2["tree"]["children"]) == 2


def test_spans_to_chrome_shape():
    now = time.time()
    traces = {"tA": [
        {"name": "request", "trace": "tA", "parent": "", "ts": now,
         "dur": 1.0, "replica": -1},
        {"name": "prefill", "trace": "tA", "parent": "request",
         "ts": now + 0.1, "dur": 0.4, "replica": 0,
         "attrs": {"cached_tokens": 2}},
    ]}
    maint = [{"name": "kv_swap_out", "trace": "-maintenance-",
              "parent": "", "ts": now, "dur": 0.01, "replica": 0,
              "attrs": {"pages": 3}}]
    chrome = spans_to_chrome(traces, {0: "router", 1: "replica 0"},
                             maintenance=maint,
                             other_data={"note": 1})
    evs = chrome["traceEvents"]
    assert chrome["displayTimeUnit"] == "ms"
    assert chrome["otherData"] == {"note": 1}
    x = [e for e in evs if e["ph"] == "X"]
    meta = [e for e in evs if e["ph"] == "M"]
    # Router span on pid 0, worker span on pid 1, maintenance tid 0.
    assert {e["pid"] for e in x} == {0, 1}
    req = next(e for e in x if e["name"] == "request")
    pf = next(e for e in x if e["name"] == "prefill")
    assert req["pid"] == 0 and pf["pid"] == 1
    assert pf["args"]["trace_id"] == "tA"
    assert pf["args"]["cached_tokens"] == 2
    assert pf["ts"] == pytest.approx((now + 0.1) * 1e6, abs=1.0)
    assert pf["dur"] == pytest.approx(0.4e6, abs=1.0)
    m = next(e for e in x if e["name"] == "kv_swap_out")
    assert m["tid"] == 0 and m["cat"] == "maintenance"
    assert {e["name"] for e in meta} >= {"process_name", "thread_name"}


def test_rolling_window_and_pooled_quantiles():
    w = RollingWindow(size=4)
    assert w.quantile(0.95) is None
    for v in (1.0, 2.0, 3.0, 4.0):
        w.observe(v)
    assert w.quantile(0.5) == 3.0 and w.quantile(0.95) == 4.0
    w.observe(10.0)                       # evicts the oldest (1.0)
    assert sorted(w.values()) == [2.0, 3.0, 4.0, 10.0]
    # Pooling is over raw values, not per-window quantiles.
    assert pooled_quantile([[1.0, 1.0, 1.0], [100.0]], 0.5) == 1.0
    assert pooled_quantile([[], []], 0.5) is None


def test_slo_tracker_breaches_and_pooling():
    slo = SLOTracker(ttft_target_s=0.1, tpot_target_s=0.01)
    slo.observe(0.05, 0.005)              # within both targets
    slo.observe(0.5, 0.05)                # breaches both
    slo.observe(None, 0.005)              # tpot-only observation
    assert slo.ttft_breaches == 1 and slo.tpot_breaches == 1
    snap = slo.snapshot()
    assert snap["ttft_target_s"] == 0.1
    assert snap["ttft_p95_s"] == 0.5
    assert len(snap["tpot_window"]) == 3
    # No target -> quantiles yes, breaches never.
    free = SLOTracker()
    free.observe(100.0, 100.0)
    assert free.ttft_breaches == 0
    assert free.snapshot()["ttft_target_s"] is None
    pooled = pooled_slo([snap, free.snapshot()])
    assert pooled["ttft_breaches"] == 1
    assert pooled["ttft_p95_s"] == 100.0  # pooled across both windows
    import math
    assert math.isnan(SLOTracker().gauge_value("ttft", 0.95))


def test_emit_build_info_stable_series():
    r = telemetry.Registry()
    telemetry.emit_build_info(r, backend="cpu", fleet="subprocess",
                              kv_quant="int8", spec_mode="ngram",
                              routing="prefix_affinity")
    # Re-emitting (a worker restart) replaces in place: one series.
    telemetry.emit_build_info(r, backend="cpu", fleet="subprocess",
                              kv_quant="int8", spec_mode="ngram",
                              routing="prefix_affinity")
    text = telemetry.render_prometheus([({"replica": "0"}, r)])
    meta, samples = _prom.parse(text)
    rows = [(labels, v) for name, labels, v in samples
            if name == "tpu_inf_build_info"]
    assert len(rows) == 1
    labels, value = rows[0]
    assert value == 1.0
    from tpu_inference import __version__
    assert labels["version"] == __version__
    assert labels["kv_quant"] == "int8" and labels["fleet"] == "subprocess"
    assert meta["tpu_inf_build_info"]["type"] == "gauge"


# ------------------------------------- scheduler/engine span emission


def _run_one(engine, seq, timeout=120.0):
    from tpu_inference.engine.scheduler import EngineScheduler

    sched = EngineScheduler(engine)
    sched.start()
    done = threading.Event()
    try:
        sched.submit(seq, lambda s, t: None, lambda s: done.set())
        assert done.wait(timeout)
    finally:
        sched.stop(drain=False)
    return sched


def test_scheduler_emits_phase_spans_and_slo():
    from tpu_inference.engine.engine import InferenceEngine, Sequence

    engine = InferenceEngine(
        tiny_llama(512),
        EngineConfig(**ENGINE_KW, slo_ttft_ms=10_000.0,
                     slo_tpot_ms=0.000001),
        seed=0)
    seq = Sequence(request_id=7, prompt_tokens=[1, 2, 3, 4, 5],
                   max_new_tokens=6, trace_id="trace-abc")
    _run_one(engine, seq)
    rec = engine.telemetry.recorder
    spans = rec.export_recent("trace-abc")
    names = [s["name"] for s in spans]
    assert names.count("queue_wait") == 1
    assert names.count("prefill") == 1
    assert names.count("decode") == 1
    decode = next(s for s in spans if s["name"] == "decode")
    assert decode["attrs"]["output_tokens"] == 6
    assert decode["attrs"]["reason"] == "length"
    prefill = next(s for s in spans if s["name"] == "prefill")
    # Phases abut: prefill ends where decode begins (same timestamp).
    assert (prefill["ts"] + prefill["dur"]
            == pytest.approx(decode["ts"], abs=1e-5))
    # SLO window observed the request; the absurd TPOT target breached,
    # the generous TTFT one did not.
    slo = engine.telemetry.slo
    assert slo.ttft.count == 1 and slo.tpot.count == 1
    assert slo.ttft_breaches == 0 and slo.tpot_breaches == 1
    # Prometheus side: gauges + breach counters render and parse.
    text = telemetry.render_prometheus(
        [({"replica": "0"}, engine.telemetry.registry)])
    _, samples = _prom.parse(text)
    by = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
    assert by[("tpu_inf_slo_breaches_total",
               (("replica", "0"), ("slo", "tpot")))] == 1
    assert by[("tpu_inf_slo_ttft_seconds",
               (("q", "0.95"), ("replica", "0")))] > 0


def test_disabled_telemetry_disables_spans(monkeypatch):
    """TPU_INF_TELEMETRY=0 must kill spans too — the overhead budget's
    comparison arm covers the whole observability layer."""
    monkeypatch.setenv("TPU_INF_TELEMETRY", "0")
    from tpu_inference.engine.engine import InferenceEngine, Sequence

    engine = InferenceEngine(tiny_llama(512), EngineConfig(**ENGINE_KW),
                             seed=0)
    assert engine.telemetry.slo is None
    seq = Sequence(request_id=8, prompt_tokens=[2, 4, 6],
                   max_new_tokens=4, trace_id="t-off")
    _run_one(engine, seq)
    assert engine.telemetry.recorder.get_trace("t-off") is None


# ------------------------------------------- EngineGroup (in-process)


@pytest.fixture(scope="module")
def group():
    from tpu_inference.engine.engine import InferenceEngine
    from tpu_inference.server.replicas import EngineGroup

    engines = [InferenceEngine(tiny_llama(512),
                               EngineConfig(**ENGINE_KW,
                                            slo_ttft_ms=10_000.0),
                               seed=0)
               for _ in range(2)]
    g = EngineGroup(engines, ServerConfig(model_name="t",
                                          tokenizer="byte"))
    g.start()
    yield g
    g.stop(drain=False)


def _group_run(group, rid, prompt, trace_id="", max_new=6):
    from tpu_inference.engine.engine import Sequence

    done = threading.Event()
    seq = Sequence(request_id=rid, prompt_tokens=list(prompt),
                   max_new_tokens=max_new, trace_id=trace_id)
    group.submit(seq, lambda s, t: None, lambda s: done.set())
    assert done.wait(120)
    return seq


def test_group_assembles_cross_replica_trace(group):
    seq = _group_run(group, 100, [1, 2, 3, 4], trace_id="grp-1")
    deadline = time.monotonic() + 10
    snap = None
    while time.monotonic() < deadline:
        snap = group.trace_snapshot("grp-1")
        if snap and {"request", "route", "decode"} <= {
                s["name"] for s in snap["spans"]}:
            break
        time.sleep(0.02)
    assert snap is not None
    names = {s["name"] for s in snap["spans"]}
    assert {"request", "route", "queue_wait", "prefill",
            "decode"} <= names
    root = snap["tree"]
    assert root["name"] == "request" and root["replica"] == -1
    # The engine-side spans carry the replica the request ran on.
    decode = next(s for s in snap["spans"] if s["name"] == "decode")
    assert decode["replica"] == seq.routed_replica
    # Chrome export: router pid 0, the serving replica's pid = idx + 1.
    chrome = group.trace_chrome()
    x = [e for e in chrome["traceEvents"] if e.get("ph") == "X"
         and e["args"].get("trace_id") == "grp-1"]
    assert {e["pid"] for e in x} == {0, seq.routed_replica + 1}


def test_group_mints_trace_id_when_absent(group):
    seq = _group_run(group, 101, [9, 8, 7])
    assert seq.trace_id            # minted at submit
    deadline = time.monotonic() + 10
    while (time.monotonic() < deadline
           and group.trace_snapshot(seq.trace_id) is None):
        time.sleep(0.02)
    assert group.trace_snapshot(seq.trace_id) is not None
    assert group.trace_snapshot("no-such-trace") is None


def test_group_health_and_stats_carry_slo(group):
    _group_run(group, 102, [5, 5, 5])
    hz = group.health_snapshot()
    assert hz["slo"]["window_requests"] >= 1
    assert hz["slo"]["ttft_p95_s"] is not None
    assert all("slo" in r for r in hz["replicas"])
    ss = group.stats_snapshot()
    assert ss["slo"]["ttft_p95_s"] is not None
    # The fleet scrape carries per-replica AND pooled slo series with
    # no duplicate (name, labels) pairs.
    _, samples = _prom.parse(group.prometheus_text())
    seen = set()
    for name, labels, _ in samples:
        key = (name, tuple(sorted(labels.items())))
        assert key not in seen, key
        seen.add(key)
    slo_rows = [l for n, l, v in samples
                if n == "tpu_inf_slo_ttft_seconds"]
    with_replica = [l for l in slo_rows if "replica" in l]
    fleet_rows = [l for l in slo_rows if "replica" not in l]
    assert len(with_replica) == 4 and len(fleet_rows) == 2   # 2q x 2rep
    binfo = [l for n, l, v in samples if n == "tpu_inf_build_info"]
    assert len(binfo) == 3                                   # 2rep+fleet
