"""Unit tests for tpu_inference/telemetry.py: metric primitives,
percentile estimation, scrape diffing/merging, Prometheus exposition
(via the independent parser in tests/_prom.py), structured logging, and
the boot-time int4 degraded-mode gate."""

import json
import math

import pytest

import _prom
from tpu_inference import telemetry
from tpu_inference.telemetry import (Counter, EngineTelemetry, Gauge,
                                     Histogram, Registry, diff_phase,
                                     merge_phases, render_prometheus)


def test_histogram_buckets_and_percentiles():
    h = Histogram("t_seconds", "test", buckets=(0.001, 0.01, 0.1, 1.0))
    for v in (0.0005, 0.005, 0.005, 0.05, 5.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(5.0605)
    cum = h.cumulative()
    assert cum == [1, 3, 4, 4, 5]          # monotone, last = +Inf total
    # p50 lands in the (0.001, 0.01] bucket; interpolation stays inside.
    p50 = h.percentile(0.5)
    assert 0.001 <= p50 <= 0.01
    # An exact bucket-boundary observation counts into that bucket
    # (le is an inclusive upper bound).
    h2 = Histogram("t2", buckets=(1.0, 2.0))
    h2.observe(1.0)
    assert h2.cumulative() == [1, 1, 1]


def test_percentile_empty_histogram():
    h = Histogram("t_seconds", buckets=(0.1, 1.0))
    assert h.percentile(0.5) is None
    snap = h.phase_snapshot()
    assert snap["count"] == 0 and snap["p99"] is None


def test_diff_phase_isolates_window():
    h = Histogram("t", buckets=(0.1, 1.0))
    h.observe(0.05)
    before = h.phase_snapshot()
    h.observe(0.5)
    h.observe(0.5)
    after = h.phase_snapshot()
    d = diff_phase(after, before)
    assert d["count"] == 2
    assert d["sum"] == pytest.approx(1.0)
    assert 0.1 <= d["p50"] <= 1.0          # only the window's samples
    # No baseline -> after unchanged.
    assert diff_phase(after, None)["count"] == 3


def test_merge_phases_across_replicas():
    a, b = (Histogram("t", buckets=(0.1, 1.0)) for _ in range(2))
    a.observe(0.05)
    b.observe(0.5)
    b.observe(2.0)
    m = merge_phases([a.phase_snapshot(), b.phase_snapshot()])
    assert m["count"] == 3
    assert m["sum"] == pytest.approx(2.55)
    assert merge_phases([]) == {}


def test_render_prometheus_label_escaping_roundtrip():
    r = Registry()
    nasty = 'a"b\\c\nd'
    r.counter("t_total", "help with \\ backslash", reason=nasty).inc(3)
    text = render_prometheus([({"replica": "0"}, r)])
    # Escapes on the wire...
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    # ...and the independent parser recovers the original value.
    meta, samples = _prom.parse(text)
    # The page also carries the render-time self-histogram; pick ours.
    (name, labels, value), = [s for s in samples if s[0] == "t_total"]
    assert name == "t_total" and value == 3
    assert labels["reason"] == nasty and labels["replica"] == "0"
    assert meta["t_total"]["type"] == "counter"


def test_render_prometheus_histogram_contract():
    r = Registry()
    h = r.histogram("t_seconds", "hist", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    g = r.gauge("t_gauge", "a gauge")
    g.set(2.5)
    text = render_prometheus([({}, r)])
    meta, samples = _prom.parse(text)
    assert meta["t_seconds"]["type"] == "histogram"
    series = _prom.histogram_series(samples, "t_seconds")
    (buckets,) = series.values()
    les = [le for le, _ in buckets]
    vals = [v for _, v in buckets]
    assert les == [0.1, 1.0, math.inf]
    assert vals == sorted(vals)            # cumulative monotone
    by_name = {n: v for n, _, v in samples}
    assert by_name["t_seconds_count"] == vals[-1]   # +Inf == _count
    assert by_name["t_seconds_sum"] == pytest.approx(0.55)
    assert by_name["t_gauge"] == 2.5


def test_registry_readd_replaces():
    r = Registry()
    r.counter("t_total").inc(5)
    r.add(Counter("t_total"))              # restart: replaces, no dup
    assert len(r.collect()) == 1
    assert r.collect()[0].value == 0
    # fn metrics are read-through.
    r.add(Gauge("t_fn", fn=lambda: 7))
    assert [m.collect_value() for m in r.collect()
            if m.name == "t_fn"] == [7]
    # Getter with a fresh fn re-binds the closure (scheduler restart
    # over the same engine must not leave metrics reading the dead
    # scheduler's state).
    r.counter("t_fn2", fn=lambda: 1)
    m = r.counter("t_fn2", fn=lambda: 2)
    assert m.collect_value() == 2


def test_seconds_buckets_cover_request_timeout():
    """The log-bucket table must reach past the 600 s default request
    timeout: a saturation-tail queue wait may legally approach it, and
    percentile estimates clamp at the last bound."""
    from tpu_inference.config import ServerConfig
    from tpu_inference.telemetry import SECONDS_BUCKETS
    assert SECONDS_BUCKETS[-1] >= ServerConfig().request_timeout_s
    h = Histogram("t_seconds")
    h.observe(599.0)                       # lands in a real bucket
    assert h.cumulative()[-2] == 1         # not only in +Inf overflow


def test_log_event_level_gating(capsys, monkeypatch):
    monkeypatch.delenv("TPU_INF_LOG", raising=False)
    telemetry.log_event("quiet_info", level="info", request_id="x")
    telemetry.log_event("loud_warning", level="warning", request_id="y")
    err = capsys.readouterr().err
    assert "quiet_info" not in err         # default threshold: warning
    rec = json.loads([l for l in err.splitlines()
                      if "loud_warning" in l][0])
    assert rec["event"] == "loud_warning" and rec["request_id"] == "y"
    monkeypatch.setenv("TPU_INF_LOG", "info")
    telemetry.log_event("now_visible", level="info")
    assert "now_visible" in capsys.readouterr().err


def test_disabled_telemetry_is_noop(monkeypatch):
    tel = EngineTelemetry(enabled=False)
    tel.decode_dispatch_s.observe(0.1)     # all no-ops, no registry
    tel.degraded_mode.set(1)
    tel.request_finished("stop")
    assert tel.phase_snapshot() == {}
    assert tel.registry.collect() == []
    monkeypatch.setenv("TPU_INF_TELEMETRY", "0")
    assert not telemetry.telemetry_enabled()


def test_int4_pallas_degraded_gate(monkeypatch, capsys):
    """kv_quant=int4 + pallas on (simulated) real TPU without an int4
    Mosaic validation artifact: boot warns through the structured logger
    and pins tpu_inf_degraded_mode=1; the operator override clears it."""
    import jax

    import tpu_inference.engine.engine as eng_mod
    from tpu_inference.config import EngineConfig, tiny_llama

    monkeypatch.delenv("TPU_INF_INT4_VALIDATED", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    kw = dict(page_size=8, num_pages=32, max_pages_per_seq=4,
              max_batch_size=2, prefill_buckets=(16,), kv_quant="int4",
              attn_backend="pallas")
    eng = eng_mod.InferenceEngine(tiny_llama(512), EngineConfig(**kw))
    assert eng.telemetry.degraded_mode.value == 1
    err = capsys.readouterr().err
    rec = json.loads([l for l in err.splitlines()
                      if "degraded_mode" in l][0])
    assert rec["level"] == "warning" and rec["kv_quant"] == "int4"
    # The same config on CPU (no real chip) must NOT flag.
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    eng = eng_mod.InferenceEngine(tiny_llama(512), EngineConfig(**kw))
    assert eng.telemetry.degraded_mode.value == 0
    # Operator override: validated out-of-repo.
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("TPU_INF_INT4_VALIDATED", "1")
    eng = eng_mod.InferenceEngine(tiny_llama(512), EngineConfig(**kw))
    assert eng.telemetry.degraded_mode.value == 0
