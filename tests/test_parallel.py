"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

Correctness bar: a TP/EP-sharded forward (GSPMD-placed collectives) must
match the single-device forward bit-for-bit-ish (f32, highest precision).
The reference has no parallelism to compare against (SURVEY.md §2b); the
oracle is our own unsharded graph.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_inference.config import (
    EngineConfig,
    ModelConfig,
    ParallelConfig,
)
from tpu_inference.engine.engine import InferenceEngine
from tpu_inference.models.common import make_dense_attn
from tpu_inference.models.registry import build_model, get_model_fns
from tpu_inference.parallel import (
    build_mesh,
    param_shardings,
    shard_params,
)


def tp_llama_cfg():
    return ModelConfig(
        name="tp-llama", family="llama", vocab_size=512, d_model=128,
        n_layers=2, n_heads=8, n_kv_heads=4, d_ff=256, max_seq_len=512,
        rope_theta=10000.0, dtype=jnp.float32)


def tp_qwen2_cfg():
    """Qwen2 dialect under TP: the head-dim-sharded q/k/v biases must
    follow their projections (parallel/shardings.py bq/bk/bv specs)."""
    import dataclasses
    return dataclasses.replace(tp_llama_cfg(), name="tp-qwen2",
                               qkv_bias=True)


def tp_mixtral_cfg():
    return ModelConfig(
        name="tp-mixtral", family="mixtral", vocab_size=512, d_model=128,
        n_layers=2, n_heads=8, n_kv_heads=4, d_ff=256, max_seq_len=512,
        rope_theta=10000.0, n_experts=4, n_experts_per_tok=2,
        dtype=jnp.float32)


def _forward_logits(cfg, params, tokens):
    mod = get_model_fns(cfg)
    positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
    logits, _ = mod.forward(params, cfg, tokens, positions, None,
                            make_dense_attn())
    return logits


@pytest.mark.parametrize("cfg_fn", [tp_llama_cfg, tp_qwen2_cfg,
                                    tp_mixtral_cfg])
def test_tp_forward_matches_single_device(cfg_fn):
    cfg = cfg_fn()
    params, mod = build_model(cfg, seed=0)
    if cfg.qkv_bias:
        from tests.conftest import randomize_qkv_biases
        randomize_qkv_biases(params, seed=11)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)

    ref = jax.jit(lambda p, t: _forward_logits(cfg, p, t))(params, tokens)

    mesh = build_mesh(ParallelConfig(tp=4))
    sharded = shard_params(params, cfg, mesh)
    got = jax.jit(lambda p, t: _forward_logits(cfg, p, t))(sharded, tokens)

    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_param_shardings_cover_tree():
    """Every leaf of every family's params has a matching spec leaf."""
    import dataclasses

    from tpu_inference.config import tiny_gpt2

    tied_llama = dataclasses.replace(tp_llama_cfg(), tie_embeddings=True)
    gpt2 = dataclasses.replace(tiny_gpt2(), n_heads=4, n_kv_heads=4)
    for cfg in (tp_llama_cfg(), tied_llama, tp_mixtral_cfg(), gpt2):
        params, _ = build_model(cfg, seed=0)
        mesh = build_mesh(ParallelConfig(tp=4))
        sh = param_shardings(cfg, mesh)
        # tree.map raises if structures mismatch.
        jax.tree.map(lambda p, s: None, params, sh)


def test_validate_tp_rejects_indivisible():
    from tpu_inference.parallel import validate_tp

    cfg = tp_llama_cfg()  # n_kv_heads=4
    with pytest.raises(ValueError, match="n_kv_heads"):
        validate_tp(cfg, 8)


def test_tp_engine_generate_matches_unsharded():
    """End-to-end: paged-KV engine under a TP=4 mesh produces the same greedy
    tokens as the single-device engine."""
    cfg = tp_llama_cfg()
    ecfg = EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=8,
                        max_batch_size=4, prefill_buckets=(16, 32))
    prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14, 15, 16, 17]]

    base = InferenceEngine(cfg, ecfg, seed=0)
    want = base.generate(prompts, max_new_tokens=8)

    mesh = build_mesh(ParallelConfig(tp=4))
    eng = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
    got = eng.generate(prompts, max_new_tokens=8)
    assert got == want


def test_ep_engine_generate_matches_unsharded():
    cfg = tp_mixtral_cfg()
    ecfg = EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=8,
                        max_batch_size=4, prefill_buckets=(16, 32))
    prompts = [[1, 2, 3, 4, 5], [7, 8, 9]]

    base = InferenceEngine(cfg, ecfg, seed=0)
    want = base.generate(prompts, max_new_tokens=6)

    mesh = build_mesh(ParallelConfig(tp=4))
    eng = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
    got = eng.generate(prompts, max_new_tokens=6)
    assert got == want


def test_sp_engine_ring_prefill_matches_unsharded():
    """Serving prefill through ring attention (sp=4, composed with tp=2)
    produces the same greedy tokens as the single-device engine, including
    prompts long enough to span several sequence shards."""
    cfg = tp_llama_cfg()
    ecfg = EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=8,
                        max_batch_size=4, prefill_buckets=(16, 32))
    prompts = [list(range(1, 29)), [7, 8, 9], list(range(100, 117))]

    base = InferenceEngine(cfg, ecfg, seed=0)
    want = base.generate(prompts, max_new_tokens=8)

    mesh = build_mesh(ParallelConfig(tp=2, sp=4))
    eng = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
    assert eng.sp == 4
    got = eng.generate(prompts, max_new_tokens=8)
    assert got == want


@pytest.mark.slow   # 2k-token ring prefill; short-ring coverage in test_sp_engine_ring_prefill_matches_unsharded
def test_sp_long_context_prefill():
    """Long-context serving: a 2k-token prompt prefills through ring
    attention (sp=4) with per-chip sequence shards and decodes on the
    paged pool, token-equal to the unsharded engine."""
    cfg = tp_llama_cfg()
    ecfg = EngineConfig(page_size=16, num_pages=320, max_pages_per_seq=160,
                        max_batch_size=2, prefill_buckets=(256, 2048))
    prompt = [(7 * i + 3) % cfg.vocab_size for i in range(2048)]

    base = InferenceEngine(cfg, ecfg, seed=0)
    want = base.generate([prompt], max_new_tokens=4)

    mesh = build_mesh(ParallelConfig(tp=2, sp=4))
    eng = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
    got = eng.generate([prompt], max_new_tokens=4)
    assert got == want


def test_dp_tp_mesh_shapes():
    mesh = build_mesh(ParallelConfig(dp=2, tp=2, sp=2))
    assert mesh.shape == {"dp": 2, "tp": 2, "sp": 2}
    with pytest.raises(ValueError):
        build_mesh(ParallelConfig(dp=4, tp=4))


def test_replica_meshes_split():
    """replica_meshes hands back one (tp, sp) submesh per dp row; in a
    single process every row is local, each keeps dp=1 and the
    production axis names so sharding specs apply unchanged."""
    from tpu_inference import config as cfgs
    from tpu_inference.parallel.multihost import (build_hybrid_mesh,
                                                  replica_meshes)

    mesh = build_hybrid_mesh(cfgs.ParallelConfig(dp=2, tp=2, sp=2))
    rows = replica_meshes(mesh)
    assert [i for i, _ in rows] == [0, 1]
    for i, sub in rows:
        assert dict(sub.shape) == {"dp": 1, "tp": 2, "sp": 2}
        assert (sub.devices == mesh.devices[i:i + 1]).all()


def test_hybrid_mesh_single_slice():
    """build_hybrid_mesh == flat mesh layout when all devices share ICI."""
    from tpu_inference import config as cfgs
    from tpu_inference.parallel.multihost import build_hybrid_mesh

    pcfg = cfgs.ParallelConfig(dp=2, tp=2, sp=2)
    mesh = build_hybrid_mesh(pcfg)
    assert mesh.shape == {"dp": 2, "tp": 2, "sp": 2}
    # tp groups contiguous in device order (ICI neighbors).
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert ids[0, 0, 0] + 1 == ids[0, 1, 0]


def test_hybrid_mesh_multi_slice_layout():
    """dp splits across simulated slices; tp never straddles a slice."""
    from tpu_inference import config as cfgs
    from tpu_inference.parallel.multihost import build_hybrid_mesh

    pcfg = cfgs.ParallelConfig(dp=2, tp=4, sp=1)
    mesh = build_hybrid_mesh(pcfg, num_slices=2)
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    # Replica 0 = devices 0-3, replica 1 = devices 4-7: each tp group
    # stays inside one "slice" of 4 contiguous devices.
    assert set(ids[0].flat) == {0, 1, 2, 3}
    assert set(ids[1].flat) == {4, 5, 6, 7}

    with pytest.raises(ValueError, match="straddle"):
        build_hybrid_mesh(cfgs.ParallelConfig(dp=1, tp=8), num_slices=2)


def test_hybrid_mesh_runs_collectives():
    """A psum over the hybrid mesh executes (XLA inserts the collective)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from tpu_inference import config as cfgs
    from tpu_inference.parallel.multihost import build_hybrid_mesh

    mesh = build_hybrid_mesh(cfgs.ParallelConfig(dp=2, tp=2, sp=2),
                             num_slices=2)
    x = jnp.arange(8.0)
    y = jax.jit(lambda v: v.sum(),
                in_shardings=NamedSharding(mesh, P(("dp",))),
                out_shardings=NamedSharding(mesh, P()))(x)
    assert float(y) == 28.0


def test_multihost_initialize_noop_single_process():
    from tpu_inference import config as cfgs
    from tpu_inference.parallel.multihost import (initialize,
                                                  process_local_engine_role)
    initialize()                      # must not raise on single process
    from tpu_inference.parallel.mesh import build_mesh
    role = process_local_engine_role(build_mesh(cfgs.ParallelConfig(tp=2)))
    assert role["process_count"] == 1
    assert role["local_devices_in_mesh"] == 2
    assert role["hosts_frontend"] is True


def test_tp_engine_pipelined_decode_matches():
    """Dispatch-ahead decode under a tp mesh == sync unsharded engine."""
    cfg = tp_llama_cfg()
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]

    base = InferenceEngine(cfg, EngineConfig(
        page_size=8, num_pages=64, max_pages_per_seq=8, max_batch_size=2,
        prefill_buckets=(16,), decode_steps_per_call=4), seed=0)
    want = base.generate(prompts, max_new_tokens=12)

    ecfg = EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=8,
                        max_batch_size=2, prefill_buckets=(16,),
                        decode_steps_per_call=4, decode_pipeline_depth=2)
    eng = InferenceEngine(cfg, ecfg, seed=0,
                          mesh=build_mesh(ParallelConfig(tp=4)))
    from tpu_inference.engine.engine import Sequence
    seqs = [Sequence(request_id=i, prompt_tokens=p, max_new_tokens=12)
            for i, p in enumerate(prompts)]
    for s in seqs:
        eng.prefill(s)
    for _ in range(20):
        eng.decode_steps_pipelined()
        if all(s.done for s in seqs) and not eng.pipeline_pending:
            break
    eng.drain_pipeline()
    assert [s.generated for s in seqs] == want


def test_sp_engine_ulysses_prefill_matches_unsharded():
    """Serving prefill through Ulysses all-to-all SP (sp=2, composed
    with tp=2) produces the same greedy tokens as the single-device
    engine (the same contract the ring path satisfies)."""
    cfg = tp_llama_cfg()
    ecfg = EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=8,
                        max_batch_size=4, prefill_buckets=(16, 32),
                        sp_attn="ulysses")
    prompts = [list(range(1, 29)), [7, 8, 9], list(range(100, 117))]

    base = InferenceEngine(cfg, ecfg, seed=0)
    want = base.generate(prompts, max_new_tokens=8)

    mesh = build_mesh(ParallelConfig(tp=2, sp=2))
    eng = InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
    assert eng.sp == 2
    got = eng.generate(prompts, max_new_tokens=8)
    assert got == want


def test_sp_ulysses_rejects_indivisible_heads():
    """n_kv_heads=4 can't split across tp*sp=8 head groups — explicit
    error steering to the ring, not a wrong-shape crash mid-prefill."""
    cfg = tp_llama_cfg()
    ecfg = EngineConfig(page_size=8, num_pages=64, max_pages_per_seq=8,
                        max_batch_size=2, prefill_buckets=(16,),
                        sp_attn="ulysses")
    mesh = build_mesh(ParallelConfig(tp=2, sp=4))
    with pytest.raises(ValueError, match="ulysses"):
        InferenceEngine(cfg, ecfg, seed=0, mesh=mesh)
