"""Query synthesis: match scheduled token lengths to corpus prompts.

Replaces the reference's O(P*G) Python-loop lookup-table build
(main.py:96-154) with a vectorized nearest-neighbor search over the corpus:
for a scheduled (prompt_len, output_len) pair, pick the corpus entry with
the nearest prompt length, breaking ties by nearest output length — the
same row-first priority the reference's table fill encodes, computed as a
single lexicographic distance argmin per query.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import pandas as pd

from traffic_generator.data import Entry


class Query:
    """Iterates a schedule, yielding length-matched prompts.

    ``get_query() -> [prompt, len_prompt, len_output, query_id, timestamp]``
    (reference main.py:156-175 contract; the reference's ``prompr`` typo is
    not preserved).
    """

    def __init__(self, inputs: Sequence[Entry], schedule: pd.DataFrame,
                 max_prompt_len: int = 1024, max_gen_len: int = 1024):
        if len(inputs) == 0:
            raise ValueError("empty corpus")
        self.inputs = list(inputs)
        self.schedule = schedule.sort_values(
            "Timestamp", kind="stable").reset_index(drop=True)
        self.max_prompt_len = max_prompt_len
        self.max_gen_len = max_gen_len
        self._corpus_p = np.array([e[1] for e in self.inputs])
        self._corpus_g = np.array([e[2] for e in self.inputs])
        self._match_idx = self._match_all()
        self.query_id = -1

    def _match_all(self) -> np.ndarray:
        """Vectorized nearest-length match for every schedule row."""
        want_p = np.minimum(self.schedule["Request tokens"].to_numpy(),
                            self.max_prompt_len)
        want_g = np.minimum(self.schedule["Response tokens"].to_numpy(),
                            self.max_gen_len)
        # [n_sched, n_corpus] distances; prompt distance dominates.
        dp = np.abs(self._corpus_p[None, :] - want_p[:, None]).astype(np.int64)
        dg = np.abs(self._corpus_g[None, :] - want_g[:, None]).astype(np.int64)
        weight = int(max(self._corpus_g.max(), self.max_gen_len)) + 1
        return np.argmin(dp * weight + dg, axis=1)

    def __len__(self) -> int:
        return len(self.schedule)

    def reset(self) -> None:
        self.query_id = -1

    def get_query(self) -> List:
        self.query_id += 1
        row = self.schedule.iloc[self.query_id]
        len_p = int(min(row["Request tokens"], self.max_prompt_len))
        len_g = int(min(row["Response tokens"], self.max_gen_len))
        entry = self.inputs[self._match_idx[self.query_id]]
        return [entry[0], len_p, len_g, self.query_id,
                float(row["Timestamp"])]
