"""Per-request latency instrumentation (reference: main.py:184-222).

``RequestTracer`` subclasses ``aiohttp.TraceConfig`` and records request
lifecycle timestamps relative to the collector's session epoch. All state
flows through ``trace_request_ctx`` — no globals (the reference's exception
callback referenced a global ``logger`` and raised NameError when used as a
library, main.py:220).

Output schema (preserved exactly; reference logs/log.json):
per query id -> ``{number_of_input_tokens, request_start_time,
response_headers_received_time, first_token_arrive_time, response_end_time,
scheduled_start_time, success}``.
"""

from __future__ import annotations

import json
import time
from typing import Dict

import aiohttp


class MetricCollector:
    """Accumulates per-request metric dicts; JSON-serializable."""

    def __init__(self):
        self.metrics: Dict[int, dict] = {}
        self.session_start_timestamp: float = 0.0
        self.trace_config = RequestTracer()
        # Harness-level resilience counters (chaos-enabled servers shed
        # with 429/503; the generator retries with backoff): how many
        # attempts were retried, and how many queries were ultimately
        # shed after exhausting the retry budget.
        self.retries_total: int = 0
        self.shed_total: int = 0

    def start_session(self) -> None:
        self.session_start_timestamp = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self.session_start_timestamp

    def init_query(self, query_id: int, n_input_tokens: int,
                   scheduled_start: float) -> None:
        # Timing fields default to null so failed requests keep the full
        # reference schema (reference main.py:274-277 wrote None on failure).
        self.metrics[query_id] = {
            "number_of_input_tokens": n_input_tokens,
            "request_start_time": None,
            "response_headers_received_time": None,
            "first_token_arrive_time": None,
            "response_end_time": None,
            "num_output_tokens": None,
            "max_interchunk_gap": None,
            # Trace id shared with the server (X-Request-Id): joins this
            # record to server-side spans/logs. Additive field; the
            # reference schema is otherwise preserved.
            "request_id": None,
            # Class the query was tagged with (class_mix; X-Priority
            # header) — the per-class summary groups on this.
            "priority_class": None,
            "scheduled_start_time": scheduled_start,
            "num_retries": 0,
            "shed": False,
            "success": None,
        }

    def record(self, query_id: int, field: str, value) -> None:
        self.metrics.setdefault(query_id, {})[field] = value

    def record_retry(self, query_id: int) -> None:
        """One 429/503 response retried with backoff."""
        entry = self.metrics.setdefault(query_id, {})
        entry["num_retries"] = entry.get("num_retries", 0) + 1
        self.retries_total += 1

    def record_shed(self, query_id: int) -> None:
        """Query dropped after exhausting the retry budget (the server
        kept shedding) — a clean record, not a raw exception."""
        entry = self.metrics.setdefault(query_id, {})
        entry["shed"] = True
        entry["success"] = False
        self.shed_total += 1

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.metrics, f, indent=1)

    @staticmethod
    def _pctls(xs, ps=(50, 95, 99)):
        """Linear-interpolation percentiles (numpy 'linear' / the
        server's percentile_from_cumulative convention) without a
        numpy dependency in the client harness."""
        out = {}
        xs = sorted(xs)
        for p in ps:
            if not xs:
                out[f"p{p}"] = None
                continue
            rank = (len(xs) - 1) * p / 100.0
            lo = int(rank)
            hi = min(lo + 1, len(xs) - 1)
            out[f"p{p}"] = round(
                xs[lo] + (xs[hi] - xs[lo]) * (rank - lo), 4)
        return out

    def class_summary(self) -> Dict[str, dict]:
        """Per-priority-class latency breakdown (README "Elastic
        fleet"): what each class's clients actually experienced —
        TTFT + E2E percentiles, retries and sheds — keyed by the
        class the query was tagged with ("untagged" otherwise)."""
        by_class: Dict[str, list] = {}
        for m in self.metrics.values():
            by_class.setdefault(m.get("priority_class") or "untagged",
                                []).append(m)
        out: Dict[str, dict] = {}
        for name, ms in sorted(by_class.items()):
            ttft, e2e = [], []
            for m in ms:
                start = m.get("request_start_time")
                first = m.get("first_token_arrive_time")
                end = m.get("response_end_time")
                if start is not None and first is not None:
                    ttft.append(first - start)
                if start is not None and end is not None:
                    e2e.append(end - start)
            out[name] = {
                "requests": len(ms),
                "succeeded": sum(1 for m in ms if m.get("success")),
                "shed": sum(1 for m in ms if m.get("shed")),
                "retries": sum(m.get("num_retries") or 0 for m in ms),
                "ttft_s": self._pctls(ttft),
                "e2e_s": self._pctls(e2e),
            }
        return out


class RequestTracer(aiohttp.TraceConfig):
    """aiohttp request-lifecycle hooks -> MetricCollector fields."""

    def __init__(self):
        super().__init__()
        self.on_request_start.append(self._on_start)
        self.on_request_end.append(self._on_end)
        self.on_request_exception.append(self._on_exception)

    @staticmethod
    def _ctx(context):
        ctx = context.trace_request_ctx or {}
        return ctx.get("collector"), ctx.get("query_id")

    async def _on_start(self, session, context, params) -> None:
        collector, qid = self._ctx(context)
        if collector is None:
            return
        # First attempt only: a 429/503 retry re-fires this hook, and
        # overwriting would make turnaround exclude the earlier attempts
        # and backoff sleeps — exactly the client-perceived latency a
        # shed/retried query is supposed to show.
        if collector.metrics.get(qid, {}).get("request_start_time") is None:
            collector.record(qid, "request_start_time", collector.elapsed())
            print(f"[START] query {qid}")

    async def _on_end(self, session, context, params) -> None:
        collector, qid = self._ctx(context)
        if collector is None:
            return
        collector.record(qid, "response_headers_received_time",
                         collector.elapsed())

    async def _on_exception(self, session, context, params) -> None:
        collector, qid = self._ctx(context)
        if collector is None:
            return
        collector.record(qid, "success", False)
        print(f"[ERROR] query {qid}: {params.exception!r}")
