"""Synthetic user arrival models (reference: main.py:13-37).

Each user model produces a list of request timestamps (seconds from replay
start); the Scheduler turns a set of users into an arrival schedule.
"""

from __future__ import annotations

import dataclasses
from typing import List


@dataclasses.dataclass
class SteadyUser:
    """Fires requests at a constant rate for a fixed duration.

    Timestamps: delay_start, delay_start + 1/rate, ... (reference
    main.py:13-27 semantics).
    """

    req_freq: float              # requests per second
    duration: float              # seconds of activity
    delay_start: float = 0.0
    # Token sizes for schedule synthesis (reference hardcoded 500/500).
    prompt_tokens: int = 500
    response_tokens: int = 500

    def get_timestamps(self) -> List[float]:
        n = max(0, round(self.duration * self.req_freq))
        return [self.delay_start + i / self.req_freq for i in range(n)]


@dataclasses.dataclass
class BurstUser:
    """Fires n_req simultaneous requests at one instant (reference
    main.py:30-37)."""

    n_req: int
    time: float = 0.0
    prompt_tokens: int = 500
    response_tokens: int = 500

    def get_timestamps(self) -> List[float]:
        return [self.time] * self.n_req
