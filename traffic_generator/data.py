"""Prompt-corpus loading (reference: main.py:40-51).

Corpus format (`conversations.json`): ``{id: {"prompt": str,
"len_prompt": int, "len_output": int, "output": str}}`` — schema per
SURVEY.md §2a #3.
"""

from __future__ import annotations

import json
from typing import List, Tuple

Entry = Tuple[str, int, int, str]  # (prompt, len_prompt, len_output, output)


class DataLoader:
    @staticmethod
    def load_json_from_path(path: str) -> dict:
        with open(path) as f:
            return json.load(f)

    @classmethod
    def get_data_from_path(cls, path: str) -> List[Entry]:
        raw = cls.load_json_from_path(path)
        return [(v["prompt"], int(v["len_prompt"]), int(v["len_output"]),
                 v.get("output", "")) for v in raw.values()]
