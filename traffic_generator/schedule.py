"""Client-side arrival schedules (reference: main.py:53-84).

A schedule is a DataFrame with columns ``Timestamp`` (float seconds),
``Request tokens`` and ``Response tokens`` (ints) — BurstGPT trace format —
plus an optional ``User`` column for synthetic-user schedules.
"""

from __future__ import annotations

from typing import Iterable, Optional, Union

import pandas as pd

from traffic_generator.users import BurstUser, SteadyUser

User = Union[SteadyUser, BurstUser]

TRACE_DTYPES = {"Timestamp": float, "Request tokens": int,
                "Response tokens": int}


class Scheduler:
    """Builds arrival schedules from trace files or synthetic users."""

    @staticmethod
    def get_schedule_from_trace(path: str,
                                max_trace: Optional[int] = None) -> pd.DataFrame:
        df = pd.read_csv(path, usecols=list(TRACE_DTYPES)).astype(TRACE_DTYPES)
        if max_trace is not None:
            df = df.head(max_trace)
        return df.reset_index(drop=True)

    @staticmethod
    def get_schedule_from_users(users: Iterable[User]) -> pd.DataFrame:
        rows = []
        for uid, user in enumerate(users):
            for t in user.get_timestamps():
                rows.append({"Timestamp": float(t),
                             "Request tokens": user.prompt_tokens,
                             "Response tokens": user.response_tokens,
                             "User": uid})
        df = pd.DataFrame(rows, columns=["Timestamp", "Request tokens",
                                         "Response tokens", "User"])
        return df.sort_values("Timestamp", kind="stable").reset_index(drop=True)
