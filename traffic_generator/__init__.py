"""Benchmark client: BurstGPT trace replay with per-request latency tracing.

Clean-room re-implementation of the reference harness (SURVEY.md §2a
components 1-9; reference: traffic_generator/main.py) with its known defects
fixed:

- the exception-tracing callback no longer touches a global logger
  (reference bug at main.py:220);
- ``max_tokens`` / ``temperature`` are sent both at the top level (where the
  reference put them) and under ``options`` (where Ollama actually reads
  them), so the knobs take effect against either server;
- the nearest-length query matcher is vectorized numpy instead of a 1M-cell
  Python-loop table build (reference main.py:96-154);
- synthetic user schedules take configurable token sizes (reference
  hardcoded 500/500 at main.py:69-70).

The per-request metrics JSON schema is preserved exactly
(reference logs/log.json): ``number_of_input_tokens, request_start_time,
response_headers_received_time, first_token_arrive_time, response_end_time,
scheduled_start_time, success``.
"""

from traffic_generator.data import DataLoader  # noqa: F401
from traffic_generator.generator import TrafficGenerator  # noqa: F401
from traffic_generator.metrics import MetricCollector, RequestTracer  # noqa: F401
from traffic_generator.query import Query  # noqa: F401
from traffic_generator.schedule import Scheduler  # noqa: F401
from traffic_generator.users import BurstUser, SteadyUser  # noqa: F401
