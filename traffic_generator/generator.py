"""The traffic driver: open-loop trace replay over HTTP
(reference: main.py:230-294).

One coroutine per scheduled request: sleep until the scheduled arrival time,
POST to the Ollama-protocol endpoint, stream the NDJSON body, and record
TTFT (first streamed chunk), end-to-end latency, and success — all relative
to the shared session epoch.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional, Sequence

import aiohttp
import pandas as pd

from traffic_generator.data import Entry
from traffic_generator.metrics import MetricCollector
from traffic_generator.query import Query


class TrafficGenerator:
    """Replays a schedule against ``config['url']``.

    config keys (reference main.py:302-313 compatible): ``url``, ``model``,
    ``temperature``, ``max_tokens``, ``stream``, plus optional
    ``request_timeout`` (seconds).
    """

    def __init__(self, data: Sequence[Entry], schedule: pd.DataFrame,
                 config: dict, logger: MetricCollector,
                 max_prompt_len: int = 1024, max_gen_len: int = 1024):
        self.config = dict(config)
        self.logger = logger
        self.queries = Query(data, schedule, max_prompt_len=max_prompt_len,
                             max_gen_len=max_gen_len)
        # Shared retry budget (README "Elastic fleet" client contract):
        # one pool across ALL in-flight queries, consumed one token per
        # retry. Under sustained overload the budget drains and later
        # 429s shed immediately — a fleet of clients stops amplifying
        # the exact load the server is shedding. Default scales with
        # the trace; 0 disables the pool (per-query max_retries still
        # bounds each call).
        budget = config.get("retry_budget")
        if budget is None:
            self._retry_budget = max(16, len(self.queries))
        else:
            self._retry_budget = int(budget) or None  # 0 = unlimited
        # Priority-class mix (README "Elastic fleet"): ``class_mix`` like
        # "interactive:0.8,batch:0.15,background:0.05" tags each query
        # with an X-Priority header in those proportions, so the
        # per-class summary measures what each class actually
        # experienced under the server's class-aware admission. Empty =
        # off (no header; the server applies its default_class).
        self._class_mix = self._parse_class_mix(
            config.get("class_mix") or "")
        self._class_counts = {name: 0 for name, _ in self._class_mix}

    @staticmethod
    def _parse_class_mix(spec: str) -> list:
        """'name:weight,...' -> [(name, weight)]; raises ValueError on
        malformed specs (a silently dropped class would skew the mix)."""
        out = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            name, _, w = part.partition(":")
            name = name.strip().lower()
            weight = float(w) if w.strip() else 1.0
            if not name or weight <= 0:
                raise ValueError(f"bad class_mix entry {part!r}")
            out.append((name, weight))
        return out

    def _next_class(self) -> Optional[str]:
        """Deterministic proportional assignment (smallest served/weight
        ratio next — weighted round-robin without RNG, so reruns of the
        same trace tag the same queries)."""
        if not self._class_mix:
            return None
        name = min(self._class_mix,
                   key=lambda kv: self._class_counts[kv[0]] / kv[1])[0]
        self._class_counts[name] += 1
        return name

    def _payload(self, prompt: str, len_output: int) -> dict:
        temperature = float(self.config.get("temperature", 0.0))
        # Per-query generation length comes from the trace (the reference
        # sent a fixed config['max_tokens'] for every request, at a JSON
        # level Ollama ignores — SURVEY.md §2a "known defects").
        max_tokens = int(self.config.get("max_tokens") or len_output)
        return {
            "model": self.config.get("model", "default"),
            "prompt": prompt,
            "temperature": temperature,
            "max_tokens": max_tokens,
            "stream": bool(self.config.get("stream", True)),
            "options": {"temperature": temperature,
                        "num_predict": max_tokens},
        }

    @staticmethod
    def _count_tokens(last_line: bytes, n_lines: int) -> int:
        """Output-token count (additive metric field; the reference schema
        is otherwise preserved). Prefer the server-reported ``eval_count``
        from the terminal NDJSON record — line counting overcounts when a
        multi-byte UTF-8 tail is flushed as an extra non-token line."""
        import json as _json

        if last_line.strip():
            try:
                rec = _json.loads(last_line)
            except ValueError:
                rec = {}
            if rec.get("done") and isinstance(rec.get("eval_count"), int):
                return rec["eval_count"]
        return max(0, n_lines - 1)

    def _shed_delay(self, resp, attempt: int) -> float:
        """Backoff before retrying a 429/503: the server's Retry-After
        hint plus FULL-jitter exponential backoff — uniform on
        [0, base·2^attempt], capped. Multiplicative jitter (hint ×
        1.0–1.25) kept 80% of a synchronized shed wave inside a 25%
        window, re-stampeding the router right at the hinted second;
        full jitter spreads the wave across the whole backoff span
        (Exponential Backoff And Jitter, AWS architecture blog)."""
        base = float(self.config.get("retry_backoff_s", 0.25))
        cap = float(self.config.get("retry_backoff_cap_s", 10.0))
        try:
            hinted = float(resp.headers.get("Retry-After", ""))
        except ValueError:
            hinted = 0.0
        return hinted + random.uniform(0.0, min(cap, base * (2 ** attempt)))

    def _consume_retry(self) -> bool:
        """Take one token from the shared retry budget. False means the
        pool is dry: shed instead of retrying (single-threaded asyncio,
        so the read-decrement needs no lock)."""
        if self._retry_budget is None:
            return True
        if self._retry_budget <= 0:
            return False
        self._retry_budget -= 1
        return True

    async def inference_call(self, session: aiohttp.ClientSession,
                             prompt: str, len_output: int, sleep_time: float,
                             query_id: int,
                             priority: Optional[str] = None) -> None:
        collector = self.logger
        await asyncio.sleep(sleep_time)
        # Load-shed resilience: a chaos- or admission-control-enabled
        # server answers 429/503 + Retry-After instead of queueing;
        # retrying with backoff turns those into clean latency records
        # (num_retries) instead of raw failures. Budget exhaustion is
        # recorded as a shed query, still not an exception.
        max_retries = int(self.config.get("max_retries", 4))
        # End-to-end tracing: a client-minted X-Request-Id joins this
        # harness's per-query metrics to the server's structured logs
        # and /debug/requests spans (the server echoes it back).
        trace_id = f"tg-{query_id}"
        headers = {"X-Request-Id": trace_id}
        if priority:
            headers["X-Priority"] = priority
        try:
            for attempt in range(max_retries + 1):
                async with session.post(
                        self.config["url"],
                        json=self._payload(prompt, len_output),
                        headers=headers,
                        trace_request_ctx={"query_id": query_id,
                                           "collector": collector}) as resp:
                    if resp.status in (429, 503):
                        if attempt >= max_retries:
                            collector.record_shed(query_id)
                            print(f"[SHED] query {query_id}: "
                                  f"{resp.status} after {attempt} retries")
                            return
                        if not self._consume_retry():
                            collector.record_shed(query_id)
                            print(f"[SHED] query {query_id}: "
                                  f"{resp.status}, retry budget "
                                  "exhausted")
                            return
                        delay = self._shed_delay(resp, attempt)
                        collector.record_retry(query_id)
                        print(f"[RETRY] query {query_id}: {resp.status}, "
                              f"backoff {delay:.2f}s")
                        await asyncio.sleep(delay)
                        continue
                    resp.raise_for_status()
                    collector.record(query_id, "request_id",
                                     resp.headers.get("X-Request-Id",
                                                      trace_id))
                    await self._consume_stream(resp, query_id)
                    return
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            # ClientError covers response/connection AND payload errors
            # (mid-stream resets); one failed query must never abort the
            # whole gather and lose the run's metrics.
            collector.record(query_id, "success", False)
            print(f"[FAIL] query {query_id}: {exc!r}")

    async def _consume_stream(self, resp, query_id: int) -> None:
        """Stream the NDJSON body of one successful response, recording
        TTFT, end-to-end latency, token count, and chunk smoothness."""
        collector = self.logger
        first = True
        n_lines = 0
        buf = b""
        last_line = b""
        # Streaming smoothness: fused K-step decode flushes tokens
        # in bursts, so the worst inter-chunk gap (not just mean
        # TPOT) is what a user perceives as a stall. Additive
        # metric field; reference schema otherwise preserved.
        prev_chunk_t = None
        max_gap = 0.0
        async for _chunk in resp.content:
            now = collector.elapsed()
            if first:
                collector.record(query_id, "first_token_arrive_time", now)
                first = False
            else:
                max_gap = max(max_gap, now - prev_chunk_t)
            prev_chunk_t = now
            n_lines += _chunk.count(b"\n")
            # Track the last COMPLETE line whole: the terminal
            # record carries the full `context` id list and can be
            # arbitrarily long, so a fixed-size tail would truncate
            # it on exactly the long requests being measured.
            buf += _chunk
            if b"\n" in buf:
                parts = buf.split(b"\n")
                last_line = parts[-2]
                buf = parts[-1]
        collector.record(query_id, "response_end_time", collector.elapsed())
        collector.record(query_id, "num_output_tokens",
                         self._count_tokens(buf or last_line, n_lines))
        collector.record(query_id, "max_interchunk_gap", max_gap)
        collector.record(query_id, "success", True)
        end = collector.metrics[query_id]["response_end_time"]
        start = collector.metrics[query_id].get("request_start_time", end)
        # Per-request turnaround line (reference main.py:267).
        print(f"[END] ID: {query_id}, End: {end:.1f}, "
              f"turnaround: {end - start:.1f}")

    async def issue_queries(self) -> dict:
        timeout = aiohttp.ClientTimeout(
            total=float(self.config.get("request_timeout", 600.0)))
        # trust_env so NO_PROXY/HTTP(S)_PROXY are honored (the reference's
        # `no_proxy` config key / commented NO_PROXY export, main.py:316).
        async with aiohttp.ClientSession(
                trace_configs=[self.logger.trace_config],
                timeout=timeout, trust_env=True) as session:
            calls = []
            for _ in range(len(self.queries)):
                prompt, len_p, len_g, qid, t = self.queries.get_query()
                self.logger.init_query(qid, len_p, t)
                pcls = self._next_class()
                if pcls is not None:
                    self.logger.record(qid, "priority_class", pcls)
                calls.append(self.inference_call(session, prompt, len_g, t,
                                                 qid, priority=pcls))
            self.logger.start_session()
            await asyncio.gather(*calls)
        if self.logger.retries_total or self.logger.shed_total:
            print(f"[RESILIENCE] retries={self.logger.retries_total} "
                  f"shed={self.logger.shed_total}")
        if self._class_mix:
            for name, summ in self.logger.class_summary().items():
                print(f"[CLASS] {name}: n={summ['requests']} "
                      f"ttft_p95={summ['ttft_s']['p95']} "
                      f"e2e_p95={summ['e2e_s']['p95']}")
        return self.logger.metrics

    def start_profile(self) -> dict:
        self.queries.reset()
        return asyncio.run(self.issue_queries())
