"""Benchmark harness entry point (reference: main.py:298-343).

``python traffic_generator/main.py`` replays a BurstGPT-format trace against
an Ollama-protocol endpoint and writes per-request latency metrics to JSON.
The config dict keys match the reference (trace_path, data_path, max_trace,
url, model, temperature, max_tokens, log_path), and argparse overrides are
enabled (the reference left argparse commented out, main.py:4).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from traffic_generator.data import DataLoader  # noqa: E402
from traffic_generator.generator import TrafficGenerator  # noqa: E402
from traffic_generator.metrics import MetricCollector  # noqa: E402
from traffic_generator.schedule import Scheduler  # noqa: E402

MAX_PROMPT_LEN = 1024
MAX_GEN_LEN = 1024

config = {
    "trace_path": "data/trace1.csv",
    "data_path": "data/conversations.json",
    "max_trace": 100,
    "url": "http://127.0.0.1:11434/api/generate",
    "no_proxy": "",           # set NO_PROXY for LAN endpoints (main.py:307)
    "model": "tiny-llama",
    "temperature": 0.0,
    "max_tokens": None,       # None -> per-query length from the trace
    "stream": True,
    "save_log": True,         # reference main.py:311 (there: declared only)
    "log_path": "logs/log.json",
    # Load-shed resilience: 429/503 responses (chaos mode / admission
    # control) retry with exponential backoff + jitter, honoring the
    # server's Retry-After hint, before counting as shed.
    "max_retries": 4,
    "retry_backoff_s": 0.25,
    # Priority-class mix, e.g. "interactive:0.8,batch:0.15,
    # background:0.05": tags queries with X-Priority in those
    # proportions and prints a per-class TTFT/E2E breakdown. "" = off.
    "class_mix": "",
}


def parse_args() -> dict:
    p = argparse.ArgumentParser(description="BurstGPT trace replay harness")
    for key, val in config.items():
        arg = "--" + key.replace("_", "-")
        if isinstance(val, bool):
            p.add_argument(arg, default=val,
                           type=lambda s: s.lower() not in ("0", "false", "no"))
        elif val is None:
            p.add_argument(arg, default=None)
        else:
            p.add_argument(arg, type=type(val), default=val)
    return vars(p.parse_args())


def main() -> dict:
    cfg = {**config, **{k: v for k, v in parse_args().items() if v is not None}}
    if cfg.get("no_proxy"):
        os.environ["NO_PROXY"] = cfg["no_proxy"]
    data = DataLoader.get_data_from_path(cfg["data_path"])
    schedule = Scheduler.get_schedule_from_trace(cfg["trace_path"],
                                                 cfg["max_trace"])
    print(schedule)
    collector = MetricCollector()
    generator = TrafficGenerator(data, schedule, cfg, collector,
                                 max_prompt_len=MAX_PROMPT_LEN,
                                 max_gen_len=MAX_GEN_LEN)
    metrics = generator.start_profile()
    print(metrics)
    if cfg.get("class_mix"):
        import json as _json
        print(_json.dumps(collector.class_summary(), indent=1))
    if cfg.get("save_log", True):
        log_path = cfg["log_path"]
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        collector.save(log_path)
    return metrics


if __name__ == "__main__":
    main()
