"""Multi-turn conversation benchmark (BASELINE.json config 3 workload).

Simulates C concurrent chat sessions of T turns each against the
in-process Ollama-protocol server. Every turn resends the full
conversation so far plus a new user message — exactly how the
reference's interactive chat loop accumulates context (reference:
notebooks/request_demo.ipynb cell 4d5cf82f keeps `context` across
turns) — so each request's prompt is a strict extension of the previous
turn's prompt + response. That is the workload the prefix cache
(engine/prefix_cache.py) exists for: turn N's prefill should reuse turn
N-1's published KV pages and recompute only the new suffix.

Reported per run: per-turn-index TTFT (flat-ish with the cache, growing
~linearly with context without it), aggregate TTFT/TPOT percentiles,
server-side prefix-hit tokens. ``--compare`` runs the same workload a
second time with the prefix cache disabled and reports the speedup.

``--compare-routing`` runs the same pinned mix on a dp>=2 fleet twice —
routing=least_loaded then routing=prefix_affinity — and commits the
cache-aware-routing artifact: the least-loaded router sends a returning
conversation to a cold replica ~(dp-1)/dp of the time (full-history
re-prefill), the affinity router routes it back to its warm replica, so
the artifact compares prefix-hit pages, TTFT and tok/s, and checks the
greedy outputs are byte-identical across both policies (routing is a
placement decision, never a behavior change).

Usage:
    python benchmarks/multiturn.py --model tiny-llama --conversations 6 \
        --turns 5 --compare --out benchmarks/results/config3_multiturn.json
    python benchmarks/multiturn.py --smoke --compare-routing \
        --out benchmarks/results/multiturn_routing.json
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from benchmarks.replay import _percentiles, start_server  # noqa: E402

USER_TOPICS = [
    "Tell me about the weather patterns in the Pacific Northwest.",
    "How does that compare to the East Coast?",
    "What should I pack for a trip there in October?",
    "Are there any hiking trails you would recommend?",
    "How difficult is the most popular one?",
    "What wildlife might I encounter on the trail?",
    "Is it safe to hike alone in that area?",
    "What emergency supplies should I carry?",
]


async def _one_conversation(session, url: str, model: str, conv_id: int,
                            turns: int, max_tokens: int) -> list[dict]:
    """Run one chat session; each turn resends the accumulated history."""
    records = []
    history = ""
    for t in range(turns):
        # Tag the session id into every user message so conversations
        # are DISTINCT token streams (like real users): otherwise greedy
        # decoding makes every conversation an identical clone, every
        # replica warms up for the one shared prefix, and both the
        # cache and routing comparisons measure nothing.
        user_msg = (f"[session {conv_id}] "
                    f"{USER_TOPICS[t % len(USER_TOPICS)]}")
        prompt = f"{history}User: {user_msg}\nAssistant:"
        payload = {"model": model, "prompt": prompt, "temperature": 0.0,
                   "stream": True, "options": {"num_predict": max_tokens}}
        t0 = time.perf_counter()
        ttft = None
        chunks = []
        n_tokens = 0
        async with session.post(url, json=payload) as resp:
            resp.raise_for_status()
            async for line in resp.content:
                if not line.strip():
                    continue
                if ttft is None:
                    ttft = time.perf_counter() - t0
                rec = json.loads(line)
                if rec.get("response"):
                    chunks.append(rec["response"])
                if rec.get("done"):
                    n_tokens = rec.get("eval_count", len(chunks))
        e2e = time.perf_counter() - t0
        reply = "".join(chunks)
        history = prompt + reply + "\n"
        records.append({
            "conv": conv_id, "turn": t, "prompt_chars": len(prompt),
            "ttft_s": ttft, "e2e_s": e2e, "output_tokens": n_tokens,
            "tpot_s": ((e2e - ttft) / (n_tokens - 1)
                       if ttft is not None and n_tokens > 1 else None),
            # Reply text rides along (stripped before the artifact) so
            # the routing comparison can hash the full transcript set.
            "reply": reply,
        })
    return records


def _outputs_sha256(records: list[dict]) -> str:
    """Digest of every conversation's full transcript, in (conv, turn)
    order — deterministic regardless of completion interleaving, so two
    runs of the same greedy workload match iff their outputs are
    byte-identical."""
    h = hashlib.sha256()
    for r in sorted(records, key=lambda r: (r["conv"], r["turn"])):
        h.update(f"{r['conv']}:{r['turn']}:".encode())
        h.update(r["reply"].encode())
        h.update(b"\x00")
    return h.hexdigest()


async def _drive(port: int, model: str, conversations: int, turns: int,
                 max_tokens: int) -> list[dict]:
    import aiohttp

    url = f"http://127.0.0.1:{port}/api/generate"
    timeout = aiohttp.ClientTimeout(total=1800)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        results = await asyncio.gather(*[
            _one_conversation(session, url, model, c, turns, max_tokens)
            for c in range(conversations)])
    return [r for conv in results for r in conv]


def _summarize(records: list[dict], turns: int) -> dict:
    ttfts = [r["ttft_s"] for r in records if r["ttft_s"] is not None]
    tpots = [r["tpot_s"] for r in records if r["tpot_s"] is not None]
    # Returning turns (>= 1) are the prefix-cache beneficiaries: their
    # history was served before, so their TTFT is what tiering/routing
    # exist to cut. First turns are cold by construction.
    returning = [r["ttft_s"] for r in records
                 if r["turn"] > 0 and r["ttft_s"] is not None]
    by_turn = []
    for t in range(turns):
        xs = [r["ttft_s"] for r in records
              if r["turn"] == t and r["ttft_s"] is not None]
        by_turn.append(round(float(np.median(xs)), 4) if xs else None)
    return {
        "requests": len(records),
        "output_tokens": int(sum(r["output_tokens"] for r in records)),
        "ttft_s": _percentiles(ttfts, ps=(50, 95, 99)),
        "ttft_returning_s": _percentiles(returning, ps=(50, 95, 99)),
        "tpot_s": _percentiles(tpots),
        "ttft_p50_by_turn": by_turn,
        "final_prompt_chars_p50": round(float(np.median(
            [r["prompt_chars"] for r in records
             if r["turn"] == turns - 1])), 0) if records else None,
    }


def _working_set_pages(records: list[dict], turns: int,
                       page_size: int) -> int:
    """The run's KV working set in pages: every conversation's FINAL
    context (prompt + reply; byte tokenizer => chars ~ tokens), summed.
    This is what the prefix cache would need resident to serve every
    returning turn warm — the number the HBM pool is deliberately sized
    ~5x below in the tiering comparison."""
    total = 0
    for r in records:
        if r["turn"] == turns - 1:
            total += -(-(r["prompt_chars"] + r["output_tokens"])
                       // page_size)
    return total


def run_once(args, enable_prefix_cache: bool) -> dict:
    args.enable_prefix_cache = enable_prefix_cache
    srv, port, stop = start_server(args)
    try:
        t0 = time.perf_counter()
        records = asyncio.run(_drive(port, args.model, args.conversations,
                                     args.turns, args.max_tokens))
        wall = time.perf_counter() - t0
        summary = _summarize(records, args.turns)
        summary["wall_s"] = round(wall, 3)
        summary["tok_s"] = round(summary["output_tokens"] / wall, 2)
        summary["outputs_sha256"] = _outputs_sha256(records)
        summary["working_set_pages"] = _working_set_pages(
            records, args.turns, args.page_size)
        stats = srv.group.stats_snapshot()
        summary["prefix_cache_enabled"] = enable_prefix_cache
        summary["tokens_prefix_cached"] = stats.get("tokens_prefix_cached", 0)
        summary["prefix_cache"] = stats.get("prefix_cache")
        summary["swap_in_resumes"] = stats.get("swap_in_resumes", 0)
        summary["steps"] = stats.get("steps")
        summary["prefills"] = stats.get("prefills")
        # Router view (dp>1): warm/cold dispatch counts and the cached
        # pages the router counted on, per replica and fleet-wide.
        group = srv.group
        summary["routing"] = {
            "mode": group.server_cfg.routing,
            "dp": len(group.engines),
            "route_prefix_hits": group.route_prefix_hits,
            "route_cold": group.route_cold,
            "route_hit_pages": sum(st["hit_pages"]
                                   for st in group._route_stats),
            "per_replica": [dict(st) for st in group._route_stats],
        }
    finally:
        stop()
    return summary


def _compare_routing(args) -> dict:
    """Run the pinned multi-turn mix on a dp>=2 fleet under
    routing=least_loaded then routing=prefix_affinity (fresh servers
    each) and commit the side-by-side artifact: prefix-hit pages, TTFT
    p50/p95, tok/s, and the byte-identity check on greedy outputs."""
    args.dp = max(getattr(args, "dp", 1), 2)
    cfg_snapshot = dict(vars(args))
    summaries = {}
    for mode in ("least_loaded", "prefix_affinity"):
        args.routing = mode
        print(f"[multiturn] routing={mode} lane", file=sys.stderr)
        summaries[mode] = run_once(args, enable_prefix_cache=True)
    ll, aff = summaries["least_loaded"], summaries["prefix_affinity"]

    def _pages(s):
        # Server-side truth: prompt tokens actually served from KV reuse,
        # in page units (what the affinity router exists to maximize).
        return s["tokens_prefix_cached"] // args.page_size

    comparison = {
        "dp": args.dp,
        "cached_prompt_pages_least_loaded": _pages(ll),
        "cached_prompt_pages_prefix_affinity": _pages(aff),
        "route_hit_pages_least_loaded": ll["routing"]["route_hit_pages"],
        "route_hit_pages_prefix_affinity": aff["routing"]["route_hit_pages"],
        "route_warm_dispatches_least_loaded":
            ll["routing"]["route_prefix_hits"],
        "route_warm_dispatches_prefix_affinity":
            aff["routing"]["route_prefix_hits"],
        "ttft_p50_least_loaded_s": ll["ttft_s"]["p50"],
        "ttft_p50_prefix_affinity_s": aff["ttft_s"]["p50"],
        "ttft_p95_least_loaded_s": ll["ttft_s"]["p95"],
        "ttft_p95_prefix_affinity_s": aff["ttft_s"]["p95"],
        "tok_s_least_loaded": ll["tok_s"],
        "tok_s_prefix_affinity": aff["tok_s"],
        # Greedy decoding + identical weights per replica (same init
        # seed): routing must be a pure placement decision.
        "outputs_identical": bool(
            ll["outputs_sha256"] == aff["outputs_sha256"]),
        # Wall-clock TTFT swings on a loaded CI box, so the claim is
        # split (same stance as replay's tok_s_within_5pct): the
        # deterministic part — affinity routed strictly more cached
        # pages, byte-identically — is what the tier-1 smoke asserts;
        # the latency win is graded on the artifact actually committed.
        "ttft_p95_improved": bool(
            aff["ttft_s"]["p95"] is not None
            and ll["ttft_s"]["p95"] is not None
            and aff["ttft_s"]["p95"] < ll["ttft_s"]["p95"]),
        "affinity_wins": bool(
            _pages(aff) > _pages(ll)
            and aff["routing"]["route_hit_pages"]
            > ll["routing"]["route_hit_pages"]
            and ll["outputs_sha256"] == aff["outputs_sha256"]),
    }
    out = {"config": cfg_snapshot, "least_loaded": ll,
           "prefix_affinity": aff, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    result = dict(comparison)
    result["least_loaded"], result["prefix_affinity"] = ll, aff
    return result


def _compare_tiering(args) -> dict:
    """Tiered-KV-cache comparison (README "Tiered KV cache"): replay the
    multi-turn mix against an HBM pool deliberately sized ~5x SMALLER
    than the conversations' KV working set, twice — host tier off
    (evictions destroy KV; returning turns re-prefill their history)
    then on (evictions demote to host RAM; returning turns swap back
    in) — and commit the side-by-side artifact: total cached tokens
    served, returning-turn TTFT p95, swap counters, and the byte-
    identity check on greedy outputs (tiering is a memory-placement
    decision, never a behavior change)."""
    # Size the pool from the workload so the working set oversubscribes
    # it ~working_set_factor x: per-conversation final context ~ turns *
    # (user message + tag + protocol overhead + reply tokens), byte
    # tokenizer => chars ~ tokens. The per-sequence cap (and reserve
    # admission's worst case) still fits inside the pool.
    if not args.smoke:
        # Enough concurrent conversations that the working set genuinely
        # dwarfs the pool even after the one-sequence-must-fit floor on
        # num_pages below.
        args.conversations = max(args.conversations, 10)
    per_conv = args.turns * (65 + args.max_tokens)
    ws_pages_est = args.conversations * -(-per_conv // args.page_size)
    per_seq = -(-per_conv // args.page_size) + \
        -(-args.max_tokens // args.page_size) + 2
    factor = args.working_set_factor
    args.num_pages = max(per_seq + 4, int(ws_pages_est / factor))
    args.max_pages_per_seq = min(args.max_pages_per_seq,
                                 args.num_pages - 2)
    # Byte-identity across arms requires every prefill chunk to compile
    # to ONE query shape: a cold re-prefill (one big bucket) and a warm
    # tail (small bucket) otherwise run different XLA graphs, whose
    # reduction orders differ in ulps — enough to flip greedy argmax on
    # near-ties. Chunking at the smallest bucket pins the shape.
    if not args.chunked_prefill_size:
        args.chunked_prefill_size = 16 if args.smoke else 64
    host_pages = args.host_cache_pages or 2 * ws_pages_est
    cfg_snapshot = dict(vars(args))
    # The config block must reproduce the TIERED arm (the hbm_only arm
    # is the same config with host_cache_pages=0 — recorded per arm).
    cfg_snapshot["host_cache_pages"] = host_pages
    summaries = {}
    for mode, pages in (("hbm_only", 0), ("tiered", host_pages)):
        args.host_cache_pages = pages
        print(f"[multiturn] tiering={mode} lane "
              f"(num_pages={args.num_pages}, host_cache_pages={pages})",
              file=sys.stderr)
        summaries[mode] = run_once(args, enable_prefix_cache=True)
    off, on = summaries["hbm_only"], summaries["tiered"]
    pool = args.num_pages - 1
    ws = max(off["working_set_pages"], on["working_set_pages"])
    tiered_pc = on.get("prefix_cache") or {}
    comparison = {
        "hbm_pool_pages": pool,
        "host_cache_pages": host_pages,
        "working_set_pages": ws,
        "working_set_over_pool": round(ws / pool, 2),
        "cached_tokens_hbm_only": off["tokens_prefix_cached"],
        "cached_tokens_tiered": on["tokens_prefix_cached"],
        "offloaded_pages": tiered_pc.get("offloaded_pages", 0),
        "restored_pages": tiered_pc.get("restored_pages", 0),
        "swap_in_resumes": on.get("swap_in_resumes", 0),
        "ttft_returning_p95_hbm_only_s": off["ttft_returning_s"]["p95"],
        "ttft_returning_p95_tiered_s": on["ttft_returning_s"]["p95"],
        "tok_s_hbm_only": off["tok_s"],
        "tok_s_tiered": on["tok_s"],
        # Greedy decoding + identical weights/seed: tiering must be a
        # pure memory-placement decision.
        "outputs_identical": bool(
            off["outputs_sha256"] == on["outputs_sha256"]),
        # Wall-clock TTFT swings on a loaded CI box, so the claim is
        # split (same stance as the routing artifact): the
        # deterministic part — strictly more cached tokens served, with
        # real demote/restore traffic, byte-identically — is what the
        # tier-1 smoke asserts; the latency win is graded on the
        # artifact actually committed.
        "ttft_returning_p95_improved": bool(
            on["ttft_returning_s"]["p95"] is not None
            and off["ttft_returning_s"]["p95"] is not None
            and on["ttft_returning_s"]["p95"]
            < off["ttft_returning_s"]["p95"]),
        "tiering_wins": bool(
            on["tokens_prefix_cached"] > off["tokens_prefix_cached"]
            and tiered_pc.get("restored_pages", 0) > 0
            and off["outputs_sha256"] == on["outputs_sha256"]),
    }
    out = {"config": cfg_snapshot, "hbm_only": off, "tiered": on,
           "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    result = dict(comparison)
    result["hbm_only"], result["tiered"] = off, on
    return result


def main() -> dict:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="tiny-llama")
    p.add_argument("--tokenizer", default="byte")
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--draft-model", default=None)
    p.add_argument("--draft-checkpoint", default=None)
    p.add_argument("--num-speculative-tokens", type=int, default=0)
    p.add_argument("--conversations", type=int, default=6)
    p.add_argument("--turns", type=int, default=5)
    p.add_argument("--max-tokens", type=int, default=48,
                   help="assistant tokens per turn")
    # Consumed by the shared replay.start_server (its parser grew
    # --sp/--sp-attn in r4; this parser must carry them too).
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel prefill degree")
    p.add_argument("--sp-attn", default="ring", choices=("ring", "ulysses"))
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel replicas (requests route per "
                        "--routing; --compare-routing forces >= 2)")
    p.add_argument("--routing", default="prefix_affinity",
                   choices=("prefix_affinity", "least_loaded"),
                   help="dp replica routing policy")
    p.add_argument("--route-hit-weight", type=float, default=1.0,
                   help="prefix-affinity: routing-score pages one peeked "
                        "cache-hit page is worth")
    p.add_argument("--route-host-hit-weight", type=float, default=0.5,
                   help="prefix-affinity: routing-score pages one peeked "
                        "HOST-tier hit page is worth (HBM-warm > "
                        "host-warm > cold)")
    p.add_argument("--host-cache-pages", type=int, default=0,
                   help="host-RAM KV tier capacity (0 = off; "
                        "--compare-tiering sizes it from the working "
                        "set when left at 0)")
    p.add_argument("--working-set-factor", type=float, default=5.0,
                   help="--compare-tiering: size the HBM pool so the "
                        "conversations' KV working set oversubscribes "
                        "it by about this factor")
    p.add_argument("--max-batch-size", type=int, default=8)
    p.add_argument("--chunked-prefill-size", type=int, default=0,
                   help="prefill chunk tokens (0 = largest bucket); the "
                        "tiering comparison pins it to the smallest "
                        "bucket so every chunk compiles to ONE query "
                        "shape and greedy outputs stay byte-identical "
                        "across arms (XLA reduction order is "
                        "shape-dependent)")
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-pages-per-seq", type=int, default=64)
    p.add_argument("--decode-steps-per-call", type=int, default=8)
    p.add_argument("--decode-pipeline-depth", type=int, default=1)
    p.add_argument("--quant", default="none", choices=("none", "int8"))
    p.add_argument("--kv-quant", default="none",
                   choices=("none", "int8", "int4"))
    p.add_argument("--platform", default="auto",
                   choices=("auto", "cpu", "tpu"),
                   help="jax platform; 'cpu' forces the CPU backend "
                        "before any computation (same pattern as "
                        "replay.py / tests/conftest.py)")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--compare", action="store_true",
                   help="also run with the prefix cache disabled and "
                        "report the TTFT delta")
    p.add_argument("--compare-routing", action="store_true",
                   help="run the mix on a dp>=2 fleet under least-loaded "
                        "then prefix-affinity routing and commit a "
                        "prefix-hit-pages / TTFT / tok_s comparison "
                        "artifact with a byte-identity check")
    p.add_argument("--compare-tiering", action="store_true",
                   help="replay the mix with the HBM pool sized ~5x "
                        "below the KV working set, host tier off vs on, "
                        "and commit a cached-tokens / returning-TTFT / "
                        "swap-traffic artifact with a byte-identity "
                        "check")
    p.add_argument("--smoke", action="store_true",
                   help="CPU smoke lane (tier-1): tiny model, small "
                        "conversation mix, small engine + prefill "
                        "buckets — exercises the full dp=2 routing "
                        "comparison in seconds")
    p.add_argument("--out", default=None)
    args = p.parse_args()

    if sum((args.compare, args.compare_routing, args.compare_tiering)) > 1:
        p.error("--compare / --compare-routing / --compare-tiering are "
                "mutually exclusive; run them as separate invocations")

    if args.smoke:
        # One switch pins every knob to the CPU-affordable shape so the
        # tier-1 lane cannot drift from what CI actually runs (replay.py
        # --smoke stance). Small pages make the pinned mix cache-dense:
        # every turn's history re-lands on page boundaries quickly.
        args.model, args.tokenizer = "tiny-llama", "byte"
        args.platform = "cpu"
        # ODD conversation count: with an even count and a near-idle
        # fleet, the rotating tie-break cursor's parity can stay
        # constant per conversation, giving the least-loaded arm
        # accidental perfect stickiness (both arms fully warm -> the
        # routing comparison flakes to a tie on fast boxes). An odd
        # count flips the parity every round, so least-loaded provably
        # migrates conversations across replicas.
        args.conversations = min(args.conversations, 5)
        args.turns = min(args.turns, 4)
        args.max_tokens = min(args.max_tokens, 12)
        args.max_batch_size, args.num_pages = 4, 256
        args.page_size, args.max_pages_per_seq = 8, 48
        args.decode_steps_per_call = 4
        if args.compare_tiering:
            # The tiering smoke needs real churn in seconds: a ~3x
            # oversubscribed pool is enough to force demotes/restores
            # on CPU (_compare_tiering recomputes num_pages from this).
            args.working_set_factor = min(args.working_set_factor, 3.0)
        if args.out is None and args.compare_routing:
            args.out = "benchmarks/results/multiturn_routing.json"
        if args.out is None and args.compare_tiering:
            args.out = "benchmarks/results/multiturn_tiering.json"

    if args.platform != "auto":
        # Before any jax computation (env vars are read too early in
        # some images; jax.config is the reliable override). Inside an
        # already-initialized process (the in-pytest smoke) both calls
        # are harmless no-ops and the session's devices win.
        import jax

        jax.config.update("jax_platforms", args.platform)
        if args.platform == "cpu":
            from tpu_inference.compat import set_cpu_device_count

            need = max(args.dp, 2 if args.compare_routing else 1)
            set_cpu_device_count(max(1, need * args.tp * args.sp))

    if args.compare_routing:
        return _compare_routing(args)
    if args.compare_tiering:
        return _compare_tiering(args)

    # Snapshot before run_once mutates args (enable_prefix_cache toggles).
    out = {"config": dict(vars(args))}
    out["cached"] = run_once(args, enable_prefix_cache=True)
    if args.compare:
        out["uncached"] = run_once(args, enable_prefix_cache=False)
        c, u = out["cached"], out["uncached"]
        if c["ttft_s"]["p50"] and u["ttft_s"]["p50"]:
            out["ttft_p50_speedup_from_cache"] = round(
                u["ttft_s"]["p50"] / c["ttft_s"]["p50"], 3)
    print(json.dumps({k: v for k, v in out.items() if k != "config"},
                     indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    main()
