"""Synthesize the harness datasets the reference declares but doesn't ship.

``data/BurstGPT_1.csv`` and ``data/conversations.json`` are listed in the
reference's ``.MISSING_LARGE_BLOBS`` (not present in the mount), so this
regenerates statistically similar stand-ins, deterministically:

- conversations.json: corpus of prompts binned by token length (schema per
  SURVEY.md §2a #3: id -> {prompt, len_prompt, len_output, output}). Prompts
  are ASCII so byte-tokenized length == char length, letting tests reason
  about token counts exactly.
- BurstGPT_1.csv: synthetic arrival trace (gamma inter-arrivals, lognormal
  token lengths — the shape BurstGPT exhibits) with the column set the
  reference's notebooks read: Timestamp, Request tokens, Response tokens.
- trace1.csv: 6-row toy trace in the same format as the reference's
  committed copy (reference data/trace1.csv).

Run: ``python benchmarks/make_data.py [--out data]``
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

WORDS = ("the quick brown fox jumps over a lazy dog while many small "
         "systems stream tokens across fast networks to measure latency "
         "under bursty load patterns every single day").split()


def text_of_token_len(rng: np.random.Generator, n_tokens: int) -> str:
    """ASCII text of exactly n_tokens bytes (byte tokenizer: 1 byte/token)."""
    parts = []
    size = 0
    while size < n_tokens:
        w = WORDS[rng.integers(len(WORDS))]
        parts.append(w)
        size += len(w) + 1
    text = " ".join(parts)[:n_tokens]
    return text.ljust(n_tokens, "x")


def make_conversations(rng: np.random.Generator, path: str,
                       n_per_bin: int = 3) -> None:
    prompt_bins = [2, 4, 8, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512,
                   768, 1024]
    output_bins = [4, 16, 64, 200, 512, 1024]
    corpus = {}
    idx = 0
    for p in prompt_bins:
        for g in output_bins:
            for _ in range(n_per_bin if p <= 256 else 1):
                corpus[str(idx)] = {
                    "prompt": text_of_token_len(rng, p),
                    "len_prompt": p,
                    "len_output": g,
                    "output": text_of_token_len(rng, min(g, 128)),
                }
                idx += 1
    with open(path, "w") as f:
        json.dump(corpus, f)
    print(f"wrote {path}: {len(corpus)} entries")


def make_burstgpt(rng: np.random.Generator, path: str,
                  n_rows: int = 10000, mean_interarrival: float = 0.5) -> None:
    inter = rng.gamma(shape=0.6, scale=mean_interarrival / 0.6, size=n_rows)
    ts = np.cumsum(inter)
    ts[0] = 0.0
    req = np.clip(rng.lognormal(mean=5.8, sigma=1.0, size=n_rows),
                  2, 8192).astype(int)
    resp = np.clip(rng.lognormal(mean=5.0, sigma=1.0, size=n_rows),
                   1, 2048).astype(int)
    with open(path, "w") as f:
        f.write("Timestamp,Request tokens,Response tokens\n")
        for t, p, g in zip(ts, req, resp):
            f.write(f"{t:.3f},{p},{g}\n")
    print(f"wrote {path}: {n_rows} rows")


def make_trace1(path: str) -> None:
    rows = [(0, 472, 18), (1, 1087, 230), (2, 417, 276), (3, 1360, 647),
            (4, 185, 215), (5, 586, 293)]
    with open(path, "w") as f:
        f.write("Timestamp,Request tokens,Response tokens\n")
        for t, p, g in rows:
            f.write(f"{t},{p},{g}\n")
    print(f"wrote {path}: {len(rows)} rows")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data")
    ap.add_argument("--rows", type=int, default=10000)
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)
    rng = np.random.default_rng(20260729)
    make_conversations(rng, os.path.join(args.out, "conversations.json"))
    make_burstgpt(rng, os.path.join(args.out, "BurstGPT_1.csv"),
                  n_rows=args.rows)
    make_trace1(os.path.join(args.out, "trace1.csv"))


if __name__ == "__main__":
    main()
