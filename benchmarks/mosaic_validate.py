"""Mosaic-validate the window-aware Pallas kernels on the real chip.

VERDICT r4 item 4: the SWA decode/prefill kernels and the SP attention
wrappers had only ever run under interpret-mode Pallas / virtual CPU
meshes; interpret mode never exercises the Mosaic compiler, so a TPU
lowering failure would be invisible until a serving bet was placed on
them. This lane runs each kernel NON-interpret at small shapes against
the dense window-masked oracle and writes one JSON artifact.

Checks (each timed; first run includes the Mosaic/XLA compile):
  swa_decode    paged_attention(sliding_window=W, interpret=False)
  swa_decode8   same on the int8 KV pool (in-kernel dequant + window)
  swa_prefill   paged_prefill_attention(sliding_window=W, interpret=False)
  swa_prefill8  same on the int8 pool
  ring_swa      windowed ring attention over a 1-device mesh (shard_map
                compiles on the TPU backend; axis size is what the
                hardware offers)
  ulysses_swa   windowed Ulysses over the same mesh

Usage:  python benchmarks/mosaic_validate.py [--out PATH]
Exit 0 iff every check passes. Runs on the default platform — point it
at the chip (the battery does); on CPU it still passes but proves
nothing about Mosaic (artifact records the platform).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="benchmarks/results/mosaic_r5.json")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tpu_inference.engine import kv_cache as kvc
    from tpu_inference.kernels.paged_attention import paged_attention
    from tpu_inference.kernels.prefill_attention import (
        paged_prefill_attention)
    from tpu_inference.kernels.ring_attention import ring_attention
    from tpu_inference.kernels.ulysses_attention import ulysses_attention
    from tpu_inference.models import common

    platform = jax.devices()[0].platform
    rec = {"platform": platform, "checks": {}, "ok": True}
    rng = np.random.default_rng(23)

    # Shared pool geometry: TPU-tile-friendly head dim, window crossing
    # page boundaries, ragged kv lens shorter and longer than the window.
    page, mp, hq, hkv, d, window = 8, 6, 4, 2, 128, 11
    b = 3
    n_pages = 32
    kv_lens = np.array([5, 17, 41], np.int32)
    k_pool = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    v_pool = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    bt = rng.permutation(np.arange(1, 1 + b * mp)).reshape(b, mp).astype(
        np.int32)

    def check(name, fn):
        t0 = time.perf_counter()
        try:
            err = fn()
            dt = time.perf_counter() - t0
            rec["checks"][name] = {"ok": err is None, "wall_s": round(dt, 2),
                                   **({"error": err} if err else {})}
            if err:
                rec["ok"] = False
            print(f"[mosaic] {name}: {'ok' if not err else 'FAIL'} "
                  f"({dt:.1f}s){'' if not err else ' ' + err}")
        except Exception as e:                        # noqa: BLE001
            dt = time.perf_counter() - t0
            rec["checks"][name] = {"ok": False, "wall_s": round(dt, 2),
                                   "error": f"{type(e).__name__}: {e}"}
            rec["ok"] = False
            print(f"[mosaic] {name}: RAISED ({dt:.1f}s) "
                  f"{type(e).__name__}: {e}")

    def decode_ref(kp, vp, q):
        outs = []
        for i in range(b):
            n = int(kv_lens[i])
            fk = np.concatenate([kp[bt[i, j]] for j in range(mp)])[:n]
            fv = np.concatenate([vp[bt[i, j]] for j in range(mp)])[:n]
            outs.append(np.asarray(common.dense_causal_attention(
                jnp.asarray(q[i][None, None]), jnp.asarray(fk[None]),
                jnp.asarray(fv[None]), q_offset=n - 1, kv_len=n,
                sliding_window=window))[0, 0])
        return np.stack(outs)

    q1 = rng.standard_normal((b, hq, d)).astype(np.float32)

    def swa_decode():
        got = paged_attention(jnp.asarray(q1), jnp.asarray(k_pool),
                              jnp.asarray(v_pool), jnp.asarray(bt),
                              jnp.asarray(kv_lens), None, None,
                              sliding_window=window, interpret=False)
        want = decode_ref(k_pool, v_pool, q1)
        if not np.allclose(np.asarray(got), want, rtol=2e-2, atol=2e-2):
            return f"max abs err {np.abs(np.asarray(got) - want).max():.2e}"
        return None

    def swa_decode8():
        kq, ks = kvc.quantize_kv(jnp.asarray(k_pool))
        vq, vs = kvc.quantize_kv(jnp.asarray(v_pool))
        got = paged_attention(jnp.asarray(q1), kq, vq, jnp.asarray(bt),
                              jnp.asarray(kv_lens), ks, vs,
                              sliding_window=window, interpret=False)
        kd = np.asarray(kq, np.float32) * np.asarray(ks)[..., None]
        vd = np.asarray(vq, np.float32) * np.asarray(vs)[..., None]
        want = decode_ref(kd, vd, q1)
        if not np.allclose(np.asarray(got), want, rtol=5e-2, atol=5e-2):
            return f"max abs err {np.abs(np.asarray(got) - want).max():.2e}"
        return None

    def swa_decode4():
        # int4 nibble-packed pool: proves the in-kernel integer
        # unpack (shift/mask/select + lane-dim concat) lowers through
        # Mosaic, not just interpret mode.
        kq, ks = kvc.quantize_kv_int4(jnp.asarray(k_pool))
        vq, vs = kvc.quantize_kv_int4(jnp.asarray(v_pool))
        got = paged_attention(jnp.asarray(q1), kq, vq, jnp.asarray(bt),
                              jnp.asarray(kv_lens), ks, vs,
                              sliding_window=window, interpret=False)
        kd = np.asarray(kvc.unpack_int4_kv(kq), np.float32) \
            * np.asarray(ks)[..., None]
        vd = np.asarray(kvc.unpack_int4_kv(vq), np.float32) \
            * np.asarray(vs)[..., None]
        want = decode_ref(kd, vd, q1)
        if not np.allclose(np.asarray(got), want, rtol=5e-2, atol=5e-2):
            return f"max abs err {np.abs(np.asarray(got) - want).max():.2e}"
        return None

    s = 24
    q_off = np.array([0, 16, 8], np.int32)
    pf_lens = (q_off + s).astype(np.int32)
    mp_pf = 8
    n_pages_pf = 64
    k_pf = rng.standard_normal((n_pages_pf, page, hkv, d)).astype(np.float32)
    v_pf = rng.standard_normal((n_pages_pf, page, hkv, d)).astype(np.float32)
    bt_pf = rng.permutation(np.arange(1, 1 + b * mp_pf)).reshape(
        b, mp_pf).astype(np.int32)
    qs = rng.standard_normal((b, s, hq, d)).astype(np.float32)

    def prefill_ref(kp, vp):
        outs = []
        for i in range(b):
            n = int(pf_lens[i])
            fk = np.concatenate([kp[bt_pf[i, j]] for j in range(mp_pf)])[:n]
            fv = np.concatenate([vp[bt_pf[i, j]] for j in range(mp_pf)])[:n]
            outs.append(np.asarray(common.dense_causal_attention(
                jnp.asarray(qs[i][None]), jnp.asarray(fk[None]),
                jnp.asarray(fv[None]), q_offset=int(q_off[i]), kv_len=n,
                sliding_window=window))[0])
        return np.stack(outs)

    def swa_prefill():
        got = paged_prefill_attention(
            jnp.asarray(qs), jnp.asarray(k_pf), jnp.asarray(v_pf),
            jnp.asarray(bt_pf), jnp.asarray(pf_lens), jnp.asarray(q_off),
            None, None, block_q=8, sliding_window=window, interpret=False)
        want = prefill_ref(k_pf, v_pf)
        if not np.allclose(np.asarray(got), want, rtol=2e-2, atol=2e-2):
            return f"max abs err {np.abs(np.asarray(got) - want).max():.2e}"
        return None

    def swa_prefill8():
        kq, ks = kvc.quantize_kv(jnp.asarray(k_pf))
        vq, vs = kvc.quantize_kv(jnp.asarray(v_pf))
        got = paged_prefill_attention(
            jnp.asarray(qs), kq, vq, jnp.asarray(bt_pf),
            jnp.asarray(pf_lens), jnp.asarray(q_off), ks, vs, block_q=8,
            sliding_window=window, interpret=False)
        kd = np.asarray(kq, np.float32) * np.asarray(ks)[..., None]
        vd = np.asarray(vq, np.float32) * np.asarray(vs)[..., None]
        want = prefill_ref(kd, vd)
        if not np.allclose(np.asarray(got), want, rtol=5e-2, atol=5e-2):
            return f"max abs err {np.abs(np.asarray(got) - want).max():.2e}"
        return None

    # SP wrappers: shard_map compiles on this backend over the devices the
    # hardware offers (1 on the single-chip tunnel — the collective is
    # degenerate there, but the windowed local bodies still lower via XLA).
    # Axis capped at 2 (a divisor of hkv=2, Ulysses' contract); sequence
    # length fixed well above the window so the mask always binds — a
    # dropped window term fails numerically, not just at lowering.
    from jax.sharding import Mesh

    ndev = len(jax.devices())
    sp_n = 2 if ndev >= 2 else 1
    mesh = Mesh(np.array(jax.devices()[:sp_n]), ("sp",))
    sl = max(32, 8 * sp_n)
    qsp = jnp.asarray(rng.standard_normal((1, sl, 4, d)), jnp.float32)
    ksp = jnp.asarray(rng.standard_normal((1, sl, 2, d)), jnp.float32)
    vsp = jnp.asarray(rng.standard_normal((1, sl, 2, d)), jnp.float32)
    want_sp = None

    def sp_ref():
        nonlocal want_sp
        if want_sp is None:
            want_sp = np.asarray(common.dense_causal_attention(
                qsp, ksp, vsp, sliding_window=window))
        return want_sp

    def ring_swa():
        got = ring_attention(qsp, ksp, vsp, mesh=mesh, sliding_window=window)
        if not np.allclose(np.asarray(got), sp_ref(), rtol=2e-2, atol=2e-2):
            return "mismatch vs dense oracle"
        return None

    def ulysses_swa():
        got = ulysses_attention(qsp, ksp, vsp, mesh=mesh,
                                sliding_window=window)
        if not np.allclose(np.asarray(got), sp_ref(), rtol=2e-2, atol=2e-2):
            return "mismatch vs dense oracle"
        return None

    check("swa_decode", swa_decode)
    check("swa_decode8", swa_decode8)
    check("swa_decode4", swa_decode4)
    check("swa_prefill", swa_prefill)
    check("swa_prefill8", swa_prefill8)
    check("ring_swa", ring_swa)
    check("ulysses_swa", ulysses_swa)

    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps({"mosaic_ok": rec["ok"], "platform": platform,
                      "n_checks": len(rec["checks"])}))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
