#!/bin/bash
# Round-4 TPU measurement battery (VERDICT r3 items 1-4). Run when the
# axon tunnel is healthy; every stage is individually time-bounded and
# failures don't stop later stages. Artifacts land in benchmarks/results/.
#
#   bash benchmarks/run_tpu_round4.sh [stage ...]   # default: all stages
#
# Stages:
#   bench     hardened bench.py (pallas bf16 / int8 / dense lanes, 1B dims)
#   bench8b   BENCH_MODEL=8b int8 lane (BASELINE.md config-1 row)
#   replay    saturated BurstGPT replay: real 1B checkpoint, int8+int8,
#             auto batch sizing (VERDICT: >=370 tok/s, TTFT p50 < 5 s)
#   sweep     decode_steps_per_call x pipeline-depth mini-sweep for the
#             hbm_util push (short bench lanes)
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
STAGES=${@:-"bench bench32 bench8b replay sweep"}
CKPT=/tmp/real-llama-1b

probe() {
  # Shared wedge-safe probe (bench.py child runner: own process group,
  # SIGKILL on timeout — never orphans a runtime helper on the chip).
  # Outer timeout bounds the parent interpreter too (deepest wedge mode
  # blocks python at startup, before the child's 120s deadline exists).
  timeout -k 10 300 python -c "
import json, sys, bench
rc, rec = bench._run_child(['--probe'], 120)
print(json.dumps(rec)) if rec else sys.exit(1)"
}

echo "== probe: $(probe || echo UNREACHABLE)"

for s in $STAGES; do case $s in
bench)
  echo "== bench.py (3 lanes)"
  timeout 1100 python bench.py 2>benchmarks/results/bench_r4_tpu.err \
    | tee benchmarks/results/bench_r4_tpu.jsonl
  ;;
bench32)
  echo "== bench.py BENCH_BATCH=32 (chip-sized batch lane)"
  BENCH_BATCH=32 timeout 1100 python bench.py \
    2>benchmarks/results/bench_r4_bs32.err \
    | tee benchmarks/results/bench_r4_bs32.jsonl
  ;;
bench8b)
  echo "== bench.py BENCH_MODEL=8b (int8-only lane)"
  BENCH_MODEL=8b timeout 1100 python bench.py \
    2>benchmarks/results/bench_r4_8b.err \
    | tee benchmarks/results/bench_r4_8b.jsonl
  ;;
replay)
  if [ -d "$CKPT" ]; then
    echo "== saturated BurstGPT replay (real 1B, int8+int8, auto batch)"
    timeout 1500 python benchmarks/replay.py \
      --model "$CKPT" --tokenizer auto \
      --quant int8 --kv-quant int8 \
      --max-batch-size auto --num-pages auto --batch-cap 32 \
      --trace data/BurstGPT_1.csv --max-trace 100 \
      --decode-pipeline-depth 2 \
      --out benchmarks/results/real1b_burstgpt_r4_int8_auto.json \
      2>&1 | tail -5
  else
    echo "== replay SKIPPED: $CKPT missing"
  fi
  ;;
sweep)
  echo "== K x depth sweep on the int8 replay config (hbm_util push)"
  for K in 8 16; do for D in 1 2 4; do
    [ -d "$CKPT" ] || break 2
    echo "-- K=$K depth=$D"
    timeout 900 python benchmarks/replay.py \
      --model "$CKPT" --tokenizer auto --quant int8 --kv-quant int8 \
      --max-batch-size auto --num-pages auto --batch-cap 32 \
      --trace data/BurstGPT_1.csv --max-trace 40 \
      --decode-steps-per-call $K --decode-pipeline-depth $D \
      --out benchmarks/results/sweep_r4_K${K}_D${D}.json \
      2>&1 | tail -2
  done; done
  ;;
*) echo "unknown stage $s";;
esac; done
echo "== done"
