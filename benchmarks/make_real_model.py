"""Build a real-format HF checkpoint + tokenizer for end-to-end serving.

This environment has no network, so no pretrained weights exist on disk;
what CAN be real is the entire serving stack around them:

- a **real BPE tokenizer** trained on the benchmark corpus
  (data/conversations.json) with HF ``tokenizers``, saved as the standard
  tokenizer.json / tokenizer_config.json pair — exercising ``HFTokenizer``
  and incremental detokenization on genuine merges, not byte fallback;
- a **real HF checkpoint**: ``LlamaForCausalLM.save_pretrained`` sharded
  safetensors + config.json, loaded back through the streaming loader and
  served via ``--model auto`` (architecture read from config.json).

Usage:
    python benchmarks/make_real_model.py --out /tmp/real-llama --size 1b
    python benchmarks/replay.py --model /tmp/real-llama --tokenizer auto

Sizes: "tiny" (CI/CPU) and "1b" (TinyLlama-1.1B dims, TPU bench).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def corpus_texts(path: str) -> list:
    with open(path) as f:
        data = json.load(f)
    texts = []

    def walk(x):
        if isinstance(x, str):
            texts.append(x)
        elif isinstance(x, dict):
            for v in x.values():
                walk(v)
        elif isinstance(x, list):
            for v in x:
                walk(v)

    walk(data)
    return texts


def train_tokenizer(texts: list, out_dir: str, vocab_size: int) -> int:
    """Train a byte-level BPE tokenizer; returns the actual vocab size."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size, special_tokens=["<s>", "</s>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tok.train_from_iterator(texts, trainer)
    tok.save(os.path.join(out_dir, "tokenizer.json"))
    with open(os.path.join(out_dir, "tokenizer_config.json"), "w") as f:
        json.dump({"tokenizer_class": "PreTrainedTokenizerFast",
                   "bos_token": "<s>", "eos_token": "</s>",
                   "model_max_length": 2048}, f)
    return tok.get_vocab_size()


SIZES = {
    # (d_model, n_layers, n_heads, n_kv_heads, d_ff)
    "tiny": (128, 2, 4, 2, 256),
    "1b": (2048, 22, 32, 4, 5632),          # TinyLlama-1.1B architecture
}


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", required=True)
    p.add_argument("--size", default="tiny", choices=sorted(SIZES))
    p.add_argument("--vocab-size", type=int, default=8192)
    p.add_argument("--data", default="data/conversations.json")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import torch
    import transformers

    os.makedirs(args.out, exist_ok=True)
    texts = corpus_texts(args.data)
    vocab = train_tokenizer(texts, args.out, args.vocab_size)
    # Round the embedding table up to a TPU-lane-friendly multiple of 128.
    vocab_padded = -(-vocab // 128) * 128
    print(f"tokenizer: {vocab} tokens -> model vocab {vocab_padded}")

    d, layers, heads, kv_heads, ff = SIZES[args.size]
    cfg = transformers.LlamaConfig(
        vocab_size=vocab_padded, hidden_size=d, intermediate_size=ff,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=kv_heads, max_position_embeddings=2048,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=False,
        torch_dtype="bfloat16", bos_token_id=0, eos_token_id=1)
    torch.manual_seed(args.seed)
    model = transformers.LlamaForCausalLM(cfg).to(torch.bfloat16)
    # Shard below HF's default so the index.json multi-file path is real.
    model.save_pretrained(args.out, safe_serialization=True,
                          max_shard_size="500MB")
    n_params = sum(t.numel() for t in model.parameters())
    print(f"checkpoint: {n_params / 1e9:.2f}B params -> {args.out}")
    print(f"serve: python -m tpu_inference.server --model {args.out} "
          f"--tokenizer auto")


if __name__ == "__main__":
    main()
