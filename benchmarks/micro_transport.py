"""Frame-codec hot-path micro-benchmark: µs per frame over a real
socketpair, before/after the zero-copy-PR transport fixes.

Two fixes under measurement (tpu_inference/server/transport.py):

- **send**: the legacy path concatenated ``header + blob`` into a fresh
  bytes object before ``sendall`` — one full extra copy of every KV
  payload. The current path gather-writes the two buffers with
  ``sendmsg`` (vectored I/O), zero concatenation.
- **recv**: the legacy ``_read_exact`` accumulated ``sock.read(n)``
  chunks in a list and joined them — up to 2x the payload in transient
  allocations. The current path ``readinto``-fills ONE preallocated
  buffer.

Both paths are exercised here explicitly (the legacy variants are
reconstructed inline) so the delta stays measurable after the fix
lands. Frames echo through a real ``socket.socketpair`` with a reader
thread, so syscall + copy cost is what's timed, not pickling.

Run:
    python benchmarks/micro_transport.py \
        --out benchmarks/results/micro_transport.json

Committed result (this box, Linux, CPython 3.10, 200 frames/arm —
see benchmarks/results/micro_transport.json):

    arm                            µs/frame @1MiB      MB/s
    legacy (concat + join-read)          695.6        1507.5
    current (sendmsg + readinto)         563.4        1861.1

i.e. the fixed codec moves ~1.23x the bytes per second at 1 MiB (the
remaining wall is the two hardware crc32c passes + the kernel copy).
At 4 KiB frames the delta shrinks to fixed overhead (~27 -> ~21 µs),
which is why the vectored path only engages when a blob is present.

NB both arms share the crc32c backend fix that landed with this PR
(tpu_inference/integrity.py picks up the google_crc32c C extension
when present): the pure-Python table walk paid ~300 ms per 1 MiB frame
— 500x this entire codec — and would have drowned the copy savings.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_inference.server import transport
from tpu_inference.server.transport import (_frame_head, _HEADER, _MAGIC,
                                            crc32c, recv_frame, send_frame)


# ---------------------------------------------------------- legacy arms


def _legacy_send(sock, obj, blob: bytes) -> None:
    """Pre-PR send path: encode_frame's header+blob concatenation."""
    sock.sendall(_frame_head(obj, blob) + blob)


def _legacy_read_exact(rfile, n: int) -> bytes:
    """Pre-PR read path: chunk list + join (double allocation)."""
    chunks, got = [], 0
    while got < n:
        b = rfile.read(n - got)
        if not b:
            raise ConnectionError("eof")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def _legacy_recv(rfile):
    head = _legacy_read_exact(rfile, _HEADER.size)
    magic, jlen, blen, crc = _HEADER.unpack(head)
    assert magic == _MAGIC
    jraw = _legacy_read_exact(rfile, jlen)
    blob = _legacy_read_exact(rfile, blen) if blen else b""
    assert crc32c(struct.pack(">II", jlen, blen) + jraw + blob) == crc
    return json.loads(jraw), blob


# ------------------------------------------------------------ the bench


def _run_arm(arm: str, blob_bytes: int, frames: int) -> dict:
    """Echo `frames` frames through a socketpair; returns µs/frame."""
    a, b = socket.socketpair()
    for s in (a, b):
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 20)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 20)
    rfile = b.makefile("rb", buffering=256 * 1024)
    blob = os.urandom(blob_bytes)
    obj = {"verb": "submit", "id": 7, "idem": "bench"}
    done = threading.Event()
    got = [0]

    warm = 5

    def reader() -> None:
        recv = _legacy_recv if arm == "legacy" else recv_frame
        try:
            for _ in range(frames + warm):
                _, rb = recv(rfile)
                got[0] += len(rb)
        finally:
            done.set()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    send = (lambda o, bl: _legacy_send(a, o, bl)) if arm == "legacy" \
        else (lambda o, bl: send_frame(a, o, bl))
    # Warm both arms (allocator, JSON encoder) before timing.
    for _ in range(warm):
        send(obj, blob)
    t0 = time.perf_counter()
    for _ in range(frames):
        send(obj, blob)
    assert done.wait(60.0), "reader never finished"
    wall = time.perf_counter() - t0
    t.join(timeout=5.0)
    assert got[0] == (frames + warm) * blob_bytes
    rfile.close()
    a.close()
    b.close()
    return {"arm": arm, "blob_bytes": blob_bytes, "frames": frames,
            "us_per_frame": round(wall / frames * 1e6, 2),
            "mb_per_s": round(blob_bytes * frames / wall / 1e6, 1)}


def main() -> dict:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--frames", type=int, default=200)
    p.add_argument("--sizes", default="4096,1048576",
                   help="comma-separated blob sizes (bytes)")
    p.add_argument("--out", default="")
    args = p.parse_args()

    assert transport is not None
    rows = []
    for size in (int(s) for s in args.sizes.split(",") if s):
        for arm in ("legacy", "current"):
            r = _run_arm(arm, size, args.frames)
            rows.append(r)
            print(f"{arm:8s} {size:>9d}B  {r['us_per_frame']:>9.2f} "
                  f"µs/frame  {r['mb_per_s']:>8.1f} MB/s", flush=True)
    out = {"metric": "micro_transport", "rows": rows,
           "python": sys.version.split()[0], "platform": sys.platform}
    big = [r for r in rows if r["blob_bytes"] >= 1 << 20]
    if len(big) == 2:
        legacy, cur = big[0], big[1]
        out["speedup_at_1mib"] = round(
            legacy["us_per_frame"] / cur["us_per_frame"], 3)
        print(f"speedup @1MiB: {out['speedup_at_1mib']}x")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    return out


if __name__ == "__main__":
    main()
