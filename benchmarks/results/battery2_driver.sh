#!/bin/bash
cd /root/repo
echo "== battery2 start $(date -u +%H:%M:%S)"
python benchmarks/make_real_model.py --out /tmp/real-llama-1b --size 1b 2>&1 | tail -2
bash benchmarks/run_tpu_round5.sh replay bench bench8b bench32 sweep bench16k turns
echo "== battery2 end $(date -u +%H:%M:%S)"
