"""End-to-end replay benchmark: BurstGPT trace -> in-process TPU server.

The headline metric harness (BASELINE.md: "BurstGPT replay — tokens/s/chip,
p50/p99 TTFT+TPOT"). Boots the Ollama-protocol server in a background
thread, replays a trace through the vendored traffic generator (the
reference's own benchmark client, unchanged protocol), and summarizes the
per-request metrics the harness records.

Usage:
    python benchmarks/replay.py --model tiny-llama --max-trace 20
    python benchmarks/replay.py --model llama-3-8b --tp 8 \
        --trace data/BurstGPT_1.csv --out benchmarks/results/8b_tp8.json

Timing semantics match the reference client (SURVEY.md §2c): TTFT =
first streamed chunk relative to request start; headers are withheld by
the server until the first token, so header-arrival ≈ TTFT.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import re
import socket
import sys
import threading
import time
import urllib.request
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _percentiles(xs, ps=(50, 99)):
    if not xs:
        return {f"p{p}": None for p in ps}
    return {f"p{p}": round(float(np.percentile(xs, p)), 4) for p in ps}


def scrape_metrics(port: int, fmt: str = None) -> tuple:
    """GET /metrics over real HTTP (the same path an external Prometheus
    collector takes — NOT an in-process shortcut, so this lane proves
    the scrape path end-to-end). Returns (body, content_type)."""
    url = f"http://127.0.0.1:{port}/metrics"
    if fmt == "json":
        url += "?format=json"
    with urllib.request.urlopen(url, timeout=60) as r:
        return r.read().decode(), r.headers.get("Content-Type", "")


def step_attribution(port: int) -> dict:
    """GET /debug/steps compressed into the artifact's attribution
    block (README "Performance attribution"): the fleet-merged
    bottleneck verdict per step kind, the per-rung occupancy histogram,
    the top-3 time sinks, and the MFU cross-check — so every committed
    row explains WHY it ran at the throughput it did."""
    url = f"http://127.0.0.1:{port}/debug/steps"
    with urllib.request.urlopen(url, timeout=60) as r:
        snap = json.loads(r.read().decode())
    fleet = snap.get("fleet") or {}
    if not fleet.get("enabled"):
        return {"enabled": False}
    return {
        "enabled": True,
        "records": fleet.get("records_window"),
        "verdicts": {k: v.get("verdict")
                     for k, v in (fleet.get("kinds") or {}).items()},
        "rung_occupancy": fleet.get("rung_occupancy") or {},
        "top_sinks": fleet.get("top_sinks") or [],
        "compile_events": fleet.get("compile_events"),
        "mfu": fleet.get("mfu") or {},
        "replica_verdicts": {
            rep: {k: v.get("verdict")
                  for k, v in (rr.get("kinds") or {}).items()}
            for rep, rr in (snap.get("replicas") or {}).items()
            if rr.get("enabled")},
    }


def phase_breakdown(before: dict, after: dict) -> dict:
    """Diff two /metrics?format=json scrapes into the run window's phase
    histograms: dispatch wall vs host bubble vs queue wait (p50/p95/p99)
    plus the per-request phase sums, with a sum-check of queue + prefill
    + decode against E2E — the artifact that answers "where does the
    roofline go" without archaeology."""
    from tpu_inference import telemetry as tm

    aph = after.get("phases") or {}
    bph = before.get("phases") or {}
    out = {}
    for key in ("decode_dispatch_s", "decode_sync_s", "dispatch_bubble_s",
                "prefill_dispatch_s", "tokens_per_dispatch",
                "hybrid_dispatch_s", "decode_stall_during_prefill_s",
                "queue_wait_s",
                "prefill_phase_s", "decode_phase_s", "ttft_s", "e2e_s"):
        if key in aph:
            d = tm.diff_phase(aph[key], bph.get(key))
            out[key] = {k: d[k] for k in ("count", "sum", "p50", "p95",
                                          "p99")}
    phase_sum = sum(out.get(k, {}).get("sum") or 0.0
                    for k in ("queue_wait_s", "prefill_phase_s",
                              "decode_phase_s"))
    e2e_sum = out.get("e2e_s", {}).get("sum") or 0.0
    out["sum_check"] = {
        # queue + prefill + decode vs e2e: same timestamps on the server
        # side, so the ratio must be ~1.0 (the artifact's self-test).
        "queue_plus_prefill_plus_decode_s": round(phase_sum, 6),
        "e2e_s": round(e2e_sum, 6),
        "ratio": round(phase_sum / e2e_sum, 4) if e2e_sum else None,
    }
    return out


def summarize(metrics: dict, n_chips: int = 1) -> dict:
    """Reduce the harness's per-request dicts to the headline numbers."""
    ok = {k: m for k, m in metrics.items() if m.get("success")}
    # Client-side resilience accounting: 429/503 attempts retried with
    # backoff, and queries given up after the retry budget (shed) — the
    # shed RATE is the number the admission-mode comparison lane reads.
    retries = sum(m.get("num_retries") or 0 for m in metrics.values())
    shed = sum(1 for m in metrics.values() if m.get("shed"))
    ttft, tpot, e2e, gaps, tokens = [], [], [], [], 0
    t_first, t_last = float("inf"), 0.0
    for m in ok.values():
        start = m["request_start_time"]
        first = m["first_token_arrive_time"]
        end = m["response_end_time"]
        n_out = m.get("num_output_tokens") or 0
        if first is not None and start is not None:
            ttft.append(first - start)
        if end is not None and start is not None:
            e2e.append(end - start)
        if end is not None and first is not None and n_out > 1:
            tpot.append((end - first) / (n_out - 1))
        if m.get("max_interchunk_gap") is not None:
            gaps.append(m["max_interchunk_gap"])
        tokens += n_out
        if start is not None:
            t_first = min(t_first, start)
        if end is not None:
            t_last = max(t_last, end)
    wall = max(t_last - t_first, 1e-9)
    return {
        "requests": len(metrics),
        "succeeded": len(ok),
        "client_retries": retries,
        "shed": shed,
        "shed_rate": round(shed / max(len(metrics), 1), 4),
        "output_tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(tokens / wall, 2),
        "tokens_per_s_per_chip": round(tokens / wall / max(n_chips, 1), 2),
        "ttft_s": _percentiles(ttft),
        "tpot_s": _percentiles(tpot),
        "e2e_s": _percentiles(e2e),
        # Worst per-request stall between streamed chunks (the K-bursty
        # flush sawtooth a mean TPOT hides).
        "max_interchunk_gap_s": _percentiles(gaps),
    }


def start_server(args) -> tuple:
    """Boot the server (with warmup) on a background event loop; returns
    (port, stop_fn). Blocks until it accepts connections."""
    import jax  # noqa: F401 (import before aiohttp threads)

    from aiohttp import web

    from tpu_inference.server.http import build_server

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    srv = build_server(
        model=args.model, tokenizer=args.tokenizer, tp=args.tp,
        sp=args.sp, sp_attn=args.sp_attn, dp=getattr(args, "dp", 1),
        draft_model=args.draft_model, checkpoint=args.checkpoint,
        draft_checkpoint=args.draft_checkpoint,
        warmup=not args.no_warmup,
        max_batch_size=args.max_batch_size, num_pages=args.num_pages,
        decode_ladder=tuple(getattr(args, "decode_ladder_rungs", ()) or ()),
        stage_host_reuse=getattr(args, "stage_host_reuse", True),
        ladder_admit_headroom_pages=getattr(
            args, "ladder_admit_headroom_pages", 0),
        page_size=args.page_size, max_pages_per_seq=args.max_pages_per_seq,
        decode_steps_per_call=args.decode_steps_per_call,
        decode_pipeline_depth=args.decode_pipeline_depth,
        chunked_prefill_size=getattr(args, "chunked_prefill_size", 0),
        hybrid_prefill=getattr(args, "hybrid_prefill", False),
        step_token_budget=getattr(args, "step_token_budget", 0),
        quant=getattr(args, "quant", "none"),
        kv_quant=getattr(args, "kv_quant", "none"),
        enable_prefix_cache=getattr(args, "enable_prefix_cache", True),
        host_cache_pages=getattr(args, "host_cache_pages", 0),
        admission=getattr(args, "admission", "reserve"),
        preempt_watermark_pages=getattr(
            args, "preempt_watermark_pages", 4),
        # Rolling SLO targets (README "Observability"): feed the
        # windowed quantile gauges + breach counters the artifact and
        # the autoscaler read.
        slo_ttft_ms=getattr(args, "slo_ttft_ms", 0.0),
        slo_tpot_ms=getattr(args, "slo_tpot_ms", 0.0),
        # Debug surfaces on: the bench scrapes /debug/trace for the
        # Chrome-trace artifact (local bench server, never production).
        enable_debug=True,
        server_overrides={
            "admission_queue_depth":
                getattr(args, "admission_queue_depth", 0),
            "routing": getattr(args, "routing", "prefix_affinity"),
            "route_hit_weight": getattr(args, "route_hit_weight", 1.0),
            "route_host_hit_weight":
                getattr(args, "route_host_hit_weight", 0.5),
            # Fleet KV fabric (README "KV fabric"): shared cross-
            # replica prefix pool + warm worker boot for the
            # --compare-fabric arms.
            "fabric_cache_pages":
                getattr(args, "fabric_cache_pages", 0),
            "fabric_publish_min_pages":
                getattr(args, "fabric_publish_min_pages", 1),
            "fabric_warmboot_pages":
                getattr(args, "fabric_warmboot_pages", 64),
            "route_fabric_hit_weight":
                getattr(args, "route_fabric_hit_weight", 0.25),
            # Zero-copy KV data plane (README "KV data plane"): shm
            # arena vs through-router relay for the --compare-kv-plane
            # arms.
            "kv_plane": getattr(args, "kv_plane", "relay"),
            "shm_arena_bytes": getattr(args, "shm_arena_bytes",
                                       256 * 1024 * 1024),
            # Process fleet (README "Process fleet"): backend + worker
            # supervision knobs for the subprocess arms.
            "fleet": getattr(args, "fleet", "in-process"),
            "fleet_migrate": getattr(args, "fleet_migrate", True),
            # P/D disaggregation (README "P/D disaggregation"): per-
            # worker phase roles + shared-CPU prefill deprioritization
            # for the --compare-pd arms.
            "worker_roles": tuple(getattr(args, "worker_roles", ())
                                  or ()),
            "pd_prefill_nice": getattr(args, "pd_prefill_nice", 0),
            "worker_restart_max":
                getattr(args, "worker_restart_max", 3),
            "worker_restart_backoff_s":
                getattr(args, "worker_restart_backoff_s", 0.5),
            "drain_timeout_s": getattr(args, "drain_timeout_s", 10.0),
            # Byzantine transport (README "Failure model"): per-verb
            # RPC deadline classes for the --compare-chaos-rpc arms
            # (wedge detection cost is 3 consecutive fast deadlines).
            "rpc_deadline_fast_s":
                getattr(args, "rpc_deadline_fast_s", 10.0),
            "rpc_deadline_slow_s":
                getattr(args, "rpc_deadline_slow_s", 60.0),
            # Elastic fleet (README "Elastic fleet"): autoscaler +
            # priority-class admission for the --compare-elastic arms.
            "autoscale": getattr(args, "autoscale", False),
            "autoscale_min_replicas":
                getattr(args, "autoscale_min_replicas", 1),
            "autoscale_max_replicas":
                getattr(args, "autoscale_max_replicas", 0),
            "autoscale_breach_window_s":
                getattr(args, "autoscale_breach_window_s", 3.0),
            "autoscale_cooldown_s":
                getattr(args, "autoscale_cooldown_s", 10.0),
            "autoscale_low_watermark":
                getattr(args, "autoscale_low_watermark", 0.25),
            "autoscale_idle_window_s":
                getattr(args, "autoscale_idle_window_s", 5.0),
            "default_class": getattr(args, "default_class",
                                     "interactive"),
            "class_queue_depth":
                getattr(args, "class_queue_depth", 0)},
        spec_mode=("ngram" if getattr(args, "spec_mode", None) == "ngram"
                   else "draft"),
        ngram_window=getattr(args, "ngram_window", 3),
        num_speculative_tokens=(
            args.num_speculative_tokens
            if (args.draft_model
                or getattr(args, "spec_mode", None) == "ngram") else 0),
        # Smoke lane: small prefill buckets so the CPU tier-1 run
        # compiles in seconds, not minutes (a lane can pin its own —
        # compare-pd needs 256-token chunks so an in-engine prefill
        # dispatch is a VISIBLE decode stall).
        **({"prefill_buckets": (getattr(args, "prefill_buckets", None)
                                or (16, 32, 64))}
           if getattr(args, "smoke", False) else {}))
    loop = asyncio.new_event_loop()
    ready = threading.Event()
    boot_err: list = []

    def run():
        asyncio.set_event_loop(loop)
        try:
            app = srv.make_app()
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "127.0.0.1", port)
            loop.run_until_complete(site.start())
        except BaseException as e:  # surface boot failures immediately
            boot_err.append(e)
            ready.set()
            return
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=run, name="bench-server", daemon=True)
    t.start()
    if not ready.wait(timeout=1800):
        raise TimeoutError("server failed to start (warmup hang?)")
    if boot_err:
        raise boot_err[0]

    def stop():
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=30)

    return srv, port, stop


def main() -> dict:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="tiny-llama")
    p.add_argument("--tokenizer", default="byte")
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel prefill degree")
    p.add_argument("--sp-attn", default="ring", choices=("ring", "ulysses"))
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel replicas (each its own submesh, "
                        "KV pool and scheduler; requests route per "
                        "--routing)")
    p.add_argument("--routing", default="prefix_affinity",
                   choices=("prefix_affinity", "least_loaded"),
                   help="dp replica routing policy")
    p.add_argument("--route-hit-weight", type=float, default=1.0,
                   help="prefix-affinity: routing-score pages one peeked "
                        "cache-hit page is worth")
    p.add_argument("--route-host-hit-weight", type=float, default=0.5,
                   help="prefix-affinity: routing-score pages one peeked "
                        "HOST-tier hit page is worth")
    p.add_argument("--host-cache-pages", type=int, default=0,
                   help="host-RAM KV tier capacity in pages (0 = off; "
                        "README 'Tiered KV cache')")
    p.add_argument("--draft-model", default=None)
    p.add_argument("--draft-checkpoint", default=None)
    p.add_argument("--num-speculative-tokens", type=int, default=4)
    p.add_argument("--spec-mode", default=None, choices=("ngram",),
                   help="'ngram' = draft-free self-drafting speculation "
                        "(README 'Speculative decoding'); default off")
    p.add_argument("--ngram-window", type=int, default=3,
                   help="ngram spec: longest suffix n-gram matched "
                        "against each sequence's history")
    p.add_argument("--trace", default="data/trace1.csv")
    p.add_argument("--data", default="data/conversations.json")
    p.add_argument("--max-trace", type=int, default=100)
    from tpu_inference.engine.autosize import int_or_auto

    p.add_argument("--max-batch-size", type=int_or_auto, default=8,
                   help="decode slots, or 'auto' (size from chip HBM — "
                        "engine/autosize.py)")
    p.add_argument("--decode-ladder", default="off",
                   help="compiled decode-graph batch ladder: 'auto' "
                        "(doubling rungs up to max-batch-size), 'off' "
                        "(one graph, legacy), or comma rungs '8,16,32'")
    p.add_argument("--num-pages", type=int_or_auto, default=512,
                   help="KV pool pages, or 'auto'")
    p.add_argument("--target-ctx", type=int, default=0,
                   help="auto sizing: expected typical context per "
                        "sequence (0 = half the per-sequence max)")
    p.add_argument("--batch-cap", type=int, default=32,
                   help="upper bound for --max-batch-size auto")
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-pages-per-seq", type=int, default=64)
    p.add_argument("--decode-steps-per-call", type=int, default=8)
    p.add_argument("--decode-pipeline-depth", type=int, default=1)
    p.add_argument("--chunked-prefill-size", type=int, default=0,
                   help="split prompts into chunks of this many tokens "
                        "(0 = largest prefill bucket governs)")
    p.add_argument("--hybrid-prefill", action="store_true",
                   help="fuse each prefill chunk into the decode "
                        "dispatch (hybrid steps) instead of stalling "
                        "decode lanes a chunk wall per chunk")
    p.add_argument("--step-token-budget", type=int, default=0,
                   help="hybrid steps: per-fused-dispatch token budget "
                        "(chunk tokens capped at budget minus granted "
                        "decode tokens; 0 = "
                        "uncapped)")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--quant", default="none",
                   choices=("none", "int8", "int4"))
    p.add_argument("--kv-quant", default="none",
                   choices=("none", "int8", "int4"))
    p.add_argument("--platform", default="auto",
                   choices=("auto", "cpu", "tpu"),
                   help="jax platform; 'cpu' forces the CPU backend "
                        "(tp*sp virtual devices) before any computation")
    p.add_argument("--admission", default="reserve",
                   choices=("reserve", "optimistic"),
                   help="KV admission mode: worst-case reservation vs "
                        "optimistic admission with watermark preemption "
                        "+ recompute-resume")
    p.add_argument("--admission-queue-depth", type=int, default=0,
                   help="server-side 429 shed cap (0 = queue unbounded)")
    p.add_argument("--client-max-retries", type=int, default=4,
                   help="traffic-generator 429/503 retry budget per "
                        "query; give-ups are recorded as shed")
    p.add_argument("--compare-admission", action="store_true",
                   help="run the trace twice — admission=reserve then "
                        "optimistic — and commit an occupancy / "
                        "throughput / shed-rate comparison artifact")
    p.add_argument("--compare-hybrid", action="store_true",
                   help="run the workload twice — serial chunked prefill "
                        "then hybrid fused steps — and commit a decode-"
                        "stall / throughput / TTFT comparison artifact "
                        "(with --smoke: a pinned long-prompt-plus-"
                        "decoding-shorts mix)")
    p.add_argument("--compare-ladder", action="store_true",
                   help="run a pinned bursty mix three times — fixed "
                        "bs=8, the auto batch ladder, and the ladder "
                        "with host-staging reuse disabled — and commit "
                        "the ladder artifact: aggregate tok/s, per-"
                        "stream latency, outputs_sha256 byte-identity, "
                        "rung/occupancy telemetry, and the host-bubble "
                        "p95 the staging reuse removes")
    p.add_argument("--ladder-requests", type=int, default=48,
                   help="compare-ladder: burst size (needs to exceed "
                        "the top rung to fill it)")
    p.add_argument("--ladder-top", type=int, default=32,
                   help="compare-ladder: top ladder rung (the bs>=32 "
                        "arm the acceptance gate measures)")
    p.add_argument("--compare-spec", action="store_true",
                   help="run two pinned mixes twice each — plain decode "
                        "vs draft-free ngram speculation — and commit "
                        "the spec artifact: per-stream decode tok/s and "
                        "outputs_sha256 byte-identity on an echo-heavy "
                        "greedy multi-turn mix (where self-drafting "
                        "wins), plus throughput on an adversarial "
                        "no-echo sampled mix (where adaptive γ must "
                        "throttle so spec never loses), with acceptance-"
                        "rate / throttle telemetry from /metrics")
    p.add_argument("--spec-streams", type=int, default=4,
                   help="compare-spec: concurrent streams per mix")
    p.add_argument("--compare-fleet", action="store_true",
                   help="run a pinned greedy burst through the two "
                        "fleet backends (README 'Process fleet') — "
                        "in-process threads vs subprocess workers, plus "
                        "a subprocess arm with kill -9-a-worker chaos — "
                        "asserting byte-identical outputs and recording "
                        "tok/s ratio + failover counts; then a pinned "
                        "drain scenario twice (migration vs plain "
                        "resubmission), recording migrated vs "
                        "recomputed tokens and swap-in-resumes")
    p.add_argument("--fleet-streams", type=int, default=6,
                   help="compare-fleet: concurrent streams per arm")
    p.add_argument("--compare-chaos-rpc", action="store_true",
                   help="Byzantine-transport lane (README 'Failure "
                        "model'): the pinned greedy burst through a "
                        "clean dp=2 subprocess fleet, then again under "
                        "seeded frame-level RPC chaos — random byte "
                        "corruption, injected delays, and one wedged "
                        "(silently muted) connection — grading that "
                        "every corrupt frame is detected (CRC) and "
                        "recycled, outputs stay byte-identical (zero "
                        "silent corruptions), no worker process "
                        "restarts for a transport fault, and p95 "
                        "latency inflation stays bounded")
    p.add_argument("--compare-pd", action="store_true",
                   help="P/D disaggregation lane (README 'P/D "
                        "disaggregation'): the pinned long-prompt burst "
                        "through three dp=2 subprocess topologies — "
                        "mixed, mixed+hybrid-prefill, and a 1-prefill+"
                        "1-decode split with live KV handoff — each "
                        "measured unloaded (decode streams only) and "
                        "loaded (same streams under a CONTINUOUS "
                        "10x-plus long-prompt prefill burst spanning "
                        "every decode window), asserting byte-identical "
                        "outputs across every arm and phase and "
                        "recording decode TPOT p95 loaded/unloaded "
                        "ratios, handoff counts, and the zero-recompute "
                        "clean-handoff claim")
    p.add_argument("--compare-elastic", action="store_true",
                   help="elastic-fleet lane (README 'Elastic fleet'): a "
                        "pinned mini-diurnal burst (>=20x offered-load "
                        "swing, mixed interactive/batch X-Priority "
                        "classes) through a FIXED one-worker subprocess "
                        "fleet and through the same fleet with the "
                        "autoscaler + class lanes on, firing a rolling "
                        "upgrade mid-burst in the elastic arm — grading "
                        "that interactive TTFT p95 holds the SLO while "
                        "batch absorbs the slack (preemptions > 0, "
                        "interactive shed == 0), the fleet scales up "
                        "AND back down with events in /metrics and "
                        "/debug/trace, and the rollout completes with "
                        "zero failed requests and byte-identical greedy "
                        "outputs")
    p.add_argument("--elastic-quiet-requests", type=int, default=2,
                   help="compare-elastic: trickle arrivals in the quiet "
                        "phase, one per second (the diurnal trough)")
    p.add_argument("--elastic-burst-interactive", type=int, default=6,
                   help="compare-elastic: interactive requests in the "
                        "peak wave")
    p.add_argument("--elastic-burst-batch", type=int, default=28,
                   help="compare-elastic: batch requests in the peak "
                        "wave (the lane the interactives preempt)")
    p.add_argument("--compare-fabric", action="store_true",
                   help="fleet-KV-fabric lane (README 'KV fabric'): "
                        "many users sharing one long system prompt hit "
                        "a dp=2 subprocess fleet three times — fabric "
                        "off, fabric on, and fabric on with a mid-run "
                        "scale-up whose new worker warm-boots from the "
                        "pool — grading that the shared prefix is "
                        "prefilled ONCE fleet-wide (a second replica's "
                        "first turn is fabric-warm with zero recomputed "
                        "prefix tokens), returning-turn TTFT p95 "
                        "improves >=1.3x over fabric-off, the warmboot "
                        "worker serves its first request with fabric-"
                        "sourced warmth, and greedy outputs stay byte-"
                        "identical across every arm")
    p.add_argument("--fabric-users", type=int, default=10,
                   help="compare-fabric: concurrent returning users in "
                        "the graded wave (each prompt = shared system "
                        "prompt + a distinct tail)")
    p.add_argument("--fabric-wave2-users", type=int, default=14,
                   help="compare-fabric: users in the second wave (the "
                        "one that spills onto the warmboot worker in "
                        "the scale-up arm)")
    p.add_argument("--fabric-prefix-pages", type=int, default=9,
                   help="compare-fabric: shared system-prompt length in "
                        "full KV pages (page_size tokens each)")
    p.add_argument("--fabric-tokens", type=int, default=8,
                   help="compare-fabric: greedy generation budget per "
                        "request")
    p.add_argument("--fabric-pool-pages", type=int, default=256,
                   help="compare-fabric: router fabric pool capacity "
                        "for the fabric-on arms (--fabric-cache-pages)")
    p.add_argument("--fabric-warmboot-pages", type=int, default=64,
                   help="compare-fabric: MRU pool pages pushed into a "
                        "newly spawned worker before it is routable")
    p.add_argument("--compare-kv-plane", action="store_true",
                   help="zero-copy KV data plane lane (README 'KV data "
                        "plane'): a 1-prefill + 1-decode subprocess "
                        "fleet serves the same handoff-heavy burst "
                        "twice — KV blobs relayed through router "
                        "frames vs handed worker-to-worker through "
                        "the shared-memory page arena — grading that "
                        "the shm arm's router relays ~0 KV payload "
                        "bytes for handoff/fabric verbs, the "
                        "handoff+adopt wall p95 improves >=1.5x "
                        "(committed-artifact grade), a kill -9 "
                        "mid-wave reclaims the dead worker's slabs "
                        "via the region epoch bump with recompute-"
                        "resume fallback, and greedy outputs stay "
                        "byte-identical across both arms")
    p.add_argument("--kvp-users", type=int, default=8,
                   help="compare-kv-plane: concurrent requests in the "
                        "measured handoff wave (each carries a "
                        "distinct multi-hundred-KB KV context)")
    p.add_argument("--kvp-prompt-pages", type=int, default=30,
                   help="compare-kv-plane: per-request prompt length "
                        "in full KV pages — sizes the handoff blob "
                        "the planes move")
    p.add_argument("--kvp-tokens", type=int, default=8,
                   help="compare-kv-plane: greedy generation budget "
                        "per request")
    p.add_argument("--kvp-pool-pages", type=int, default=256,
                   help="compare-kv-plane: router fabric pool capacity "
                        "(fabric ON in both arms so fabric_put blob "
                        "traffic is part of the contrast)")
    p.add_argument("--shm-arena-bytes", type=int, default=64 * 1024 * 1024,
                   help="compare-kv-plane: shared-memory arena size "
                        "for the shm arm (the server flag of the same "
                        "name)")
    p.add_argument("--route-fabric-hit-weight", type=float, default=0.25,
                   help="prefix-affinity: routing-score pages one "
                        "fabric-pool hit page is worth (fourth "
                        "temperature)")
    p.add_argument("--pd-streams", type=int, default=4,
                   help="compare-pd: steady decode streams per phase")
    p.add_argument("--pd-decode-tokens", type=int, default=192,
                   help="compare-pd: generation budget per decode "
                        "stream (the measured decode window)")
    p.add_argument("--pd-load-prompts", type=int, default=64,
                   help="compare-pd: cap on long prompts the loaded "
                        "phase's continuous pressure generator issues "
                        "(a runaway bound — the generator stops when "
                        "the last stream finishes)")
    p.add_argument("--pd-load-prompt-tokens", type=int, default=448,
                   help="compare-pd: tokens per long prompt")
    p.add_argument("--pd-prefill-nice", type=int, default=19,
                   help="compare-pd: os.nice() for the pd arm's "
                        "prefill worker (shared-CPU hosts; see the "
                        "server CLI flag of the same name)")
    p.add_argument("--slo-ttft-ms", type=float, default=0.0,
                   help="rolling SLO target for TTFT (ms): feeds "
                        "tpu_inf_slo_*_seconds gauges + breach "
                        "counters; 0 = no target (gauges still export)")
    p.add_argument("--slo-tpot-ms", type=float, default=0.0,
                   help="rolling SLO target for TPOT (ms); 0 = none")
    p.add_argument("--trace-artifact", default=None,
                   help="with --compare-pd: write the pd arm's "
                        "recent-request ring as Chrome trace-event "
                        "JSON (GET /debug/trace?format=chrome) to this "
                        "path — one pid per replica, router as pid 0, "
                        "loadable in Perfetto (default with --smoke: "
                        "replay_pd_trace.json next to --out)")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--out", default=None, help="write summary JSON here")
    p.add_argument("--smoke", action="store_true",
                   help="CPU smoke lane (tier-1): tiny model, tiny trace, "
                        "small engine — exercises the full server boot + "
                        "replay + /metrics scrape + phase_breakdown "
                        "artifact path in seconds")
    args = p.parse_args()

    if sum(map(bool, (args.compare_admission, args.compare_hybrid,
                      args.compare_ladder, args.compare_spec,
                      args.compare_fleet, args.compare_pd,
                      args.compare_elastic, args.compare_fabric,
                      args.compare_chaos_rpc,
                      args.compare_kv_plane))) > 1:
        # Each comparison pins its own workload/sizing; combining them
        # would silently measure one lane on the other's shape.
        p.error("--compare-admission/--compare-hybrid/--compare-ladder/"
                "--compare-spec/--compare-fleet/--compare-pd/"
                "--compare-elastic/--compare-fabric/--compare-chaos-rpc/"
                "--compare-kv-plane "
                "are mutually exclusive; run them as separate "
                "invocations")

    if args.smoke:
        # One switch pins every knob to the CPU-affordable shape so the
        # tier-1 lane cannot drift from what CI actually runs.
        args.model, args.tokenizer = "tiny-llama", "byte"
        args.platform = "cpu"
        args.max_trace = min(args.max_trace, 4)
        args.max_batch_size, args.num_pages = 4, 128
        args.page_size, args.max_pages_per_seq = 8, 8
        args.decode_steps_per_call = 4
        if args.compare_admission:
            # The comparison needs a pool TIGHT enough that worst-case
            # reservation actually binds: generations budgeted well past
            # their prompts, a pool that holds ~2 worst cases, and a
            # burst arrival so requests overlap. Optimistic admission
            # packs more lanes and preempts under pressure — the
            # occupancy delta is the artifact's point.
            args.num_pages, args.max_pages_per_seq = 20, 12
        if args.compare_hybrid:
            # The comparison needs one LONG (multi-chunk) prompt
            # prefilling while short requests decode: room for a
            # 127-token prompt, a 16-token chunk size (8 chunks), and
            # shorts with enough generation budget to still be decoding
            # through every chunk. run_replay pins the matching schedule.
            args.max_pages_per_seq = 16
            args.chunked_prefill_size = 16
        if args.compare_ladder:
            # The comparison needs a burst WIDER than the top rung so
            # the ladder actually climbs: a pool holding every request's
            # worst case (the comparison measures concurrency, not
            # admission), and enough generation budget per stream that
            # decode — not prefill — dominates the wall. K=1 keeps the
            # per-dispatch host round trip (the thing wide batches
            # amortize) in the measurement instead of fusing it away —
            # on CPU the fused-K scan is compute-bound and would
            # understate the chip-side concurrency win being pinned.
            args.max_batch_size = 8            # per-arm override below
            args.num_pages, args.max_pages_per_seq = 448, 8
            args.decode_steps_per_call = 1
        if args.compare_spec:
            # The comparison needs room for multi-turn transcripts (two
            # turns of prompt+reply per stream: 256-token contexts),
            # long enough generations that the tiny greedy model's
            # repetition cycles form (the echo self-drafting exploits),
            # and a γ deep enough that an accepted round visibly beats
            # a plain dispatch. K=1 keeps the per-dispatch host round
            # trip — the cost every accepted speculative token removes —
            # in the measurement (the compare-ladder stance: the fused-K
            # scan is compute-bound on CPU and would bury the dispatch
            # amortization this lane pins; on TPU decode is HBM-bound
            # and the verify's extra positions ride the same weight
            # stream).
            args.max_pages_per_seq, args.num_pages = 64, 320
            args.decode_steps_per_call = 1
            args.num_speculative_tokens = 5
            args.ngram_window = 3
        if args.compare_fleet:
            # dp=2 both backends; host tier on so drain migration has a
            # destination; no warmup (8 worker boots across the arms —
            # lazy compile keeps the tier-1 lane affordable and greedy
            # byte-identity is compile-order-independent).
            args.dp = 2
            args.num_pages, args.max_pages_per_seq = 128, 8
            args.host_cache_pages = 64
            args.decode_steps_per_call = 4
            args.no_warmup = True
        if args.compare_chaos_rpc:
            # Same dp=2 subprocess shape as compare-fleet; tight
            # per-verb deadlines so the wedged connection's detection
            # (3 consecutive timeouts -> recycle) costs seconds, not
            # the default minute, inside the tier-1 budget.
            args.dp = 2
            args.num_pages, args.max_pages_per_seq = 128, 8
            args.host_cache_pages = 64
            args.decode_steps_per_call = 4
            args.no_warmup = True
            args.rpc_deadline_fast_s = 2.0
            args.rpc_deadline_slow_s = 4.0
        if args.compare_elastic:
            # One subprocess worker to start (the whole point: the
            # AUTOSCALER adds the second), a shed cap tight enough that
            # the 20-request peak actually overflows it, and an SLO
            # target sized so parked batch TTFT breaches it by seconds
            # while a preempting interactive holds it easily. Host tier
            # on so drains migrate. Warmup stays ON — scale-up workers
            # and rollout successors join mid-burst, and a cold
            # replica's lazy compile would land in exactly the
            # interactive TTFT this lane grades; one tiny prefill
            # bucket keeps each warm boot to seconds.
            args.dp = 1
            args.num_pages, args.max_pages_per_seq = 128, 8
            args.host_cache_pages = 64
            args.decode_steps_per_call = 2
            args.admission_queue_depth = 6
            args.prefill_buckets = (16,)
            if not args.slo_ttft_ms:
                # Sits in the wide gap between warm interactive TTFT
                # (~tens of ms) and parked-batch TTFT (seconds): the
                # router-observed p95 breaches while the batch wave is
                # parked, yet the interactive class holds it with
                # margin.
                args.slo_ttft_ms = 600.0
        if args.compare_fabric:
            # Many users share one 256-token system prompt across a
            # dp=2 subprocess fleet: prompts are prefix_pages *
            # page_size shared tokens + a short distinct tail, and the
            # prefill buckets are split so a fabric-warm prefill (tail
            # only) runs the small bucket while a cold one pays the
            # big one. Host tier ON (fabric pulls restore through it);
            # no warmup (up to 7 worker boots across the three arms —
            # each arm runs an unmeasured compile-warm pass first).
            # The raised preempt watermark makes chaos page pressure —
            # the lane's deterministic stand-in for a saturated
            # replica — actually flip the routing pressure bit: a
            # pressured worker's free+evictable (its whole prefix
            # cache) stays under 128 once every free page is held,
            # while the unpressured replica (384-page pool, ~200 pages
            # of worst-case wave footprint) never dips below it.
            args.dp = 2
            args.page_size, args.max_pages_per_seq = 8, 40
            args.num_pages = 384
            args.host_cache_pages = 128
            args.decode_steps_per_call = 4
            args.no_warmup = True
            args.fabric_prefix_pages = 32
            args.fabric_users = 6
            args.fabric_wave2_users = 6
            args.prefill_buckets = (16, 64, 320)
            args.preempt_watermark_pages = 128
        if args.compare_kv_plane:
            # 1 prefill + 1 decode worker; EVERY request hands its KV
            # off between them, so the wave is pure data-plane
            # traffic. BIG payloads without long-context compute: the
            # fatkv model carries 16 KiB of KV per token (the
            # production KV:compute ratio the stock tiny models are
            # two orders of magnitude under), so a 448-token prompt —
            # 7 full 64-token pages, distinct per user so nothing
            # prefix-caches away, one prefill bucket fitting it whole
            # — hands off ~7.3 MiB of serialized KV after a sub-second
            # CPU prefill. The fixed costs of a handoff (dispatch RPC,
            # admission, device restore, first decode step) are
            # identical in both arms; MiB-scale blobs are what make
            # the per-byte contrast visible over that floor. The relay
            # arm moves every payload twice through router sockets
            # (plus a router-side digest pass); the shm arm's
            # descriptors carry bytes that never left the arena.
            # Fabric stays ON so fabric_put publishes are part of the
            # relay-vs-shm blob contrast. No warmup (4 worker boots
            # across the arms); each arm runs an unmeasured compile-
            # warm wave first.
            args.dp = 2
            args.model = "tiny-llama-fatkv"
            args.page_size, args.max_pages_per_seq = 64, 8
            # Pool headroom and NO host tier: a reclaim during the
            # measured series must be a free-list pop, not an eviction
            # batch demoting victims through a device_get — that demote
            # lands as a ~50 ms outlier inside whichever adopt it
            # interrupts (both arms equally) and owns the p95.
            args.num_pages = 144
            args.host_cache_pages = 0
            # One decode dispatch in flight at a time: the export's
            # device_get orders after in-flight dispatch, so a deeper
            # dispatch-ahead window pads BOTH arms' export wall with
            # identical decode work and dilutes the transit contrast.
            args.decode_steps_per_call = 1
            args.no_warmup = True
            args.prefill_buckets = (16, 512)
            args.kvp_users = 12
            args.kvp_prompt_pages = 7
            args.kvp_pool_pages = 64
            # Sized so the WHOLE run's slabs fit a region without one
            # free ever landing: frees ride the periodic stats tick, so
            # during back-to-back waves the prefill region must hold
            # warm+measured+kill publishes at once (36 x ~7.45 MiB
            # extents ~= 268 MiB < 384 MiB/region at dp=2). An
            # undersized arena degrades gracefully (ArenaFull -> relay
            # fallback) but that contaminates the shm arm's walls.
            args.shm_arena_bytes = 768 * 1024 * 1024
        if args.compare_pd:
            # dp=2 subprocess topologies, room for the 448-token long
            # prompts (ctx 640 at page_size 16), host tier on. K=2
            # flushes give the client-side gap measurement ~2-token
            # resolution; no warmup (6 worker boots across 3 arms —
            # each arm runs an UNMEASURED warm pass of the exact
            # workload first, so lazy compiles never land in a measured
            # phase).
            args.dp = 2
            # SLO targets sized to the CPU lane's loaded-phase latency
            # so the breach counters exercise for real (the quantile
            # gauges export regardless; magnitudes are recorded, not
            # graded live).
            if not args.slo_ttft_ms:
                args.slo_ttft_ms = 2000.0
            if not args.slo_tpot_ms:
                args.slo_tpot_ms = 200.0
            args.page_size, args.max_pages_per_seq = 16, 40
            args.num_pages = 512
            args.host_cache_pages = 64
            args.decode_steps_per_call = 2
            args.no_warmup = True
            # 256-token chunks: one in-engine prefill dispatch stalls
            # decode by a full chunk wall (the interference this lane
            # exists to show); the pd arm's decode engine never
            # dispatches one.
            args.prefill_buckets = (16, 64, 256)
        if args.out is None:
            args.out = ("benchmarks/results/replay_hybrid.json"
                        if args.compare_hybrid
                        else "benchmarks/results/replay_ladder.json"
                        if args.compare_ladder
                        else "benchmarks/results/replay_spec.json"
                        if args.compare_spec
                        else "benchmarks/results/replay_fleet.json"
                        if args.compare_fleet
                        else "benchmarks/results/replay_pd.json"
                        if args.compare_pd
                        else "benchmarks/results/replay_elastic.json"
                        if args.compare_elastic
                        else "benchmarks/results/replay_fabric.json"
                        if args.compare_fabric
                        else "benchmarks/results/replay_chaos_rpc.json"
                        if args.compare_chaos_rpc
                        else "benchmarks/results/replay_kv_plane.json"
                        if args.compare_kv_plane
                        else "benchmarks/results/replay_smoke.json")
        if args.compare_pd and args.trace_artifact is None:
            args.trace_artifact = os.path.join(
                os.path.dirname(args.out) or ".", "replay_pd_trace.json")

    if args.platform != "auto":
        # Before any jax computation (env vars are read too early in
        # some images; jax.config is the reliable override — same
        # pattern as the server CLI and tests/conftest.py).
        import jax

        jax.config.update("jax_platforms", args.platform)
        if args.platform == "cpu" and args.dp * args.tp * args.sp > 1:
            # Only force the virtual-device count when the run actually
            # needs a multi-device mesh: the CPU default is 1 device,
            # and shrinking a host that asked for more (the in-process
            # --smoke test runs inside pytest's 8-device session) would
            # pin the whole process to 1 device before backend init.
            # (After backend init the call is a harmless no-op, so the
            # pytest session's 8 devices always win.)
            from tpu_inference.compat import set_cpu_device_count

            set_cpu_device_count(args.dp * args.tp * args.sp)

    from tpu_inference.engine.autosize import (parse_decode_ladder,
                                               resolve_sizing_args)

    args.max_batch_size, args.num_pages = resolve_sizing_args(args)

    try:
        args.decode_ladder_rungs = parse_decode_ladder(
            args.decode_ladder, args.max_batch_size)
    except ValueError as e:
        p.error(str(e))

    if args.compare_admission:
        return _compare_admission(args)
    if args.compare_hybrid:
        return _compare_hybrid(args)
    if args.compare_ladder:
        return _compare_ladder(args)
    if args.compare_spec:
        return _compare_spec(args)
    if args.compare_fleet:
        return _compare_fleet(args)
    if args.compare_pd:
        return _compare_pd(args)
    if args.compare_elastic:
        return _compare_elastic(args)
    if args.compare_fabric:
        return _compare_fabric(args)
    if args.compare_chaos_rpc:
        return _compare_chaos_rpc(args)
    if args.compare_kv_plane:
        return _compare_kv_plane(args)

    summary = run_replay(args)
    out = {"config": vars(args), "summary": summary}
    print(json.dumps(summary, indent=1))
    _write_out(args.out, out)
    return summary


def run_replay(args) -> dict:
    """Boot one server, replay the trace, scrape, summarize."""
    from traffic_generator.data import DataLoader
    from traffic_generator.generator import TrafficGenerator
    from traffic_generator.metrics import MetricCollector
    from traffic_generator.schedule import Scheduler

    srv, port, stop = start_server(args)
    try:
        data = DataLoader.get_data_from_path(args.data)
        schedule = Scheduler.get_schedule_from_trace(args.trace,
                                                     args.max_trace)
        if args.compare_admission:
            # Burst arrival: all requests land at t=0 so both admission
            # modes face the same overlapping demand (trace gaps on a
            # fast CPU model would serialize the run and hide the
            # occupancy difference being measured).
            schedule["Timestamp"] = 0.0
        if getattr(args, "compare_hybrid", False) and args.smoke:
            # Pinned decode-stall workload: ONE long prompt (8 chunks of
            # 16 once truncated to max_context-1=127) submitted first so
            # it starts its incremental prefill, then three shorts that
            # batch-admit while it is mid-chunks and keep decoding
            # through every remaining chunk. Serial chunking stalls
            # those lanes once per chunk (decode_stall samples); hybrid
            # steps fuse the chunks into their decode dispatches
            # (structurally zero samples) — the artifact compares
            # exactly that histogram.
            import pandas as pd
            schedule = pd.DataFrame({
                "Timestamp": [0.0, 0.0, 0.0, 0.0],
                "Request tokens": [128, 8, 8, 8],
                "Response tokens": [8, 64, 64, 64],
            })
        collector = MetricCollector()
        gen_kw = {}
        if args.smoke:
            gen_kw = ({"max_prompt_len": 24, "max_gen_len": 48}
                      if args.compare_admission else
                      {"max_prompt_len": 128, "max_gen_len": 64}
                      if getattr(args, "compare_hybrid", False) else
                      {"max_prompt_len": 48, "max_gen_len": 12})
        gen = TrafficGenerator(
            data, schedule,
            {"url": f"http://127.0.0.1:{port}/api/generate",
             "model": args.model, "temperature": args.temperature,
             "max_tokens": None, "stream": True,
             "max_retries": args.client_max_retries},
            collector, **gen_kw)
        # Pre-run scrape over real HTTP: phase_breakdown diffs the
        # histograms so only THIS run's window is attributed.
        before_json, _ = scrape_metrics(port, fmt="json")
        before = json.loads(before_json)
        t0 = time.perf_counter()
        metrics = gen.start_profile()
        replay_s = time.perf_counter() - t0
        after_json, _ = scrape_metrics(port, fmt="json")
        after = json.loads(after_json)
        prom_text, prom_ctype = scrape_metrics(port)
        attribution = step_attribution(port)
        summary = summarize(metrics,
                            n_chips=getattr(args, "dp", 1) * args.tp * args.sp)
        summary["replay_s"] = round(replay_s, 3)
        summary["server_stats"] = after
        # Admission-mode lane: the occupancy / preemption / shed numbers
        # the reserve-vs-optimistic artifact compares.
        summary["admission"] = {
            "mode": after.get("admission"),
            "mean_batch_occupancy": after.get("mean_batch_occupancy"),
            "preemptions": after.get("preemptions"),
            "recompute_resumes": after.get("recompute_resumes"),
            "requests_rejected": after.get("requests_rejected"),
            "peak_pages_in_use": after.get("peak_pages_in_use"),
            "pool_pressure": after.get("pool_pressure"),
            "shed_rate": summary["shed_rate"],
        }
        summary["phase_breakdown"] = phase_breakdown(before, after)
        summary["step_attribution"] = attribution
        # Rolling SLO gauges (README "Observability"): the fleet's
        # exact windowed quantiles + breach counts at scrape time
        # (windows dropped — the artifact carries the numbers).
        if after.get("slo"):
            summary["slo"] = {k: v for k, v in after["slo"].items()
                              if not k.endswith("_window")}
        # Speculative-decoding lane (README "Speculative decoding"):
        # mode/γ/acceptance from the server's own counters when spec is
        # on (absent otherwise).
        if after.get("speculative"):
            summary["speculative"] = after["speculative"]
        # Hybrid-stepping lane: the decode-stall-during-prefill numbers
        # the serial-vs-hybrid artifact compares (count 0 -> p95 0.0:
        # nothing ever stalled).
        stall = summary["phase_breakdown"].get(
            "decode_stall_during_prefill_s") or {}
        summary["hybrid"] = {
            "enabled": bool(after.get("hybrid_prefill")),
            "hybrid_steps": after.get("hybrid_steps"),
            "decode_stall_count": stall.get("count", 0),
            "decode_stall_p95_s": stall.get("p95") or 0.0,
            "decode_stall_sum_s": stall.get("sum") or 0.0,
        }
        summary["prometheus_scrape"] = {
            "content_type": prom_ctype,
            "families": prom_text.count("# TYPE "),
            "samples": sum(1 for l in prom_text.splitlines()
                           if l and not l.startswith("#")),
        }
    finally:
        stop()
    return summary


def _compare_admission(args) -> dict:
    """Run the trace under admission=reserve then admission=optimistic
    (fresh server each) and commit the side-by-side artifact: batch
    occupancy, tokens/s, shed rate, preemption counts."""
    # Snapshot the invocation BEFORE the per-arm mutation below, so the
    # committed config reproduces this comparison (not the last arm).
    cfg_snapshot = dict(vars(args))
    summaries = {}
    for mode in ("reserve", "optimistic"):
        args.admission = mode
        print(f"[replay] admission={mode} lane", file=sys.stderr)
        summaries[mode] = run_replay(args)
    res, opt = summaries["reserve"], summaries["optimistic"]

    def _occ(s):
        return s["admission"]["mean_batch_occupancy"] or 0.0

    comparison = {
        "occupancy_reserve": round(_occ(res), 4),
        "occupancy_optimistic": round(_occ(opt), 4),
        "occupancy_gain": round(_occ(opt) - _occ(res), 4),
        "tokens_per_s_reserve": res["tokens_per_s"],
        "tokens_per_s_optimistic": opt["tokens_per_s"],
        "shed_rate_reserve": res["shed_rate"],
        "shed_rate_optimistic": opt["shed_rate"],
        "preemptions": opt["admission"]["preemptions"],
        "recompute_resumes": opt["admission"]["recompute_resumes"],
        # The artifact's claim: optimistic admission packs more of the
        # batch (or matches throughput with a lower shed rate).
        "optimistic_wins": bool(
            _occ(opt) > _occ(res)
            or (opt["tokens_per_s"] >= res["tokens_per_s"]
                and opt["shed_rate"] <= res["shed_rate"])),
    }
    out = {"config": cfg_snapshot, "reserve": res, "optimistic": opt,
           "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result["reserve"], result["optimistic"] = res, opt
    return result


def _compare_hybrid(args) -> dict:
    """Run the workload under serial chunked prefill then under hybrid
    fused steps (fresh server each) and commit the side-by-side
    artifact: p95 decode stall while a prompt prefills (the server-side
    inter-token stall hybrid exists to remove), aggregate tokens/s,
    TTFT, and the client-observed worst inter-chunk gap."""
    # Snapshot the invocation BEFORE the per-arm mutation below, so the
    # committed config reproduces this comparison (not the last arm).
    cfg_snapshot = dict(vars(args))
    summaries = {}
    for mode in ("serial", "hybrid"):
        args.hybrid_prefill = (mode == "hybrid")
        print(f"[replay] scheduling={mode} lane", file=sys.stderr)
        summaries[mode] = run_replay(args)
    ser, hyb = summaries["serial"], summaries["hybrid"]

    comparison = {
        "decode_stall_count_serial": ser["hybrid"]["decode_stall_count"],
        "decode_stall_count_hybrid": hyb["hybrid"]["decode_stall_count"],
        "decode_stall_p95_serial_s": ser["hybrid"]["decode_stall_p95_s"],
        "decode_stall_p95_hybrid_s": hyb["hybrid"]["decode_stall_p95_s"],
        "hybrid_steps": hyb["hybrid"]["hybrid_steps"],
        "tokens_per_s_serial": ser["tokens_per_s"],
        "tokens_per_s_hybrid": hyb["tokens_per_s"],
        "tok_s_ratio": round(hyb["tokens_per_s"]
                             / max(ser["tokens_per_s"], 1e-9), 4),
        "ttft_p99_serial_s": ser["ttft_s"]["p99"],
        "ttft_p99_hybrid_s": hyb["ttft_s"]["p99"],
        "max_interchunk_gap_p99_serial_s":
            ser["max_interchunk_gap_s"]["p99"],
        "max_interchunk_gap_p99_hybrid_s":
            hyb["max_interchunk_gap_s"]["p99"],
        # Greedy decoding + identical prompts: both arms must emit the
        # same token counts (the HTTP-level echo of the byte-equality
        # tests/test_hybrid.py pins at engine level).
        "output_tokens_serial": ser["output_tokens"],
        "output_tokens_hybrid": hyb["output_tokens"],
        # Committed-artifact throughput check (tok/s no more than 5%
        # below serial). Deliberately NOT folded into hybrid_wins: the
        # tier-1 smoke asserts hybrid_wins, and wall-clock tok/s on a
        # loaded CI box swings far more than 5% run to run — the
        # deterministic stall histogram is the CI-gradable claim, the
        # ratio is graded on the artifact actually committed.
        "tok_s_within_5pct": bool(
            hyb["tokens_per_s"] >= 0.95 * ser["tokens_per_s"]),
        # The artifact's claim: fusing removes the decode stall (the
        # chunk-sized inter-token spike) entirely. Guarded on the serial
        # arm actually MEASURING a stall (same guard as bench.py's
        # stall_removed) so a run whose chunks never met a busy batch —
        # or one with telemetry disabled — can't claim a vacuous win.
        "hybrid_wins": bool(
            ser["hybrid"]["decode_stall_count"] > 0
            and hyb["hybrid"]["decode_stall_p95_s"]
            <= ser["hybrid"]["decode_stall_p95_s"]
            and hyb["hybrid"]["decode_stall_count"]
            < ser["hybrid"]["decode_stall_count"]),
    }
    out = {"config": cfg_snapshot, "serial": ser, "hybrid": hyb,
           "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result["serial"], result["hybrid"] = ser, hyb
    return result


async def _ladder_burst(port: int, model: str, n_requests: int,
                        max_tokens: int) -> list:
    """Fire ``n_requests`` DISTINCT greedy requests at once (the bursty
    mix the ladder exists for) and stream every reply, so the arms can
    be hashed for byte-identity and timed per stream."""
    import aiohttp

    url = f"http://127.0.0.1:{port}/api/generate"
    timeout = aiohttp.ClientTimeout(total=1800)

    async def one(session, i: int) -> dict:
        # Distinct prompts (byte tokenizer: chars = tokens), so greedy
        # decoding produces a distinct transcript per stream; short
        # enough that prompt + the generation budget fits the smoke
        # shape's 64-token context.
        # NON-streamed: a 48-stream burst of per-token NDJSON chunks
        # bottlenecks on the client event loop, not the engine — the
        # ladder's chip-side concurrency win is what this lane pins,
        # so responses come back whole and timing is request-level.
        prompt = f"[{i:02d}] probe"
        payload = {"model": model, "prompt": prompt, "temperature": 0.0,
                   "stream": False, "options": {"num_predict": max_tokens}}
        t0 = time.perf_counter()
        async with session.post(url, json=payload) as resp:
            resp.raise_for_status()
            rec = await resp.json()
        e2e = time.perf_counter() - t0
        n_tokens = rec.get("eval_count", 0)
        # Server-side decode wall per token (eval_duration is the
        # engine's own decode-phase accounting): the per-stream latency
        # the batch width actually changes, independent of queue wait.
        tpot = (rec.get("eval_duration", 0) / 1e9 / (n_tokens - 1)
                if n_tokens > 1 else None)
        return {"idx": i, "reply": rec.get("response", ""),
                "ttft_s": None, "e2e_s": e2e, "output_tokens": n_tokens,
                "tpot_s": tpot}

    async with aiohttp.ClientSession(timeout=timeout) as session:
        return list(await asyncio.gather(*[one(session, i)
                                           for i in range(n_requests)]))


def _ladder_arm(args, label: str) -> dict:
    """Boot one server, run the pinned burst, summarize one arm."""
    import hashlib

    print(f"[replay] ladder arm: {label}", file=sys.stderr)
    srv, port, stop = start_server(args)
    try:
        t0 = time.perf_counter()
        records = asyncio.run(_ladder_burst(
            port, args.model, args.ladder_requests, args.ladder_tokens))
        wall = time.perf_counter() - t0
        after = json.loads(scrape_metrics(port, fmt="json")[0])
    finally:
        stop()
    h = hashlib.sha256()
    for r in sorted(records, key=lambda r: r["idx"]):
        h.update(f"{r['idx']}:".encode())
        h.update(r["reply"].encode())
        h.update(b"\x00")
    tokens = sum(r["output_tokens"] for r in records)
    tpots = [r["tpot_s"] for r in records if r["tpot_s"] is not None]
    bubble = (after.get("phases") or {}).get("dispatch_bubble_s") or {}
    return {
        "label": label,
        "max_batch_size": args.max_batch_size,
        "decode_ladder": list(args.decode_ladder_rungs
                              or (args.max_batch_size,)),
        "stage_host_reuse": getattr(args, "stage_host_reuse", True),
        "requests": len(records),
        "output_tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(tokens / wall, 2),
        "ttft_s": _percentiles([r["ttft_s"] for r in records
                                if r["ttft_s"] is not None], ps=(50, 95)),
        "tpot_s": _percentiles(tpots, ps=(50, 95)),
        "e2e_s": _percentiles([r["e2e_s"] for r in records], ps=(50, 95)),
        "outputs_sha256": h.hexdigest(),
        "rung_peak": after.get("rung_peak"),
        "rung_switches": after.get("rung_switches"),
        "mean_batch_occupancy": after.get("mean_batch_occupancy"),
        "mfu_estimate": after.get("mfu_estimate"),
        "dispatch_bubble_p50_s": bubble.get("p50"),
        "dispatch_bubble_p95_s": bubble.get("p95"),
        "dispatch_bubble_count": bubble.get("count"),
    }


def _staging_micro(model_cfg, *, page_size, num_pages, max_pages_per_seq,
                   top) -> dict:
    """Deterministic per-dispatch host staging cost at the top rung,
    reuse vs rebuild (microseconds). The arm-level bubble histograms
    also carry scheduler/callback work; this isolates exactly what the
    staging reuse removes, engine-inline with no server. THE one
    implementation — bench.py's ladder lane imports it, so the two
    committed artifacts measure the same thing."""
    from tpu_inference.config import EngineConfig
    from tpu_inference.engine.autosize import decode_ladder_rungs
    from tpu_inference.engine.engine import InferenceEngine, Sequence

    ecfg = EngineConfig(
        page_size=page_size, num_pages=num_pages,
        max_pages_per_seq=max_pages_per_seq,
        max_batch_size=top, decode_ladder=decode_ladder_rungs(top),
        prefill_buckets=(16, 32), decode_steps_per_call=1)
    engine = InferenceEngine(model_cfg, ecfg)
    for i in range(top):
        engine.prefill(Sequence(
            request_id=i, prompt_tokens=[1 + (i + j) % 250
                                         for j in range(16)],
            max_new_tokens=8))
    act = engine.active_sequences()
    out = {}
    for reuse in (True, False):
        engine._stage_reuse = reuse
        engine._stage_batch(act, top)          # warm the buffers
        t0 = time.perf_counter()
        reps = 500
        for _ in range(reps):
            engine._stage_batch(act, top)
        out["reuse_us" if reuse else "rebuild_us"] = round(
            (time.perf_counter() - t0) / reps * 1e6, 1)
    out["speedup"] = round(out["rebuild_us"] / max(out["reuse_us"], 1e-9),
                           2)
    return out


def _compare_ladder(args) -> dict:
    """The batch-ladder artifact (README "Batch ladder"): the same
    pinned greedy burst served by (a) the fixed bs=8 graph, (b) the
    compiled ladder up to ``--ladder-top``, and (c) the ladder with
    host-staging reuse disabled — so one committed file carries the
    concurrency win (aggregate tok/s at bs>=32 vs bs=8), the per-stream
    latency bound, greedy byte-identity across batch shapes, and the
    host-bubble p95 drop the staging reuse buys."""
    from tpu_inference.engine.autosize import (decode_ladder_rungs,
                                               resolve_model_config)

    args.ladder_tokens = 48
    cfg_snapshot = dict(vars(args))
    arms = {}

    args.max_batch_size, args.decode_ladder_rungs = 8, ()
    args.stage_host_reuse = True
    arms["bs8"] = _ladder_arm(args, "bs8")

    args.max_batch_size = args.ladder_top
    args.decode_ladder_rungs = decode_ladder_rungs(args.ladder_top)
    arms["ladder"] = _ladder_arm(args, "ladder")

    args.stage_host_reuse = False
    arms["ladder_rebuild"] = _ladder_arm(args, "ladder_rebuild")
    args.stage_host_reuse = True

    bs8, lad, reb = arms["bs8"], arms["ladder"], arms["ladder_rebuild"]
    comparison = {
        "ladder": lad["decode_ladder"],
        "tokens_per_s_bs8": bs8["tokens_per_s"],
        "tokens_per_s_ladder": lad["tokens_per_s"],
        "tok_s_ratio": round(lad["tokens_per_s"]
                             / max(bs8["tokens_per_s"], 1e-9), 4),
        "tpot_p50_bs8_s": bs8["tpot_s"]["p50"],
        "tpot_p50_ladder_s": lad["tpot_s"]["p50"],
        # Decode-wall-per-token ratio, reported transparently: on a
        # single-core CPU lane the 32-wide graph's compute serializes,
        # so this exceeds 1 by construction here; on TPU decode is
        # HBM-bound and the batch rides the same weight stream.
        "tpot_ratio": (
            round(lad["tpot_s"]["p50"] / bs8["tpot_s"]["p50"], 4)
            if lad["tpot_s"]["p50"] and bs8["tpot_s"]["p50"] else None),
        # The acceptance bound: what a STREAM experiences under the
        # same offered burst — per-request latency (queue wait included:
        # the fixed bs=8 graph makes 48 streams queue 6 waves deep,
        # which is precisely the cost the ladder removes). Within 1.5x
        # of bs=8 required; in practice the ladder is strictly faster.
        "per_stream_latency_ratio": (
            round(lad["e2e_s"]["p50"] / bs8["e2e_s"]["p50"], 4)
            if lad["e2e_s"]["p50"] and bs8["e2e_s"]["p50"] else None),
        "e2e_p50_bs8_s": bs8["e2e_s"]["p50"],
        "e2e_p50_ladder_s": lad["e2e_s"]["p50"],
        "e2e_p95_bs8_s": bs8["e2e_s"]["p95"],
        "e2e_p95_ladder_s": lad["e2e_s"]["p95"],
        "rung_peak": lad["rung_peak"],
        "rung_switches": lad["rung_switches"],
        "mfu_estimate_ladder": lad["mfu_estimate"],
        # Byte-identity across batch shapes: greedy decode is a per-lane
        # computation, so graph width must never change tokens.
        "outputs_identical": (bs8["outputs_sha256"]
                              == lad["outputs_sha256"]
                              == reb["outputs_sha256"]),
        # Host-staging reuse (the per-dispatch bubble shrinker): the
        # host-side gap between decode dispatches, reuse vs rebuild,
        # plus the isolated staging micro-cost (the bubble histograms
        # also carry scheduler/callback work).
        "bubble_p50_reuse_s": lad["dispatch_bubble_p50_s"],
        "bubble_p50_rebuild_s": reb["dispatch_bubble_p50_s"],
        "bubble_p95_reuse_s": lad["dispatch_bubble_p95_s"],
        "bubble_p95_rebuild_s": reb["dispatch_bubble_p95_s"],
        "bubble_p95_improved": bool(
            lad["dispatch_bubble_p95_s"] is not None
            and reb["dispatch_bubble_p95_s"] is not None
            and lad["dispatch_bubble_p95_s"]
            <= reb["dispatch_bubble_p95_s"]),
        "stage_us_per_dispatch": _staging_micro(
            resolve_model_config(args.model, args.checkpoint),
            page_size=args.page_size, num_pages=args.num_pages,
            max_pages_per_seq=args.max_pages_per_seq,
            top=args.ladder_top),
        # The artifact's claim: the ladder serves the burst strictly
        # faster in aggregate, within the per-stream latency bound,
        # with byte-identical outputs, having actually reached the top.
        "ladder_wins": bool(
            lad["tokens_per_s"] > bs8["tokens_per_s"]
            and lad["rung_peak"] == lad["decode_ladder"][-1]
            and bs8["outputs_sha256"] == lad["outputs_sha256"]),
    }
    out = {"config": cfg_snapshot, "bs8": bs8, "ladder": lad,
           "ladder_rebuild": reb, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result.update(bs8=bs8, ladder=lad, ladder_rebuild=reb)
    return result


async def _spec_burst(port: int, model: str, prompts: list,
                      max_tokens: int, temperature: float) -> list:
    """Fire one request per prompt at once (non-streamed) and return
    [{reply, eval_count, eval_duration_ns}] in prompt order — the spec
    arms hash replies for byte-identity and read per-stream decode rate
    from the server's own eval accounting."""
    import aiohttp

    url = f"http://127.0.0.1:{port}/api/generate"
    timeout = aiohttp.ClientTimeout(total=1800)

    async def one(session, prompt: str) -> dict:
        payload = {"model": model, "prompt": prompt,
                   "temperature": temperature, "stream": False,
                   "options": {"num_predict": max_tokens}}
        async with session.post(url, json=payload) as resp:
            resp.raise_for_status()
            rec = await resp.json()
        return {"reply": rec.get("response", ""),
                "eval_count": rec.get("eval_count", 0),
                "eval_duration_ns": rec.get("eval_duration", 0)}

    async with aiohttp.ClientSession(timeout=timeout) as session:
        return list(await asyncio.gather(*[one(session, p)
                                           for p in prompts]))


def _spec_arm(args, label: str, mix: str, ngram: bool) -> dict:
    """Boot one server (plain or ngram-spec), run one pinned mix, and
    summarize: per-stream decode tok/s (server-side eval accounting, so
    queue effects don't pollute the per-stream claim), aggregate tok/s,
    a transcript hash, and the /metrics speculative block.

    Mixes:
    - "echo": greedy, two turns per stream, turn 2 re-sends turn 1's
      transcript — the multi-turn/RAG echo shape self-drafting exists
      for (the tiny model's greedy repetition cycles stand in for
      real-text echo). Byte-identity across arms is asserted here.
    - "adversarial": temperature-sampled streams whose proposals almost
      never verify — the mix adaptive γ must throttle on so spec never
      loses. No byte-identity (sampled), throughput only.
    """
    import hashlib

    print(f"[replay] spec arm: {label}/{mix}", file=sys.stderr)
    args.spec_mode = "ngram" if ngram else None
    srv, port, stop = start_server(args)
    n = args.spec_streams
    try:
        t0 = time.perf_counter()
        if mix == "echo":
            turn1 = [f"<s{i}> the quick brown fox {i:02d} " for i in range(n)]
            rec1 = asyncio.run(_spec_burst(port, args.model, turn1,
                                           max_tokens=200, temperature=0.0))
            turn2 = [p + r["reply"] for p, r in zip(turn1, rec1)]
            rec2 = asyncio.run(_spec_burst(port, args.model, turn2,
                                           max_tokens=120, temperature=0.0))
            records = rec1 + rec2
        else:
            rng = __import__("random").Random(1234)
            # 2n streams (two admission waves): decode-phase rates are
            # queue-independent, and the larger sample steadies the
            # median on a noisy CI box.
            prompts = ["".join(chr(33 + rng.randrange(90))
                               for _ in range(24)) for _ in range(2 * n)]
            # Long streams: the never-lose overhead (initial narrow
            # rounds + backed-off probes) is front-loaded, so length
            # amortizes it toward zero — and steadies the rates.
            records = asyncio.run(_spec_burst(port, args.model, prompts,
                                              max_tokens=320,
                                              temperature=1.0))
        wall = time.perf_counter() - t0
        after = json.loads(scrape_metrics(port, fmt="json")[0])
    finally:
        stop()
    h = hashlib.sha256()
    for r in records:
        h.update(r["reply"].encode())
        h.update(b"\x00")
    tokens = sum(r["eval_count"] for r in records)
    timed = sorted((r for r in records
                    if r["eval_count"] > 1 and r["eval_duration_ns"] > 0),
                   key=lambda r: (r["eval_count"] - 1)
                   / r["eval_duration_ns"])
    if len(timed) > 4:
        # Trim each arm's fastest and slowest record before pooling: one
        # GC pause or OS-scheduler stall hitting one stream otherwise
        # dominates the pooled rate on a shared CI box.
        timed = timed[1:-1]
    eval_toks = sum(r["eval_count"] - 1 for r in timed)
    eval_s = sum(r["eval_duration_ns"] / 1e9 for r in timed)
    spec = after.get("speculative") or {}
    return {
        "label": label, "mix": mix, "streams": n,
        "requests": len(records),
        "output_tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(tokens / wall, 2),
        # Pooled per-stream decode rate from the server's own
        # eval_duration (total decode tokens / total decode wall across
        # streams) — the "per-stream tok/s" the acceptance gate names.
        # Decode phase only, so queue wait / prefill / HTTP noise are
        # excluded by design, and pooling beats a median of few noisy
        # per-request rates on a loaded CI box.
        "per_stream_tok_s": round(eval_toks / eval_s, 2) if eval_s
        else None,
        "outputs_sha256": h.hexdigest(),
        "speculative": {k: spec.get(k) for k in
                        ("mode", "gamma", "drafted", "accepted",
                         "acceptance_rate", "rounds", "fallback_rounds",
                         "throttles")} if spec else None,
    }


def _compare_spec(args) -> dict:
    """The draft-free speculation artifact (README "Speculative
    decoding"): the same pinned echo-heavy greedy multi-turn mix served
    plain and with ngram self-drafting (byte-identical outputs required
    — speculation is a scheduling decision, never a behavior change),
    plus an adversarial no-echo sampled mix where the adaptive-γ
    throttle must keep the spec arm within noise of plain (spec never
    loses)."""
    cfg_snapshot = dict(vars(args))
    arms = {}
    for mix in ("echo", "adversarial"):
        for label, ngram in (("plain", False), ("ngram", True)):
            arms[f"{mix}_{label}"] = _spec_arm(args, label, mix, ngram)
    args.spec_mode = None

    def _ratio(a, b):
        return round(a / b, 4) if a and b else None

    ep, en = arms["echo_plain"], arms["echo_ngram"]
    ap, an = arms["adversarial_plain"], arms["adversarial_ngram"]
    espec = en["speculative"] or {}
    aspec = an["speculative"] or {}
    comparison = {
        "gamma": espec.get("gamma"),
        # Echo mix: the win. Byte-identity is the deterministic claim;
        # the per-stream decode ratio is the headline magnitude.
        "per_stream_tok_s_plain": ep["per_stream_tok_s"],
        "per_stream_tok_s_ngram": en["per_stream_tok_s"],
        "per_stream_ratio": _ratio(en["per_stream_tok_s"],
                                   ep["per_stream_tok_s"]),
        "tokens_per_s_plain": ep["tokens_per_s"],
        "tokens_per_s_ngram": en["tokens_per_s"],
        "tok_s_ratio": _ratio(en["tokens_per_s"], ep["tokens_per_s"]),
        "outputs_identical": (ep["outputs_sha256"]
                              == en["outputs_sha256"]),
        "acceptance_rate": espec.get("acceptance_rate"),
        "spec_drafted": espec.get("drafted"),
        "spec_accepted": espec.get("accepted"),
        # Adversarial mix: the insurance. The throttle must engage (or
        # matchless rounds fall back outright) and the per-stream decode
        # rate must stay within noise of plain. Per-stream (server-side
        # eval accounting) is the graded number for both mixes — the
        # wall-clock aggregates also carry prefill/HTTP/queue noise and
        # are reported transparently, not graded.
        "adversarial_per_stream_plain": ap["per_stream_tok_s"],
        "adversarial_per_stream_ngram": an["per_stream_tok_s"],
        "adversarial_ratio": _ratio(an["per_stream_tok_s"],
                                    ap["per_stream_tok_s"]),
        "adversarial_tok_s_plain": ap["tokens_per_s"],
        "adversarial_tok_s_ngram": an["tokens_per_s"],
        "adversarial_acceptance_rate": aspec.get("acceptance_rate"),
        "adversarial_throttles": aspec.get("throttles"),
        "adversarial_fallback_rounds": aspec.get("fallback_rounds"),
        # The artifact's claims. spec_wins carries the deterministic
        # parts (graded live by the tier-1 smoke); the >=1.3x /
        # >=0.95x magnitudes are graded on the committed artifact (the
        # ladder/tiering lanes' stance — CI wall clocks swing).
        "spec_wins": bool(
            ep["outputs_sha256"] == en["outputs_sha256"]
            and (espec.get("accepted") or 0) > 0
            and (en["per_stream_tok_s"] or 0)
            > (ep["per_stream_tok_s"] or 0)),
        "spec_never_loses": bool(
            (an["per_stream_tok_s"] or 0)
            >= 0.95 * (ap["per_stream_tok_s"] or 1e9)),
    }
    out = {"config": cfg_snapshot, **arms, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result.update(arms)
    return result


def _wait_inflight_tokens(group, min_tokens: int,
                          timeout: float = 120.0) -> Optional[int]:
    """Block until the subprocess router has streamed ``min_tokens``
    across its tracked requests, then return the replica index holding
    the most in-flight work (the chaos victim). None if the burst
    finished first."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        with group._lock:
            entries = list(group._tracked.values())
            total = sum(len(e.tokens) for e in entries)
            if total >= min_tokens and entries:
                counts = {}
                for e in entries:
                    if e.worker is not None:
                        counts[e.worker.replica] = counts.get(
                            e.worker.replica, 0) + 1
                if counts:
                    return max(counts, key=counts.get)
        time.sleep(0.005)
    return None


def _fleet_arm(args, label: str, fleet: str, chaos: Optional[str] = None,
               migrate: bool = True,
               chaos_rpc: Optional[dict] = None) -> dict:
    """Boot one server on the given fleet backend, run the pinned
    greedy burst, optionally injecting mid-burst chaos (``"kill9"`` =
    SIGKILL the busiest worker; ``"drain"`` = graceful drain of the
    busiest worker, with or without KV migration; ``chaos_rpc`` =
    frame-level transport fault injection armed for the whole burst),
    and summarize."""
    import hashlib

    print(f"[replay] fleet arm: {label}", file=sys.stderr)
    args.fleet = fleet
    args.fleet_migrate = migrate
    args.worker_restart_backoff_s = 0.1
    args.worker_restart_max = 10
    srv, port, stop = start_server(args)
    group = srv.group
    chaos_fired = False
    try:
        # Warm requests before the clock starts: the fleet arms boot
        # without warmup (8 worker processes across the comparison), so
        # these keep lazy XLA compile out of the timed burst — the arms
        # then measure serving, not compile scheduling. Distinct cold
        # prompts ride the rotating tie-break so every replica warms.
        for i in range(2 * getattr(args, "dp", 1)):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/generate",
                data=json.dumps({"model": args.model,
                                 "prompt": f"[w{i}] warm",
                                 "temperature": 0.0, "stream": False,
                                 "options": {"num_predict": 4}}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=600).read()
        if chaos_rpc is not None:
            # Armed AFTER warmup (the warm pass is scaffolding, not the
            # graded burst) and for the burst's whole life: every frame
            # both directions rolls the seeded schedule.
            group.apply_chaos({"rpc": dict(chaos_rpc)})
            chaos_fired = True
        box = {}

        def run_burst():
            box["records"] = asyncio.run(_ladder_burst(
                port, args.model, args.fleet_streams, args.fleet_tokens))

        t0 = time.perf_counter()
        th = threading.Thread(target=run_burst, name="fleet-burst")
        th.start()
        if chaos is not None:
            # Let every stream get going, then hit the busiest worker
            # while its requests are mid-decode.
            victim = _wait_inflight_tokens(
                group, min_tokens=2 * args.fleet_streams)
            if victim is not None:
                if chaos == "kill9":
                    group.apply_chaos({"replica": victim,
                                       "kill": "kill9"})
                else:
                    group.drain_worker(victim, migrate=migrate)
                chaos_fired = True
        th.join()
        wall = time.perf_counter() - t0
        records = box["records"]
        if chaos_fired:
            # Let the supervisor finish the respawn before scraping, so
            # the arm records the restart it caused (the burst usually
            # outpaces worker boot).
            deadline = time.perf_counter() + 60
            while (time.perf_counter() < deadline
                   and not all(h.state == "up" for h in group.workers)):
                time.sleep(0.1)
        after = json.loads(scrape_metrics(port, fmt="json")[0])
        health = group.health_snapshot()
    finally:
        # Stop the fleet explicitly: the bench's loop-stop shortcut
        # skips aiohttp cleanup, and subprocess workers are real OS
        # processes that must not outlive their arm.
        group.stop(drain=False)
        stop()
    h = hashlib.sha256()
    for r in sorted(records, key=lambda r: r["idx"]):
        h.update(f"{r['idx']}:".encode())
        h.update(r["reply"].encode())
        h.update(b"\x00")
    tokens = sum(r["output_tokens"] for r in records)
    sup = after.get("supervision") or {}
    return {
        "label": label, "fleet": fleet, "chaos": chaos,
        "fleet_migrate": migrate, "chaos_fired": chaos_fired,
        "requests": len(records),
        "output_tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(tokens / wall, 2),
        "e2e_s": _percentiles([r["e2e_s"] for r in records],
                              ps=(50, 95)),
        "outputs_sha256": h.hexdigest(),
        "failovers": sup.get("failovers", 0),
        "retries_attempted": sup.get("retries_attempted", 0),
        "worker_restarts": sup.get("worker_restarts", 0),
        "migrations": sup.get("migrations", 0),
        "migrated_pages": sup.get("migrated_pages", 0),
        "migrated_bytes": sup.get("migrated_bytes", 0),
        "resume_resubmits": sup.get("resume_resubmits", 0),
        "resume_recomputed_tokens": sup.get(
            "resume_recomputed_tokens", 0),
        "resume_reused_tokens": sup.get("resume_reused_tokens", 0),
        "swap_in_resumes": sup.get("swap_in_resumes",
                                   after.get("swap_in_resumes", 0)),
        # Byzantine-transport counters (README "Failure model"): the
        # chaos-rpc lane grades these; zero everywhere else.
        "worker_reconnects": sup.get("worker_reconnects", 0),
        "rpc_timeouts": sup.get("rpc_timeouts", 0),
        "frame_errors": sup.get("frame_errors", 0),
        "kv_integrity_rejections": sup.get("kv_integrity_rejections", 0),
        "poison_requests": sup.get("poison_requests", 0),
        "fleet_status": health.get("status"),
    }


def _compare_fleet(args) -> dict:
    """The process-fleet artifact (README "Process fleet"): one pinned
    greedy burst served by (a) the in-process thread fleet, (b) the
    subprocess worker fleet, and (c) the subprocess fleet with a worker
    SIGKILLed mid-decode — outputs must be byte-identical across ALL
    arms (failover resumes replay the router's token record, so even a
    killed worker's streams complete exactly); then the pinned DRAIN
    scenario twice — graceful SIGTERM-drain with KV page migration vs
    plain resubmission — so one committed file carries the migration
    win: swap-in-resumes > 0 and strictly fewer recomputed tokens than
    the resubmission arm."""
    args.fleet_tokens = 32
    cfg_snapshot = {k: v for k, v in vars(args).items()
                    if not k.startswith("_")}
    arms = {}
    arms["in_process"] = _fleet_arm(args, "in_process", "in-process")
    arms["subprocess"] = _fleet_arm(args, "subprocess", "subprocess")
    arms["subprocess_kill"] = _fleet_arm(
        args, "subprocess_kill", "subprocess", chaos="kill9")
    arms["drain_migrate"] = _fleet_arm(
        args, "drain_migrate", "subprocess", chaos="drain", migrate=True)
    arms["drain_resubmit"] = _fleet_arm(
        args, "drain_resubmit", "subprocess", chaos="drain",
        migrate=False)
    args.fleet = "in-process"

    ip, sp = arms["in_process"], arms["subprocess"]
    kill = arms["subprocess_kill"]
    dm, dr = arms["drain_migrate"], arms["drain_resubmit"]
    shas = {a["outputs_sha256"] for a in arms.values()}
    comparison = {
        "streams": args.fleet_streams,
        "tokens_per_s_in_process": ip["tokens_per_s"],
        "tokens_per_s_subprocess": sp["tokens_per_s"],
        # The RPC-hop cost (or multi-process win — workers dodge the
        # router's GIL), reported transparently.
        "tok_s_ratio": round(sp["tokens_per_s"]
                             / max(ip["tokens_per_s"], 1e-9), 4),
        "e2e_p50_in_process_s": ip["e2e_s"]["p50"],
        "e2e_p50_subprocess_s": sp["e2e_s"]["p50"],
        # Byte-identity across backends AND chaos: the fleet is a
        # placement/supervision decision, never a behavior change.
        "outputs_identical": len(shas) == 1,
        # kill -9 arm: the real out-of-process failure mode.
        "kill_chaos_fired": kill["chaos_fired"],
        "failover_count": kill["failovers"],
        "kill_worker_restarts": kill["worker_restarts"],
        "kill_fleet_status": kill["fleet_status"],
        # Drain scenario: migration vs resubmission.
        "migrations": dm["migrations"],
        "migrated_pages": dm["migrated_pages"],
        "migrated_bytes": dm["migrated_bytes"],
        "swap_in_resumes": dm["swap_in_resumes"],
        "recomputed_tokens_migrate": dm["resume_recomputed_tokens"],
        "recomputed_tokens_resubmit": dr["resume_recomputed_tokens"],
        "reused_tokens_migrate": dm["resume_reused_tokens"],
        "reused_tokens_resubmit": dr["resume_reused_tokens"],
        # The artifact's claims (acceptance): byte-identity everywhere,
        # the killed worker's streams failed over and completed, and
        # drain-time migration swap-in-resumed with strictly fewer
        # recomputed tokens than resubmission.
        "failover_wins": bool(
            len(shas) == 1 and kill["chaos_fired"]
            and kill["failovers"] >= 1
            and kill["worker_restarts"] >= 1),
        "migration_wins": bool(
            dm["chaos_fired"] and dr["chaos_fired"]
            and dm["swap_in_resumes"] > 0
            and dm["migrated_pages"] > 0
            and dm["resume_recomputed_tokens"]
            < dr["resume_recomputed_tokens"]),
    }
    out = {"config": cfg_snapshot, **arms, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result.update(arms)
    return result


def _compare_chaos_rpc(args) -> dict:
    """The Byzantine-transport artifact (README "Failure model"): the
    pinned greedy burst served by a clean dp=2 subprocess fleet, then
    by the same fleet under seeded frame-level RPC chaos — random byte
    corruption and injected delays on every router<->worker frame in
    both directions, plus ONE wedged connection (socket open, writes
    silently swallowed) mid-burst. Acceptance: outputs byte-identical
    across both arms (every corrupt frame was caught by the codec CRC
    and the connection recycled+resynced — zero silent corruptions),
    frame errors and RPC timeouts actually counted, connections were
    reconnected WITHOUT any worker process restart, and p95 latency
    inflation stays bounded (detection deadlines, not hangs)."""
    args.fleet_tokens = 32
    cfg_snapshot = {k: v for k, v in vars(args).items()
                    if not k.startswith("_")}
    arms = {}
    arms["clean"] = _fleet_arm(args, "clean", "subprocess")
    arms["chaos_rpc"] = _fleet_arm(
        args, "chaos_rpc", "subprocess",
        chaos_rpc={
            # Seeded: the whole fault schedule replays bit-for-bit
            # (test_chaos_deterministic_schedule holds the contract).
            "seed": 20240,
            # ~1 frame in 50 corrupted: a handful of CRC rejections +
            # connection recycles across the burst's few hundred
            # frames, on both directions.
            "corrupt_rate": 0.02,
            # Transport jitter on every 10th frame.
            "delay_rate": 0.1, "delay_s": 0.01,
            # One connection wedges right as the burst opens (router->
            # worker writes swallowed); the per-verb deadline watchdog
            # must recycle it, not hang the stream or restart the
            # process. The frame count is per-connection and corruption
            # recycles connections, so the trigger sits low enough to
            # fire before a CRC hit can reset the count.
            "wedge_after": 2, "wedge_replica": 0,
            "direction": "both",
        })
    args.fleet = "in-process"

    clean, chaos = arms["clean"], arms["chaos_rpc"]
    identical = clean["outputs_sha256"] == chaos["outputs_sha256"]
    p95_clean = max(clean["e2e_s"]["p95"], 1e-9)
    inflation = round(chaos["e2e_s"]["p95"] / p95_clean, 3)
    comparison = {
        "streams": args.fleet_streams,
        "chaos_fired": chaos["chaos_fired"],
        # Byte-identity IS the zero-silent-corruption claim: a single
        # adopted corrupt frame would change some stream's bytes.
        "outputs_identical": identical,
        "silent_corruptions": 0 if identical else 1,
        "frame_errors": chaos["frame_errors"],
        "rpc_timeouts": chaos["rpc_timeouts"],
        "worker_reconnects": chaos["worker_reconnects"],
        "kv_integrity_rejections": chaos["kv_integrity_rejections"],
        # Transport faults are repaired at the connection, never the
        # process: restarts under chaos must stay at zero.
        "worker_restarts_chaos": chaos["worker_restarts"],
        "tokens_per_s_clean": clean["tokens_per_s"],
        "tokens_per_s_chaos": chaos["tokens_per_s"],
        "e2e_p95_clean_s": clean["e2e_s"]["p95"],
        "e2e_p95_chaos_s": chaos["e2e_s"]["p95"],
        "p95_inflation": inflation,
        # Bounded: detection is deadline-driven (3 fast deadlines for
        # the wedge, one frame for a CRC hit), so chaos costs a
        # constant few seconds — not a hang. The 20x ceiling is a
        # loaded-CI-box guard, not a perf claim.
        "p95_inflation_bounded": inflation <= 20.0,
        "chaos_wins": bool(
            identical and chaos["chaos_fired"]
            and chaos["frame_errors"] >= 1
            and chaos["rpc_timeouts"] >= 1
            and chaos["worker_reconnects"] >= 1
            and chaos["worker_restarts"] == 0
            and inflation <= 20.0),
    }
    out = {"config": cfg_snapshot, **arms, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result.update(arms)
    return result


async def _fabric_burst(port: int, model: str, reqs: list,
                        n_predict: int) -> list:
    """Fire the given (trace_id, prompt) requests at once, greedy and
    non-streamed. Client timing is recorded but the lane grades the
    SERVER-side per-request spans (/debug/requests), matched back by
    the X-Request-Id each request carries."""
    import aiohttp

    url = f"http://127.0.0.1:{port}/api/generate"
    timeout = aiohttp.ClientTimeout(total=1800)

    async def one(session, tid: str, prompt: str) -> dict:
        payload = {"model": model, "prompt": prompt, "temperature": 0.0,
                   "stream": False,
                   "options": {"num_predict": n_predict}}
        t0 = time.perf_counter()
        async with session.post(url, json=payload,
                                headers={"X-Request-Id": tid}) as resp:
            resp.raise_for_status()
            rec = await resp.json()
        return {"trace_id": tid, "reply": rec.get("response", ""),
                "e2e_s": time.perf_counter() - t0,
                "output_tokens": rec.get("eval_count", 0)}

    async with aiohttp.ClientSession(timeout=timeout) as session:
        return list(await asyncio.gather(
            *[one(session, t, pr) for t, pr in reqs]))


def _fabric_spans(port: int, prefix: str) -> list:
    """The server-side request spans whose trace id starts with
    ``prefix``, ordered by enqueue time (finished_unix - e2e_s: the
    spans carry no enqueue stamp, but every wave fires concurrently so
    the difference recovers arrival order)."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/requests?n=128",
            timeout=60) as r:
        spans = json.loads(r.read())
    out = [s for s in spans
           if str(s.get("trace_id", "")).startswith(prefix)]
    out.sort(key=lambda s: s.get("finished_unix", 0.0)
             - s.get("e2e_s", 0.0))
    return out


def _fabric_arm(args, label: str, fabric_on: bool,
                warmboot: bool = False) -> dict:
    """Boot a dp=2 subprocess fleet (fabric pool on or off), run the
    pinned shared-system-prompt workload — one seed turn, then two
    concurrent returning-user waves — optionally scaling up a third
    worker between the waves (the warm-boot grade), and summarize from
    the server-side spans."""
    import hashlib

    print(f"[replay] fabric arm: {label}", file=sys.stderr)
    args.fleet = "subprocess"
    args.fabric_cache_pages = (args.fabric_pool_pages if fabric_on
                               else 0)
    page = args.page_size
    prefix_tokens = args.fabric_prefix_pages * page
    # Byte tokenizer: chars == tokens, so the shared system prompt is
    # exactly fabric-prefix-pages FULL pages and every user's distinct
    # tail starts on the next page boundary — all users share the same
    # prefix digest chain.
    shared = ("You are a terse, careful assistant. Cite sources. "
              * ((prefix_tokens // 49) + 1))[:prefix_tokens]
    srv, port, stop = start_server(args)
    group = srv.group
    records = []

    def _pressure(replica: int) -> None:
        # Chaos page pressure is the lane's deterministic stand-in for
        # a saturated replica: the worker holds every free page, the
        # raised preempt watermark keeps free+evictable under it, and
        # the router's pressure bit routes the next wave AROUND the
        # replica — the saturation moment the fabric exists for.
        group.apply_chaos({"replica": replica,
                           "page_pressure": args.num_pages})
        deadline = time.perf_counter() + 15
        while time.perf_counter() < deadline:
            reps = group.health_snapshot()["replicas"]
            if (replica < len(reps)
                    and reps[replica].get("under_pressure")):
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"replica {replica} never reported under_pressure")

    def _pool_settle(still: float = 0.8, timeout: float = 15.0) -> None:
        # Publishes ride async event frames; wait until the pool's
        # page count has been still for a beat so a later growth wait
        # can't count straggling earlier publishes.
        deadline = time.perf_counter() + timeout
        last, t_last = group.fabric.used, time.perf_counter()
        while time.perf_counter() < deadline:
            now = group.fabric.used
            if now != last:
                last, t_last = now, time.perf_counter()
            elif time.perf_counter() - t_last >= still:
                return
            time.sleep(0.05)

    try:
        # Compile warmth (the arms boot without warmup): distinct cold
        # prompts ride the rotating tie-break so every replica
        # compiles BOTH prefill buckets the measured waves use — the
        # big bucket (a cold shared-prefix prefill) and the small one
        # (a warm tail-only prefill). Without this, the fabric-off
        # arm's first cross-replica turn would pay compile + prefill
        # while the fabric-on arm's paid only compile — a contrast
        # that isn't the fabric's.
        dp = getattr(args, "dp", 1)
        warm_len = prefix_tokens + 2 * page
        longs = [(f"[w{i}] warm " + "compile pad " * 64)[:warm_len]
                 for i in range(dp)]
        for prompt in longs + [f"[w{i + dp}] warm" for i in range(dp)]:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/generate",
                data=json.dumps({"model": args.model,
                                 "prompt": prompt,
                                 "temperature": 0.0, "stream": False,
                                 "options": {"num_predict": 4}}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=600).read()
        # Decode-ladder warmth: a concurrent burst fills every decode
        # lane on both replicas so the batch rungs compile here — not
        # scattered across the measured waves, where a rung compile
        # would dwarf the prefill contrast being graded.
        asyncio.run(_fabric_burst(
            port, args.model,
            [(f"wmc{i:02d}", f"[c{i:02d}] spin") for i in range(4 * dp)],
            12))
        if fabric_on:
            _pool_settle()
        pool_baseline = group.fabric.used
        # Seed turn: ONE user prefills the shared system prompt,
        # somewhere. With the fabric on, its settled pages publish to
        # the router pool — the only fleet-wide prefill of the prefix.
        records += asyncio.run(_fabric_burst(
            port, args.model, [("seed", shared + " u00")],
            args.fabric_tokens))
        seed_span = (_fabric_spans(port, "seed") or [{}])[0]
        seed_replica = int(seed_span.get("routed_replica", 0))
        if fabric_on:
            # Wait until the pool grew by the whole prefix before
            # grading the returning wave.
            deadline = time.perf_counter() + 15
            while (time.perf_counter() < deadline
                   and group.fabric.used
                   < pool_baseline + args.fabric_prefix_pages):
                time.sleep(0.05)
        # Saturate the replica that prefilled the prefix, then the
        # returning wave: users sharing the system prompt arrive at
        # once and ALL route to the other replica — which either
        # recomputes the prefix (fabric off) or pulls it from the pool
        # (fabric on). This wave's server-side TTFT p95 is the graded
        # stat.
        _pressure(seed_replica)
        # Swap-path warmth (unmeasured): repeat each warm long with a
        # fresh tail. With the seed replica saturated these land on the
        # OTHER replica: the primer whose prefix lived on the pressured
        # replica restores it through the host tier (fabric on) or
        # recomputes it (fabric off) — compiling the swap-in scatter
        # and the first publish's offload gather on the measured
        # replica BEFORE the graded wave (the shared prefix itself
        # stays un-pulled: the wave's fabric hit is still the first).
        records += asyncio.run(_fabric_burst(
            port, args.model,
            [(f"pr{i:02d}", longs[i] + f" p{i:02d}") for i in range(dp)],
            args.fabric_tokens))
        t0 = time.perf_counter()
        w1 = [(f"w1u{i:02d}", shared + f" u{i:02d}")
              for i in range(1, args.fabric_users + 1)]
        records += asyncio.run(_fabric_burst(
            port, args.model, w1, args.fabric_tokens))
        wave1_wall = time.perf_counter() - t0
        new_replica = None
        wb_host_pages = 0
        if warmboot:
            # Saturate EVERY original replica, then scale up: _spawn
            # pushes the fabric hot set into the new worker BEFORE it
            # becomes routable, so the second wave lands on a worker
            # that never prefilled a byte yet serves its first request
            # already warm.
            _pool_settle()
            for h in list(group.workers):
                if h.replica != seed_replica:
                    _pressure(h.replica)
            group._scale_up("bench-warmboot")
            new_replica = max(h.replica for h in group.workers)
            deadline = time.perf_counter() + 90
            while (time.perf_counter() < deadline
                   and not all(h.state == "up" for h in group.workers)):
                time.sleep(0.1)
            for h, w in zip(group.workers,
                            group.health_snapshot()["replicas"]):
                if h.replica == new_replica:
                    wb_host_pages = int(
                        (w.get("host_cache") or {}).get("pages_used", 0))
        # Second wave: more returning users. In the scale-up arm every
        # old replica is saturated, so the wave lands on the
        # warm-booted worker; in the base arms it lands on the replica
        # wave 1 warmed.
        w2 = [(f"w2u{i:02d}", shared + f" u{i:02d}")
              for i in range(50, 50 + args.fabric_wave2_users)]
        records += asyncio.run(_fabric_burst(
            port, args.model, w2, args.fabric_tokens))
        w1_spans = _fabric_spans(port, "w1u")
        w2_spans = _fabric_spans(port, "w2u")
        fabric_snap = group.fabric.snapshot()
        sup = group.supervision_counters()
    finally:
        group.stop(drain=False)
        stop()

    h = hashlib.sha256()
    for r in sorted(records, key=lambda r: r["trace_id"]):
        h.update(f"{r['trace_id']}:".encode())
        h.update(r["reply"].encode())
        h.update(b"\x00")

    def _prefix_recomputed(span: dict) -> int:
        return max(0, prefix_tokens - int(span.get("cached_tokens", 0)))

    cross = [s for s in w1_spans
             if s.get("routed_replica") != seed_replica]
    wb_spans = ([s for s in w2_spans
                 if s.get("routed_replica") == new_replica]
                if new_replica is not None else [])
    wb_first = wb_spans[0] if wb_spans else None
    return {
        "label": label, "fabric_on": fabric_on, "warmboot": warmboot,
        "requests": len(records),
        "outputs_sha256": h.hexdigest(),
        "prefix_tokens": prefix_tokens,
        "wave1_wall_s": round(wave1_wall, 3),
        # Server-side TTFT (enqueue -> first token) of the graded
        # returning wave.
        "returning_ttft_s": _percentiles(
            [s.get("ttft_s", 0.0) for s in w1_spans], ps=(50, 95)),
        "wave2_ttft_s": _percentiles(
            [s.get("ttft_s", 0.0) for s in w2_spans], ps=(50, 95)),
        "seed_replica": seed_replica,
        # Returning turns the router spilled onto a replica that never
        # prefilled the shared prompt — the fabric's reason to exist.
        "cross_replica_turns": len(cross),
        "cross_fabric_hit_pages": sum(
            int(s.get("route_fabric_hit_pages", 0)) for s in cross),
        "cross_host_restored_pages": sum(
            int(s.get("host_restored_pages", 0)) for s in cross),
        # Shared-prefix tokens the wave recomputed anywhere (0 =
        # prefilled once fleet-wide).
        "prefix_recomputed_tokens": sum(
            _prefix_recomputed(s) for s in w1_spans),
        "cross_first_turn": (None if not cross else {
            "trace_id": cross[0].get("trace_id"),
            "replica": cross[0].get("routed_replica"),
            "route_fabric_hit_pages":
                int(cross[0].get("route_fabric_hit_pages", 0)),
            "host_restored_pages":
                int(cross[0].get("host_restored_pages", 0)),
            "cached_tokens": int(cross[0].get("cached_tokens", 0)),
            "prefix_recomputed_tokens": _prefix_recomputed(cross[0]),
        }),
        # Warm-boot grade (scale-up arm only): host pages the new
        # worker held BEFORE serving anything, and its first request's
        # warmth (all of it fabric-sourced — the worker never prefilled
        # a byte before this).
        "warmboot_replica": new_replica,
        "warmboot_host_pages": wb_host_pages,
        "warmboot_requests": len(wb_spans),
        "warmboot_first_turn": (None if wb_first is None else {
            "trace_id": wb_first.get("trace_id"),
            "route_hit_pages": int(wb_first.get("route_hit_pages", 0)),
            "route_fabric_hit_pages":
                int(wb_first.get("route_fabric_hit_pages", 0)),
            "host_restored_pages":
                int(wb_first.get("host_restored_pages", 0)),
            "cached_tokens": int(wb_first.get("cached_tokens", 0)),
            "prefix_recomputed_tokens": _prefix_recomputed(wb_first),
        }),
        "fabric": fabric_snap,
        "route_fabric_hits": sup.get("route_fabric_hits", 0),
        "fabric_puts": sup.get("fabric_puts", 0),
        "fabric_hits": sup.get("fabric_hits", 0),
        "kv_integrity_rejections": sup.get("kv_integrity_rejections", 0),
    }


def _compare_fabric(args) -> dict:
    """The fleet-KV-fabric artifact (README "KV fabric"): many users
    sharing one long system prompt, served three ways — fabric off
    (every replica pays its own prefix prefill), fabric on (the prefix
    is prefilled ONCE fleet-wide and every other replica pulls it from
    the router pool), and fabric on with a mid-run scale-up whose new
    worker warm-boots from the pool and serves its first request
    already warm. Outputs must stay byte-identical across every arm:
    the fabric moves settled KV bytes, it never changes them."""
    cfg_snapshot = {k: v for k, v in vars(args).items()
                    if not k.startswith("_")}
    arms = {}
    arms["fabric_off"] = _fabric_arm(args, "fabric_off", False)
    arms["fabric_on"] = _fabric_arm(args, "fabric_on", True)
    arms["fabric_warmboot"] = _fabric_arm(
        args, "fabric_warmboot", True, warmboot=True)
    args.fleet = "in-process"

    off, on, wb = (arms["fabric_off"], arms["fabric_on"],
                   arms["fabric_warmboot"])
    shas = {a["outputs_sha256"] for a in arms.values()}
    ratio = (off["returning_ttft_s"]["p95"]
             / max(on["returning_ttft_s"]["p95"], 1e-9))
    wb_first = wb.get("warmboot_first_turn") or {}
    comparison = {
        "users": args.fabric_users,
        "prefix_tokens": on["prefix_tokens"],
        # Byte-identity across all arms: pooled pages are the same
        # bit-exact serialized KV the point-to-point paths move.
        "outputs_identical": len(shas) == 1,
        # The fleet-wide prefill-once claim: with the fabric on, no
        # returning turn recomputes a shared-prefix token anywhere —
        # the cross-replica turns adopt pooled pages instead.
        "prefix_recomputed_tokens_off": off["prefix_recomputed_tokens"],
        "prefix_recomputed_tokens_on": on["prefix_recomputed_tokens"],
        "cross_replica_turns_on": on["cross_replica_turns"],
        "cross_fabric_hit_pages_on": on["cross_fabric_hit_pages"],
        "prefix_prefilled_once": bool(
            on["cross_replica_turns"] >= 1
            and on["cross_fabric_hit_pages"] >= args.fabric_prefix_pages
            and on["prefix_recomputed_tokens"] == 0
            and (on["cross_first_turn"] or {}).get(
                "route_fabric_hit_pages", 0) > 0),
        # Returning-turn TTFT p95, fabric off vs on (>= 1.3x is the
        # artifact's acceptance claim; CPU-noise makes it a committed-
        # artifact grade, not a live tier-1 assert).
        "returning_ttft_p95_off_s": off["returning_ttft_s"]["p95"],
        "returning_ttft_p95_on_s": on["returning_ttft_s"]["p95"],
        "returning_ttft_ratio": round(ratio, 4),
        "fabric_ttft_wins": bool(ratio >= 1.3),
        # Warm worker boot: the scaled-up worker held pooled pages
        # before its first request, and that request's warmth is
        # fabric-sourced (the worker had prefilled nothing).
        "warmboot_host_pages": wb["warmboot_host_pages"],
        "warmboot_requests": wb["warmboot_requests"],
        "warmboot_first_hit_pages": wb_first.get("route_hit_pages", 0),
        "warmboot_wins": bool(
            wb["warmboot_host_pages"] > 0
            and wb["warmboot_requests"] >= 1
            and wb_first.get("route_hit_pages", 0) > 0
            and wb_first.get("prefix_recomputed_tokens", 1) == 0),
        "fabric_wins": bool(
            len(shas) == 1
            and on["cross_replica_turns"] >= 1
            and on["prefix_recomputed_tokens"] == 0
            and on["fabric_hits"] > 0 and on["fabric_puts"] > 0),
    }
    out = {"config": cfg_snapshot, **arms, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result.update(arms)
    return result


def _kv_plane_arm(args, label: str, plane: str) -> dict:
    """Boot a 1-prefill + 1-decode subprocess fleet on one KV data
    plane, run the pinned handoff-heavy burst — an unmeasured compile
    warm wave, the measured wave, then a kill -9 wave — and summarize
    the per-request handoff walls and the router's relayed-blob books."""
    import hashlib
    import threading

    print(f"[replay] kv-plane arm: {label}", file=sys.stderr)
    args.fleet = "subprocess"
    args.worker_roles = ("prefill", "decode")
    args.kv_plane = plane
    args.fabric_cache_pages = args.kvp_pool_pages
    args.worker_restart_backoff_s = 0.1
    args.worker_restart_max = 10
    page = args.page_size
    prompt_tokens = args.kvp_prompt_pages * page
    srv, port, stop = start_server(args)
    group = srv.group
    records = []

    def _wave(tag: str, n: int, start: int = 0) -> list:
        # Distinct per-user bodies (the tag+index is IN the page-0
        # content) so nothing prefix-caches away: every request
        # prefills its own ~kvp-prompt-pages pages and hands the whole
        # context off to the decode worker.
        reqs = [(f"{tag}{i:02d}",
                 (f"[{tag}{i:02d}] " + "kv plane payload " * 512)
                 [:prompt_tokens])
                for i in range(start, start + n)]
        return asyncio.run(_fabric_burst(port, args.model, reqs,
                                         args.kvp_tokens))

    try:
        # Compile warmth (the arms boot without warmup): the same wave
        # shape as the measured one, so the big prefill bucket, the
        # decode rungs at full width, and the handoff export/adopt
        # graphs all compile HERE — the measured wave times the data
        # plane, not XLA.
        records += _wave("wm", args.kvp_users)
        # Sequential warm singles: the concurrent wave above compiles
        # the full-width decode rungs, but a lone request rides the
        # batch-1 rung — its first trip through prefill+handoff+decode
        # still pays one-time setup (rung compile, allocator paths)
        # that would otherwise land as a ~40 ms outlier inside the
        # measured series and own its p95.
        for i in range(3):
            records += _wave("ws", 1, start=i)
        # Measured handoffs, SEQUENTIAL: one request in flight at a
        # time, so each wall prices exactly one trip through the data
        # plane with no cross-request compute queueing contaminating
        # the spans (the concurrent regime's walls measure the router
        # backlog and the decode worker's step queue, identically in
        # both arms — not the plane).
        t0 = time.perf_counter()
        for i in range(args.kvp_users):
            records += _wave("kw", 1, start=i)
        wave_wall = time.perf_counter() - t0
        # Per-request handoff+adopt wall, measured across processes on
        # the assembled trace timeline (the /debug/trace stance: every
        # span carries its emitter's unix-anchored timestamps): from
        # the prefill worker's "handoff_export" span END — the moment
        # the serialized payload exists and the data plane takes over —
        # to the decode worker's "handoff_adopt" span END. The window
        # covers everything the PLANES differ on: arena publish vs
        # frame send, the router's event-socket ingest and dispatch
        # (where the relay arm carries megabytes in and out), and the
        # adoption read+restore. The export span itself (device KV
        # gather + serialize) is identical prefill-side compute on
        # either plane and is reported separately below.
        walls, exports, adopts, legs = [], [], [], []
        for i in range(args.kvp_users):
            sp = {}
            for s in group._recorder.get_trace(f"kw{i:02d}") or ():
                if s.get("name") in ("handoff_export", "handoff",
                                     "handoff_adopt"):
                    sp[s["name"]] = (float(s.get("ts", 0.0)),
                                     float(s.get("dur", 0.0)))
            if "handoff_export" in sp:
                exports.append(sp["handoff_export"][1])
            if "handoff_adopt" in sp:
                adopts.append(sp["handoff_adopt"][1])
            if "handoff_export" in sp and "handoff_adopt" in sp:
                t_exp = sum(sp["handoff_export"])
                t_done = sum(sp["handoff_adopt"])
                walls.append(max(0.0, t_done - t_exp))
                if "handoff" in sp:
                    # The wall's legs on the assembled timeline: the
                    # export, the event-frame transit into the router
                    # (where the relay arm carries the payload), the
                    # router's routing+dispatch span (where it carries
                    # it out again), and the decode worker's admission
                    # wait + adoption.
                    legs.append({
                        "export_s": round(sp["handoff_export"][1], 6),
                        "transit_in_s": round(
                            sp["handoff"][0]
                            - sum(sp["handoff_export"]), 6),
                        "route_dispatch_s": round(sp["handoff"][1], 6),
                        "sched_wait_s": round(
                            sp["handoff_adopt"][0]
                            - sum(sp["handoff"]), 6),
                        "adopt_s": round(sp["handoff_adopt"][1], 6),
                    })
        blob_bytes_measured = dict(group.rpc_blob_bytes)
        sup_measured = group.supervision_counters()
        # Kill -9 mid-wave: fire the wave, then SIGKILL the prefill
        # worker while its handoffs are in flight. The shm arm's
        # supervisor must reclaim the dead incarnation's slabs via the
        # region epoch bump; the caught-out requests recompute-resume
        # (byte-identical under greedy) — the relay fallback books
        # below record whatever blob traffic the salvage paths moved.
        prefill_replica = next(
            h.replica for h in group.workers
            if group.roles[h.replica] == "prefill")
        kill_records: list = []
        kill_err: list = []

        def _kill_wave() -> None:
            try:
                kill_records.extend(_wave("kk", args.kvp_users))
            except Exception as e:          # surfaced after join
                kill_err.append(e)

        t = threading.Thread(target=_kill_wave)
        t.start()
        time.sleep(0.25)
        group.apply_chaos({"replica": prefill_replica, "kill": "kill9"})
        t.join(timeout=600)
        assert not t.is_alive(), "kill wave never finished"
        if kill_err:
            raise kill_err[0]
        records += kill_records
        deadline = time.perf_counter() + 90
        while (time.perf_counter() < deadline
               and not all(h.state == "up" for h in group.workers)):
            time.sleep(0.1)
        sup = group.supervision_counters()
        blob_bytes_final = dict(group.rpc_blob_bytes)
        shm_reclaims = group.shm_reclaims
        fabric_snap = group.fabric.snapshot()
    finally:
        group.stop(drain=False)
        stop()

    h = hashlib.sha256()
    for r in sorted(records, key=lambda r: r["trace_id"]):
        h.update(f"{r['trace_id']}:".encode())
        h.update(r["reply"].encode())
        h.update(b"\x00")
    return {
        "label": label, "kv_plane": plane,
        "requests": len(records),
        "outputs_sha256": h.hexdigest(),
        "prompt_tokens": prompt_tokens,
        "wave_wall_s": round(wave_wall, 3),
        # Handoff+adopt wall of the measured wave (export settled ->
        # adoption complete: transit + route + dispatch + adopt), per
        # request.
        "handoff_wall_s": _percentiles(walls, ps=(50, 95)),
        # The wall's worker-side legs (identical work in both arms:
        # KV gather+serialize on the prefill side, restore on the
        # decode side) — everything between them is the data plane.
        "handoff_export_s": _percentiles(exports, ps=(50, 95)),
        "handoff_adopt_s": _percentiles(adopts, ps=(50, 95)),
        "handoff_legs_p50_s": {
            k: round(float(np.median([leg[k] for leg in legs])), 6)
            for k in (legs[0] if legs else ())},
        "handoff_walls_observed": len(walls),
        # Router-relayed KV payload bytes by verb, before and after
        # the kill wave: the measured-phase books grade the zero-copy
        # claim; the final books show what the post-kill salvage /
        # fallback paths moved (the relay fallback is a feature).
        "rpc_blob_bytes_measured": blob_bytes_measured,
        "rpc_blob_bytes": blob_bytes_final,
        "pd_handoffs_measured": sup_measured.get("pd_handoffs", 0),
        "pd_handoffs": sup.get("pd_handoffs", 0),
        "pd_adoptions": sup.get("pd_adoptions", 0),
        "pd_handoff_recomputes": sup.get("pd_handoff_recomputes", 0),
        "recompute_resumes": sup.get("recompute_resumes", 0),
        "resume_recomputed_tokens": sup.get(
            "resume_recomputed_tokens", 0),
        "worker_restarts": sup.get("worker_restarts", 0),
        "kv_integrity_rejections": sup.get(
            "kv_integrity_rejections", 0),
        "shm_reclaims": shm_reclaims,
        "fabric_puts": sup.get("fabric_puts", 0),
        "fabric": fabric_snap,
        "kill_wave_requests": len(kill_records),
    }


def _compare_kv_plane(args) -> dict:
    """The zero-copy KV data plane artifact (README "KV data plane"):
    the same handoff-heavy burst through a 1-prefill + 1-decode
    subprocess fleet on both planes — KV blobs relayed through router
    frames vs handed worker-to-worker through the shared-memory page
    arena. The planes move the same bytes, so outputs must stay
    byte-identical; the shm arm's router must relay ~0 KV payload
    bytes on the handoff/fabric verbs; and a kill -9 mid-wave must
    reclaim the dead worker's slabs and recompute-resume cleanly."""
    cfg_snapshot = {k: v for k, v in vars(args).items()
                    if not k.startswith("_")}
    arms = {}
    arms["relay"] = _kv_plane_arm(args, "relay", "relay")
    arms["shm"] = _kv_plane_arm(args, "shm", "shm")
    args.worker_roles, args.fleet, args.kv_plane = (), "in-process", \
        "relay"

    relay, shm = arms["relay"], arms["shm"]
    shas = {a["outputs_sha256"] for a in arms.values()}
    ratio = (relay["handoff_wall_s"]["p95"]
             / max(shm["handoff_wall_s"]["p95"], 1e-9))
    shm_m, relay_m = (shm["rpc_blob_bytes_measured"],
                      relay["rpc_blob_bytes_measured"])
    comparison = {
        "users": args.kvp_users,
        "prompt_tokens": relay["prompt_tokens"],
        # Byte-identity: a descriptor adoption reads the same bit-exact
        # serialized KV the relay frames carry (incl. through the kill
        # wave's recompute-resumes).
        "outputs_identical": len(shas) == 1,
        # The zero-copy claim, graded on the measured phase (before
        # the kill wave's INTENTIONAL relay fallbacks): with the shm
        # plane on, no KV payload byte traversed a router frame on any
        # verb, while the relay arm moved every handoff through the
        # router twice (handoff event in, dispatch out) plus every
        # fabric publish.
        "rpc_blob_bytes_measured_relay": relay_m,
        "rpc_blob_bytes_measured_shm": shm_m,
        "shm_zero_copy": bool(
            sum(shm_m.values()) == 0
            and relay_m.get("handoff", 0) > 0
            and relay_m.get("submit", 0) > 0
            and relay_m.get("fabric_put", 0) > 0),
        # Handoff+adopt wall p95, relay vs shm (>= 1.5x is the
        # artifact's acceptance claim; CPU-noise makes it a committed-
        # artifact grade, not a live tier-1 assert).
        "handoff_p95_relay_s": relay["handoff_wall_s"]["p95"],
        "handoff_p95_shm_s": shm["handoff_wall_s"]["p95"],
        "handoff_p95_ratio": round(ratio, 4),
        "shm_handoff_wins": bool(ratio >= 1.5),
        # Kill -9 mid-wave: the dead prefill incarnation's slabs were
        # reclaimed via the epoch bump (shm arm), the worker restarted,
        # and every request in both arms' kill waves still finished
        # byte-identically (recompute-resume fallback).
        "shm_reclaims": shm["shm_reclaims"],
        "worker_restarts": {k: a["worker_restarts"]
                            for k, a in arms.items()},
        "kill_recovered": bool(
            shm["shm_reclaims"] >= 1
            and all(a["worker_restarts"] >= 1 for a in arms.values())
            and all(a["kill_wave_requests"] == args.kvp_users
                    for a in arms.values())),
        "kv_integrity_rejections": {
            k: a["kv_integrity_rejections"] for k, a in arms.items()},
        "kv_plane_wins": bool(
            len(shas) == 1
            and sum(shm_m.values()) == 0
            and relay_m.get("handoff", 0) > 0
            and shm["shm_reclaims"] >= 1
            and shm["pd_handoffs_measured"] > 0
            and all(a["kv_integrity_rejections"] == 0
                    for a in arms.values())),
    }
    out = {"config": cfg_snapshot, **arms, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result.update(arms)
    return result


def _diurnal_schedule(args) -> list:
    """The pinned BurstGPT-shaped mini-diurnal: a quiet trickle (one
    interactive arrival per second — the trough), then a peak wave
    arriving inside half a second (>= 20x the trough's offered load),
    then silence — the night the autoscaler drains back down. Batch
    jobs land just ahead of the peak's interactives so the wave hits a
    fleet already saturated by the class the interactives preempt."""
    sched, idx = [], 0
    for i in range(args.elastic_quiet_requests):
        sched.append({"idx": idx, "t": float(i), "cls": "interactive",
                      "prompt": f"[q{idx:02d}] tick", "max_tokens": 8})
        idx += 1
    t_peak = float(args.elastic_quiet_requests)
    for i in range(args.elastic_burst_batch):
        # Batch jobs carry the bulk of the work: enough generation
        # budget that the peak saturates the single worker for tens of
        # seconds — park time is what breaches the SLO sensor, and the
        # burst must still be in flight when the rolling upgrade hits.
        sched.append({"idx": idx, "t": t_peak + 0.02 * i, "cls": "batch",
                      "prompt": f"[b{idx:02d}] job", "max_tokens": 96})
        idx += 1
    for i in range(args.elastic_burst_interactive):
        sched.append({"idx": idx, "t": t_peak + 0.1 + 0.02 * i,
                      "cls": "interactive",
                      "prompt": f"[i{idx:02d}] ask", "max_tokens": 12})
        idx += 1
    return sched


async def _diurnal_burst(port: int, model: str, schedule: list) -> list:
    """Fire the diurnal schedule: one streamed greedy request per entry
    at its arrival offset, tagged with its X-Priority class, recording
    client TTFT (first streamed chunk). 429/503 answers are retried per
    the client contract (README "Elastic fleet"): Retry-After hint plus
    FULL-jitter exponential backoff, from a shared retry budget —
    budget exhaustion sheds instead of amplifying the overload."""
    import aiohttp

    url = f"http://127.0.0.1:{port}/api/generate"
    timeout = aiohttp.ClientTimeout(total=1800)
    budget = {"n": 6 * len(schedule)}

    async def one(session, req: dict) -> dict:
        await asyncio.sleep(req["t"])
        payload = {"model": model, "prompt": req["prompt"],
                   "temperature": 0.0, "stream": True,
                   "options": {"num_predict": req["max_tokens"]}}
        headers = {"X-Priority": req["cls"],
                   "X-Request-Id": f"el-{req['idx']:02d}"}
        rec = {"idx": req["idx"], "cls": req["cls"], "t": req["t"],
               "shed": False, "retries": 0, "ttft_s": None,
               "e2e_s": None, "reply": "", "output_tokens": 0}
        t0 = time.perf_counter()
        for attempt in range(12):
            async with session.post(url, json=payload,
                                    headers=headers) as resp:
                if resp.status in (429, 503):
                    if budget["n"] <= 0 or attempt >= 11:
                        rec["shed"], rec["retries"] = True, attempt
                        return rec
                    budget["n"] -= 1
                    try:
                        hint = float(resp.headers.get("Retry-After", ""))
                    except ValueError:
                        hint = 0.0
                    await asyncio.sleep(hint + random.uniform(
                        0.0, min(10.0, 0.25 * (2 ** attempt))))
                    continue
                resp.raise_for_status()
                parts = []
                async for line in resp.content:
                    if not line.strip():
                        continue
                    if rec["ttft_s"] is None:
                        rec["ttft_s"] = time.perf_counter() - t0
                    obj = json.loads(line)
                    if obj.get("done"):
                        rec["output_tokens"] = obj.get("eval_count", 0)
                    else:
                        parts.append(obj.get("response", ""))
                rec["reply"] = "".join(parts)
                rec["e2e_s"] = time.perf_counter() - t0
                rec["retries"] = attempt
                return rec
        return rec

    async with aiohttp.ClientSession(timeout=timeout) as session:
        return list(await asyncio.gather(*[one(session, r)
                                           for r in schedule]))


def _elastic_arm(args, label: str, elastic: bool) -> dict:
    """One diurnal pass: ``elastic=False`` pins a single fixed
    subprocess worker with the legacy global 429 cap; ``elastic=True``
    turns on the autoscaler and the per-class lanes, and fires a
    rolling upgrade over HTTP once the scale-up has landed (so the
    upgrade replaces BOTH live workers under the burst)."""
    print(f"[replay] elastic arm: {label}", file=sys.stderr)
    args.fleet = "subprocess"
    args.fleet_migrate = True
    args.worker_restart_backoff_s = 0.1
    args.worker_restart_max = 10
    args.autoscale = elastic
    args.autoscale_min_replicas = 1
    args.autoscale_max_replicas = 2
    args.autoscale_breach_window_s = 1.0
    args.autoscale_cooldown_s = 2.0
    args.autoscale_low_watermark = 0.05
    args.autoscale_idle_window_s = 1.5
    args.default_class = "interactive"
    args.class_queue_depth = 32 if elastic else 0
    schedule = _diurnal_schedule(args)
    srv, port, stop = start_server(args)
    group = srv.group
    rollout: dict = {}
    try:
        # Router-path warm pass (worker boots already ran engine
        # warmup): first-request setup stays out of the measured
        # diurnal — the arms time serving, not compile.
        for i in range(2):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/generate",
                data=json.dumps({"model": args.model,
                                 "prompt": f"[w{i}] warm",
                                 "temperature": 0.0, "stream": False,
                                 "options": {"num_predict": 4}}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=600).read()
        box = {}

        def run_burst():
            box["records"] = asyncio.run(
                _diurnal_burst(port, args.model, schedule))

        t0 = time.perf_counter()
        th = threading.Thread(target=run_burst, name="diurnal-burst")
        th.start()
        if elastic:
            # Mid-replay rolling upgrade: wait for the breach-driven
            # scale-up to land, then replace every live worker one at a
            # time — under the still-running burst.
            deadline = time.perf_counter() + 90
            while time.perf_counter() < deadline and group.scale_ups < 1:
                time.sleep(0.05)
            while (time.perf_counter() < deadline
                   and not all(h.state == "up"
                               for h in group._live_workers())):
                time.sleep(0.05)
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/debug/rollout", data=b"{}",
                headers={"Content-Type": "application/json"},
                method="POST")
            rollout = json.loads(
                urllib.request.urlopen(req, timeout=600).read())
        th.join()
        wall = time.perf_counter() - t0
        records = box["records"]
        if elastic:
            # The night shift: idle occupancy under the low watermark
            # must drain the extra replica once the breach samples age
            # out of the sensor horizon.
            deadline = time.perf_counter() + 90
            while (time.perf_counter() < deadline
                   and group.scale_downs < 1):
                time.sleep(0.2)
        after = json.loads(scrape_metrics(port, fmt="json")[0])
        prom = scrape_metrics(port)[0]
        health = group.health_snapshot()
        traces = {
            "scale_up": group.trace_snapshot("scale-up-1") is not None,
            "scale_down":
                group.trace_snapshot("scale-down-1") is not None,
            "rollout": group.trace_snapshot("rollout-1") is not None,
        }
    finally:
        group.stop(drain=False)
        stop()
    sup = after.get("supervision") or {}
    done = [r for r in records if not r["shed"]]
    by_cls = lambda c: [r for r in done if r["cls"] == c]  # noqa: E731

    def _ttft(rs):
        return _percentiles([r["ttft_s"] for r in rs
                             if r["ttft_s"] is not None], ps=(50, 95))

    return {
        "label": label, "elastic": elastic,
        "requests": len(records), "completed": len(done),
        "client_shed": {c: sum(1 for r in records
                               if r["shed"] and r["cls"] == c)
                        for c in ("interactive", "batch")},
        "client_retries": sum(r["retries"] for r in records),
        "wall_s": round(wall, 3),
        "output_tokens": sum(r["output_tokens"] for r in done),
        "interactive_ttft_s": _ttft(by_cls("interactive")),
        "batch_ttft_s": _ttft(by_cls("batch")),
        "interactive_e2e_s": _percentiles(
            [r["e2e_s"] for r in by_cls("interactive")], ps=(50, 95)),
        "replies": {str(r["idx"]): r["reply"] for r in done},
        "scale_ups": sup.get("scale_ups", 0),
        "scale_downs": sup.get("scale_downs", 0),
        "rollouts": sup.get("rollouts", 0),
        "rollout": rollout,
        "class_preemptions": sup.get("class_preemptions", {}),
        "server_shed": sup.get("class_shed", {}),
        "scale_events_in_metrics": bool(
            re.search(r"^tpu_inf_fleet_scale_ups_total [1-9]", prom,
                      re.M)
            and re.search(r"^tpu_inf_fleet_scale_downs_total [1-9]",
                          prom, re.M)) if elastic else False,
        "traces": traces,
        "fleet_status": health.get("status"),
        "worker_restarts": sup.get("worker_restarts", 0),
        "migrations": sup.get("migrations", 0),
        "migrated_pages": sup.get("migrated_pages", 0),
    }


def _compare_elastic(args) -> dict:
    """The elastic-fleet artifact (README "Elastic fleet"): the pinned
    mini-diurnal (>= 20x offered-load swing, mixed priority classes)
    through a fixed one-worker fleet and through the elastic fleet —
    autoscaler + class lanes + a mid-burst rolling upgrade — grading
    the PR's acceptance claims in one committed file: interactive TTFT
    p95 holds the SLO while batch absorbs the slack, the fleet scales
    up AND back down (events in /metrics and /debug/trace), the
    upgrade replaces every worker with zero failed requests, and
    greedy outputs stay byte-identical across arms."""
    cfg_snapshot = {k: v for k, v in vars(args).items()
                    if not k.startswith("_")}
    peak = args.elastic_burst_interactive + args.elastic_burst_batch
    # Offered load: the trough trickles 1 req/s; the peak wave lands
    # inside one second.
    load_swing = float(peak)
    arms = {}
    arms["fixed"] = _elastic_arm(args, "fixed", elastic=False)
    arms["elastic"] = _elastic_arm(args, "elastic", elastic=True)
    fx, el = arms["fixed"], arms["elastic"]
    slo_s = args.slo_ttft_ms / 1000.0
    common = sorted(set(fx["replies"]) & set(el["replies"]), key=int)
    identical = bool(common) and all(fx["replies"][k] == el["replies"][k]
                                     for k in common)
    el_int_p95 = (el["interactive_ttft_s"] or {}).get("p95")
    interactive_shed = (el["client_shed"].get("interactive", 0)
                        + el["server_shed"].get("interactive", 0))
    comparison = {
        "slo_ttft_s": slo_s,
        "load_swing": load_swing,
        "requests": fx["requests"],
        "interactive_ttft_p95_fixed_s":
            (fx["interactive_ttft_s"] or {}).get("p95"),
        "interactive_ttft_p95_elastic_s": el_int_p95,
        "interactive_slo_held_elastic": bool(
            el_int_p95 is not None and el_int_p95 <= slo_s),
        "batch_preemptions_elastic":
            el["class_preemptions"].get("batch", 0),
        "interactive_shed_elastic": interactive_shed,
        "batch_shed_elastic": (el["client_shed"].get("batch", 0)
                               + el["server_shed"].get("batch", 0)),
        "shed_fixed": dict(fx["client_shed"]),
        "scale_ups": el["scale_ups"],
        "scale_downs": el["scale_downs"],
        "scale_events_in_metrics": el["scale_events_in_metrics"],
        "scale_events_in_trace": bool(el["traces"]["scale_up"]
                                      and el["traces"]["scale_down"]),
        "rollout_replaced": len(el["rollout"].get("replaced", [])),
        "rollout_failed": len(el["rollout"].get("failed", [])),
        "rollout_in_trace": el["traces"]["rollout"],
        # In-flight sequences drained off retiring workers during the
        # scale-down + rollout (reported here; the under-traffic
        # migration claim itself is pinned in tests/test_elastic.py).
        "migrations_elastic": el["migrations"],
        "elastic_completed_all": el["completed"] == el["requests"],
        "outputs_identical_common": identical,
        "common_requests": len(common),
    }
    # The acceptance gate, one boolean: every claim the committed
    # artifact makes, graded from this run.
    comparison["elastic_wins"] = bool(
        load_swing >= 20
        and comparison["interactive_slo_held_elastic"]
        and comparison["batch_preemptions_elastic"] > 0
        and interactive_shed == 0
        and comparison["elastic_completed_all"]
        and el["scale_ups"] >= 1 and el["scale_downs"] >= 1
        and comparison["scale_events_in_metrics"]
        and comparison["scale_events_in_trace"]
        and comparison["rollout_replaced"] >= 1
        and comparison["rollout_failed"] == 0
        and comparison["rollout_in_trace"]
        and identical)
    out = {"config": cfg_snapshot, **arms, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result.update(arms)
    return result


def _grade_handoff_traces(chrome: dict) -> dict:
    """Grade a Chrome-trace export for the P/D acceptance claim: at
    least one handed-off request whose spans appear under ONE trace id
    across THREE pids (router + prefill worker + decode worker), with
    the handoff export/adopt spans adjacent to and non-overlapping with
    the prefill/decode spans. Same-process comparisons are exact; the
    one cross-process gap (export end -> adopt start) allows a 5 ms
    wall-clock anchor tolerance."""
    by_trace: dict = {}
    for e in chrome.get("traceEvents", ()):
        if e.get("ph") != "X":
            continue
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    total = clean = 0
    example = None
    for tid, evs in by_trace.items():
        spans = {}
        for e in sorted(evs, key=lambda e: e["ts"]):
            spans.setdefault(e["name"], e)
        need = ("prefill", "handoff_export", "handoff_adopt", "decode")
        if not all(k in spans for k in need):
            continue
        pids = {e["pid"] for e in evs}
        if len(pids) < 3:
            continue
        total += 1

        def end(e):
            return e["ts"] + e["dur"]

        pf, ex = spans["prefill"], spans["handoff_export"]
        ad, de = spans["handoff_adopt"], spans["decode"]
        ok = (pf["pid"] == ex["pid"] and ad["pid"] == de["pid"]
              and ex["pid"] != ad["pid"]
              and end(pf) <= ex["ts"] + 1          # same process: exact
              and end(ex) <= ad["ts"] + 5000       # cross-process: 5 ms
              and end(ad) <= de["ts"] + 1)
        if ok:
            clean += 1
            example = example or tid
    return {"handoff_traces_3pid": total,
            "handoff_traces_clean": clean,
            "adjacency_ok": total > 0 and clean == total,
            "example_trace_id": example}


# Long-prompt loads the pressure generator keeps in flight at once: 2
# per mixed worker (its other 2 slots hold the decode streams), and on
# the pd split 4 on the prefill worker — whose slots hold nothing else,
# because a num_predict=1 load finishes at prefill-settle and never
# reaches the decode tier.
PD_LOADS_IN_FLIGHT = 4


async def _pd_burst(port: int, model: str, n_streams: int,
                    decode_tokens: int, pressure: bool,
                    load_tokens: int, load_cap: int,
                    load_tag: str = "L") -> tuple:
    """The P/D lane's workload: ``n_streams`` steady greedy decode
    streams plus — when ``pressure`` — a CONTINUOUS long-prompt prefill
    burst: from the moment every stream has delivered its first chunk
    until the last stream finishes, a generator keeps
    PD_LOADS_IN_FLIGHT loads in flight (capped at ``load_cap`` total, a
    runaway bound), so every stream's entire decode window runs under
    sustained prefill pressure — no race between a one-shot volley and
    the windows it must overlap. Returns (streams, loads, issued)."""
    import aiohttp

    url = f"http://127.0.0.1:{port}/api/generate"
    timeout = aiohttp.ClientTimeout(total=1800)
    first_chunk = [asyncio.Event() for _ in range(n_streams)]
    streams_done = asyncio.Event()
    n_done = [0]

    async def stream(session, i: int) -> dict:
        prompt = f"[s{i:02d}] steady decode"
        payload = {"model": model, "prompt": prompt,
                   "temperature": 0.0, "stream": True,
                   "options": {"num_predict": decode_tokens}}
        text, final = [], {}
        t0 = time.perf_counter()
        ttft = None
        async with session.post(url, json=payload) as resp:
            resp.raise_for_status()
            async for line in resp.content:
                if not line.strip():
                    continue
                rec = json.loads(line)
                tok = rec.get("response", "")
                if tok:
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    text.append(tok)
                    first_chunk[i].set()
                if rec.get("done"):
                    final = rec
                    break
        n_done[0] += 1
        if n_done[0] == n_streams:
            streams_done.set()
        return {"idx": i, "reply": "".join(text),
                "ttft_s": round(ttft, 6) if ttft is not None else None,
                # Router-side decode window (the Ollama eval fields):
                # first token -> finish, measured by the serving
                # process — the stalls a prefill inflicts on decode
                # land here, while the measuring CLIENT's own
                # event-loop hiccups (this is a shared CPU) do not.
                "eval_count": final.get("eval_count", 0),
                "eval_duration_ns": final.get("eval_duration", 0),
                "output_tokens": final.get("eval_count", 0)}

    async def load(session, j: int) -> dict:
        # One long prompt, ONE-token reply: pure prefill pressure —
        # the request finishes at prefill-settle (its token comes out
        # of the prefill dispatch), so on the pd split a load never
        # occupies a decode-worker slot and on the mixed arms it adds
        # no decode work, only the prefill interference this lane
        # exists to measure. Content is deterministic and distinct per
        # index (and per warm/measured pass via load_tag — a measured
        # load must never hit the warm pass's prefix cache, or the
        # burst stops being prefill work).
        body = f"[{load_tag}{j:02d}] " + "the quick onyx tpu jumps "
        prompt = (body * (load_tokens // len(body) + 1))[:load_tokens]
        payload = {"model": model, "prompt": prompt,
                   "temperature": 0.0, "stream": False,
                   "options": {"num_predict": 1}}
        t0 = time.perf_counter()
        async with session.post(url, json=payload) as resp:
            resp.raise_for_status()
            rec = await resp.json()
        e2e = time.perf_counter() - t0
        # A num_predict=1 unary reply: the whole response IS the first
        # token, so e2e stands in for TTFT in the SLO comparison pool.
        return {"idx": j, "reply": rec.get("response", ""),
                "ttft_s": round(e2e, 6),
                "e2e_s": round(e2e, 4)}

    issued = [0]

    async def pump(session) -> list:
        await asyncio.gather(*[fc.wait() for fc in first_chunk])
        results, pending = [], set()
        waiter = asyncio.ensure_future(streams_done.wait())
        while not streams_done.is_set() and issued[0] < load_cap:
            while (len(pending) < PD_LOADS_IN_FLIGHT
                   and issued[0] < load_cap):
                pending.add(asyncio.ensure_future(
                    load(session, issued[0])))
                issued[0] += 1
            done, pending = await asyncio.wait(
                pending | {waiter},
                return_when=asyncio.FIRST_COMPLETED)
            pending.discard(waiter)
            results.extend(d.result() for d in done if d is not waiter)
        if pending:
            # Stop ISSUING at streams-done; in-flight loads complete
            # (the idle fleet drains them in milliseconds).
            results.extend(await asyncio.gather(*pending))
        if not waiter.done():
            waiter.cancel()
        return sorted(results, key=lambda r: r["idx"])

    async with aiohttp.ClientSession(timeout=timeout) as session:
        tasks = [stream(session, i) for i in range(n_streams)]
        if pressure:
            tasks.append(pump(session))
        res = await asyncio.gather(*tasks)
    return (res[:n_streams], (res[n_streams] if pressure else []),
            issued[0])


def _pd_tpot(streams: list) -> dict:
    """Per-stream decode TPOT (the main replay summary's definition:
    decode window over tokens-1, per request) reduced to p50/p95
    across streams, from the server's own eval accounting. The
    whole-window mean is the right estimator on a shared CPU: every
    stall a prefill inflicts on a stream lands in its window SUM,
    while measurement hiccups amortize over the stream's 100+
    tokens."""
    tpots = [s["eval_duration_ns"] / 1e9 / (s["eval_count"] - 1)
             for s in streams if s["eval_count"] > 1]
    return _percentiles(tpots, ps=(50, 95))


def _pd_tpot_merged(passes: list) -> dict:
    """Per-stream TPOT pooled across repeated passes of the same
    workload (sum of windows over sum of token gaps, per stream index),
    then p50/p95 across streams — the unloaded baseline runs twice and
    merges, halving the single-pass scheduling noise a 1-core host
    inflicts on a 1-2s window."""
    dur: dict = {}
    cnt: dict = {}
    for streams in passes:
        for s in streams:
            if s["eval_count"] > 1:
                dur[s["idx"]] = dur.get(s["idx"], 0) \
                    + s["eval_duration_ns"] / 1e9
                cnt[s["idx"]] = cnt.get(s["idx"], 0) \
                    + s["eval_count"] - 1
    tpots = [dur[i] / cnt[i] for i in sorted(dur) if cnt[i]]
    return _percentiles(tpots, ps=(50, 95))


def _pd_outputs_sha(streams: list) -> str:
    import hashlib

    h = hashlib.sha256()
    for r in sorted(streams, key=lambda r: r["idx"]):
        h.update(f"{r['idx']}:".encode())
        h.update(r["reply"].encode())
        h.update(b"\x00")
    return h.hexdigest()


def _pd_arm(args, label: str, roles: tuple,
            hybrid: bool = False) -> dict:
    """Boot one dp=2 subprocess topology, run warm + unloaded +
    loaded passes of the pinned workload, and summarize."""
    print(f"[replay] pd arm: {label}", file=sys.stderr)
    args.fleet = "subprocess"
    args.worker_roles = roles
    args.hybrid_prefill = hybrid
    args.worker_restart_backoff_s = 0.1
    args.worker_restart_max = 10
    srv, port, stop = start_server(args)
    group = srv.group
    n, dt = args.pd_streams, args.pd_decode_tokens
    nl, lt = args.pd_load_prompts, args.pd_load_prompt_tokens
    # Every client-measured TTFT this arm's server sees, across every
    # phase (pin requests, warm pass, baselines, loaded) — the SAME
    # population the workers' rolling SLO windows observed, so the
    # gauge-vs-replay comparison is apples to apples.
    client_ttfts: list = []

    def _collect(streams, loads=()):
        client_ttfts.extend(r["ttft_s"] for r in list(streams) + list(loads)
                            if r.get("ttft_s") is not None)

    chrome_trace = None
    try:
        # Pin stream placement first: prefill each stream prompt
        # SEQUENTIALLY so the rotating cold tie-break alternates
        # workers deterministically (2+2 on the mixed arms) and the
        # measured phases inherit that placement via prefix affinity —
        # concurrent cold admission with stale load peeks can land
        # 3+1, which skews the p95-across-streams baseline.
        for i in range(n):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/generate",
                data=json.dumps({"model": args.model,
                                 "prompt": f"[s{i:02d}] steady decode",
                                 "temperature": 0.0, "stream": False,
                                 "options": {"num_predict": 4}}).encode(),
                headers={"Content-Type": "application/json"})
            t_pin = time.perf_counter()
            urllib.request.urlopen(req, timeout=600).read()
            # Unary 4-token replies: e2e ~= TTFT at this size; close
            # enough for the pooled p95 of a ~100-request population.
            client_ttfts.append(round(time.perf_counter() - t_pin, 6))
        # UNMEASURED warm pass of the exact loaded workload (distinct
        # load content, a handful of loads): compiles every lazy graph
        # this arm will touch — prefill buckets, chunked/hybrid prefill
        # at real occupancy, decode, and (pd) the handoff export/adopt
        # path — so measured phases time serving, not XLA.
        warm_s, warm_l, _ = asyncio.run(
            _pd_burst(port, args.model, n, dt, True, lt,
                      load_cap=6, load_tag="W"))
        _collect(warm_s, warm_l)
        # Unloaded baseline x2 (merged per stream: a single 1-2s pass
        # on a 1-core host carries scheduling noise the merge halves).
        base_a, _, _ = asyncio.run(
            _pd_burst(port, args.model, n, dt, False, lt, 0))
        base_b, _, _ = asyncio.run(
            _pd_burst(port, args.model, n, dt, False, lt, 0))
        _collect(base_a)
        _collect(base_b)
        loaded_streams, loads, issued = asyncio.run(
            _pd_burst(port, args.model, n, dt, True, lt, nl))
        _collect(loaded_streams, loads)
        after = json.loads(scrape_metrics(port, fmt="json")[0])
        health = group.health_snapshot()
        if label == "pd" and getattr(args, "trace_artifact", None):
            # The Chrome-trace artifact (README "Observability"): the
            # recent-request ring over real HTTP — handed-off requests
            # show spans from three pids under one trace id.
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/trace?format=chrome",
                    timeout=60) as r:
                chrome_trace = json.loads(r.read().decode())
    finally:
        group.stop(drain=False)
        stop()
    sup = after.get("supervision") or {}
    sha_base = _pd_outputs_sha(base_a)
    sha_loaded = _pd_outputs_sha(loaded_streams)
    tpot_base = _pd_tpot_merged([base_a, base_b])
    tpot_loaded = _pd_tpot(loaded_streams)
    # SLO-gauge tracking: the fleet's pooled rolling-window TTFT p95
    # (scraped off the live servers) vs the same population's
    # client-measured p95, computed with the ring's own estimator so
    # the comparison isolates measurement-point skew (HTTP overhead),
    # not estimator choice.
    from tpu_inference import telemetry as _tm

    slo = {k: v for k, v in (after.get("slo") or {}).items()
           if not k.endswith("_window")}
    client_p95 = _tm.pooled_quantile([client_ttfts], 0.95)
    client_p95 = round(client_p95, 6) if client_p95 is not None else None
    gauge_p95 = slo.get("ttft_p95_s")
    ratio = (round(gauge_p95 / client_p95, 4)
             if gauge_p95 and client_p95 else None)
    return {
        "slo": slo,
        "client_ttft_p95_s": client_p95,
        "client_ttft_requests": len(client_ttfts),
        "slo_ttft_p95_tracking_ratio": ratio,
        "_chrome_trace": chrome_trace,
        "label": label, "roles": list(roles) or ["mixed", "mixed"],
        "hybrid_prefill": hybrid,
        "streams": n, "decode_tokens": dt,
        "loads_issued": issued, "loads_completed": len(loads),
        "load_prompt_tokens": lt,
        "output_tokens": sum(s["output_tokens"]
                             for s in loaded_streams),
        # Decode TPOT (per-stream window mean), per phase.
        "decode_tpot_s_unloaded": tpot_base,
        "decode_tpot_s_loaded": tpot_loaded,
        "decode_tpot_p95_ratio": (
            round(tpot_loaded["p95"] / tpot_base["p95"], 4)
            if tpot_base["p95"] else None),
        "load_e2e_s": _percentiles([r["e2e_s"] for r in loads],
                                   ps=(50, 95)),
        # Byte-identity: the same streams must read the same in both
        # phases (warm cache is a placement detail) and across arms.
        "outputs_sha256": sha_base,
        "outputs_phases_identical": (
            sha_base == sha_loaded == _pd_outputs_sha(base_b)),
        "load_replies": [r["reply"] for r in loads],
        "pd_handoffs": sup.get("pd_handoffs", 0),
        "pd_adoptions": sup.get("pd_adoptions", 0),
        "pd_handoff_recomputes": sup.get("pd_handoff_recomputes", 0),
        "resume_recomputed_tokens": sup.get(
            "resume_recomputed_tokens", 0),
        "worker_restarts": sup.get("worker_restarts", 0),
        "fleet_status": health.get("status"),
    }


def _compare_pd(args) -> dict:
    """The P/D disaggregation artifact (README "P/D disaggregation"):
    the pinned long-prompt burst through three dp=2 subprocess
    topologies — mixed (every worker runs both phases), hybrid (mixed
    + PR-4 fused prefill-decode steps), and pd (1 prefill + 1 decode
    worker with live KV handoff). Each arm measures decode TPOT p95
    unloaded (decode streams only) then loaded (same streams + a
    prefill burst >= 10x the streams' own prefill tokens). The pd
    split keeps decode cadence flat — prefill never enters the decode
    engine, and on shared-CPU hosts the prefill tier is nice()d down
    (pd_prefill_nice; on TPU the isolation is physical) — while mixed/
    hybrid serialize prefill INTO the decode engine's dispatch stream,
    an interference no priority can remove. Outputs must be
    byte-identical across every arm and phase, and the pd arm's clean
    handoffs must recompute zero tokens."""
    cfg_snapshot = {k: v for k, v in vars(args).items()
                    if not k.startswith("_")}
    arms = {}
    arms["mixed"] = _pd_arm(args, "mixed", ())
    arms["hybrid"] = _pd_arm(args, "hybrid", (), hybrid=True)
    arms["pd"] = _pd_arm(args, "pd", ("prefill", "decode"))
    args.worker_roles, args.fleet = (), "in-process"

    # Chrome-trace artifact (README "Observability"): the pd arm's
    # recent-request ring, graded for the one-trace-three-pids
    # handoff claim and the SLO-gauge tracking claim, then written as
    # pure Chrome trace-event JSON (grading rides in otherData so the
    # file stays Perfetto-loadable).
    chrome = arms["pd"].pop("_chrome_trace", None)
    for a in arms.values():
        a.pop("_chrome_trace", None)
    trace_grading = None
    if chrome is not None:
        trace_grading = _grade_handoff_traces(chrome)
        trace_grading["slo"] = dict(arms["pd"]["slo"])
        trace_grading["client_ttft_p95_s"] = \
            arms["pd"]["client_ttft_p95_s"]
        trace_grading["slo_ttft_p95_tracking_ratio"] = \
            arms["pd"]["slo_ttft_p95_tracking_ratio"]
        trace_grading["slo_tracks_within_10pct"] = bool(
            arms["pd"]["slo_ttft_p95_tracking_ratio"] is not None
            and abs(arms["pd"]["slo_ttft_p95_tracking_ratio"] - 1.0)
            <= 0.10)
        chrome.setdefault("otherData", {}).update(trace_grading)
        if getattr(args, "trace_artifact", None):
            _write_out(args.trace_artifact, chrome)
            print(f"[replay] chrome trace artifact -> "
                  f"{args.trace_artifact}", file=sys.stderr)

    mixed, hybrid, pd = arms["mixed"], arms["hybrid"], arms["pd"]
    shas = {a["outputs_sha256"] for a in arms.values()}
    phases_ok = all(a["outputs_phases_identical"]
                    for a in arms.values())
    # A load's single greedy token is deterministic per index content,
    # so the arms must agree on every load they have in common (each
    # arm absorbs a different COUNT under pressure — the pd arm's
    # nice()d prefill tier grinds slower by design).
    n_common = min(a["loads_completed"] for a in arms.values())
    loads_ok = n_common > 0 and len(
        {tuple(a["load_replies"][:n_common])
         for a in arms.values()}) == 1
    for a in arms.values():
        del a["load_replies"]
    # Offered prefill tokens vs the streams' own prompts: every arm's
    # generator ISSUED at least min_issued loads into its fleet while
    # the streams decoded.
    stream_prefill = args.pd_streams * 18      # "[sNN] steady decode"
    min_issued = min(a["loads_issued"] for a in arms.values())
    comparison = {
        "prefill_load_ratio": round(
            (stream_prefill
             + min_issued * args.pd_load_prompt_tokens)
            / stream_prefill, 1),
        "loads_issued": {k: a["loads_issued"]
                         for k, a in arms.items()},
        "loads_completed": {k: a["loads_completed"]
                            for k, a in arms.items()},
        "decode_tpot_p95_unloaded_s": {
            k: a["decode_tpot_s_unloaded"]["p95"]
            for k, a in arms.items()},
        "decode_tpot_p95_loaded_s": {
            k: a["decode_tpot_s_loaded"]["p95"]
            for k, a in arms.items()},
        "decode_tpot_p95_ratio": {
            k: a["decode_tpot_p95_ratio"] for k, a in arms.items()},
        # The lane's headline: under the 10x+ prefill burst the pd
        # arm's decode TPOT p95 holds within 10% of its own unloaded
        # baseline; the in-engine topologies degrade.
        "pd_tpot_flat": bool(pd["decode_tpot_p95_ratio"] is not None
                             and pd["decode_tpot_p95_ratio"] <= 1.10),
        "hybrid_degrades": bool(
            hybrid["decode_tpot_p95_ratio"] is not None
            and pd["decode_tpot_p95_ratio"] is not None
            and hybrid["decode_tpot_p95_ratio"] >= 1.25
            and hybrid["decode_tpot_p95_ratio"]
            > pd["decode_tpot_p95_ratio"]),
        "mixed_tpot_p95_ratio": mixed["decode_tpot_p95_ratio"],
        "outputs_identical": bool(len(shas) == 1 and loads_ok
                                  and phases_ok),
        "pd_handoffs": pd["pd_handoffs"],
        "pd_adoptions": pd["pd_adoptions"],
        # Clean-handoff path: adoption restores the exported KV (incl.
        # the partial final page) — nothing recomputes.
        "pd_handoff_recomputes": pd["pd_handoff_recomputes"],
        "pd_recomputed_tokens": pd["resume_recomputed_tokens"],
        "pd_clean_handoffs": bool(pd["pd_handoffs"] > 0
                                  and pd["pd_handoff_recomputes"] == 0
                                  and pd["resume_recomputed_tokens"]
                                  == 0),
        # Distributed tracing + SLO gauges (README "Observability"):
        # the pd arm's cross-process trace grading and the rolling
        # TTFT-p95 gauge vs the replay's own measurement.
        "trace": trace_grading,
        "slo_breaches": {k: {"ttft": (a["slo"] or {}).get(
                                 "ttft_breaches"),
                             "tpot": (a["slo"] or {}).get(
                                 "tpot_breaches")}
                         for k, a in arms.items()},
    }
    comparison["pd_wins"] = bool(
        comparison["outputs_identical"]
        and comparison["pd_clean_handoffs"]
        and comparison["pd_tpot_flat"]
        and comparison["hybrid_degrades"])
    out = {"config": cfg_snapshot, **arms, "comparison": comparison}
    print(json.dumps(comparison, indent=1))
    _write_out(args.out, out)
    result = dict(comparison)
    result.update(arms)
    return result


def _write_out(path, record) -> None:
    if not path:
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(record, f, indent=1)


if __name__ == "__main__":
    main()
