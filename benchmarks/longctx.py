"""Long-context serving benchmark: chunked prefill + deep-context decode.

Long context is a first-class capability (SURVEY.md §5): this measures
the two numbers that define it on a single chip — **prefill throughput**
(tok/s through the incremental chunked-prefill path, interleavable with
decode in production) and **decode TPOT at deep context** (per-token
latency once the KV holds ``--ctx`` tokens, where paged attention's
O(pages) reads and the int8 KV tier earn their keep).

Drives the PRODUCTION serving loop (EngineScheduler: admission, chunked
prefill, fused decode, streaming callbacks) — not a hand-rolled forward
loop — with one synthetic ``--ctx``-token prompt. TTFT here is
engine-side (no HTTP/tokenizer), labeled as such in the output.

Usage:
    python benchmarks/longctx.py --model /tmp/real-llama-1b --ctx 8192 \
        --quant int8 --kv-quant int8 --out benchmarks/results/longctx.json

Emits one JSON line: {"metric": "longctx", "ctx": N,
"prefill_tok_s": ..., "ttft_s": ..., "tpot_ms": ..., ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> dict:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="tiny-llama",
                   help="preset name or HF checkpoint dir")
    p.add_argument("--ctx", type=int, default=8192,
                   help="prompt length (tokens) to prefill")
    p.add_argument("--decode-tokens", type=int, default=64,
                   help="decode steps measured at full context")
    p.add_argument("--chunk", type=int, default=512,
                   help="prefill chunk size (the compiled bucket)")
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--quant", default="none",
                   choices=("none", "int8", "int4"))
    p.add_argument("--kv-quant", default="none",
                   choices=("none", "int8", "int4"))
    p.add_argument("--attn-backend", default="auto",
                   choices=("auto", "dense", "pallas"))
    p.add_argument("--platform", default="auto",
                   choices=("auto", "cpu", "tpu"))
    p.add_argument("--out", default=None)
    args = p.parse_args()

    if args.platform != "auto":
        import jax

        jax.config.update("jax_platforms", args.platform)

    from tpu_inference.config import PRESETS, EngineConfig
    from tpu_inference.engine.engine import InferenceEngine, Sequence
    from tpu_inference.engine.scheduler import EngineScheduler

    if os.path.isdir(args.model):
        from tpu_inference.models.weights import config_from_hf

        model_cfg = config_from_hf(args.model)
        checkpoint = args.model
    else:
        model_cfg = PRESETS[args.model]()
        checkpoint = None

    total = args.ctx + args.decode_tokens + 1
    pages_per_seq = -(-total // args.page_size) + 1
    ecfg = EngineConfig(
        page_size=args.page_size, num_pages=pages_per_seq + 2,
        max_pages_per_seq=pages_per_seq, max_batch_size=1,
        prefill_buckets=(args.chunk,), max_new_tokens=args.decode_tokens,
        quant=args.quant, kv_quant=args.kv_quant,
        attn_backend=args.attn_backend)

    t_build = time.perf_counter()
    if checkpoint:
        from tpu_inference.models.weights import load_checkpoint

        params = load_checkpoint(model_cfg, checkpoint, quant=args.quant)
        engine = InferenceEngine(model_cfg, ecfg, params=params)
    else:
        engine = InferenceEngine(model_cfg, ecfg)
    build_s = time.perf_counter() - t_build

    # Synthetic prompt: deterministic ids away from special tokens.
    prompt = [17 + (i * 7919) % (model_cfg.vocab_size - 32)
              for i in range(args.ctx)]

    token_times: list = []
    done = threading.Event()
    sched = EngineScheduler(engine).start()
    try:
        seq = Sequence(request_id=0, prompt_tokens=prompt,
                       max_new_tokens=args.decode_tokens)
        t0 = time.perf_counter()
        sched.submit(seq, on_token=lambda s, t: token_times.append(
            time.perf_counter()), on_finish=lambda s: done.set())
        if not done.wait(timeout=3600):
            raise TimeoutError("long-context generation hung")
    finally:
        sched.stop(drain=False)

    ttft = token_times[0] - t0
    decode_s = token_times[-1] - token_times[0]
    n = len(token_times)
    import jax

    rec = {
        "metric": "longctx",
        "model": model_cfg.name,
        "ctx": args.ctx,
        "chunk": args.chunk,
        "quant": args.quant,
        "kv_quant": args.kv_quant,
        "backend": engine.attn_backend,
        "platform": jax.default_backend(),
        # TTFT covers the full chunked prefill of ctx tokens plus the
        # first decode dispatch (engine-side: no HTTP/tokenizer in the
        # path, unlike replay.py's client-side TTFT).
        "ttft_s": round(ttft, 3),
        "prefill_tok_s": round(args.ctx / ttft, 1),
        "decode_tokens": n,
        # One token = no decode interval to measure; null, not a
        # 1e-9-floor artifact.
        "tpot_ms": round(decode_s / (n - 1) * 1e3, 2) if n > 1 else None,
        "decode_tok_s": round((n - 1) / decode_s, 2) if n > 1 else None,
        "build_s": round(build_s, 1),
    }
    print(json.dumps(rec), flush=True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    return rec


if __name__ == "__main__":
    main()
