#!/bin/bash
# Round-5 TPU measurement battery (VERDICT r4 items 1-4). Stages run in
# VALUE order so a mid-battery re-wedge still captures the headline:
#   bench    hardened bench.py, pallas bf16/int8/int4/dense lanes
#            (BENCH_r05 content; 556/612 tok/s bf16/int8 landed 01:15)
#   mosaic   Mosaic-validate the window-aware Pallas kernels + SP
#            wrappers non-interpret (landed: mosaic_r5.json 6/6 ok)
#   replay   saturated BurstGPT replay: real 1B ckpt, int8+int8, auto
#            batch (VERDICT item 2: >=370 tok/s, TTFT p50 < 5 s)
#   bench8b  BENCH_MODEL=8b int8 lane (BASELINE.md config-1 row)
#   longctx  8k chunked prefill + deep-context decode TPOT, KV bf16 vs
#            int8 A/B (benchmarks/longctx.py — SURVEY §5 long context)
#   sweep    decode_steps x pipeline-depth mini-sweep (hbm_util push)
#   bench32  BENCH_BATCH=32 chip-sized batch lane
#   bench16k BENCH_KSTEPS=16 fused-K A/B vs the K=8 headline
#   turns    multi-turn chat replay with prefix cache (config-3 row
#            on the chip; CPU demo landed round 3)
#
#   bash benchmarks/run_tpu_round5.sh [stage ...]   # default: all
#
# EVERY python invocation that can touch the TPU goes through guard():
# its own session/process group, SIGKILLed wholesale on deadline. A
# TERM-then-orphan kill (plain `timeout`) leaves axon runtime helpers
# holding the chip — that is exactly how the first round-5 battery run
# wedged the tunnel mid-battery (replay overran its 1500 s timeout).
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
STAGES=${@:-"bench mosaic replay bench8b longctx sweep bench32 bench64 bench16k turns"}
CKPT=/tmp/real-llama-1b

guard() {
  # guard <deadline_s> <cmd...>: run in a fresh process group; on
  # deadline SIGKILL the whole group (never TERM — no orphan window).
  local deadline=$1; shift
  setsid "$@" &
  local pid=$!
  # Watchdog stdout MUST be detached: call sites pipe the function's
  # stdout (tee/tail/$()), and an inherited write-end held by the
  # watchdog's sleep would stall the pipe at EOF for the full deadline
  # even after the guarded command exits. The deadline diagnostic goes
  # to stderr, which call sites tie to files (never blocks).
  (
    sleep "$deadline"
    if kill -0 "$pid" 2>/dev/null; then
      echo "[guard] deadline ${deadline}s hit; SIGKILL group $pid" >&2
      kill -KILL -- "-$pid" 2>/dev/null
    fi
  ) >/dev/null &
  local watchdog=$!
  wait "$pid"
  local rc=$?
  kill "$watchdog" 2>/dev/null
  wait "$watchdog" 2>/dev/null
  return $rc
}

probe() {
  # Shared wedge-safe probe (bench.py child runner: own process group,
  # SIGKILL on timeout — never orphans a runtime helper on the chip).
  guard 300 python -c "
import json, sys, bench
rc, rec = bench._run_child(['--probe'], 120)
print(json.dumps(rec)) if rec else sys.exit(1)"
}

echo "== probe: $(probe || echo UNREACHABLE)"

for s in $STAGES; do case $s in
bench)
  echo "== bench.py (5 lanes, headline)"
  guard 1400 python bench.py 2>benchmarks/results/bench_r5_tpu.err \
    | tee benchmarks/results/bench_r5_tpu.jsonl
  ;;
mosaic)
  echo "== mosaic-validate windowed kernels (non-interpret)"
  guard 600 env "PYTHONPATH=.:${PYTHONPATH:-}" python benchmarks/mosaic_validate.py \
    --out benchmarks/results/mosaic_r5.json \
    2>benchmarks/results/mosaic_r5.err | tail -8
  ;;
replay)
  if [ -d "$CKPT" ]; then
    # 60 queries + a 2400 s guard: the first battery's 100-query run
    # overran 1500 s (early queries TTFT-stall while the autosized
    # batch-32 decode graphs compile); the guard is sized to never
    # fire on a healthy run.
    echo "== saturated BurstGPT replay (real 1B, int8+int8, auto batch)"
    guard 2400 python benchmarks/replay.py \
      --model "$CKPT" --tokenizer auto \
      --quant int8 --kv-quant int8 \
      --max-batch-size auto --num-pages auto --batch-cap 32 \
      --trace data/BurstGPT_1.csv --max-trace 60 \
      --decode-pipeline-depth 2 \
      --out benchmarks/results/real1b_burstgpt_r5_int8_auto.json \
      2>benchmarks/results/replay_r5.err | tail -5
  else
    echo "== replay SKIPPED: $CKPT missing"
  fi
  ;;
bench8b)
  echo "== bench.py BENCH_MODEL=8b (int8-only lane, config-1 row)"
  guard 1400 env BENCH_MODEL=8b python bench.py \
    2>benchmarks/results/bench_r5_8b.err \
    | tee benchmarks/results/bench_r5_8b.jsonl
  ;;
bench32)
  echo "== bench.py BENCH_BATCH=32 (chip-sized batch lane)"
  guard 1400 env BENCH_BATCH=32 python bench.py \
    2>benchmarks/results/bench_r5_bs32.err \
    | tee benchmarks/results/bench_r5_bs32.jsonl
  ;;
bench64)
  # Decode reads the weights once per step regardless of batch: if the
  # bs32 lane still scales ~linearly, 64 slots push hbm_util further
  # toward the roofline (HBM supports it at 1B scale; autosize math).
  echo "== bench.py BENCH_BATCH=64 (roofline-push batch lane)"
  guard 1400 env BENCH_BATCH=64 python bench.py \
    2>benchmarks/results/bench_r5_bs64.err \
    | tee benchmarks/results/bench_r5_bs64.jsonl
  ;;
bench16k)
  echo "== bench.py BENCH_KSTEPS=16 (fused-K A/B vs the K=8 headline)"
  guard 1400 env BENCH_KSTEPS=16 python bench.py \
    2>benchmarks/results/bench_r5_k16.err \
    | tee benchmarks/results/bench_r5_k16.jsonl
  ;;
sweep)
  echo "== K x depth sweep on the int8 replay config (hbm_util push)"
  for KD in "8 2" "16 2" "16 4"; do
    [ -d "$CKPT" ] || break
    set -- $KD
    echo "-- K=$1 depth=$2"
    guard 1200 python benchmarks/replay.py \
      --model "$CKPT" --tokenizer auto --quant int8 --kv-quant int8 \
      --max-batch-size auto --num-pages auto --batch-cap 32 \
      --trace data/BurstGPT_1.csv --max-trace 30 \
      --decode-steps-per-call "$1" --decode-pipeline-depth "$2" \
      --out "benchmarks/results/sweep_r5_K$1_D$2.json" \
      2>"benchmarks/results/sweep_r5_K$1_D$2.err" | tail -2
  done
  ;;
longctx)
  if [ -d "$CKPT" ]; then
    # Long context on ONE chip (SURVEY §5 first-class capability):
    # 8k-token chunked prefill + decode TPOT at full context, int8
    # weights, KV bf16 vs int8 vs packed-int4 A/B (the KV tiers'
    # deep-context payoff).
    echo "== long-context: 8k prefill + deep-ctx decode (real 1B, int8)"
    for KVQ in none int8 int4; do
      guard 1200 python benchmarks/longctx.py \
        --model "$CKPT" --ctx 8192 --decode-tokens 64 --chunk 512 \
        --quant int8 --kv-quant "$KVQ" \
        --out "benchmarks/results/longctx_r5_kv$KVQ.json" \
        2>"benchmarks/results/longctx_r5_kv$KVQ.err" | tail -1
    done
  else
    echo "== longctx SKIPPED: $CKPT missing"
  fi
  ;;
turns)
  if [ -d "$CKPT" ]; then
    echo "== multi-turn chat replay (prefix cache, real 1B, int8)"
    guard 1800 python benchmarks/multiturn.py \
      --model "$CKPT" --tokenizer auto --quant int8 \
      --conversations 6 --turns 5 \
      --out benchmarks/results/config3_multiturn_r5_tpu.json \
      2>benchmarks/results/multiturn_r5.err | tail -6
  else
    echo "== turns SKIPPED: $CKPT missing"
  fi
  ;;
*) echo "unknown stage $s";;
esac; done
echo "== done"
