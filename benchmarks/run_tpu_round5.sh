#!/bin/bash
# Round-5 TPU measurement battery (VERDICT r4 items 1-4). Stages run in
# VALUE order so a mid-battery re-wedge still captures the headline:
#   bench    hardened bench.py, pallas bf16/int8/dense lanes (BENCH_r05
#            content; target: re-verify >=510 tok/s on the chip)
#   mosaic   Mosaic-validate the window-aware Pallas kernels + SP
#            wrappers non-interpret (VERDICT item 4; cheap)
#   replay   saturated BurstGPT replay: real 1B ckpt, int8+int8, auto
#            batch (VERDICT item 2: >=370 tok/s, TTFT p50 < 5 s)
#   bench8b  BENCH_MODEL=8b int8 lane (BASELINE.md config-1 row)
#   bench32  BENCH_BATCH=32 chip-sized batch lane
#   sweep    decode_steps x pipeline-depth mini-sweep (hbm_util push)
#
#   bash benchmarks/run_tpu_round5.sh [stage ...]   # default: all
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
STAGES=${@:-"bench mosaic replay bench8b bench32 sweep"}
CKPT=/tmp/real-llama-1b

probe() {
  # Shared wedge-safe probe (bench.py child runner: own process group,
  # SIGKILL on timeout — never orphans a runtime helper on the chip).
  timeout -k 10 300 python -c "
import json, sys, bench
rc, rec = bench._run_child(['--probe'], 120)
print(json.dumps(rec)) if rec else sys.exit(1)"
}

echo "== probe: $(probe || echo UNREACHABLE)"

for s in $STAGES; do case $s in
bench)
  echo "== bench.py (4 lanes, headline)"
  timeout 1400 python bench.py 2>benchmarks/results/bench_r5_tpu.err \
    | tee benchmarks/results/bench_r5_tpu.jsonl
  ;;
mosaic)
  echo "== mosaic-validate windowed kernels (non-interpret)"
  PYTHONPATH=.:${PYTHONPATH:-} timeout 600 python benchmarks/mosaic_validate.py \
    --out benchmarks/results/mosaic_r5.json \
    2>benchmarks/results/mosaic_r5.err | tail -8
  ;;
replay)
  if [ -d "$CKPT" ]; then
    echo "== saturated BurstGPT replay (real 1B, int8+int8, auto batch)"
    timeout 1500 python benchmarks/replay.py \
      --model "$CKPT" --tokenizer auto \
      --quant int8 --kv-quant int8 \
      --max-batch-size auto --num-pages auto --batch-cap 32 \
      --trace data/BurstGPT_1.csv --max-trace 100 \
      --decode-pipeline-depth 2 \
      --out benchmarks/results/real1b_burstgpt_r5_int8_auto.json \
      2>&1 | tail -5
  else
    echo "== replay SKIPPED: $CKPT missing"
  fi
  ;;
bench8b)
  echo "== bench.py BENCH_MODEL=8b (int8-only lane, config-1 row)"
  BENCH_MODEL=8b timeout 1400 python bench.py \
    2>benchmarks/results/bench_r5_8b.err \
    | tee benchmarks/results/bench_r5_8b.jsonl
  ;;
bench32)
  echo "== bench.py BENCH_BATCH=32 (chip-sized batch lane)"
  BENCH_BATCH=32 timeout 1400 python bench.py \
    2>benchmarks/results/bench_r5_bs32.err \
    | tee benchmarks/results/bench_r5_bs32.jsonl
  ;;
sweep)
  echo "== K x depth sweep on the int8 replay config (hbm_util push)"
  for K in 8 16; do for D in 1 2 4; do
    [ -d "$CKPT" ] || break 2
    echo "-- K=$K depth=$D"
    timeout 900 python benchmarks/replay.py \
      --model "$CKPT" --tokenizer auto --quant int8 --kv-quant int8 \
      --max-batch-size auto --num-pages auto --batch-cap 32 \
      --trace data/BurstGPT_1.csv --max-trace 40 \
      --decode-steps-per-call $K --decode-pipeline-depth $D \
      --out benchmarks/results/sweep_r5_K${K}_D${D}.json \
      2>&1 | tail -2
  done; done
  ;;
*) echo "unknown stage $s";;
esac; done
echo "== done"
