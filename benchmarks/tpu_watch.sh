#!/bin/bash
# Probe the axon TPU tunnel until it heals, then run the round-5
# measurement battery exactly once. Intended to run in the background:
#   bash benchmarks/tpu_watch.sh >> benchmarks/results/tpu_watch.log 2>&1
set -u
cd "$(dirname "$0")/.."
INTERVAL=${TPU_WATCH_INTERVAL_S:-600}
DEADLINE=${TPU_WATCH_DEADLINE_S:-43200}   # give up after 12h (a full round)
start=$(date +%s)
n=0
while :; do
  n=$((n + 1))
  now=$(date +%s)
  if [ $((now - start)) -gt "$DEADLINE" ]; then
    echo "[watch] $(date -u +%H:%M:%S) deadline reached after $n probes; giving up"
    exit 1
  fi
  # One shared, wedge-safe probe: bench.py's hardened child runner
  # (own process group, SIGKILL on timeout, stdout via temp file) — a
  # naive `timeout python -c "import jax..."` can orphan axon runtime
  # helpers that hold the TPU and keep the tunnel wedged (round-3 mode).
  # Outer timeout bounds the PARENT interpreter (the deepest wedge mode
  # blocks python at startup, before _run_child's 120s can start); it is
  # well above the child's own deadline so it never kills a live child.
  # stderr flows to the watch log — a broken probe must look broken,
  # not like "still wedged" for 8 hours.
  # 60s child deadline: a healthy tunnel probes in ~15s; only a wedged
  # init ever runs longer, and every wedged probe burns the box's single
  # core (it contends with foreground suite/bench runs).
  if timeout -k 10 180 python -c "
import sys, bench
rc, rec = bench._run_child(['--probe'], 60)
sys.exit(0 if rec and rec.get('platform') == 'tpu' else 1)"; then
    echo "[watch] $(date -u +%H:%M:%S) tunnel healthy after $n probes; running battery"
    # Replay first: the saturated BurstGPT replay is the round's most
    # valuable missing artifact (bench/mosaic headline already landed
    # 01:15; a mid-battery re-wedge must not cost it again).
    # mosaic re-runs even though 6/6 landed 01:15: swa_decode4 (int4 KV
    # unpack) was added after that run and needs its Mosaic proof.
    bash benchmarks/run_tpu_round5.sh replay bench mosaic bench8b longctx bench32 bench64 sweep bench16k turns
    exit 0
  fi
  echo "[watch] $(date -u +%H:%M:%S) probe $n: tunnel still wedged; sleeping ${INTERVAL}s"
  sleep "$INTERVAL"
done
