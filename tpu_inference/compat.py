"""jax version compatibility shims.

The codebase targets the modern ``jax.shard_map`` API (jax >= 0.5);
older runtimes (0.4.x) only ship ``jax.experimental.shard_map`` whose
replication-check kwarg is spelled ``check_rep`` instead of
``check_vma``. Everything in-repo imports ``shard_map`` from here so a
version bump (either direction) is a one-file change.
"""

from __future__ import annotations

import os

import jax


def set_cpu_device_count(n: int) -> None:
    """Ask for ``n`` virtual CPU devices before any computation runs.

    ``jax_num_cpu_devices`` is the modern knob; jax < 0.5 only honors
    ``--xla_force_host_platform_device_count``, which XLA parses at lazy
    backend initialization — so mutating XLA_FLAGS after import (but
    before the first computation) still works."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}")

def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis, inside shard_map.

    ``jax.lax.axis_size`` is the modern spelling; on 0.4.x the constant
    fold of ``psum(1, axis)`` is the canonical way to get the same
    static int."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pvary(vals, axes):
    """Mark values device-varying over ``axes`` for shard_map's
    varying-axis typing. pcast is the current spelling, pvary the
    deprecated one (attribute access alone warns, so probe pcast
    first); 0.4.x shard_map has no varying-axis typing at all, so
    values pass through untouched."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(vals, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(vals, axes)
    return vals


if hasattr(jax, "shard_map"):
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
