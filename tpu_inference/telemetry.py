"""Step-phase telemetry: allocation-light metrics + Prometheus exposition.

The round-5 verdict's top directive is evidence: ``hbm_util`` sits far
below target and nothing in the repo can say where the missing roofline
goes — weights vs KV vs dispatch vs host-side bubbles. This module is
the instrumentation layer that answers that with an artifact instead of
archaeology:

- **Counter / Gauge / Histogram**: plain-Python metric primitives cheap
  enough for the dispatch hot path. ``observe()`` is one ``bisect`` (C
  code) + two attribute writes — no allocation, no locks; CPython's GIL
  makes the individual updates atomic and metrics tolerate the rare
  torn read-modify-write under thread races (same stance as the
  scheduler's existing ring buffer). Histograms are log-bucketed
  (powers of two) so one static bucket table spans 10 µs dispatches
  through queue waits at the 600 s request timeout.
- **Registry + render_prometheus()**: standards-compliant Prometheus
  text exposition (format 0.0.4: HELP/TYPE lines, escaped labels,
  cumulative ``_bucket`` series with ``le="+Inf"``, ``_sum``/``_count``)
  over any number of label-tagged registries — the dp replica view
  (server/replicas.py) renders one registry per replica under
  ``replica="i"`` labels plus a fleet registry.
- **Phase snapshots**: JSON-able histogram dumps (cumulative buckets +
  sum + estimated percentiles) that survive scrape-diffing, so
  benchmarks (replay.py / bench.py) can scrape before/after a run and
  commit a ``phase_breakdown`` of exactly that window.
- **log_event()**: one-line structured JSON logs on stderr, leveled via
  ``TPU_INF_LOG`` (default "warning" so test/bench output stays clean;
  set ``TPU_INF_LOG=info`` for per-request lifecycle events). Events
  carry the propagated request id.

``TPU_INF_TELEMETRY=0`` disables collection entirely (every metric
becomes a shared no-op singleton) — the comparison arm of the overhead
budget (README "Observability": ≤1% on the decode dispatch microbench).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from bisect import bisect_left
from typing import (Any, Callable, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Tuple)

# ---------------------------------------------------------------------------
# Structured logging
# ---------------------------------------------------------------------------

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}


def _log_threshold() -> int:
    return _LEVELS.get(os.environ.get("TPU_INF_LOG", "warning").lower(), 30)


def log_event(event: str, level: str = "info", **fields: Any) -> None:
    """Emit one structured JSON log line to stderr.

    Levels below the ``TPU_INF_LOG`` threshold are dropped before any
    serialization work. stderr (not stdout) so bench harnesses that
    parse JSON records off stdout never see log lines.
    """
    if _LEVELS.get(level, 20) < _log_threshold():
        return
    rec = {"ts": round(time.time(), 4), "level": level, "event": event}
    rec.update(fields)
    try:
        line = json.dumps(rec, default=str)
    except (TypeError, ValueError):
        line = json.dumps({"ts": rec["ts"], "level": level, "event": event,
                           "error": "unserializable fields"})
    print(line, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Metric primitives
# ---------------------------------------------------------------------------

class _NullMetric:
    """Shared no-op stand-in when telemetry is disabled: every mutator
    is a single attribute lookup + empty call, so instrumented code
    needs no ``if enabled`` branches of its own."""

    __slots__ = ()

    def inc(self, n: float = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


NULL_METRIC = _NullMetric()


class Counter:
    """Monotonic counter. ``fn`` makes it a read-through counter whose
    value is computed at collect time (zero hot-path cost for counters
    the code base already tracks, e.g. SchedulerStats fields)."""

    __slots__ = ("name", "help", "labels", "value", "fn")
    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Mapping[str, str]] = None,
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.value: float = 0
        self.fn = fn

    def inc(self, n: float = 1) -> None:
        self.value += n

    def collect_value(self) -> float:
        return self.fn() if self.fn is not None else self.value


class Gauge:
    """Point-in-time value; ``fn`` = computed at collect time."""

    __slots__ = ("name", "help", "labels", "value", "fn")
    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Mapping[str, str]] = None,
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.value: float = 0
        self.fn = fn

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n

    def collect_value(self) -> float:
        return self.fn() if self.fn is not None else self.value


# Log-spaced (powers of two) bucket bounds. Seconds: ~7.6 µs .. 1024 s
# covers a Pallas decode dispatch through a queue wait at the 600 s
# default request timeout (the saturation tail must not clamp at the
# last bound — that is exactly the regime these histograms measure);
# counts: 1 .. 512 covers tokens-per-dispatch at any sane fused-K*batch.
SECONDS_BUCKETS = tuple(2.0 ** e for e in range(-17, 11))
COUNT_BUCKETS = tuple(float(2 ** e) for e in range(0, 10))
# Ratio-valued histograms (e.g. per-round speculative acceptance rate):
# eighths of [0, 1] — fine enough to see "mostly rejected" vs "mostly
# accepted", coarse enough to stay allocation-light.
RATE_BUCKETS = tuple(i / 8 for i in range(9))


class Histogram:
    """Fixed-bucket histogram (Prometheus ``histogram`` semantics).

    ``_counts`` holds per-bucket (non-cumulative) counts with one
    overflow bucket at the end; exposition renders them cumulative with
    a final ``le="+Inf"``. ``observe`` is allocation-free: one C-level
    bisect + two in-place adds.
    """

    __slots__ = ("name", "help", "labels", "bounds", "_counts", "sum")
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = SECONDS_BUCKETS,
                 labels: Optional[Mapping[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.bounds: Tuple[float, ...] = tuple(buckets)
        assert list(self.bounds) == sorted(self.bounds)
        self._counts: List[int] = [0] * (len(self.bounds) + 1)
        self.sum: float = 0.0

    def observe(self, v: float) -> None:
        # bisect_left(bounds, v) = first bucket whose bound >= v, i.e.
        # Prometheus's le (inclusive upper bound) convention.
        self._counts[bisect_left(self.bounds, v)] += 1
        self.sum += v

    @property
    def count(self) -> int:
        return sum(self._counts)

    def cumulative(self) -> List[int]:
        """Per-le cumulative counts (len(bounds) + 1, last = +Inf).
        Computed from a point-in-time copy so a concurrent observe can
        never yield a non-monotone series."""
        counts = list(self._counts)
        out, acc = [], 0
        for c in counts:
            acc += c
            out.append(acc)
        return out

    def percentile(self, p: float) -> Optional[float]:
        return percentile_from_cumulative(self.bounds, self.cumulative(), p)

    def phase_snapshot(self) -> Dict[str, Any]:
        """JSON-able dump: cumulative buckets (diffable across scrapes)
        + sum + estimated percentiles."""
        return _phase_dict(self.bounds, self.cumulative(), self.sum)


def _phase_dict(bounds: Sequence[float], cumulative: List[int],
                total_sum: float,
                les: Optional[Sequence[Any]] = None) -> Dict[str, Any]:
    """The one assembly point for the {count, sum, percentiles, buckets}
    snapshot shape shared by phase_snapshot / diff_phase / merge_phases —
    consumers (replay phase_breakdown, fleet merge) rely on the three
    producers never drifting apart."""
    if les is None:
        les = list(bounds) + ["+Inf"]
    return {
        "count": cumulative[-1],
        "sum": round(total_sum, 6),
        "p50": percentile_from_cumulative(bounds, cumulative, 0.50),
        "p95": percentile_from_cumulative(bounds, cumulative, 0.95),
        "p99": percentile_from_cumulative(bounds, cumulative, 0.99),
        "buckets": [[le, c] for le, c in zip(les, cumulative)],
    }


def percentile_from_cumulative(bounds: Sequence[float],
                               cumulative: Sequence[int],
                               p: float) -> Optional[float]:
    """Estimate the p-quantile from cumulative bucket counts by linear
    interpolation inside the containing bucket (the standard Prometheus
    histogram_quantile estimate). None when the histogram is empty."""
    total = cumulative[-1]
    if total <= 0:
        return None
    target = p * total
    prev_cum = 0
    for i, cum in enumerate(cumulative):
        if cum >= target:
            lower = bounds[i - 1] if i > 0 else 0.0
            upper = bounds[i] if i < len(bounds) else bounds[-1]
            in_bucket = cum - prev_cum
            frac = (target - prev_cum) / in_bucket if in_bucket else 1.0
            return round(lower + (upper - lower) * frac, 9)
        prev_cum = cum
    return round(bounds[-1], 9)


def diff_phase(after: Dict[str, Any],
               before: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """phase_snapshot(after) - phase_snapshot(before): the histogram of
    exactly the window between two scrapes, with recomputed percentiles.
    ``before=None`` (or an incompatible bucket table) returns ``after``
    unchanged."""
    if not before or len(before.get("buckets", ())) != len(after["buckets"]):
        return dict(after)
    bounds = [b[0] for b in after["buckets"][:-1]]
    cum = [max(0, a[1] - b[1])
           for a, b in zip(after["buckets"], before["buckets"])]
    # Re-monotonize (counter reset / racy scrape can dent the diff).
    for i in range(1, len(cum)):
        cum[i] = max(cum[i], cum[i - 1])
    return _phase_dict(bounds, cum,
                       max(0.0, after["sum"] - before["sum"]),
                       les=[b[0] for b in after["buckets"]])


def merge_phases(snaps: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Element-wise merge of same-shaped phase snapshots (dp replicas
    into one fleet histogram)."""
    snaps = [s for s in snaps if s]
    if not snaps:
        return {}
    base = snaps[0]
    if len(snaps) == 1:
        return dict(base)
    bounds = [b[0] for b in base["buckets"][:-1]]
    cum = [0] * len(base["buckets"])
    total_sum = 0.0
    for s in snaps:
        if len(s["buckets"]) != len(cum):
            continue
        total_sum += s["sum"]
        for i, (_, c) in enumerate(s["buckets"]):
            cum[i] += c
    return _phase_dict(bounds, cum, total_sum,
                       les=[b[0] for b in base["buckets"]])


# ---------------------------------------------------------------------------
# Registry + Prometheus text exposition
# ---------------------------------------------------------------------------

class Registry:
    """Ordered collection of metrics. Re-adding the same (name, labels)
    replaces the old metric, so restartable components (test servers
    cycling schedulers) never accumulate stale duplicates."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Any] = {}

    def add(self, metric):
        key = (metric.name, tuple(sorted(metric.labels.items())))
        self._metrics[key] = metric
        return metric

    def counter(self, name: str, help: str = "", fn=None,
                **labels: str) -> Counter:
        key = (name, tuple(sorted(labels.items())))
        m = self._metrics.get(key)
        if m is None:
            m = self.add(Counter(name, help, labels=labels, fn=fn))
        elif fn is not None:
            # Component restart (e.g. a new scheduler re-binding over the
            # same engine): the fresh closure must replace the dead
            # component's, or the read-through metric freezes at the old
            # values and pins the dead object in memory.
            m.fn = fn
        return m

    def gauge(self, name: str, help: str = "", fn=None,
              **labels: str) -> Gauge:
        key = (name, tuple(sorted(labels.items())))
        m = self._metrics.get(key)
        if m is None:
            m = self.add(Gauge(name, help, labels=labels, fn=fn))
        elif fn is not None:
            m.fn = fn                      # re-bind on component restart
        return m

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = SECONDS_BUCKETS,
                  **labels: str) -> Histogram:
        key = (name, tuple(sorted(labels.items())))
        m = self._metrics.get(key)
        if m is None:
            m = self.add(Histogram(name, help, buckets=buckets,
                                   labels=labels))
        return m

    def collect(self) -> List[Any]:
        # Snapshot: the engine thread may register a new labeled counter
        # while a scrape iterates.
        return list(self._metrics.values())


def escape_label_value(v: str) -> str:
    """Prometheus text-format label value escaping: backslash, double
    quote and newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v != v:                                   # NaN
        return "NaN"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(v)


def _fmt_labels(labels: Mapping[str, str],
                extra: Optional[Mapping[str, str]] = None) -> str:
    merged = dict(extra or {})
    merged.update(labels)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in merged.items())
    return "{" + inner + "}"


# Self-metrics (README "Performance attribution"): the telemetry path
# observes its own exposition cost, so observability overhead is itself
# observable. One module-level registry per process; rendered as an
# extra unlabeled group on every scrape (the render that is being timed
# exposes the PREVIOUS renders' histogram — exact-once semantics are
# not worth a second pass).
_SELF_REGISTRY = Registry()
_RENDER_SECONDS = _SELF_REGISTRY.histogram(
    "tpu_inf_metrics_render_seconds",
    "Host wall of one Prometheus text exposition render")


def render_prometheus(groups: Iterable[Tuple[Mapping[str, str], Registry]]
                      ) -> str:
    """Render label-tagged registries as one Prometheus text page.

    ``groups``: (shared labels, registry) pairs — e.g. one per dp
    replica with ``{"replica": "0"}`` plus an unlabeled fleet registry.
    HELP/TYPE are emitted once per metric name (first definition wins);
    all samples of a name stay contiguous, as the format requires.
    """
    t_render = time.perf_counter()
    groups = list(groups)
    if telemetry_enabled():
        groups.append(({}, _SELF_REGISTRY))
    # name -> (kind, help, [(merged labels, metric)])
    families: Dict[str, Tuple[str, str, List[Tuple[Dict[str, str], Any]]]] = {}
    order: List[str] = []
    for shared, registry in groups:
        for m in registry.collect():
            fam = families.get(m.name)
            if fam is None:
                families[m.name] = fam = (m.kind, m.help, [])
                order.append(m.name)
            fam[2].append((dict(shared), m))
    lines: List[str] = []
    for name in order:
        kind, help_, samples = families[name]
        lines.append(f"# HELP {name} {escape_help(help_)}")
        lines.append(f"# TYPE {name} {kind}")
        for shared, m in samples:
            if kind == "histogram":
                cum = m.cumulative()
                for le, c in zip(m.bounds, cum):
                    ll = _fmt_labels({**m.labels, "le": _fmt_value(le)},
                                     shared)
                    lines.append(f"{name}_bucket{ll} {c}")
                ll = _fmt_labels({**m.labels, "le": "+Inf"}, shared)
                lines.append(f"{name}_bucket{ll} {cum[-1]}")
                ls = _fmt_labels(m.labels, shared)
                lines.append(f"{name}_sum{ls} {_fmt_value(m.sum)}")
                lines.append(f"{name}_count{ls} {cum[-1]}")
            else:
                ls = _fmt_labels(m.labels, shared)
                lines.append(f"{name}{ls} {_fmt_value(m.collect_value())}")
    out = "\n".join(lines) + "\n"
    _RENDER_SECONDS.observe(time.perf_counter() - t_render)
    return out


# Content type the text page must be served under (version matters:
# parsers key on it).
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# ---------------------------------------------------------------------------
# Registry transport (subprocess fleet, README "Process fleet"): an
# engine-worker process dumps its registry as JSON-able samples over the
# RPC channel; the router rebuilds concrete metrics from the dump and
# renders them under the worker's stable replica="i" label. Counter and
# histogram series from dead worker incarnations fold into a per-replica
# CARRY so a restart never resets the fleet-level scrape (Prometheus
# counters must be monotone per series or rate() misreads the reset).
# ---------------------------------------------------------------------------


def dump_registry(registry: Registry) -> List[Dict[str, Any]]:
    """Serialize a registry's current samples (read-through metrics are
    evaluated here, so the dump is self-contained)."""
    out: List[Dict[str, Any]] = []
    for m in registry.collect():
        rec: Dict[str, Any] = {"name": m.name, "kind": m.kind,
                               "help": m.help, "labels": dict(m.labels)}
        if m.kind == "histogram":
            rec["bounds"] = list(m.bounds)
            rec["counts"] = list(m._counts)
            rec["sum"] = m.sum
        else:
            rec["value"] = m.collect_value()
        out.append(rec)
    return out


def registry_from_dump(samples: Sequence[Dict[str, Any]]) -> Registry:
    """Rebuild a renderable Registry from :func:`dump_registry` output."""
    r = Registry()
    for rec in samples:
        labels = rec.get("labels") or {}
        if rec["kind"] == "histogram":
            h = Histogram(rec["name"], rec.get("help", ""),
                          buckets=rec.get("bounds") or SECONDS_BUCKETS,
                          labels=labels)
            counts = list(rec.get("counts") or [])
            if len(counts) == len(h._counts):
                h._counts = counts
            h.sum = rec.get("sum", 0.0)
            r.add(h)
        else:
            cls = Gauge if rec["kind"] == "gauge" else Counter
            m = cls(rec["name"], rec.get("help", ""), labels=labels)
            m.value = rec.get("value", 0)
            r.add(m)
    return r


def _dump_key(rec: Dict[str, Any]) -> Tuple:
    return (rec["name"], tuple(sorted((rec.get("labels") or {}).items())))


def fold_dump_into_carry(carry: Dict[Tuple, Dict[str, Any]],
                         dump: Sequence[Dict[str, Any]]) -> None:
    """Accumulate a dead worker incarnation's MONOTONIC series (counters
    + histograms; gauges are point-in-time and die with the process)
    into ``carry``, in place."""
    import copy
    for rec in dump or ():
        if rec["kind"] == "gauge":
            continue
        key = _dump_key(rec)
        base = carry.get(key)
        if base is None:
            carry[key] = copy.deepcopy(rec)
        elif rec["kind"] == "counter":
            base["value"] = base.get("value", 0) + rec.get("value", 0)
        elif (rec["kind"] == "histogram"
              and base.get("bounds") == rec.get("bounds")):
            base["counts"] = [a + b for a, b in zip(base["counts"],
                                                    rec["counts"])]
            base["sum"] = base.get("sum", 0.0) + rec.get("sum", 0.0)


def apply_carry(carry: Dict[Tuple, Dict[str, Any]],
                dump: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Live dump + carried prior-incarnation totals, non-destructively.
    Carried series the fresh incarnation hasn't re-minted yet (lazy
    labeled children like requests_finished{reason=...}) still render,
    so a restart can never make a series vanish from the scrape."""
    import copy
    if not carry:
        return list(dump or ())
    out: List[Dict[str, Any]] = []
    seen = set()
    for rec in dump or ():
        key = _dump_key(rec)
        seen.add(key)
        base = carry.get(key)
        if base is None or rec["kind"] == "gauge":
            out.append(rec)
            continue
        rec = copy.deepcopy(rec)
        if rec["kind"] == "counter":
            rec["value"] = rec.get("value", 0) + base.get("value", 0)
        elif (rec["kind"] == "histogram"
              and base.get("bounds") == rec.get("bounds")):
            rec["counts"] = [a + b for a, b in zip(rec["counts"],
                                                   base["counts"])]
            rec["sum"] = rec.get("sum", 0.0) + base.get("sum", 0.0)
        out.append(rec)
    for key, rec in carry.items():
        if key not in seen:
            out.append(rec)
    return out


def telemetry_enabled() -> bool:
    return os.environ.get("TPU_INF_TELEMETRY", "1") != "0"


# ---------------------------------------------------------------------------
# Distributed request tracing (README "Observability": span schema).
#
# A span is one JSON-able dict describing a timed phase of one request:
#
#     {"name", "trace": trace_id, "parent": parent span NAME ("" = the
#      root "request" span), "ts": unix seconds, "dur": seconds,
#      "replica": emitting replica (-1 = the router), "attrs": {...}}
#
# Timestamps are monotonic-anchored-to-wallclock: instrumented code
# passes ``time.perf_counter()`` readings (the clock every existing
# request timestamp already uses) and the recorder converts them to
# unix seconds via a (time.time(), perf_counter()) anchor taken at
# construction — so spans exported by DIFFERENT processes (router,
# prefill worker, decode worker) land on one comparable timeline.
# Parent linkage is by span NAME within a trace (the span set is a
# small fixed vocabulary, and names are unique per trace per replica
# except prefill_chunk, whose parent "prefill" is unambiguous), which
# keeps cross-process assembly free of id coordination.
# ---------------------------------------------------------------------------


class SpanRecorder:
    """Bounded per-process span sink (one per engine replica, plus one
    in the router). Completed request traces move to a recent ring at
    ``seal()``; spans for requests the process cannot attribute (cache-
    eviction swap-outs) land in a maintenance ring instead. Thread
    stance: a lock guards the dicts (spans are recorded at request
    granularity, not the dispatch hot path), and all export methods
    return copies. Disabled (``TPU_INF_TELEMETRY=0``) every method is a
    cheap no-op, so spans ride the same kill switch as the metrics."""

    MAX_TRACES = 256
    MAX_SPANS_PER_TRACE = 96

    def __init__(self, enabled: Optional[bool] = None, replica: int = -1):
        self.enabled = (telemetry_enabled() if enabled is None else enabled)
        self.replica = replica
        self._anchor_unix = time.time()
        self._anchor_mono = time.perf_counter()
        self._open: "collections.OrderedDict[str, List[dict]]" = \
            collections.OrderedDict()
        self._recent: "collections.OrderedDict[str, List[dict]]" = \
            collections.OrderedDict()
        self._maintenance: collections.deque = collections.deque(maxlen=128)
        self._lock = threading.Lock()
        self.spans_dropped = 0
        self.traces_evicted = 0

    def to_unix(self, t_mono: float) -> float:
        return self._anchor_unix + (t_mono - self._anchor_mono)

    def _span(self, name: str, trace_id: str, t0: float, t1: float,
              parent: str, attrs: Dict[str, Any]) -> dict:
        span = {"name": name, "trace": trace_id, "parent": parent,
                "ts": round(self.to_unix(t0), 6),
                "dur": round(max(0.0, t1 - t0), 6),
                "replica": self.replica}
        if attrs:
            span["attrs"] = attrs
        return span

    def add(self, name: str, trace_id: str, t0: float, t1: float,
            parent: str = "request", **attrs: Any) -> None:
        """Record one completed span (perf_counter start/end) under a
        trace. Per-trace span counts and the number of open traces are
        both capped so an unsealed trace (engine-direct callers that
        bypass the scheduler) can never grow without bound."""
        if not self.enabled or not trace_id:
            return
        span = self._span(name, trace_id, t0, t1, parent, attrs)
        with self._lock:
            spans = self._open.get(trace_id)
            if spans is None:
                while len(self._open) >= self.MAX_TRACES:
                    self._open.popitem(last=False)
                    self.traces_evicted += 1
                spans = self._open[trace_id] = []
            if len(spans) >= self.MAX_SPANS_PER_TRACE:
                self.spans_dropped += 1
                return
            spans.append(span)

    def add_maintenance(self, name: str, t0: float, t1: float,
                        **attrs: Any) -> None:
        """Record a span no single request owns (e.g. a cache-eviction
        swap-out batch): shows up in the Chrome timeline under a
        per-replica maintenance lane, never in request trees."""
        if not self.enabled:
            return
        self._maintenance.append(self._span(name, "-maintenance-",
                                            t0, t1, "", attrs))

    def ingest(self, trace_id: str, spans: Sequence[dict]) -> None:
        """Fold spans exported by ANOTHER process (worker event frames)
        into this recorder's open table — they carry their source's
        replica tag and absolute unix timestamps already."""
        if not self.enabled or not trace_id or not spans:
            return
        with self._lock:
            dest = self._open.get(trace_id)
            if dest is None:
                # A finish frame's spans can arrive after the router
                # already sealed the trace (FIFO per connection, but
                # handoff traces span two connections): append there.
                dest = self._recent.get(trace_id)
            if dest is None:
                while len(self._open) >= self.MAX_TRACES:
                    self._open.popitem(last=False)
                    self.traces_evicted += 1
                dest = self._open[trace_id] = []
            room = self.MAX_SPANS_PER_TRACE - len(dest)
            if room < len(spans):
                self.spans_dropped += len(spans) - max(0, room)
            dest.extend(list(spans)[:max(0, room)])

    def seal(self, trace_id: str) -> None:
        """The request finished: move its spans to the recent ring (the
        /debug/trace + Chrome-export source)."""
        if not self.enabled or not trace_id:
            return
        with self._lock:
            spans = self._open.pop(trace_id, None)
            if spans is None:
                return
            prior = self._recent.pop(trace_id, None)
            if prior:
                spans = prior + spans
            while len(self._recent) >= self.MAX_TRACES:
                self._recent.popitem(last=False)
                self.traces_evicted += 1
            self._recent[trace_id] = spans

    def get_trace(self, trace_id: str) -> Optional[List[dict]]:
        with self._lock:
            spans = self._recent.get(trace_id) or self._open.get(trace_id)
            return list(spans) if spans else None

    def export_recent(self, trace_id: str) -> List[dict]:
        """Copy a sealed trace's spans (kept in the ring for the pull
        verb) — the worker's finish-event payload."""
        with self._lock:
            return list(self._recent.get(trace_id) or ())

    def export_open(self, trace_id: str) -> List[dict]:
        """Copy an UNFINISHED trace's spans so far (drain-time migrate
        events ship these: the request continues elsewhere)."""
        with self._lock:
            return list(self._open.get(trace_id) or ())

    def recent_traces(self, n: int = 64) -> Dict[str, List[dict]]:
        """The last ``n`` sealed traces, oldest first (n <= 0 returns
        none — the maintenance-only pull uses n=0)."""
        if n <= 0:
            return {}
        with self._lock:
            ids = list(self._recent)[-n:]
            return {tid: list(self._recent[tid]) for tid in ids}

    def maintenance_spans(self, n: int = 128) -> List[dict]:
        return list(self._maintenance)[-n:]


# The full span-name vocabulary any recorder in the repo can emit.
# tests/test_metric_catalog.py gates this against both the code's
# add()/add_maintenance() literals and the README span table, so a new
# span cannot ship undocumented (and a doc row cannot outlive its span).
SPAN_NAMES = (
    "request", "route", "queue_wait", "prefill", "prefill_chunk",
    "decode", "handoff", "handoff_adopt", "handoff_export",
    "drain_export", "migrate",
    "kv_swap_in", "kv_swap_out", "rollout", "scale_up", "scale_down",
)


def register_span_ring(registry: Registry, recorder: SpanRecorder) -> None:
    """Span-ring self-metrics (README "Performance attribution"):
    occupancy gauges + drop/eviction counters over one SpanRecorder, so
    trace loss under ring pressure is visible on /metrics instead of
    silently truncating /debug/trace. Shared by the engine bundle (its
    replica recorder) and both fleet backends (the router recorder)."""
    registry.gauge("tpu_inf_trace_ring_traces",
                   "Sealed request traces resident in the recent ring",
                   fn=lambda: float(len(recorder._recent)))
    registry.gauge("tpu_inf_trace_ring_open",
                   "Unsealed (in-flight or abandoned) traces in the "
                   "open table",
                   fn=lambda: float(len(recorder._open)))
    registry.counter("tpu_inf_trace_spans_dropped_total",
                     "Spans dropped by the per-trace span cap",
                     fn=lambda: recorder.spans_dropped)
    registry.counter("tpu_inf_trace_evictions_total",
                     "Whole traces evicted from the rings by the "
                     "trace-count cap",
                     fn=lambda: recorder.traces_evicted)


def assemble_trace(trace_id: str, spans: Sequence[dict]) -> dict:
    """One request's cross-process span TREE: spans sorted by start
    time, children nested under their parent by NAME (first match in
    the same replica wins, then any replica; orphans attach to the
    root). The root is the router's ``request`` span when present,
    else a synthetic envelope covering every span."""
    spans = sorted(spans, key=lambda s: (s.get("ts", 0.0),
                                         -s.get("dur", 0.0)))
    nodes = [{**s, "children": []} for s in spans]
    root = next((n for n in nodes if n["name"] == "request"), None)
    if root is None:
        t0 = min((n["ts"] for n in nodes), default=0.0)
        t1 = max((n["ts"] + n["dur"] for n in nodes), default=0.0)
        root = {"name": "request", "trace": trace_id, "parent": "",
                "ts": round(t0, 6), "dur": round(t1 - t0, 6),
                "replica": -1, "children": [], "synthetic": True}
    by_name: Dict[Tuple[str, int], dict] = {}
    for n in nodes:
        by_name.setdefault((n["name"], n.get("replica", -1)), n)
        by_name.setdefault((n["name"], None), n)
    for n in nodes:
        if n is root:
            continue
        parent = n.get("parent") or "request"
        if parent == n["name"]:
            parent = "request"
        target = (by_name.get((parent, n.get("replica", -1)))
                  or by_name.get((parent, None)))
        if target is None or target is n:
            target = root
        target["children"].append(n)
    return {"trace_id": trace_id, "n_spans": len(spans),
            "replicas": sorted({s.get("replica", -1) for s in spans}),
            "spans": spans, "tree": root}


def spans_to_chrome(traces: Mapping[str, Sequence[dict]],
                    pid_names: Optional[Mapping[int, str]] = None,
                    maintenance: Optional[Sequence[dict]] = None,
                    other_data: Optional[dict] = None) -> dict:
    """Render span traces as Chrome trace-event JSON (the "JSON Array
    Format" with complete ``ph:"X"`` events) loadable in Perfetto /
    chrome://tracing: one pid per replica (router = pid 0, replica i =
    pid i+1), one tid per trace, absolute-unix microsecond timestamps
    so spans from different processes interleave correctly."""
    events: List[dict] = []
    seen_pids: Dict[int, str] = {}
    seen_tids: set = set()
    pid_names = dict(pid_names or {})

    def _pid(replica: int) -> int:
        pid = replica + 1 if replica >= 0 else 0
        if pid not in seen_pids:
            seen_pids[pid] = pid_names.get(
                pid, "router" if pid == 0 else f"replica {pid - 1}")
        return pid

    for tidx, (trace_id, spans) in enumerate(traces.items(), start=1):
        for s in spans:
            pid = _pid(int(s.get("replica", -1)))
            if (pid, tidx) not in seen_tids:
                seen_tids.add((pid, tidx))
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": tidx,
                               "args": {"name": f"trace {trace_id}"}})
            events.append({
                "name": s["name"], "cat": "request", "ph": "X",
                "ts": round(s["ts"] * 1e6, 1),
                "dur": round(max(s["dur"], 1e-6) * 1e6, 1),
                "pid": pid, "tid": tidx,
                "args": {**(s.get("attrs") or {}),
                         "trace_id": trace_id,
                         "parent": s.get("parent", "")},
            })
    for s in maintenance or ():
        pid = _pid(int(s.get("replica", -1)))
        events.append({
            "name": s["name"], "cat": "maintenance", "ph": "X",
            "ts": round(s["ts"] * 1e6, 1),
            "dur": round(max(s["dur"], 1e-6) * 1e6, 1),
            "pid": pid, "tid": 0,
            "args": dict(s.get("attrs") or {}),
        })
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": name}}
            for pid, name in sorted(seen_pids.items())]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms",
            "otherData": dict(other_data or {})}


# ---------------------------------------------------------------------------
# Rolling SLO gauges (README "Observability": SLO gauges). A fixed-size
# ring of the most recent request latencies yields EXACT windowed
# quantiles (unlike the log-bucketed histograms, whose interpolation
# error can exceed an SLO margin) — the input signal the autoscaler
# (ROADMAP item 3) consumes. Ring writes are GIL-atomic list stores
# (the scheduler's decode_call_s stance); quantile reads sort a copy.
# ---------------------------------------------------------------------------

SLO_WINDOW = 512
SLO_QUANTILES = (0.5, 0.95)


class RollingWindow:
    """Ring of the last ``size`` observations with exact quantiles."""

    __slots__ = ("_ring", "_n")

    def __init__(self, size: int = SLO_WINDOW):
        self._ring = [0.0] * size
        self._n = 0

    def observe(self, v: float) -> None:
        self._ring[self._n % len(self._ring)] = v
        self._n += 1

    @property
    def count(self) -> int:
        return self._n

    def values(self) -> List[float]:
        return self._ring[:min(self._n, len(self._ring))]

    def quantile(self, q: float) -> Optional[float]:
        # Delegates so the per-replica and fleet-pooled gauges can
        # never drift onto different estimators.
        return pooled_quantile([self.values()], q)


def pooled_quantile(windows: Sequence[Sequence[float]],
                    q: float) -> Optional[float]:
    """Exact quantile over several replicas' pooled ring contents (the
    fleet view — per-replica quantiles do not compose by max/mean)."""
    xs = sorted(v for w in windows for v in (w or ()))
    if not xs:
        return None
    return xs[min(len(xs) - 1, int(q * len(xs)))]


class SLOTracker:
    """Windowed TTFT/TPOT quantiles + breach counting against the
    ``--slo-ttft-ms`` / ``--slo-tpot-ms`` targets (0 = no target: the
    quantile gauges still export, breaches never count)."""

    def __init__(self, ttft_target_s: float = 0.0,
                 tpot_target_s: float = 0.0):
        self.ttft_target_s = max(0.0, ttft_target_s)
        self.tpot_target_s = max(0.0, tpot_target_s)
        self.ttft = RollingWindow()
        self.tpot = RollingWindow()
        self.ttft_breaches = 0
        self.tpot_breaches = 0

    def observe(self, ttft_s: Optional[float],
                tpot_s: Optional[float]) -> None:
        if ttft_s is not None:
            self.ttft.observe(ttft_s)
            if self.ttft_target_s > 0 and ttft_s > self.ttft_target_s:
                self.ttft_breaches += 1
        if tpot_s is not None:
            self.tpot.observe(tpot_s)
            if self.tpot_target_s > 0 and tpot_s > self.tpot_target_s:
                self.tpot_breaches += 1

    def gauge_value(self, which: str, q: float) -> float:
        """Read-through value for the Prometheus gauges (NaN = empty
        window, the Prometheus idiom for 'no data')."""
        ring = self.ttft if which == "ttft" else self.tpot
        v = ring.quantile(q)
        return float("nan") if v is None else v

    def snapshot(self, include_window: bool = True) -> dict:
        def _r(v):
            return None if v is None else round(v, 6)

        out = {
            "ttft_target_s": self.ttft_target_s or None,
            "tpot_target_s": self.tpot_target_s or None,
            "ttft_p50_s": _r(self.ttft.quantile(0.5)),
            "ttft_p95_s": _r(self.ttft.quantile(0.95)),
            "tpot_p50_s": _r(self.tpot.quantile(0.5)),
            "tpot_p95_s": _r(self.tpot.quantile(0.95)),
            "ttft_breaches": self.ttft_breaches,
            "tpot_breaches": self.tpot_breaches,
            "window_requests": min(self.ttft.count, SLO_WINDOW),
        }
        if include_window:
            # Raw ring contents so fleet aggregation can pool EXACT
            # quantiles across replicas (max/mean of p95s is not a p95).
            out["ttft_window"] = [round(v, 6) for v in self.ttft.values()]
            out["tpot_window"] = [round(v, 6) for v in self.tpot.values()]
        return out


def pooled_slo(slos: Sequence[Optional[dict]]) -> dict:
    """Fleet-level SLO view from per-replica snapshots (with windows):
    pooled exact quantiles + summed breach counts."""
    slos = [s for s in slos if s]

    def _r(v):
        return None if v is None else round(v, 6)

    ttft = [s.get("ttft_window") or [] for s in slos]
    tpot = [s.get("tpot_window") or [] for s in slos]
    return {
        "ttft_target_s": next((s.get("ttft_target_s") for s in slos
                               if s.get("ttft_target_s")), None),
        "tpot_target_s": next((s.get("tpot_target_s") for s in slos
                               if s.get("tpot_target_s")), None),
        "ttft_p50_s": _r(pooled_quantile(ttft, 0.5)),
        "ttft_p95_s": _r(pooled_quantile(ttft, 0.95)),
        "tpot_p50_s": _r(pooled_quantile(tpot, 0.5)),
        "tpot_p95_s": _r(pooled_quantile(tpot, 0.95)),
        "ttft_breaches": sum(s.get("ttft_breaches", 0) for s in slos),
        "tpot_breaches": sum(s.get("tpot_breaches", 0) for s in slos),
        "window_requests": sum(s.get("window_requests", 0) for s in slos),
    }


def register_fleet_slo(registry: Registry,
                       quantile_fn: Callable[[str, float], float],
                       breaches_fn: Callable[[str], float]) -> None:
    """THE fleet-level SLO series registration, shared by both fleet
    backends (EngineGroup pools live trackers, ProcessEngineGroup pools
    cached worker windows + the restart carry) so their /metrics
    surfaces cannot drift. ``quantile_fn(kind, q)`` returns the pooled
    exact quantile (NaN = no data); ``breaches_fn(kind)`` the monotone
    fleet breach total."""
    for q in SLO_QUANTILES:
        registry.gauge("tpu_inf_slo_ttft_seconds",
                       "Fleet rolling exact TTFT quantile (pooled "
                       "across replica windows; NaN = no data)",
                       fn=lambda q=q: quantile_fn("ttft", q),
                       q=f"{q:g}")
        registry.gauge("tpu_inf_slo_tpot_seconds",
                       "Fleet rolling exact TPOT quantile (pooled "
                       "across replica windows; NaN = no data)",
                       fn=lambda q=q: quantile_fn("tpot", q),
                       q=f"{q:g}")
    for kind in ("ttft", "tpot"):
        registry.counter("tpu_inf_slo_breaches_total",
                         "Fleet SLO target breaches (monotone across "
                         "worker restarts)",
                         fn=lambda k=kind: breaches_fn(k), slo=kind)


def register_fleet_elastic(registry: Registry,
                           scale_ups: Callable[[], int],
                           scale_downs: Callable[[], int],
                           rollouts: Callable[[], int],
                           class_preempted: Callable[[str], int],
                           class_deferred: Callable[[str], int],
                           class_shed: Callable[[str], int]) -> None:
    """Elastic-fleet series (README "Elastic fleet"): autoscaler and
    rollout actuations, plus the per-class admission outcomes. All
    router-side state, so the series survive worker restarts without a
    carry. Interactive requests never defer or preempt (they are the
    preemptORs), so those two series only exist for the lower classes."""
    from tpu_inference.config import PRIORITY_CLASSES

    registry.counter("tpu_inf_fleet_scale_ups_total",
                     "Autoscaler scale-up actuations (worker spawned on "
                     "a sustained pooled-SLO breach)", fn=scale_ups)
    registry.counter("tpu_inf_fleet_scale_downs_total",
                     "Autoscaler scale-down actuations (coldest replica "
                     "drain-and-migrated away on a sustained lull)",
                     fn=scale_downs)
    registry.counter("tpu_inf_fleet_rollouts_total",
                     "Completed rolling-upgrade passes (POST "
                     "/debug/rollout)", fn=rollouts)
    for cls in PRIORITY_CLASSES:
        registry.counter("tpu_inf_class_shed_total",
                         "Requests shed with 429 after every class "
                         "escape (defer/preempt) failed",
                         fn=lambda c=cls: class_shed(c), **{"class": cls})
        if cls == PRIORITY_CLASSES[0]:
            continue
        registry.counter("tpu_inf_class_preempted_total",
                         "Running requests of this class preempted back "
                         "to their lane by an interactive arrival",
                         fn=lambda c=cls: class_preempted(c),
                         **{"class": cls})
        registry.gauge("tpu_inf_class_deferred",
                       "Requests currently parked in this class's "
                       "deferred admission lane",
                       fn=lambda c=cls: float(class_deferred(c)),
                       **{"class": cls})


def register_fabric(registry: Registry, pool) -> None:
    """THE fleet KV-fabric series registration (README "KV fabric"),
    shared by both fleet backends so their /metrics surfaces cannot
    drift. ``pool`` is a server.kv_fabric.FabricPool; every series is
    an fn= read-through over its GIL-atomic counters — router-side
    state, so the series survive worker restarts without a carry."""
    registry.counter("tpu_inf_fabric_hits_total",
                     "Fabric pool pages served to a replica's host tier "
                     "(crc-verified before adoption)",
                     fn=lambda: pool.hits)
    registry.counter("tpu_inf_fabric_misses_total",
                     "Fabric lookups that ended short of the requested "
                     "chain (absent or corrupt entry)",
                     fn=lambda: pool.misses)
    registry.counter("tpu_inf_fabric_puts_total",
                     "Pages published into the fabric pool (supersedes "
                     "included)", fn=lambda: pool.puts)
    registry.counter("tpu_inf_fabric_evictions_total",
                     "Fabric pool LRU capacity evictions",
                     fn=lambda: pool.evictions)
    registry.gauge("tpu_inf_fabric_pages_used",
                   "Serialized KV pages resident in the fabric pool",
                   fn=lambda: float(pool.used))
    registry.gauge("tpu_inf_fabric_bytes_used",
                   "Bytes of serialized KV resident in the fabric pool",
                   fn=lambda: float(pool.bytes_used))


def capture_jax_profile(profile_dir: str, replica: int,
                        seconds: float) -> Dict[str, Any]:
    """THE jax.profiler capture body behind POST /debug/profile, shared
    by the worker's profile RPC verb and the in-process group: clamp,
    trace into a per-replica dir under the OPERATOR's profile_dir
    (never a client-chosen path), return where it landed. Serving
    continues while the profiler runs — that is the point."""
    import jax

    seconds = min(max(0.1, float(seconds)), 60.0)
    trace_dir = os.path.join(profile_dir, f"replica{int(replica)}")
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    try:
        time.sleep(seconds)
    finally:
        jax.profiler.stop_trace()
    return {"dir": trace_dir, "seconds": seconds,
            "replica": int(replica)}


def emit_build_info(registry: Registry, *, backend: str = "",
                    fleet: str = "", kv_quant: str = "",
                    spec_mode: str = "", routing: str = "") -> None:
    """The ``tpu_inf_build_info`` info-gauge (constant 1; the labels
    are the payload) every registry emits so dashboards can join series
    across replicas and restarts. Label VALUES are pure config — a
    worker restart re-mints the identical series, so the restart carry
    never sees a label change."""
    from tpu_inference import __version__
    registry.gauge(
        "tpu_inf_build_info",
        "Build/config info gauge (constant 1; the labels carry the "
        "version and serving configuration for dashboard joins)",
        fn=lambda: 1.0,
        version=__version__, backend=backend or "unknown",
        fleet=fleet or "none", kv_quant=kv_quant or "none",
        spec_mode=spec_mode or "off", routing=routing or "none")


# ---------------------------------------------------------------------------
# Step ledger + roofline attribution (README "Performance attribution").
#
# The phase histograms say how LONG dispatches take; the step ledger
# says WHY. Every engine dispatch pushes one fixed-shape record into an
# allocation-light ring; an analytic cost model (FLOPs from the
# architecture config, HBM bytes from weight bytes per device iteration
# + KV pages touched at the active kv_quant) converts each record into
# achieved FLOP/s and bytes/s, and windowed aggregation yields one
# bottleneck verdict per step kind: compute-bound, HBM-bound, or
# host-bound (staging + bubble dominate the dispatch wall).
# ---------------------------------------------------------------------------

STEP_KINDS = ("prefill_chunk", "decode", "hybrid", "spec_verify")

# Record layout (one tuple per dispatch; field order is the wire shape
# the flight recorder and /debug/steps serialize):
STEP_FIELDS = (
    "ts",             # unix seconds the record was pushed (≈ sync time)
    "kind",           # one of STEP_KINDS
    "rung",           # compiled batch-ladder rung dispatched (0=prefill)
    "slots",          # decode lanes occupied in the dispatch
    "tokens",         # tokens GENERATED (the MFU gauge's unit)
    "chunk_tokens",   # prompt tokens processed (prefill/hybrid chunk)
    "steps",          # device loop iterations (fused-K; weights stream
                      # from HBM once per iteration)
    "device_s",       # device wall (dispatch + sync for pipelined calls)
    "staging_s",      # host batch-staging wall (_stage_batch micro)
    "bubble_s",       # host gap before the dispatch (device-idle
                      # exposure while lanes were active)
    "kv_read_tokens",  # Σ (query position, context token) pairs attended
    "kv_swap_bytes",  # host<->device KV tier traffic since last record
    "spec_accepted",  # speculative positions accepted (spec_verify)
    "compile_event",  # 1 = first dispatch of this rung/bucket (compile)
)


class StepLedger:
    """Fixed-depth ring of per-dispatch step records.

    ``push`` is the hot-path write: one tuple build + one list store +
    one int add (GIL-atomic, same stance as the metric primitives); no
    locks, no allocation growth. Readers copy the ring first, so a
    concurrent push can at worst duplicate-or-miss the newest record,
    never tear one."""

    __slots__ = ("depth", "_ring", "_n")

    def __init__(self, depth: int = 256):
        self.depth = max(8, int(depth))
        self._ring: List[Optional[tuple]] = [None] * self.depth
        self._n = 0

    def push(self, kind: str, rung: int, slots: int, tokens: int,
             chunk_tokens: int, steps: int, device_s: float,
             staging_s: float, bubble_s: float, kv_read_tokens: int,
             kv_swap_bytes: float, spec_accepted: int,
             compile_event: bool) -> None:
        self._ring[self._n % self.depth] = (
            time.time(), kind, int(rung), int(slots), int(tokens),
            int(chunk_tokens), int(steps), float(device_s),
            float(staging_s), float(bubble_s), int(kv_read_tokens),
            float(kv_swap_bytes), int(spec_accepted),
            1 if compile_event else 0)
        self._n += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def overflowed(self) -> bool:
        return self._n > self.depth

    def records(self) -> List[tuple]:
        """Resident records, oldest first (point-in-time ring copy)."""
        ring, n = list(self._ring), self._n
        if n <= self.depth:
            return [r for r in ring[:n] if r is not None]
        i = n % self.depth
        return [r for r in ring[i:] + ring[:i] if r is not None]

    def snapshot(self) -> List[Dict[str, Any]]:
        """JSON-able dump (flight-recorder payload)."""
        return [dict(zip(STEP_FIELDS, r)) for r in self.records()]


class _NullLedger:
    """No-op ledger when telemetry is disabled (shared singleton, the
    NULL_METRIC stance): push is one attribute lookup + empty call."""

    __slots__ = ()
    depth = 0
    count = 0
    overflowed = False

    def push(self, *a, **k) -> None:
        pass

    def records(self) -> List[tuple]:
        return []

    def snapshot(self) -> List[Dict[str, Any]]:
        return []


NULL_LEDGER = _NullLedger()


class StepCostModel:
    """Analytic per-record FLOPs + HBM bytes from the architecture
    config — no device counters needed, so the same model grades CPU
    smoke runs and real-TPU campaigns.

    - matmul FLOPs: 2 x params per token position processed (generated
      tokens + prompt chunk tokens).
    - attention FLOPs: 4 x n_heads x head_dim per layer per (query
      position, context token) pair (QK^T + AV, 2 multiply-adds each).
    - HBM bytes: resident weight bytes once per device loop iteration
      (fused-K decode streams the weights K times) + KV bytes for every
      context token attended (at the active kv_quant's per-token
      footprint) + KV bytes written for new positions + host<->device
      swap traffic.
    """

    __slots__ = ("n_params", "n_layers", "n_heads", "head_dim",
                 "weight_bytes", "kv_token_bytes", "peak_flops",
                 "peak_hbm_bw")

    def __init__(self, *, n_params: int, n_layers: int, n_heads: int,
                 head_dim: int, weight_bytes: int, kv_token_bytes: int,
                 peak_flops: float, peak_hbm_bw: float):
        self.n_params = int(n_params)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.weight_bytes = int(weight_bytes)
        self.kv_token_bytes = int(kv_token_bytes)
        self.peak_flops = float(peak_flops)
        self.peak_hbm_bw = float(peak_hbm_bw)

    @classmethod
    def from_engine(cls, engine) -> "StepCostModel":
        from tpu_inference.engine import autosize
        mcfg, ecfg = engine.model_cfg, engine.engine_cfg
        return cls(n_params=engine.n_params, n_layers=mcfg.n_layers,
                   n_heads=mcfg.n_heads, head_dim=mcfg.head_dim,
                   weight_bytes=autosize.weight_bytes(mcfg, ecfg.quant),
                   kv_token_bytes=autosize.kv_bytes_per_token(
                       mcfg, ecfg.kv_quant),
                   peak_flops=autosize.detect_peak_flops(),
                   peak_hbm_bw=autosize.detect_peak_hbm_bw())

    def flops(self, rec: tuple) -> float:
        positions = rec[4] + rec[5]          # tokens + chunk_tokens
        return (2.0 * self.n_params * positions
                + 4.0 * self.n_layers * self.n_heads * self.head_dim
                * rec[10])                   # kv_read_tokens

    def hbm_bytes(self, rec: tuple) -> float:
        positions = rec[4] + rec[5]
        return (float(self.weight_bytes) * max(1, rec[6])   # steps
                + float(self.kv_token_bytes) * (rec[10] + positions)
                + rec[11])                   # kv_swap_bytes

    def as_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}


def _finalize_kind(agg: Dict[str, Any], peak_flops: float,
                   peak_hbm_bw: float) -> Dict[str, Any]:
    """Derive achieved rates, roofline fractions, and the bottleneck
    verdict from one kind's raw sums — shared by the per-replica report
    and the fleet merge so the two can never disagree on semantics."""
    device_s = agg["device_s"]
    host_s = agg["staging_s"] + agg["bubble_s"]
    out = dict(agg)
    out["host_s"] = round(host_s, 6)
    if device_s > 0:
        out["achieved_flops_per_s"] = round(agg["flops"] / device_s, 3)
        out["achieved_bytes_per_s"] = round(agg["hbm_bytes"] / device_s, 3)
    else:
        out["achieved_flops_per_s"] = 0.0
        out["achieved_bytes_per_s"] = 0.0
    compute_frac = out["achieved_flops_per_s"] / max(peak_flops, 1.0)
    hbm_frac = out["achieved_bytes_per_s"] / max(peak_hbm_bw, 1.0)
    host_frac = host_s / max(host_s + device_s, 1e-12)
    out["compute_frac"] = round(compute_frac, 6)
    out["hbm_frac"] = round(hbm_frac, 6)
    out["host_frac"] = round(host_frac, 6)
    if host_frac > 0.5:
        out["verdict"] = "host-bound"
    elif compute_frac >= hbm_frac:
        out["verdict"] = "compute-bound"
    else:
        out["verdict"] = "hbm-bound"
    for k in ("device_s", "staging_s", "bubble_s", "flops", "hbm_bytes",
              "kv_swap_bytes"):
        out[k] = round(out[k], 6)
    return out


def _ledger_mfu_ewma(recs: Sequence[tuple], n_params: int,
                     peak_flops: float, bind_unix: Optional[float],
                     now: float, tau_s: float = 30.0) -> Optional[float]:
    """Replay the MFU gauge's dt-weighted EWMA (telemetry bind_scheduler:
    alpha = 1 - exp(-dt/tau), tau ≈ 30 s) over the ledger's (ts, tokens)
    events, from the gauge's bind time — the apples-to-apples value the
    /debug/steps cross-check compares against ``tpu_inf_mfu_estimate``.
    A plain window-average would NOT agree with the gauge over short
    windows; the EWMA replay does, up to ring truncation (flagged by the
    caller via ``truncated``)."""
    import math

    if not recs:
        return None
    rate = 0.0
    t = bind_unix if bind_unix is not None else recs[0][0]
    for r in recs:
        ts, tokens = r[0], r[4]
        dt = max(1e-6, ts - t)
        inst = tokens / dt
        rate += (1.0 - math.exp(-dt / tau_s)) * (inst - rate)
        t = ts
    dt = now - t
    if dt > 1e-3:
        rate *= math.exp(-dt / tau_s)   # zero-rate tail, gauge-identical
    return rate * 2.0 * n_params / max(peak_flops, 1.0)


def roofline_report(ledger, model: StepCostModel, *,
                    mfu_gauge: Optional[float] = None,
                    bind_unix: Optional[float] = None,
                    window_s: float = 60.0,
                    now: Optional[float] = None) -> Dict[str, Any]:
    """One replica's step-attribution report: per-kind roofline sums +
    bottleneck verdicts over the trailing window, per-rung occupancy,
    the top time sinks, and the ledger-replayed MFU cross-check."""
    now = time.time() if now is None else now
    recs = ledger.records()
    cutoff = now - window_s
    window = [r for r in recs if r[0] >= cutoff]
    kinds: Dict[str, Dict[str, Any]] = {}
    rungs: Dict[str, Dict[str, float]] = {}
    for r in window:
        agg = kinds.get(r[1])
        if agg is None:
            agg = kinds[r[1]] = {
                "records": 0, "tokens": 0, "chunk_tokens": 0,
                "device_s": 0.0, "staging_s": 0.0, "bubble_s": 0.0,
                "flops": 0.0, "hbm_bytes": 0.0, "kv_swap_bytes": 0.0,
                "kv_read_tokens": 0, "spec_accepted": 0,
                "compile_events": 0}
        agg["records"] += 1
        agg["tokens"] += r[4]
        agg["chunk_tokens"] += r[5]
        agg["device_s"] += r[7]
        agg["staging_s"] += r[8]
        agg["bubble_s"] += r[9]
        agg["kv_read_tokens"] += r[10]
        agg["kv_swap_bytes"] += r[11]
        agg["spec_accepted"] += r[12]
        agg["compile_events"] += r[13]
        agg["flops"] += model.flops(r)
        agg["hbm_bytes"] += model.hbm_bytes(r)
        if r[1] != "prefill_chunk":
            ra = rungs.setdefault(str(r[2]), {"dispatches": 0,
                                              "slots_sum": 0})
            ra["dispatches"] += 1
            ra["slots_sum"] += r[3]
    kinds = {k: _finalize_kind(v, model.peak_flops, model.peak_hbm_bw)
             for k, v in kinds.items()}
    occupancy = {rung: {"dispatches": ra["dispatches"],
                        "mean_slots": round(ra["slots_sum"]
                                            / max(ra["dispatches"], 1), 2)}
                 for rung, ra in rungs.items()}
    sinks = sorted(
        ({"sink": f"{k}.{comp}", "seconds": v[f"{comp}_s"]}
         for k, v in kinds.items() for comp in ("device", "staging",
                                                "bubble")
         if v[f"{comp}_s"] > 0),
        key=lambda s: -s["seconds"])[:3]
    ledger_mfu = _ledger_mfu_ewma(recs, model.n_params, model.peak_flops,
                                  bind_unix, now)
    mfu = {"gauge": mfu_gauge,
           "ledger": None if ledger_mfu is None else round(ledger_mfu, 12)}
    if mfu_gauge and ledger_mfu is not None and mfu_gauge > 0:
        mfu["agreement"] = round(ledger_mfu / mfu_gauge, 4)
    else:
        mfu["agreement"] = None
    return {
        "enabled": True,
        "ts": round(now, 3),
        "window_s": window_s,
        "records_window": len(window),
        "records_total": ledger.count,
        "ledger_depth": ledger.depth,
        "truncated": bool(ledger.overflowed),
        "peaks": {"flops_per_s": model.peak_flops,
                  "hbm_bytes_per_s": model.peak_hbm_bw},
        "kinds": kinds,
        "rung_occupancy": occupancy,
        "top_sinks": sinks,
        "compile_events": sum(r[13] for r in window),
        "mfu": mfu,
    }


# Raw per-kind sums merge_steps_reports re-accumulates before
# re-deriving the verdict fields (which do not sum).
_KIND_SUM_FIELDS = ("records", "tokens", "chunk_tokens", "device_s",
                    "staging_s", "bubble_s", "flops", "hbm_bytes",
                    "kv_swap_bytes", "kv_read_tokens", "spec_accepted",
                    "compile_events")


def merge_steps_reports(reports: Sequence[Optional[Dict[str, Any]]]
                        ) -> Dict[str, Any]:
    """Fleet-merged step attribution from per-replica reports: per-kind
    raw sums re-finalized (verdicts recomputed over the pooled window —
    fractions and verdicts do not average), occupancy pooled, MFU gauge
    and ledger replay averaged across replicas (MFU is a per-chip
    utilization; the fleet runs dp chips)."""
    reports = [r for r in reports if r and r.get("enabled")]
    if not reports:
        return {"enabled": False}
    peaks = reports[0].get("peaks") or {}
    peak_flops = peaks.get("flops_per_s") or 1.0
    peak_bw = peaks.get("hbm_bytes_per_s") or 1.0
    kinds: Dict[str, Dict[str, Any]] = {}
    rungs: Dict[str, Dict[str, float]] = {}
    for rep in reports:
        for k, v in (rep.get("kinds") or {}).items():
            agg = kinds.setdefault(k, {f: 0 for f in _KIND_SUM_FIELDS})
            for f in _KIND_SUM_FIELDS:
                agg[f] += v.get(f, 0)
        for rung, ra in (rep.get("rung_occupancy") or {}).items():
            dst = rungs.setdefault(rung, {"dispatches": 0,
                                          "slots_sum": 0.0})
            dst["dispatches"] += ra.get("dispatches", 0)
            dst["slots_sum"] += (ra.get("mean_slots", 0)
                                 * ra.get("dispatches", 0))
    kinds = {k: _finalize_kind(v, peak_flops, peak_bw)
             for k, v in kinds.items()}
    occupancy = {rung: {"dispatches": int(ra["dispatches"]),
                        "mean_slots": round(ra["slots_sum"]
                                            / max(ra["dispatches"], 1), 2)}
                 for rung, ra in rungs.items()}
    sinks = sorted(
        ({"sink": f"{k}.{comp}", "seconds": v[f"{comp}_s"]}
         for k, v in kinds.items() for comp in ("device", "staging",
                                                "bubble")
         if v[f"{comp}_s"] > 0),
        key=lambda s: -s["seconds"])[:3]
    gauges = [r["mfu"].get("gauge") for r in reports
              if (r.get("mfu") or {}).get("gauge") is not None]
    ledgers = [r["mfu"].get("ledger") for r in reports
               if (r.get("mfu") or {}).get("ledger") is not None]
    mfu = {"gauge": round(sum(gauges) / len(gauges), 12) if gauges
           else None,
           "ledger": round(sum(ledgers) / len(ledgers), 12) if ledgers
           else None}
    if mfu["gauge"] and mfu["ledger"] is not None and mfu["gauge"] > 0:
        mfu["agreement"] = round(mfu["ledger"] / mfu["gauge"], 4)
    else:
        mfu["agreement"] = None
    return {
        "enabled": True,
        "replicas_merged": len(reports),
        "window_s": max(r.get("window_s", 0) for r in reports),
        "records_window": sum(r.get("records_window", 0)
                              for r in reports),
        "records_total": sum(r.get("records_total", 0) for r in reports),
        "truncated": any(r.get("truncated") for r in reports),
        "peaks": {"flops_per_s": peak_flops, "hbm_bytes_per_s": peak_bw},
        "kinds": kinds,
        "rung_occupancy": occupancy,
        "top_sinks": sinks,
        "compile_events": sum(r.get("compile_events", 0)
                              for r in reports),
        "mfu": mfu,
    }


# ---------------------------------------------------------------------------
# Crash flight recorder (README "Performance attribution"). A bounded
# per-replica blackbox/ directory of JSON captures — last-N step
# records + recent spans + resolved config + stats — written on watchdog
# trip, step_error, SIGTERM, and atexit, plus a periodic heartbeat
# capture that survives kill -9 (tmp+rename keeps every file whole).
# The fleet monitor harvests dead workers' directories and serves the
# index at GET /debug/blackbox. Every write path swallows exceptions:
# the recorder must never take serving down with it.
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Per-replica crash capture sink under ``{root}/replica-{i}/``.

    ``capture(trigger)`` writes ``capture-{seq:06d}-{trigger}.json``
    atomically and prunes beyond the retention cap (oldest first);
    ``maybe_periodic()`` refreshes a single ``periodic.json`` heartbeat
    at most every ``periodic_interval_s`` — the evidence a kill -9
    leaves behind. Per-trigger rate limiting stops a step_error storm
    from churning the whole retention window."""

    def __init__(self, root_dir: str, replica: int = 0, *,
                 retain: int = 8, config: Optional[dict] = None,
                 steps_fn: Optional[Callable[[], list]] = None,
                 spans_fn: Optional[Callable[[], list]] = None,
                 stats_fn: Optional[Callable[[], dict]] = None,
                 periodic_interval_s: float = 10.0):
        self.root = root_dir
        self.replica = int(replica)
        self.dir = os.path.join(root_dir, f"replica-{self.replica}")
        self.retain = max(1, int(retain))
        self.config = dict(config or {})
        self.steps_fn = steps_fn
        self.spans_fn = spans_fn
        self.stats_fn = stats_fn
        self.periodic_interval_s = max(0.5, float(periodic_interval_s))
        self._last_periodic = 0.0
        self._last_by_trigger: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._seq = 0
        try:
            os.makedirs(self.dir, exist_ok=True)
            for fname in os.listdir(self.dir):
                if fname.startswith("capture-"):
                    try:
                        self._seq = max(self._seq,
                                        int(fname.split("-")[1]) + 1)
                    except (ValueError, IndexError):
                        pass
            # A heartbeat left behind by a prior incarnation IS the
            # kill -9 postmortem: archive it under a sequence number
            # before this process's first beat overwrites it.
            prior = os.path.join(self.dir, "periodic.json")
            if os.path.exists(prior):
                dest = os.path.join(
                    self.dir, f"capture-{self._seq:06d}-postmortem.json")
                try:
                    with open(prior) as f:
                        payload = json.load(f)
                    payload["trigger"] = "postmortem"
                    self._write(dest, payload)
                    os.remove(prior)
                except (OSError, ValueError):
                    os.replace(prior, dest)
                self._seq += 1
        except OSError:
            pass

    def _payload(self, trigger: str) -> Dict[str, Any]:
        payload: Dict[str, Any] = {
            "ts": round(time.time(), 3), "replica": self.replica,
            "pid": os.getpid(), "trigger": trigger,
            "config": self.config}
        for key, fn, empty in (("steps", self.steps_fn, []),
                               ("spans", self.spans_fn, []),
                               ("stats", self.stats_fn, {})):
            try:
                payload[key] = fn() if fn is not None else empty
            except Exception:
                payload[key] = empty
        return payload

    def _write(self, path: str, payload: Dict[str, Any]) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def capture(self, trigger: str,
                min_interval_s: float = 1.0) -> Optional[str]:
        """Write one capture; returns its path (None = rate-limited or
        failed — the recorder never raises into serving code)."""
        try:
            with self._lock:
                now = time.time()
                if (now - self._last_by_trigger.get(trigger, -1e9)
                        < min_interval_s):
                    return None
                self._last_by_trigger[trigger] = now
                seq = self._seq
                self._seq += 1
            path = os.path.join(self.dir,
                                f"capture-{seq:06d}-{trigger}.json")
            self._write(path, self._payload(trigger))
            self._prune()
            log_event("blackbox_capture", trigger=trigger, path=path,
                      replica=self.replica)
            return path
        except Exception:
            return None

    def _prune(self) -> None:
        caps = sorted(f for f in os.listdir(self.dir)
                      if f.startswith("capture-") and f.endswith(".json"))
        for fname in caps[:-self.retain]:
            try:
                os.unlink(os.path.join(self.dir, fname))
            except OSError:
                pass

    def maybe_periodic(self) -> None:
        """Cheap scheduler-loop hook: refresh the heartbeat capture at
        most once per interval (two float compares otherwise)."""
        now = time.time()
        if now - self._last_periodic < self.periodic_interval_s:
            return
        self._last_periodic = now
        try:
            self._write(os.path.join(self.dir, "periodic.json"),
                        self._payload("periodic"))
        except Exception:
            pass

    def install_atexit(self) -> None:
        import atexit
        atexit.register(lambda: self.capture("atexit",
                                             min_interval_s=0.0))


def blackbox_index(root_dir: str) -> Dict[str, Any]:
    """Scan a blackbox root for per-replica captures (newest first) —
    the GET /debug/blackbox body, shared by both fleet backends. Each
    entry carries enough to triage without downloading the capture:
    trigger, timestamp, pid, and payload section sizes."""
    out: Dict[str, Any] = {"dir": root_dir, "captures": []}
    if not root_dir or not os.path.isdir(root_dir):
        return out
    for sub in sorted(os.listdir(root_dir)):
        rdir = os.path.join(root_dir, sub)
        if not (sub.startswith("replica-") and os.path.isdir(rdir)):
            continue
        try:
            replica = int(sub.split("-", 1)[1])
        except ValueError:
            continue
        try:
            fnames = sorted(os.listdir(rdir))
        except OSError:
            continue
        for fname in fnames:
            if not fname.endswith(".json"):
                continue
            path = os.path.join(rdir, fname)
            entry: Dict[str, Any] = {"replica": replica, "file": fname,
                                     "path": path}
            try:
                with open(path) as f:
                    payload = json.load(f)
                entry.update({
                    "trigger": payload.get("trigger"),
                    "ts": payload.get("ts"),
                    "pid": payload.get("pid"),
                    "n_steps": len(payload.get("steps") or ()),
                    "n_spans": len(payload.get("spans") or ()),
                    "has_config": bool(payload.get("config")),
                    "has_stats": bool(payload.get("stats")),
                })
            except (OSError, ValueError):
                entry["error"] = "unreadable"
            out["captures"].append(entry)
    out["captures"].sort(key=lambda e: e.get("ts") or 0.0, reverse=True)
    return out


def attach_flight_recorder(tel: "EngineTelemetry", root_dir: str,
                           replica: int, *, retain: int = 8,
                           config: Optional[dict] = None,
                           stats_fn: Optional[Callable[[], dict]] = None
                           ) -> Optional[FlightRecorder]:
    """Bind a FlightRecorder to one engine's telemetry bundle (shared
    by the subprocess worker and the in-process fleet, so the payload
    shape cannot drift between backends). No-op when the operator left
    ``blackbox_dir`` empty or telemetry is disabled."""
    if not root_dir or not tel.enabled:
        return None
    recorder = tel.recorder

    def spans_fn() -> list:
        spans: list = []
        for tid, trace in recorder.recent_traces(32).items():
            spans.extend(trace)
        spans.extend(recorder.maintenance_spans(32))
        return spans

    fr = FlightRecorder(root_dir, replica, retain=retain, config=config,
                        steps_fn=lambda: tel.step_ledger.snapshot(),
                        spans_fn=spans_fn, stats_fn=stats_fn)
    tel.flight = fr
    fr.install_atexit()
    return fr


def attach_router_flight_recorder(
        root_dir: str, *, retain: int = 8,
        config: Optional[dict] = None,
        stats_fn: Optional[Callable[[], dict]] = None,
        spans_fn: Optional[Callable[[], list]] = None,
        ) -> Optional[FlightRecorder]:
    """Router-side (process-fleet) capture sink: replica -1, so its
    ``replica--1/`` directory sorts apart from the workers' in the
    shared blackbox root. Poison quarantines and corrupt-KV rejections
    are router verdicts — the evidence (which workers failed, what the
    supervision counters said) lives here, not in any one worker's
    blackbox. No-op when the operator left ``blackbox_dir`` empty."""
    if not root_dir:
        return None
    return FlightRecorder(root_dir, -1, retain=retain, config=config,
                          spans_fn=spans_fn, stats_fn=stats_fn)


# ---------------------------------------------------------------------------
# Engine-side bundle
# ---------------------------------------------------------------------------

# Histograms exported under the JSON "phases" key (and scraped into the
# bench phase_breakdown). Name -> attribute on EngineTelemetry.
PHASE_HISTOGRAMS = {
    "prefill_dispatch_s": "prefill_dispatch_s",
    "decode_dispatch_s": "decode_dispatch_s",
    "decode_sync_s": "decode_sync_s",
    "dispatch_bubble_s": "dispatch_bubble_s",
    "tokens_per_dispatch": "tokens_per_dispatch",
    "hybrid_dispatch_s": "hybrid_dispatch_s",
    "decode_stall_during_prefill_s": "decode_stall_during_prefill_s",
    "kv_swap_s": "kv_swap_s",
    "spec_acceptance_rate": "spec_accept_rate",
    "queue_wait_s": "queue_wait_s",
    "prefill_phase_s": "prefill_phase_s",
    "decode_phase_s": "decode_phase_s",
    "ttft_s": "ttft_s",
    "e2e_s": "e2e_s",
}


class EngineTelemetry:
    """Per-engine (= per dp replica) metric bundle.

    Engine phases (observed by engine/engine.py):
    - ``prefill_dispatch_s``: host wall of one prefill dispatch
      (staging + device call + the blocking first-token readback).
    - ``decode_dispatch_s``: host wall of one fused-decode engine call
      (sync mode: includes the device wait; dispatch-ahead mode: the
      non-blocking dispatch only — the device wait shows up in
      ``decode_sync_s`` instead).
    - ``decode_sync_s``: host wall blocked syncing a dispatch-ahead
      call's outputs.
    - ``dispatch_bubble_s``: host-side gap between consecutive decode
      engine calls while sequences were active — scheduler bookkeeping,
      token callbacks, admission: the time the device could sit idle
      waiting for the host (hidden when pipeline depth > 1, but still
      measured so the host overhead is visible).
    - ``tokens_per_dispatch``: tokens surfaced per fused decode call.
    - ``hybrid_dispatch_s``: host wall of one hybrid prefill+decode
      fused dispatch (EngineConfig.hybrid_prefill).
    - ``decode_stall_during_prefill_s``: wall of a serial prefill
      dispatch issued while decode lanes were active — exactly the
      inter-token stall hybrid steps exist to remove, so the
      serial-vs-hybrid replay artifact compares its p95.

    Request phases (observed by engine/scheduler.py at finish):
    ``queue_wait_s``, ``prefill_phase_s`` (prefill start -> first
    token), ``decode_phase_s`` (first token -> finish), ``ttft_s``,
    ``e2e_s``. queue + prefill + decode sums to e2e by construction
    (same timestamps), the sum-check the bench artifact commits.
    """

    def __init__(self, engine=None, enabled: Optional[bool] = None):
        self.enabled = (telemetry_enabled() if enabled is None else enabled)
        self.registry = Registry()
        # Distributed tracing (README "Observability"): the replica's
        # span sink. Disabled with the rest of telemetry, so the ≤1%
        # overhead budget covers spans too. The owning fleet stamps
        # the replica index after construction.
        self.recorder = SpanRecorder(enabled=self.enabled)
        # Rolling SLO gauges; bound to targets in bind_engine.
        self.slo: Optional[SLOTracker] = None
        # Step ledger + roofline attribution (README "Performance
        # attribution"): sized/bound in bind_engine; the flight
        # recorder is attached by the owning worker/fleet (it needs the
        # operator's --blackbox-dir, which the engine never sees).
        self.step_ledger = NULL_LEDGER
        self.cost_model: Optional[StepCostModel] = None
        self.flight: Optional[FlightRecorder] = None
        if not self.enabled:
            for attr in PHASE_HISTOGRAMS.values():
                setattr(self, attr, NULL_METRIC)
            self.decode_dispatches = NULL_METRIC
            self.prefill_dispatches = NULL_METRIC
            self.hybrid_steps = NULL_METRIC
            self.degraded_mode = NULL_METRIC
            self.spec_gamma_g = NULL_METRIC
            self.kv_offload_pages = NULL_METRIC
            self.kv_restore_pages = NULL_METRIC
            self.kv_offload_bytes = NULL_METRIC
            self.kv_restore_bytes = NULL_METRIC
            return
        r = self.registry
        register_span_ring(r, self.recorder)
        self.prefill_dispatch_s = r.histogram(
            "tpu_inf_prefill_dispatch_seconds",
            "Host wall time of one prefill dispatch")
        self.decode_dispatch_s = r.histogram(
            "tpu_inf_decode_dispatch_seconds",
            "Host wall time of one fused-decode engine call")
        self.decode_sync_s = r.histogram(
            "tpu_inf_decode_sync_seconds",
            "Host wall blocked syncing a dispatch-ahead decode call")
        self.dispatch_bubble_s = r.histogram(
            "tpu_inf_dispatch_bubble_seconds",
            "Host-side gap between consecutive decode calls with active "
            "sequences (device-idle exposure)")
        self.tokens_per_dispatch = r.histogram(
            "tpu_inf_tokens_per_dispatch",
            "Tokens surfaced per fused decode call",
            buckets=COUNT_BUCKETS)
        self.hybrid_dispatch_s = r.histogram(
            "tpu_inf_hybrid_dispatch_seconds",
            "Host wall time of one hybrid prefill+decode fused dispatch")
        self.decode_stall_during_prefill_s = r.histogram(
            "tpu_inf_decode_stall_during_prefill_seconds",
            "Wall time active decode lanes sat stalled behind a serial "
            "chunked-prefill dispatch (structurally zero while hybrid "
            "steps fuse chunks into the decode dispatch; pressure-"
            "degraded rounds chunk serially and record their real stalls)")
        self.kv_swap_s = r.histogram(
            "tpu_inf_kv_swap_seconds",
            "Host wall of one device<->host KV page-batch swap "
            "(offload is a blocking device_get; restore is the host "
            "side of an async scatter dispatch)")
        self.spec_accept_rate = r.histogram(
            "tpu_inf_spec_acceptance_rate",
            "Per-sequence-round speculative acceptance rate "
            "(accepted / drafted positions; one observation per lane "
            "per spec round)",
            buckets=RATE_BUCKETS)
        self.spec_gamma_g = r.gauge(
            "tpu_inf_spec_gamma",
            "Mean adaptive speculation depth γ across the latest spec "
            "round's lanes (0 = every lane throttled to plain decode)")
        self.kv_offload_pages = r.counter(
            "tpu_inf_kv_offload_pages_total",
            "KV pages demoted from the HBM pool to the host-RAM tier")
        self.kv_restore_pages = r.counter(
            "tpu_inf_kv_restore_pages_total",
            "KV pages promoted from the host-RAM tier back into the "
            "HBM pool")
        self.kv_offload_bytes = r.counter(
            "tpu_inf_kv_offload_bytes_total",
            "Bytes copied device->host by KV page demotion")
        self.kv_restore_bytes = r.counter(
            "tpu_inf_kv_restore_bytes_total",
            "Bytes copied host->device by KV page promotion")
        self.queue_wait_s = r.histogram(
            "tpu_inf_queue_wait_seconds",
            "Request admission queue wait (enqueue -> prefill start)")
        self.prefill_phase_s = r.histogram(
            "tpu_inf_prefill_phase_seconds",
            "Request prefill phase (prefill start -> first token)")
        self.decode_phase_s = r.histogram(
            "tpu_inf_decode_phase_seconds",
            "Request decode phase (first token -> finish)")
        self.ttft_s = r.histogram(
            "tpu_inf_ttft_seconds",
            "Time to first token (enqueue -> first token)")
        self.e2e_s = r.histogram(
            "tpu_inf_e2e_seconds",
            "Request end-to-end latency (enqueue -> finish)")
        self.decode_dispatches = r.counter(
            "tpu_inf_decode_dispatches_total",
            "Fused-decode engine calls dispatched")
        self.prefill_dispatches = r.counter(
            "tpu_inf_prefill_dispatches_total",
            "Prefill dispatches issued")
        self.hybrid_steps = r.counter(
            "tpu_inf_hybrid_steps_total",
            "Hybrid prefill+decode fused dispatches issued")
        self.degraded_mode = r.gauge(
            "tpu_inf_degraded_mode",
            "1 when serving in a known-degraded configuration (e.g. "
            "unvalidated int4 Pallas path on real TPU)")
        if engine is not None:
            self.bind_engine(engine)

    def bind_engine(self, engine) -> None:
        """Read-through metrics over state the engine already tracks
        (zero hot-path cost)."""
        if not self.enabled:
            return
        self.step_ledger = StepLedger(engine.engine_cfg.step_ledger_depth)
        self.cost_model = StepCostModel.from_engine(engine)
        r = self.registry
        alloc = engine.allocator
        total = engine.engine_cfg.num_pages - 1   # page 0 = trash page
        r.counter("tpu_inf_kv_page_allocs_total",
                  "KV pool pages allocated",
                  fn=lambda: alloc.pages_allocated_total)
        r.counter("tpu_inf_kv_page_frees_total",
                  "KV pool pages freed",
                  fn=lambda: alloc.pages_freed_total)
        r.gauge("tpu_inf_kv_pages_total", "Allocatable KV pool pages",
                fn=lambda: total)
        r.gauge("tpu_inf_kv_pages_in_use", "KV pool pages in use",
                fn=lambda: total - alloc.num_free)
        r.gauge("tpu_inf_kv_page_util",
                "KV pool utilization (in_use / total)",
                fn=lambda: (total - alloc.num_free) / max(total, 1))
        r.gauge("tpu_inf_kv_pool_pressure",
                "1 - (free+evictable)/total: fraction of the pool "
                "pinned by running sequences",
                fn=lambda: engine.pool_pressure)
        r.counter("tpu_inf_preemptions_total",
                  "Sequences preempted for KV pool pressure "
                  "(admission=optimistic watermark safety net)",
                  fn=lambda: engine.preemptions_total)
        r.counter("tpu_inf_recompute_resumes_total",
                  "Preempted sequences re-prefilled (recompute-resume)",
                  fn=lambda: engine.resumes_total)
        r.counter("tpu_inf_swap_in_resumes_total",
                  "Resume prefills that restored KV pages from the "
                  "cache tiers instead of recomputing them all",
                  fn=lambda: engine.swap_in_resumes)
        # KV page migration (README "Process fleet"): drain-time exports
        # to / imports from sibling replicas. Structurally zero under
        # the in-process fleet (kept exported so backend counter shapes
        # match and dashboards need one query).
        r.counter("tpu_inf_kv_migrate_out_pages_total",
                  "KV pages exported at drain for migration to a "
                  "sibling replica",
                  fn=lambda: engine.migrate_out_pages)
        r.counter("tpu_inf_kv_migrate_out_bytes_total",
                  "Bytes exported at drain for KV migration",
                  fn=lambda: engine.migrate_out_bytes)
        r.counter("tpu_inf_kv_migrate_in_pages_total",
                  "Migrated KV pages adopted into this replica's host "
                  "tier",
                  fn=lambda: engine.migrate_in_pages)
        r.counter("tpu_inf_kv_migrate_in_bytes_total",
                  "Bytes adopted into the host tier by KV migration",
                  fn=lambda: engine.migrate_in_bytes)
        r.gauge("tpu_inf_model_params", "Model parameter count",
                fn=lambda: engine.n_params)
        r.gauge("tpu_inf_active_sequences", "Bound decode slots",
                fn=lambda: sum(s is not None for s in engine.slots))
        # Batch ladder (README "Batch ladder"): which compiled decode
        # graph the engine is currently dispatching, how far up it has
        # ever climbed, how often it switched graphs, and how full the
        # top rung's lanes are.
        r.gauge("tpu_inf_decode_rung",
                "Active batch-ladder rung (batch size of the compiled "
                "decode graph the latest dispatch ran)",
                fn=lambda: engine.decode_rung)
        r.gauge("tpu_inf_decode_ladder_top",
                "Top batch-ladder rung (HBM-budgeted max concurrent "
                "decode lanes)",
                fn=lambda: engine.ladder[-1])
        r.counter("tpu_inf_rung_switches_total",
                  "Decode dispatches that changed ladder rung (compiled-"
                  "graph switches)",
                  fn=lambda: engine.rung_switches_total)
        r.gauge("tpu_inf_decode_occupancy",
                "Decode lane occupancy: bound slots / top ladder rung",
                fn=lambda: (sum(s is not None for s in engine.slots)
                            / max(engine.ladder[-1], 1)))
        # Rolling SLO gauges (README "Observability"): exact windowed
        # TTFT/TPOT quantiles over the last SLO_WINDOW requests, plus
        # breach counters against the --slo-ttft-ms/--slo-tpot-ms
        # targets — the autoscaler's input signal (ROADMAP item 3).
        ecfg = engine.engine_cfg
        slo = self.slo = SLOTracker(ecfg.slo_ttft_ms / 1e3,
                                    ecfg.slo_tpot_ms / 1e3)
        for q in SLO_QUANTILES:
            r.gauge("tpu_inf_slo_ttft_seconds",
                    "Rolling exact TTFT quantile over the last "
                    f"{SLO_WINDOW} requests (NaN = no data)",
                    fn=lambda q=q: slo.gauge_value("ttft", q),
                    q=f"{q:g}")
            r.gauge("tpu_inf_slo_tpot_seconds",
                    "Rolling exact TPOT quantile over the last "
                    f"{SLO_WINDOW} requests (NaN = no data)",
                    fn=lambda q=q: slo.gauge_value("tpot", q),
                    q=f"{q:g}")
        r.counter("tpu_inf_slo_breaches_total",
                  "Finished requests whose TTFT exceeded --slo-ttft-ms "
                  "(never counts while no target is set)",
                  fn=lambda: slo.ttft_breaches, slo="ttft")
        r.counter("tpu_inf_slo_breaches_total",
                  "Finished requests whose TPOT exceeded --slo-tpot-ms "
                  "(never counts while no target is set)",
                  fn=lambda: slo.tpot_breaches, slo="tpot")

    def bind_spec(self, engine) -> None:
        """Read-through speculative-decoding counters over state the
        engine already tracks (called only when spec decode is on, so
        non-spec servers don't expose dead spec series)."""
        if not self.enabled:
            return
        r = self.registry
        r.counter("tpu_inf_spec_drafted_total",
                  "Speculative positions proposed for verification "
                  "(draft-model or n-gram proposals)",
                  fn=lambda: engine.spec_drafted)
        r.counter("tpu_inf_spec_accepted_total",
                  "Speculative positions accepted by the target model",
                  fn=lambda: engine.spec_accepted)
        r.counter("tpu_inf_spec_rounds_total",
                  "Verify rounds dispatched (ngram mode)",
                  fn=lambda: engine.spec_rounds_total)
        r.counter("tpu_inf_spec_fallback_rounds_total",
                  "Spec-mode rounds that ran the plain fused-K decode "
                  "graph because no lane proposed (cold/throttled "
                  "streams — the 'spec never loses' path)",
                  fn=lambda: engine.spec_fallback_rounds)
        r.counter("tpu_inf_spec_throttles_total",
                  "Sequences throttled to γ=0 by the acceptance EWMA",
                  fn=lambda: engine.spec_throttles_total)

    def bind_host_pool(self, pool) -> None:
        """Read-through metrics over the host-RAM KV tier's capacity
        accounting (engine/kv_cache.py HostPagePool). Called by the
        engine after the pool exists — bind_engine runs before the
        prefix cache / host tier are constructed."""
        if not self.enabled:
            return
        r = self.registry
        r.gauge("tpu_inf_kv_host_pages_total",
                "Host-RAM KV tier capacity (pages)",
                fn=lambda: pool.capacity)
        r.gauge("tpu_inf_kv_host_pages_used",
                "Host-RAM KV tier pages resident",
                fn=lambda: pool.used)
        r.counter("tpu_inf_kv_host_evictions_total",
                  "Host-tier entries dropped for good (second-tier LRU "
                  "eviction or supersession by a fresh HBM publish)",
                  fn=lambda: pool.evicted_total)

    def bind_scheduler(self, sched) -> None:
        """Read-through metrics over SchedulerStats counters."""
        if not self.enabled:
            return
        r = self.registry
        stats = sched.stats
        r.counter("tpu_inf_steps_total", "Scheduler loop decode steps",
                  fn=lambda: stats.steps)
        r.counter("tpu_inf_prefills_total", "Prefills completed",
                  fn=lambda: stats.prefills)
        r.counter("tpu_inf_tokens_generated_total", "Tokens generated",
                  fn=lambda: stats.tokens_generated)
        r.counter("tpu_inf_tokens_prefix_cached_total",
                  "Prompt tokens served from KV prefix reuse",
                  fn=lambda: stats.tokens_prefix_cached)
        r.counter("tpu_inf_requests_rejected_total",
                  "Requests rejected at submission",
                  fn=lambda: stats.requests_rejected)
        r.counter("tpu_inf_step_failures_total",
                  "Prefill/decode dispatch exceptions",
                  fn=lambda: stats.step_failures)
        r.gauge("tpu_inf_queue_depth", "Requests waiting for admission",
                fn=lambda: len(sched._waiting))
        # Derived MFU estimate: decoded-token rate x ~2 FLOPs/param/
        # token over the chip's bf16 peak (engine/autosize.py table;
        # CPU reports against a v5e, like the rest of the sizing math).
        # The rate is a dt-weighted EWMA (~30 s time constant) updated by
        # WHOEVER collects — /metrics scrapes, stats snapshots, and
        # fleet merges all read the same smoothed value, so a fast
        # poller can't reset a slow scraper's window (a plain
        # between-scrapes delta would report only the last poll's
        # sliver).
        import math

        from tpu_inference.engine import autosize as _autosize

        engine = sched.engine
        peak = _autosize.detect_peak_flops()
        tau_s = 30.0
        state = {"tokens": stats.tokens_generated,
                 "t": time.perf_counter(), "rate": 0.0}
        # Wall-clock EWMA epoch: the /debug/steps cross-check replays
        # this gauge's smoothing over the step ledger's timestamps, and
        # both must integrate from the same origin to agree.
        self._mfu_bind_unix = time.time()

        def _mfu() -> float:
            now = time.perf_counter()
            dt = now - state["t"]
            if dt >= 1e-3:
                tok = stats.tokens_generated
                inst = max(0, tok - state["tokens"]) / dt
                alpha = 1.0 - math.exp(-dt / tau_s)
                state["rate"] += alpha * (inst - state["rate"])
                state["tokens"], state["t"] = tok, now
            return state["rate"] * 2 * engine.n_params / peak

        self._mfu_gauge = r.gauge(
            "tpu_inf_mfu_estimate",
            "Estimated model FLOPs utilization (EWMA decode tokens/s "
            "x 2 x params / chip bf16 peak, ~30s time constant)",
            fn=_mfu)

    def mfu_estimate(self) -> Optional[float]:
        """Latest scrape-window MFU estimate (None when telemetry is
        off or no scheduler is bound)."""
        g = getattr(self, "_mfu_gauge", None)
        # 12 decimals, not 6: a toy CPU model against a real chip's
        # peak sits at MFU ~1e-9, and the /debug/steps agreement
        # cross-check needs the ratio, not a rounded-to-zero pair.
        return round(g.collect_value(), 12) if g is not None else None

    def steps_report(self, window_s: float = 60.0) -> Dict[str, Any]:
        """This replica's step-attribution report (the ``steps`` worker
        RPC verb / GET /debug/steps body)."""
        if not self.enabled or self.cost_model is None:
            return {"enabled": False}
        return roofline_report(
            self.step_ledger, self.cost_model,
            mfu_gauge=self.mfu_estimate(),
            bind_unix=getattr(self, "_mfu_bind_unix", None),
            window_s=window_s)

    def request_finished(self, reason: str) -> None:
        """Per-finish-reason counter (lazy label children)."""
        if not self.enabled:
            return
        self.registry.counter(
            "tpu_inf_requests_finished_total",
            "Finished requests by terminal reason",
            reason=reason or "unknown").inc()

    def phase_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """JSON phases dump for /metrics?format=json and the bench
        scrape (empty when disabled)."""
        if not self.enabled:
            return {}
        return {key: getattr(self, attr).phase_snapshot()
                for key, attr in PHASE_HISTOGRAMS.items()}
